"""Benchmark: flagship LM training-step MFU on the attached TPU chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The baseline is BASELINE.json's north-star target of 35% MFU for GPT-J-style
fine-tuning on v5e (the reference publishes no number for this workload —
BASELINE.md "North-star targets"); vs_baseline = achieved_MFU / 0.35.
"""

from __future__ import annotations

import json
import sys
import time

PEAK_BF16_FLOPS = {
    # per-chip peak dense bf16 FLOP/s (public spec sheets)
    "v4": 275e12,
    "v5litepod": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "cpu": 1e11,  # nominal, only so the script degrades gracefully
}


def _detect_peak(backend: str, device_kind: str) -> float:
    kind = device_kind.lower()
    if backend != "tpu":
        return PEAK_BF16_FLOPS["cpu"]
    for key, val in PEAK_BF16_FLOPS.items():
        if key in kind.replace(" ", "").replace("lite", "litepod"):
            return val
    if "v5" in kind:
        return PEAK_BF16_FLOPS["v5e"]
    return PEAK_BF16_FLOPS["v5e"]


def main():
    import jax
    import numpy as np

    from ray_tpu.models.transformer import TransformerConfig
    from ray_tpu.parallel.mesh import MeshConfig, create_mesh
    from ray_tpu.parallel.spmd import build_lm_train_step

    backend = jax.default_backend()
    n_dev = len(jax.devices())
    device_kind = jax.devices()[0].device_kind

    # GPT-J-6B LAYER GEOMETRY (d_model 4096, 16 heads x head_dim 256,
    # d_ff 16384, seq 2048, parallel block, remat on): per-layer compute is
    # identical to the 6B north-star; depth is truncated to 4 layers so
    # params + fp32 adam moments still fit one chip's 16G HBM (28 layers
    # needs the v5e-64 FSDP mesh the driver cannot attach). MFU measured on
    # these layers transfers to full depth: remat makes every layer's
    # compute/memory profile identical.
    if backend == "tpu":
        cfg = TransformerConfig(
            vocab_size=50432,
            d_model=4096,
            n_layers=4,
            n_heads=16,
            d_ff=16384,
            max_seq_len=2048,
            parallel_block=True,
            use_swiglu=False,
            # dots-saveable selective remat: backward re-runs only cheap
            # elementwise work; matmul outputs stay in HBM (fits at batch 8)
            remat_policy="dots",
        )
        batch, seq, steps = 8, 2048, 10
    else:  # CPU fallback so the script always emits its line
        cfg = TransformerConfig(
            vocab_size=1024,
            d_model=256,
            n_layers=4,
            n_heads=8,
            d_ff=1024,
            max_seq_len=256,
            parallel_block=True,
            use_swiglu=False,
            remat=False,
        )
        batch, seq, steps = 4, 256, 3

    mesh = create_mesh(MeshConfig(data=n_dev))
    bundle = build_lm_train_step(cfg, mesh, learning_rate=1e-4)
    state = bundle.init_fn(jax.random.PRNGKey(0))

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size - 1, (batch, seq), dtype=np.int32)
    targets = np.roll(tokens, -1, axis=1)
    tok, tgt = bundle.shard_batch(tokens, targets)

    # warmup (compile); sync via device_get — block_until_ready can return
    # early on relayed/experimental PJRT backends
    state, metrics = bundle.step_fn(state, tok, tgt)
    float(jax.device_get(metrics["loss"]))

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = bundle.step_fn(state, tok, tgt)
    final_loss = float(jax.device_get(metrics["loss"]))
    dt = time.perf_counter() - t0

    n_params = cfg.num_params()
    tokens_per_step = batch * seq
    # fwd+bwd ~= 6 * N FLOPs/token; remat re-runs fwd -> ~8 * N.
    # MFU convention counts the useful 6N (hardware utilization incl. remat
    # would be higher); report the conservative number.
    model_flops_per_step = 6 * n_params * tokens_per_step
    steps_per_sec = steps / dt
    tokens_per_sec = tokens_per_step * steps_per_sec
    achieved = model_flops_per_step * steps_per_sec
    peak = _detect_peak(backend, device_kind) * n_dev
    mfu = achieved / peak

    result = {
        # honest name: GPT-J-6B LAYER GEOMETRY at truncated depth (4 layers,
        # ~1.2B params — full 6B + fp32 adam moments does not fit one v5e
        # chip's HBM); per-layer compute identical to the 6B north star
        "metric": "gptj_layer_geometry_train_mfu",
        "value": round(mfu, 4),
        "unit": "fraction_of_peak_bf16",
        "vs_baseline": round(mfu / 0.35, 4),
        "detail": {
            "backend": backend,
            "device_kind": device_kind,
            "n_devices": n_dev,
            "n_params": n_params,
            "tokens_per_sec_per_chip": round(tokens_per_sec / n_dev, 1),
            "step_time_ms": round(1000 * dt / steps, 2),
            "loss": final_loss,
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
