"""Scale envelope bench: node count, deep task queues, actor fleets,
object broadcast.

Parity targets: the reference's scalability envelope
(``release/benchmarks/README.md:1-31`` — 2k+ nodes, 40k+ actors, 1M+ queued
tasks, 1 GiB broadcast to 50 nodes in 20.2 s on 64x 64-core machines).
This box is ONE machine (few cores), so the absolute numbers here measure
the control plane's *per-entity* costs and stability at depth, not fleet
wall-clock; ratios against the reference are recorded honestly with the
hardware caveat in the metric name.

Run: python bench_scale.py [--nodes N] [--tasks N] [--actors N] [--quick]
Prints one JSON line per metric: {"metric", "value", "unit", "reference",
"ratio"}.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

import ray_tpu
from ray_tpu.cluster_utils import Cluster


def emit(metric, value, unit, reference=None):
    scalar = isinstance(value, (int, float))
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value, 3) if scalar else value,
                "unit": unit,
                "reference": reference,
                "ratio": (
                    round(value / reference, 4) if reference and scalar else None
                ),
            }
        ),
        flush=True,
    )


def bench_nodes(cluster, n_nodes: int) -> None:
    t0 = time.perf_counter()
    for i in range(n_nodes):
        # the first 8 nodes carry a broadcast-reader marker so the transfer
        # bench can force exactly one remote reader per node (head-local
        # reads are zero-copy shm maps and would measure nothing)
        res = {"bcast": 1.0} if i < 8 else None
        cluster.add_node(num_cpus=1, resources=res, wait=False)
    cluster.wait_for_nodes(timeout=600)
    dt = time.perf_counter() - t0
    alive = sum(1 for n in ray_tpu.nodes() if n["alive"])
    assert alive >= n_nodes + 1, f"only {alive} nodes alive"
    emit("scale_nodes_joined", alive - 1, "nodes")
    emit("scale_node_join_rate", n_nodes / dt, "nodes/s")


def _tick_hist_snapshot() -> dict:
    from ray_tpu._private.worker import get_runtime

    return json.loads(json.dumps(get_runtime().node.scheduler._tick_hist))


def bench_queue_depth(n_tasks: int, curve_points: int = 10) -> None:
    @ray_tpu.remote
    def noop(i):
        return i

    h0 = _tick_hist_snapshot()
    t0 = time.perf_counter()
    refs = [noop.remote(i) for i in range(n_tasks)]
    submit_dt = time.perf_counter() - t0
    emit("scale_task_submit_rate", n_tasks / submit_dt, "tasks/s")
    # drain: the scheduler must stay responsive with a deep queue. Results
    # are collected in ordered chunks — completions are ~FIFO, so the chunk
    # timestamps trace the drain-rate curve.
    t1 = time.perf_counter()
    chunk = max(1, n_tasks // curve_points)
    curve = []
    done = 0
    out = []
    for i in range(0, n_tasks, chunk):
        out = ray_tpu.get(refs[i : i + chunk], timeout=3600)
        done += len(out)
        curve.append([done, round(time.perf_counter() - t1, 3)])
    drain_dt = time.perf_counter() - t1
    assert out[-1] == n_tasks - 1
    emit("scale_queued_tasks_drained", float(n_tasks), "tasks")
    emit("scale_task_drain_rate", n_tasks / drain_dt, "tasks/s")
    emit(f"scale_task_drain_curve_{n_tasks}", curve, "[tasks,s]")
    # per-tick dispatch cost at this depth (histogram delta over the phase):
    # flatness across 100k -> 1M runs is the million-task acceptance signal
    h1 = _tick_hist_snapshot()
    dcount = h1["count"] - h0["count"]
    dsum = h1["sum"] - h0["sum"]
    emit(
        f"scale_sched_tick_mean_us_{n_tasks}",
        (dsum / dcount * 1e6) if dcount else 0.0,
        "us/tick",
    )
    emit(f"scale_sched_tick_count_{n_tasks}", float(dcount), "ticks")


def bench_locality(n_nodes: int, mib: int, rounds: int = 8) -> None:
    """Big-arg placement: counter-based cross-node transfer accounting with
    locality-aware dispatch ON vs OFF (host-noise-immune — counts and bytes,
    not wall clock). Each round pre-stages a fresh blob on a rotating
    node-affinity target (an upstream producer's output living somewhere
    specific), then dispatches one unconstrained consumer: ON follows the
    bytes (zero pulls), OFF lands wherever the default policy says and pays
    a pull whenever that differs from the stage node. Consumers are pinned
    off the head (bcast marker — the head holds every driver put, so it
    would trivially win locality), and the shm short-circuit is disabled so
    residency is explicit, as on a real multi-machine fleet."""
    from ray_tpu._private.worker import get_runtime
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    sch = get_runtime().node.scheduler
    marked = [
        n["node_id"]
        for n in ray_tpu.nodes()
        if n["alive"] and n["total"].get("bcast")
    ]

    @ray_tpu.remote(num_cpus=1, resources={"bcast": 0.01})
    def consume(x):
        assert float(x[0]) == 1.0 and float(x[-1]) == 1.0
        return x.nbytes

    @ray_tpu.remote(num_cpus=1)
    def stage(x):
        return x.nbytes  # arg delivery pulls the blob onto this node

    # warm per-node workers so spawn latency doesn't serialize the phase
    small = ray_tpu.put(np.ones(8))
    ray_tpu.get([consume.remote(small) for _ in range(n_nodes)], timeout=1200)

    def run_once(flag: bool):
        sch.config.locality_aware_dispatch = flag
        moved = xfers = 0
        for r in range(rounds):
            blob = ray_tpu.put(
                np.ones(mib * 1024 * 1024 // 8, dtype=np.float64)
            )
            target = marked[r % len(marked)]
            ray_tpu.get(
                stage.options(
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node_id=target
                    )
                ).remote(blob),
                timeout=1200,
            )
            b0 = sum(sch._xfer_done_bytes)
            c0 = sum(sch._xfer_done_count)
            ray_tpu.get(consume.remote(blob), timeout=1200)
            moved += sum(sch._xfer_done_bytes) - b0
            xfers += sum(sch._xfer_done_count) - c0
            del blob
        return moved, xfers

    sch.config.same_host_shm_transfer = False
    try:
        on_b, on_x = run_once(True)
        off_b, off_x = run_once(False)
    finally:
        sch.config.same_host_shm_transfer = True
        sch.config.locality_aware_dispatch = True
    emit("scale_locality_rounds", float(rounds), "staged consumers")
    emit("scale_locality_transfers_off", float(off_x), "transfers")
    emit("scale_locality_transfers_on", float(on_x), "transfers")
    emit("scale_locality_xfer_mib_off", off_b / 2**20, "MiB")
    emit(
        "scale_locality_xfer_mib_on",
        on_b / 2**20,
        "MiB",
        reference=round(off_b / 2**20, 3) or None,
    )


def bench_actor_fleet(n_actors: int) -> None:
    @ray_tpu.remote(num_cpus=0)
    class Member:
        def pid(self):
            import os

            return os.getpid()

    t0 = time.perf_counter()
    actors = [Member.remote() for _ in range(n_actors)]
    # one round-trip proves every registration landed
    pids = ray_tpu.get([a.pid.remote() for a in actors], timeout=3600)
    dt = time.perf_counter() - t0
    assert len(pids) == n_actors
    emit("scale_actor_fleet", float(n_actors), "actors")
    emit("scale_actor_launch_rate", n_actors / dt, "actors/s")
    for a in actors:
        ray_tpu.kill(a)


def bench_broadcast(n_nodes: int, mib: int) -> None:
    """One driver-put object read by a task pinned to each daemon node.

    Reference: 1 GiB -> 50 nodes in 20.2 s (~2.48 GiB/s aggregate,
    release_logs/2.9.3/scalability/object_store.json — their readers receive
    the object into plasma; consumption is not part of the measurement).

    Two metrics here, because the object plane has two paths:
    * ``..._agg`` — the default plane: nodes colocated on one machine
      deliver through /dev/shm (zero-copy pinned views; the reader verifies
      edge content). This is the plasma model — on one host the broadcast
      IS shared memory.
    * ``..._socket_agg`` — same run with the shm short-circuit disabled:
      the cross-host plane (striped multi-stream fetch + relay tree), which
      is what a real multi-machine fleet would exercise.
    """
    blob = ray_tpu.put(np.ones(mib * 1024 * 1024 // 8, dtype=np.float64))

    # one reader pinned per daemon node (the bcast marker)
    @ray_tpu.remote(num_cpus=0, resources={"bcast": 1.0})
    def reader(x):
        # verify edges (delivery proof) without turning the metric into a
        # numpy-sum throughput test
        n = x.shape[0]
        assert float(x[0]) == 1.0 and float(x[n // 2]) == 1.0 and float(x[-1]) == 1.0
        return x.nbytes

    # warm the per-node workers with a tiny object first: the metric is the
    # object plane's delivered bandwidth, not python import time (the
    # reference's release benchmark also measures an established cluster)
    small = ray_tpu.put(np.ones(8))
    ray_tpu.get([reader.remote(small) for _ in range(n_nodes)], timeout=1200)

    t0 = time.perf_counter()
    out = ray_tpu.get(
        [reader.remote(blob) for _ in range(n_nodes)], timeout=1200
    )
    dt = time.perf_counter() - t0
    assert len(out) == n_nodes
    # metric name matches the committed BENCH_SCALE.jsonl artifact
    # ("..._{n}nodes_..."): one reader task is pinned per daemon node
    emit(
        f"scale_broadcast_{mib}mib_{n_nodes}nodes_shm_agg",
        (mib / 1024.0) * n_nodes / dt,
        "GiB/s",
        reference=round(50.0 / 20.2, 3),  # 1 GiB x 50 nodes / 20.2 s
    )

    # cross-host plane: disable the shm short-circuit cluster-wide and force
    # socket transfers of a fresh object
    from ray_tpu._private.worker import get_runtime

    sch = get_runtime().node.scheduler
    sch.config.same_host_shm_transfer = False
    try:
        blob2 = ray_tpu.put(np.ones(mib * 1024 * 1024 // 8, dtype=np.float64))
        oid2 = blob2.id()
        nids = [
            nid
            for nid, n in sch.nodes.items()
            if n.daemon_conn is not None and n.total.get("bcast")
        ][:n_nodes]
        t0 = time.perf_counter()
        for nid in nids:
            sch.post(("local_rpc", "ensure_local", (oid2, nid),
                      __import__("threading").Event(), {}))
        deadline = time.monotonic() + 1200
        land_at = {}
        while time.monotonic() < deadline:
            locs = sch._object_locations.get(oid2, ())
            for x in nids:
                if x in locs and x not in land_at:
                    land_at[x] = time.perf_counter() - t0
            if len(land_at) == len(nids):
                break
            time.sleep(0.02)
        dt = time.perf_counter() - t0
        assert len(land_at) == len(nids), (
            f"socket broadcast incomplete: {len(land_at)}/{len(nids)} replicas "
            "landed before the deadline — refusing to emit a bogus rate"
        )
        emit(
            f"scale_broadcast_{mib}mib_{len(nids)}nodes_socket_agg",
            (mib / 1024.0) * len(nids) / dt,
            "GiB/s",
            reference=round(50.0 / 20.2, 3),
        )
        # pipelined-relay evidence: per-hop landing times. Store-and-forward
        # chains stagger completions by ~(object time) per hop; pipelined
        # chains land together shortly after the first delivery (overlap) —
        # on a 1-core box the AGGREGATE stays memcpy-bound either way (all
        # hops share one core), but the spread shows the chunks flowed
        # through relays concurrently. On real NICs the same overlap turns
        # into aggregate bandwidth.
        lands = sorted(land_at.values())
        emit(
            f"scale_broadcast_{mib}mib_{len(nids)}nodes_socket_landings",
            [round(x, 3) for x in lands],
            "s",
        )
        emit(
            f"scale_broadcast_{mib}mib_{len(nids)}nodes_socket_tail_spread",
            round((lands[-1] - lands[0]) / max(lands[-1], 1e-9), 4),
            "fraction",
        )
    finally:
        sch.config.same_host_shm_transfer = True


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=50)
    ap.add_argument("--tasks", type=int, default=100_000)
    ap.add_argument("--actors", type=int, default=1000)
    ap.add_argument("--broadcast-mib", type=int, default=256)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--locality-mib", type=int, default=32)
    ap.add_argument(
        "--only",
        choices=["nodes", "broadcast", "tasks", "actors", "locality"],
        help="run one phase (nodes are always set up first)",
    )
    args = ap.parse_args()
    if args.quick:
        args.nodes, args.tasks, args.actors = 8, 5_000, 100
        args.broadcast_mib = 64
        args.locality_mib = 8

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        # only the broadcast/locality-only modes shrink the fleet (they use
        # at most 8 marked nodes anyway); task/actor phases keep the
        # requested size so their numbers are comparable with full runs
        n_nodes = (
            min(args.nodes, 8)
            if args.only in ("broadcast", "locality")
            else args.nodes
        )
        bench_nodes(cluster, n_nodes)
        # broadcast before the churn-heavy phases: reaping thousands of
        # worker processes would otherwise contaminate its timing
        if args.only in (None, "broadcast"):
            bench_broadcast(min(n_nodes, 8), args.broadcast_mib)
        if args.only in (None, "locality"):
            bench_locality(min(n_nodes, 8), args.locality_mib)
        if args.only in (None, "tasks"):
            bench_queue_depth(args.tasks)
        if args.only in (None, "actors"):
            bench_actor_fleet(args.actors)
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
