"""MNIST data-parallel training with JaxTrainer (BASELINE.json config #2).

Runs on any device set: real TPU chips or the virtual CPU mesh
(XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu).
Uses synthetic MNIST-shaped data so the example is hermetic (zero egress);
point ``load_data`` at real MNIST arrays to train the real thing.
"""

import numpy as np

import ray_tpu
from ray_tpu import data as rd, train
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig


def load_data(n=8192):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 784)).astype(np.float32)
    w = rng.normal(size=(784, 10))
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    return x, y


def train_loop(config):
    import jax
    import optax

    from ray_tpu.models.mnist import accuracy, apply_mlp, cross_entropy_loss, init_mlp
    from ray_tpu.parallel.mesh import MeshConfig, create_mesh
    from ray_tpu.parallel.sharding import batch_sharding

    mesh = create_mesh(MeshConfig(data=-1))  # pure DP over all local devices
    params = init_mlp(jax.random.PRNGKey(0), hidden=(128, 128))
    opt = optax.adam(config["lr"])
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, x, y):
        def loss(p):
            return cross_entropy_loss(apply_mlp(p, x), y)

        lval, grads = jax.value_and_grad(loss)(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, lval

    sh = batch_sharding(mesh)
    it = config["__datasets__"]["train"]
    for epoch in range(config["epochs"]):
        losses = []
        for batch in it.iter_batches(batch_size=config["batch_size"], drop_last=True):
            x = jax.device_put(batch["x"], sh)
            y = jax.device_put(batch["y"], sh)
            params, opt_state, lval = step(params, opt_state, x, y)
            losses.append(float(lval))
        train.report({"epoch": epoch, "loss": float(np.mean(losses))})


def main():
    ray_tpu.init(ignore_reinit_error=True)
    x, y = load_data()
    ds = rd.Dataset(
        [ray_tpu.put({"x": x[i : i + 1024], "y": y[i : i + 1024]}) for i in range(0, len(x), 1024)]
    )
    result = JaxTrainer(
        train_loop,
        train_loop_config={"lr": 1e-3, "epochs": 3, "batch_size": 256},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="mnist_dp"),
        datasets={"train": rd.DataIterator(ds)},
    ).fit()
    print("final:", result.metrics)


if __name__ == "__main__":
    main()
