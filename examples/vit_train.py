"""ViT classification training under GSPMD data+tensor parallelism.

Runs on whatever devices exist (1 real TPU chip, or the virtual CPU mesh when
XLA_FLAGS=--xla_force_host_platform_device_count=8 is set).

Run: python examples/vit_train.py
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.models import vit
from ray_tpu.parallel.mesh import create_mesh
from ray_tpu.parallel.sharding import DEFAULT_LM_RULES, batch_sharding, shard_params


def main():
    n = len(jax.devices())
    mesh = create_mesh(data=-1, tensor=2 if n % 2 == 0 and n > 1 else 1,
                       drop_trivial_axes=True)
    print("mesh:", dict(mesh.shape))
    cfg = vit.ViTConfig(image_size=32, patch_size=4, num_classes=10,
                        d_model=128, n_layers=4, n_heads=4, d_ff=256)
    params = vit.init_params(jax.random.PRNGKey(0), cfg)
    params = shard_params(params, vit.param_logical_axes(cfg), DEFAULT_LM_RULES, mesh)
    opt = optax.adamw(3e-4)
    opt_state = opt.init(params)
    bshard = batch_sharding(mesh)

    @jax.jit
    def step(params, opt_state, images, labels):
        (loss, acc), grads = jax.value_and_grad(
            lambda p: vit.loss_fn(cfg, p, images, labels), has_aux=True
        )(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss, acc

    rng = np.random.RandomState(0)
    # synthetic 10-class problem: class-dependent mean patterns
    means = rng.randn(10, 32, 32, 3).astype(np.float32)
    for i in range(30):
        labels = rng.randint(0, 10, 32)
        images = means[labels] + 0.5 * rng.randn(32, 32, 32, 3).astype(np.float32)
        images = jax.device_put(images, bshard)
        labels_d = jax.device_put(labels, bshard)
        params, opt_state, loss, acc = step(params, opt_state, images, labels_d)
        if i % 5 == 0:
            print(f"step {i:3d} loss={float(loss):.3f} acc={float(acc):.2f}")


if __name__ == "__main__":
    main()
