"""Request-tracing & continuous-profiling demo (the `make trace-demo`
smoke target).

Runs a nested task graph and a streaming serve request, reconstructs both
span trees with ``ray_tpu.trace``, checks the acceptance invariants (stage
sum within 10% of wall; TTFT span present), and exports a speedscope flame
graph. Exits non-zero on any violation, so CI can smoke the whole plane.
"""

import sys
import time

import ray_tpu


def main() -> int:
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    failures = []

    # -- nested task graph -------------------------------------------------
    @ray_tpu.remote
    def leaf(x):
        time.sleep(0.03)
        return x * 2

    @ray_tpu.remote
    def root(x):
        return ray_tpu.get(leaf.remote(x)) + 1

    assert ray_tpu.get(root.remote(3)) == 7
    tid = next(
        t["trace_id"]
        for t in ray_tpu.recent_traces(limit=10)
        if t["root"] == "root"
    )
    tr = ray_tpu.trace(tid)
    print("=== nested task graph ===")
    print(tr.summary())
    if tr.span_count() != 2:
        failures.append(f"expected 2 spans, got {tr.span_count()}")
    r = tr.roots[0]
    covered = sum(r.stage_breakdown().values())
    if r.duration_ms and abs(covered - r.duration_ms) / r.duration_ms > 0.10:
        failures.append(
            f"stage sum {covered:.1f}ms vs wall {r.duration_ms:.1f}ms"
        )

    # -- streaming serve request (TTFT) ------------------------------------
    from ray_tpu import serve

    @serve.deployment(num_replicas=1)
    class Tokens:
        def gen(self, n):
            for i in range(int(n)):
                time.sleep(0.01)
                yield f"tok{i}"

    h = serve.run(Tokens.bind(), name="trace_demo")
    try:
        out = list(h.options(stream=True).gen.remote(4))
        assert len(out) == 4
        serve_tr = None
        deadline = time.time() + 15
        while time.time() < deadline and serve_tr is None:
            for d in ray_tpu.recent_traces(limit=30):
                cand = ray_tpu.trace(d["trace_id"])
                spans = list(cand.spans.values())
                if any(
                    (s.name or "").startswith("serve:replica:Tokens")
                    and s.extra.get("ttft_ms") is not None
                    for s in spans
                ):
                    serve_tr = cand
                    break
            time.sleep(0.3)
        print("=== streaming serve request ===")
        if serve_tr is None:
            failures.append("no serve trace with a TTFT span found")
        else:
            print(serve_tr.summary())
    finally:
        serve.shutdown()

    # -- continuous profiler ------------------------------------------------
    @ray_tpu.remote
    def spin(s):
        t0 = time.time()
        while time.time() - t0 < s:
            pass

    ray_tpu.request_profile(hz=150, duration_s=2.0)
    ray_tpu.get([spin.remote(0.6) for _ in range(2)], timeout=60)
    time.sleep(1.2)
    n = ray_tpu.profile_dump("/tmp/ray_tpu_trace_demo_flame.json")
    print(f"flame graph: {n} profiles -> /tmp/ray_tpu_trace_demo_flame.json")
    if n < 1:
        failures.append("profiler produced no samples")

    ray_tpu.shutdown()
    if failures:
        print("TRACE-DEMO FAILURES:", *failures, sep="\n  ")
        return 1
    print("trace-demo OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
