"""DQN on CartPole with the RL library.

Run: python examples/dqn_cartpole.py
"""

from ray_tpu.rl import DQNConfig


def main():
    algo = (
        DQNConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=8)
        .debugging(seed=0)
        .build()
    )
    for i in range(120):
        result = algo.train()
        if i % 10 == 0:
            print(
                f"iter {i:3d} return={result['episode_return_mean']:7.1f} "
                f"steps={result['num_env_steps_sampled_lifetime']} "
                f"eps={result['epsilon']:.2f}"
            )
        if result["episode_return_mean"] >= 300:
            print("solved")
            break


if __name__ == "__main__":
    main()
