"""A bound two-deployment composition graph, importable by the declarative
Serve config path (``serve build`` / ``serve run examples.serve_config_app:app``).
"""

from ray_tpu import serve


@serve.deployment
class Doubler:
    def __call__(self, x):
        return 2 * x


@serve.deployment
class Ingress:
    def __init__(self, doubler):
        self.doubler = doubler

    def __call__(self, x):
        return self.doubler.remote(x).result() + 1


app = Ingress.bind(Doubler.bind())
