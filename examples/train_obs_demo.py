"""Training step-plane smoke (`make train-obs-demo`).

Two proofs, non-zero exit on violation:

1. **Stage coverage** — a calm 2-rank run with a throttled dataset and
   per-step checkpoints: every per-rank step record's stage decomposition
   (data_wait / host_to_device / compile / compute / collective_wait /
   checkpoint_stall / other) must sum to within 10% of its measured step
   wall, the throttled data operator must be named in the ingest stalls,
   and the per-rank step waterfall is printed.

2. **Downtime attribution** — the same run re-executed with one seeded
   kill (rank 1 dies once mid-run): the goodput gap vs the calm run must
   be attributed by the downtime ledger — ledger seconds within 10% of
   the calm-vs-churned wall delta (plus a small absolute slack for
   scheduler noise on shared hosts).
"""

from __future__ import annotations

import os
import sys
import tempfile
import time

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (
    Checkpoint,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.util import state

STEPS = 8
STEP_SLEEP = 0.15
STAGES = (
    "data_wait_ms",
    "host_to_device_ms",
    "compile_ms",
    "compute_ms",
    "collective_wait_ms",
    "checkpoint_stall_ms",
    "other_ms",
)


def make_loop(kill_marker=None):
    def loop(config):
        ctx = train.get_context()
        it = train.get_dataset_shard("train")
        batches = it.iter_batches(batch_size=4) if it is not None else None
        # checkpoint-resumable: a recovered attempt continues from the
        # committed step instead of redoing work — the churned run then
        # does the SAME useful work as the calm one, so the wall delta is
        # pure downtime for the ledger to attribute
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "step.txt")) as fh:
                start = int(fh.read()) + 1
        for i in range(start, STEPS):
            if batches is not None:
                next(batches, None)  # throttled ingest -> data_wait
            time.sleep(STEP_SLEEP)  # "compute"
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "step.txt"), "w") as fh:
                fh.write(str(i))
            train.report(
                {"step": float(i)}, checkpoint=Checkpoint.from_directory(d)
            )
            if (
                kill_marker
                and i == 3
                and ctx.get_world_rank() == 1
                and not os.path.exists(kill_marker)
            ):
                open(kill_marker, "w").close()
                os._exit(1)  # seeded preemption

    return loop


def run(name, tmp, kill_marker=None):
    def slow(block):
        time.sleep(0.02)
        return block

    ds = ray_tpu.data.range(STEPS * 2 * 4, num_blocks=16).map_batches(slow)
    trainer = JaxTrainer(
        make_loop(kill_marker),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            storage_path=os.path.join(tmp, name),
            name=name,
            failure_config=FailureConfig(max_failures=2, retry_backoff_s=0.2),
        ),
        datasets={"train": ds},
    )
    t0 = time.perf_counter()
    res = trainer.fit()
    wall = time.perf_counter() - t0
    assert res.error is None, f"{name} failed: {res.error}"
    return res, wall


def fail(msg):
    print(f"FAIL: {msg}")
    sys.exit(1)


def main():
    ray_tpu.init(num_cpus=6, ignore_reinit_error=True)
    tmp = tempfile.mkdtemp(prefix="train_obs_demo_")

    # warm the worker pool / jit of the data path so the calm-vs-churned
    # wall comparison below isn't dominated by first-run startup costs
    run("obs_demo_warm", tmp)

    # -- 1. calm run: stage coverage -----------------------------------
    _res, calm_wall = run("obs_demo_calm", tmp)
    d = state.train_run("obs_demo_calm")
    if d is None or d["steps_seen"] < STEPS:
        fail(f"step records missing: {d and d['steps_seen']}")
    worst = 0.0
    records = 0
    for srec in d["steps"]:
        for rec in srec["ranks"].values():
            total = sum(rec["stages"].get(k, 0.0) for k in STAGES)
            err = abs(total - rec["wall_ms"]) / max(rec["wall_ms"], 1e-9)
            worst = max(worst, err)
            records += 1
    print(
        f"coverage: {records} records, worst |stage_sum - wall|/wall "
        f"= {worst:.3f}"
    )
    if worst > 0.10:
        fail(f"stage coverage violation: {worst:.3f} > 0.10")
    if not d["ops"]:
        fail("no per-operator ingest stall attribution recorded")
    print("\n" + ray_tpu.train_timeline("obs_demo_calm").summary(max_steps=8))

    # -- 2. churned run: downtime ledger attribution -------------------
    marker = os.path.join(tmp, "killed_once")
    res, churn_wall = run("obs_demo_churn", tmp, kill_marker=marker)
    ledger = res.goodput["downtime_ledger"]
    attributed = sum(e["seconds"] for e in ledger)
    delta = churn_wall - calm_wall
    print(
        f"\ncalm wall {calm_wall:.2f}s  churned wall {churn_wall:.2f}s  "
        f"delta {delta:.2f}s  ledger {attributed:.2f}s"
    )
    print(ray_tpu.train_timeline("obs_demo_churn").summary(max_steps=4))
    if not ledger:
        fail("seeded kill produced no downtime ledger entries")
    if not {e["cause"] for e in ledger} & {"recovery", "gang_restart", "preemption"}:
        fail(f"ledger has no kill-attributed cause: {ledger}")
    # the goodput gap must be attributed: ledger sum within 10% of the
    # calm-vs-churned wall delta, with a small absolute slack (shared
    # hosts jitter the calm baseline itself)
    slack = max(0.10 * delta, 0.75)
    if delta > 0 and abs(attributed - delta) > slack:
        fail(
            f"downtime ledger {attributed:.2f}s does not attribute the "
            f"goodput gap {delta:.2f}s (tolerance {slack:.2f}s)"
        )
    print("\nOK: stage coverage + downtime attribution hold")
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
