"""PPO on CartPole (BASELINE.json #1): reaches return >= 150 in < 100k steps."""

import ray_tpu
from ray_tpu.rl import PPOConfig


def main():
    ray_tpu.init(ignore_reinit_error=True)
    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                     rollout_fragment_length=128)
        .training(lr=3e-4)
        .build()
    )
    for i in range(50):
        result = algo.train()
        print(
            f"iter {i}: return={result['episode_return_mean']:.1f} "
            f"steps={result['num_env_steps_sampled_lifetime']}"
        )
        if result["episode_return_mean"] >= 150:
            print("solved")
            break
    algo.stop()


if __name__ == "__main__":
    main()
