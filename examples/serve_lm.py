"""Batched LM inference serving (BASELINE.json #5 shape).

Replicas hold a jitted forward; `serve.batch` coalesces concurrent requests
into one XLA call — the TPU batching path. Tiny model keeps it hermetic;
swap in LLAMA2_7B + real weights for the full config.
"""

import numpy as np

import ray_tpu
from ray_tpu import serve


@serve.deployment(num_replicas=1, max_ongoing_requests=8)
class LMServer:
    def __init__(self):
        import jax

        from ray_tpu.models.transformer import TINY, forward, init_params

        self.cfg = TINY
        self.params = init_params(jax.random.PRNGKey(0), TINY)
        self._fwd = jax.jit(lambda p, t: forward(p, t, self.cfg))

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.02)
    def __call__(self, payloads):
        import jax.numpy as jnp

        seq = max(len(p["tokens"]) for p in payloads)
        batch = np.zeros((len(payloads), seq), np.int32)
        for i, p in enumerate(payloads):
            batch[i, : len(p["tokens"])] = p["tokens"]
        logits = self._fwd(self.params, batch)
        next_tokens = jnp.argmax(logits[:, -1], axis=-1)
        return [{"next_token": int(t)} for t in np.asarray(next_tokens)]

    def generate(self, tokens, max_new_tokens: int = 16):
        """Autoregressive completion on the KV-cache decode path
        (``models/generation.py``; bench: ``bench_lm_decode.py``)."""
        from ray_tpu.models.generation import generate, make_decode_fns

        # cache the jitted (prefill, decode_step) pair per shape — without
        # this every request would recompile the decode graphs
        key = (1, len(tokens) + max_new_tokens)
        fns_cache = getattr(self, "_fns", None)
        if fns_cache is None:
            fns_cache = self._fns = {}
        if key not in fns_cache:
            fns_cache[key] = make_decode_fns(self.cfg, key[1])
        out = generate(
            self.params,
            np.asarray([tokens], np.int32),
            self.cfg,
            max_new_tokens=max_new_tokens,
            fns=fns_cache[key],
        )
        return {"tokens": np.asarray(out)[0].tolist()}


def main():
    ray_tpu.init(ignore_reinit_error=True)
    handle = serve.run(LMServer.bind(), name="lm", route_prefix="/lm")
    out = [handle.remote({"tokens": [1, 2, 3, i]}) for i in range(8)]
    print([r.result(timeout_s=120) for r in out])
    serve.shutdown()


if __name__ == "__main__":
    main()
