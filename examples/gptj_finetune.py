"""GPT-J-style LM fine-tuning with full GSPMD sharding (BASELINE.json #4).

On a v5e-64 slice set ``MeshConfig(data=-1, fsdp=8, tensor=4)`` (or similar)
and the GPTJ_6B preset; on one chip / the CPU test mesh this runs a scaled
model with the exact same program. Synthetic token stream keeps it hermetic.
"""

import numpy as np

import ray_tpu
from ray_tpu import train
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig


def train_loop(config):
    import jax

    from ray_tpu.models.transformer import GPTJ_6B, TransformerConfig
    from ray_tpu.parallel.mesh import MeshConfig, create_mesh
    from ray_tpu.parallel.spmd import build_lm_train_step

    if config.get("full_size"):
        cfg = GPTJ_6B
    else:  # scaled-down same-architecture model
        cfg = TransformerConfig(
            vocab_size=50432, d_model=512, n_layers=4, n_heads=8, d_ff=2048,
            max_seq_len=512, parallel_block=True, use_swiglu=False,
        )
    n_dev = len(jax.devices())
    tensor = 2 if n_dev % 2 == 0 and n_dev > 1 else 1
    mesh = create_mesh(MeshConfig(data=-1, tensor=tensor))
    bundle = build_lm_train_step(cfg, mesh, learning_rate=config["lr"])
    state = bundle.init_fn(jax.random.PRNGKey(0))

    rng = np.random.default_rng(0)
    batch, seq = config["batch"], config["seq"]
    for step_i in range(config["steps"]):
        tokens = rng.integers(0, cfg.vocab_size - 1, (batch, seq), dtype=np.int32)
        tok, tgt = bundle.shard_batch(tokens, np.roll(tokens, -1, 1))
        state, metrics = bundle.step_fn(state, tok, tgt)
        if step_i % 5 == 0:
            train.report({"step": step_i, "loss": float(jax.device_get(metrics["loss"]))})
    train.report({"step": config["steps"], "loss": float(jax.device_get(metrics["loss"]))})


def main():
    ray_tpu.init(ignore_reinit_error=True)
    result = JaxTrainer(
        train_loop,
        train_loop_config={"lr": 1e-4, "batch": 4, "seq": 256, "steps": 20},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="gptj_finetune"),
    ).fit()
    print("final:", result.metrics)


if __name__ == "__main__":
    main()
