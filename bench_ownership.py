"""Ownership decentralization metric (VERDICT r4 #3).

Same actor-call workload, two ownership models, measured at the head:

* ``central``   — ``direct_actor_calls=False``: every call relays through
  the head, every result commits into the head's store, every result ref
  lives in the head's table (round-3 architecture);
* ``caller``    — ``direct_actor_calls=True`` (default): calls go
  worker→worker, results commit to a CALLER-LOCAL store with caller-side
  refcounts (parity: owner-side memory store + reference_count.h), and the
  head sees ownership traffic only when a ref escapes its owner.

Emits one JSON line per mode with the head's ref-op and commit counters
(``event_stats`` rpc, ``__ownership__``) normalized per call, plus the
reduction factor. The driver commits stdout as OWNERSHIP_r05.json.
"""

from __future__ import annotations

import json
import time

import jax

jax.config.update("jax_platforms", "cpu")

import ray_tpu  # noqa: E402
from ray_tpu._private.worker import get_runtime  # noqa: E402

N_ACTORS = 4
N_CALLERS = 4
CALLS = 1500


def run_mode(direct: bool) -> dict:
    ray_tpu.init(num_cpus=4, _system_config={"direct_actor_calls": direct})
    try:
        rt = get_runtime()

        @ray_tpu.remote(num_cpus=0)
        class Svc:
            def ping(self, i):
                return i

        @ray_tpu.remote(num_cpus=0)
        def caller(actor, n):
            got = 0
            for i in range(n):
                got += ray_tpu.get(actor.ping.remote(i), timeout=120)
            return got

        actors = [Svc.remote() for _ in range(N_ACTORS)]
        for a in actors:
            ray_tpu.get(a.ping.remote(0), timeout=60)  # warm
        s0 = rt.rpc("event_stats")["__ownership__"]
        t0 = time.perf_counter()
        out = ray_tpu.get(
            [
                caller.remote(actors[i % N_ACTORS], CALLS)
                for i in range(N_CALLERS)
            ],
            timeout=600,
        )
        dt = time.perf_counter() - t0
        s1 = rt.rpc("event_stats")["__ownership__"]
        assert out == [sum(range(CALLS))] * N_CALLERS
        total_calls = N_CALLERS * CALLS
        return {
            "mode": "caller" if direct else "central",
            "calls": total_calls,
            "calls_per_sec": round(total_calls / dt, 1),
            "head_ref_ops": s1["ref_ops"] - s0["ref_ops"],
            "head_commits": s1["commits"] - s0["commits"],
            "ref_ops_per_call": round((s1["ref_ops"] - s0["ref_ops"]) / total_calls, 3),
            "commits_per_call": round((s1["commits"] - s0["commits"]) / total_calls, 3),
        }
    finally:
        ray_tpu.shutdown()


def main():
    central = run_mode(direct=False)
    caller = run_mode(direct=True)
    for row in (central, caller):
        print(json.dumps({"metric": f"ownership_{row['mode']}", **row}), flush=True)
    red_refs = central["head_ref_ops"] / max(1, caller["head_ref_ops"])
    red_commits = central["head_commits"] / max(1, caller["head_commits"])
    print(
        json.dumps(
            {
                "metric": "ownership_decentralization",
                "head_ref_op_reduction": round(red_refs, 1),
                "head_commit_reduction": round(red_commits, 1),
                "note": (
                    "same n:n actor workload; caller-side ownership removes "
                    "head ref/commit traffic except lifecycle + escapes"
                ),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
