"""Transfer-plane observability bench (netplane acceptance).

Two measurements, recorded as BENCH_SCALE.jsonl rows with --append:

1. **Overhead ratio** — the plane's hot-path costs are (a) the enabled()
   probe + stats-dict/stage-clock fills on every fetch and (b) the
   inflight-progress watermark per received chunk. The probe is an
   ISOLATED socket fetch loop (ObjectServer + fetch_into_local_store in
   one process, no scheduler in the path) toggled plane-on/plane-off in
   ALTERNATING pairs — a full broadcast's wall is dominated by dispatch
   noise that buries a sub-1% effect (the same reasoning as
   bench_memplane's one-cluster interleaved toggles; round-7 caveats:
   the recorded signal is the median of per-pair ratios, never absolute
   times). Budget: <= 1.05.
2. **Per-path GiB/s** — the link ledger's own per-path throughput EWMAs
   (socket / relay / shm_peer) after the broadcast rounds, plus the
   stage-coverage ratio (stage sum / transfer wall — acceptance: within
   10%).

Run: python bench_netplane.py [--quick] [--append]
"""

from __future__ import annotations

import argparse
import json
import statistics
import time

import numpy as np

import ray_tpu
from ray_tpu.util import state


def _sch():
    from ray_tpu._private.worker import get_runtime

    return get_runtime().node.scheduler


def _fetch_loop_rate(nbytes: int, duration: float, plane_on: bool) -> float:
    """Isolated socket-fetch loop: fetches/s of one sealed object through
    a loopback ObjectServer into a second store, with the plane's capture
    (stats dict + stage clock + inflight watermark) on or off."""
    import tempfile
    from types import SimpleNamespace

    from ray_tpu._private import netplane
    from ray_tpu._private.object_store import ObjectStoreClient
    from ray_tpu._private.object_transfer import (
        ObjectServer,
        fetch_into_local_store,
    )
    from ray_tpu._private.ids import ObjectID

    netplane.configure(
        SimpleNamespace(
            transfer_plane_enabled=plane_on, telemetry_enabled=True
        )
    )
    key = b"bench-net"
    with tempfile.TemporaryDirectory() as tmp:
        src = ObjectStoreClient(f"{tmp}/a", f"{tmp}/af", 1 << 28)
        dst = ObjectStoreClient(f"{tmp}/b", f"{tmp}/bf", 1 << 28)
        server = ObjectServer(src, "127.0.0.1", key)
        oid = ObjectID.from_random()
        src.put_bytes(oid, bytes(nbytes))
        try:
            def one() -> None:
                stats = {} if netplane.enabled() else None
                assert fetch_into_local_store(
                    dst, server.address, oid, key, stats=stats
                )
                dst.delete(oid)

            t0 = time.perf_counter()
            while time.perf_counter() - t0 < 0.25:
                one()
            count = 0
            t0 = time.perf_counter()
            while True:
                one()
                count += 1
                elapsed = time.perf_counter() - t0
                if elapsed >= duration:
                    return count / elapsed
        finally:
            netplane._cfg_override = None
            server.close()
            src.close()
            dst.close()


def _putget_rate(duration: float, nbytes: int) -> float:
    """Driver put/get churn — the plane's only cost on this shape is the
    per-get wall-clock stamps + the enabled() probe in _entry_value."""
    payload = np.random.randint(0, 255, size=nbytes, dtype=np.uint8)

    def one() -> None:
        ref = ray_tpu.put(payload)
        ray_tpu.get(ref)
        del ref

    t0 = time.perf_counter()
    while time.perf_counter() - t0 < 0.25:
        one()
    count = 0
    t0 = time.perf_counter()
    while True:
        one()
        count += 1
        elapsed = time.perf_counter() - t0
        if elapsed >= duration:
            return count / elapsed


def _set_plane(flag: bool) -> None:
    from ray_tpu._private import netplane

    _sch().config.transfer_plane_enabled = flag
    netplane._enabled_cache = (None, False)


def _broadcast_round(nbytes: int, readers: int) -> float:
    """One broadcast: put a fresh blob, fan reads across reader nodes;
    returns the wall seconds of the read fan-out."""
    @ray_tpu.remote(num_cpus=0, resources={"reader": 1.0})
    def read(x):
        return x.nbytes

    blob = ray_tpu.put(
        np.random.randint(0, 255, size=nbytes, dtype=np.uint8)
    )
    t0 = time.perf_counter()
    out = ray_tpu.get([read.remote(blob) for _ in range(readers)], timeout=600)
    assert out == [nbytes] * readers
    del blob
    return time.perf_counter() - t0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--pairs", type=int, default=6)
    ap.add_argument("--duration", type=float, default=1.5)
    ap.add_argument("--nbytes", type=int, default=8 * 1024 * 1024)
    ap.add_argument("--readers", type=int, default=2)
    ap.add_argument("--append", action="store_true",
                    help="append result rows to BENCH_SCALE.jsonl")
    args = ap.parse_args()
    if args.quick:
        args.pairs, args.duration = 3, 0.8

    # phase 1: isolated per-fetch overhead (no cluster in the path)
    _fetch_loop_rate(args.nbytes, 0.3, True)  # warmup (pools, dials)
    ratios = []
    for _ in range(args.pairs):
        on = _fetch_loop_rate(args.nbytes, args.duration, True)
        off = _fetch_loop_rate(args.nbytes, args.duration, False)
        ratios.append(off / on)  # >1 means the plane slowed fetches down
    ratio = round(statistics.median(ratios), 4)

    # phase 2: per-path GiB/s + stage coverage off a real socket broadcast
    import ray_tpu.cluster_utils as cu

    cluster = cu.Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        for _ in range(args.readers):
            cluster.add_node(
                num_cpus=1, resources={"reader": 1.0}, wait=False
            )
        cluster.wait_for_nodes(timeout=300)
        sch = _sch()
        # put/get shape on the live cluster (alternating pairs, same-box)
        _putget_rate(0.3, 256 * 1024)  # warmup
        pg_ratios = []
        for _ in range(args.pairs):
            _set_plane(True)
            on = _putget_rate(args.duration, 256 * 1024)
            _set_plane(False)
            off = _putget_rate(args.duration, 256 * 1024)
            pg_ratios.append(off / on)
        _set_plane(True)
        pg_ratio = round(statistics.median(pg_ratios), 4)

        sch.config.same_host_shm_transfer = False  # force the socket plane
        for _ in range(3):
            _broadcast_round(args.nbytes, args.readers)
        time.sleep(1.0)
        by_path = state.summarize_transfers(group_by="path")
        path_gibps = {
            r["group"]: r.get("gib_per_s")
            for r in by_path["rows"]
            if r.get("gib_per_s") is not None
        }
        coverage = [
            sum(r["stages_ms"].values()) / r["total_ms"]
            for r in state.list_transfers(limit=200)
            if r.get("total_ms") and r.get("stages_ms") and r["ok"]
        ]
        cov = round(statistics.median(coverage), 4) if coverage else None

        rows = [
            {
                "metric": "netplane_overhead_ratio",
                "value": ratio,
                "unit": "x",
                "pairs": ratios and [round(r, 4) for r in ratios],
                "note": "isolated loopback socket-fetch rate, plane-on/"
                "plane-off alternating pairs (median of per-pair ratios "
                "per round-7 caveats — a broadcast's wall is dispatch "
                "noise); budget <= 1.05",
            },
            {
                "metric": "netplane_putget_overhead_ratio",
                "value": pg_ratio,
                "unit": "x",
                "pairs": [round(r, 4) for r in pg_ratios],
                "note": "driver put/get rate, plane-on/plane-off "
                "alternating pairs on one cluster (median per-pair ratio);"
                " budget <= 1.05",
            },
            {
                "metric": "netplane_path_gib_per_s",
                "value": path_gibps,
                "unit": "GiB/s",
                "note": "link-ledger per-path throughput EWMA after the "
                "broadcast rounds (socket + relay hops)",
            },
            {
                "metric": "netplane_stage_coverage",
                "value": cov,
                "unit": "stage_sum/wall",
                "transfers": len(coverage),
                "note": "median per-transfer (dial+request+first_byte_wait"
                "+wire+seal)/total — acceptance: within 10% of wall",
            },
        ]
        for row in rows:
            print(json.dumps(row))
        if args.append:
            with open("BENCH_SCALE.jsonl", "a") as fh:
                for row in rows:
                    fh.write(json.dumps(row) + "\n")
        if ratio > 1.05:
            raise SystemExit(f"netplane overhead ratio {ratio} > 1.05")
        if pg_ratio > 1.05:
            raise SystemExit(f"netplane put/get ratio {pg_ratio} > 1.05")
        if cov is not None and not (0.5 <= cov <= 1.10):
            raise SystemExit(f"stage coverage {cov} outside [0.5, 1.10]")
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
