"""Training step-plane overhead bench (train-observability acceptance).

The plane's hot-path cost is per train.report boundary: mark_pre_report +
finalize_step (stage arithmetic, a compact record tuple that rides the
NEXT report's collector rpc, locally-accumulated metric observations
flushed ~1/s). The probe is a tight report loop (no sleeps — the step
wall IS the report/collector round-trip, the worst case for a per-step
tax; a real training step is 10-1000ms, where the same tax is <0.1%).

Measurement, per the round-7 host caveats (BENCH_CORE.jsonl): the loop's
baseline rate drifts several percent between one-second windows on these
shared hosts, so the plane is toggled at FINE GRAIN — alternating on/off
windows inside ONE worker session (the toggle drops/restores the
session's StepTimer, which the whole worker-side plane hangs off) —
and adjacent windows pair up; the recorded signal is the median of
per-pair off/on ratios. Acceptance: ratio <= 1.05.

Run: python bench_train_obs.py [--quick] [--append]   (--append writes
the BENCH_CORE.jsonl row)
"""

from __future__ import annotations

import argparse
import json
import statistics
import tempfile
import time


def _window_rates(pairs: int, steps: int, workers: int, tmp: str):
    """One training session alternating (on, off) measurement windows;
    returns the per-window rates [(on_steps_per_s, off_steps_per_s), ...]
    measured INSIDE the worker loop."""
    from ray_tpu import train
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def loop(config):
        from ray_tpu._private import stepplane
        from ray_tpu.train import _session

        n = config["steps"]
        s = _session._get_session()
        timer = s._step_timer

        def set_plane(flag):
            s._step_timer = timer if flag else None
            stepplane.activate(timer if flag else None)

        def rate():
            t0 = time.perf_counter()
            for _ in range(n):
                train.report({"i": 0.0})
            return n / (time.perf_counter() - t0)

        for _ in range(20):  # warmup outside the timed windows
            train.report({"w": 0.0})
        out = []
        for _ in range(config["pairs"]):
            set_plane(True)
            on = rate()
            set_plane(False)
            off = rate()
            out.append((on, off))
        set_plane(True)
        train.report({"window_rates": out})

    trainer = JaxTrainer(
        loop,
        train_loop_config={"steps": steps, "pairs": pairs},
        scaling_config=ScalingConfig(num_workers=workers),
        run_config=RunConfig(storage_path=tmp, name="bench_obs"),
    )
    res = trainer.fit()
    assert res.error is None, res.error
    rates = res.metrics.get("window_rates")
    assert rates, f"window rates lost: {res.metrics}"
    return rates


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--pairs", type=int, default=120)
    ap.add_argument("--steps", type=int, default=50,
                    help="steps per measurement window")
    ap.add_argument("--workers", type=int, default=1)
    ap.add_argument("--append", action="store_true",
                    help="append the result row to BENCH_CORE.jsonl")
    args = ap.parse_args()
    if args.quick:
        args.pairs, args.steps = 20, 40

    import ray_tpu

    ray_tpu.init(num_cpus=max(2, args.workers + 1), ignore_reinit_error=True)
    with tempfile.TemporaryDirectory() as tmp:
        rates = _window_rates(args.pairs, args.steps, args.workers, tmp)
    ray_tpu.shutdown()

    pair_ratios = [off / on for on, off in rates]
    print(
        f"{len(rates)} pairs of {args.steps}-step windows: "
        f"ratio p10={sorted(pair_ratios)[len(pair_ratios) // 10]:.4f} "
        f"median={statistics.median(pair_ratios):.4f} "
        f"p90={sorted(pair_ratios)[-max(1, len(pair_ratios) // 10)]:.4f}"
    )
    ratio = statistics.median(pair_ratios)
    row = {
        "metric": "train_obs_overhead_ratio",
        "value": round(ratio, 4),
        "unit": "off/on per-step ratio",
        "budget": 1.05,
        "steps_per_s_on": round(statistics.median(r[0] for r in rates), 1),
        "steps_per_s_off": round(statistics.median(r[1] for r in rates), 1),
        "steps_per_window": args.steps,
        "workers": args.workers,
        "pairs": args.pairs,
        "pair_ratio_p10": round(sorted(pair_ratios)[len(pair_ratios) // 10], 4),
        "pair_ratio_p90": round(
            sorted(pair_ratios)[-max(1, len(pair_ratios) // 10)], 4
        ),
        "note": "fine-grained alternating on/off windows inside ONE "
        "worker session (toggle = drop/restore the session's StepTimer), "
        "median over many small adjacent pairs so the host's per-second "
        "rate drift cancels — coarse windows drift ±10% on these hosts "
        "(round-7 caveats) and bury the ~10us/step tax; tight no-sleep "
        "report loop = worst case (a real 10-1000ms training step sees "
        "<0.1%)",
    }
    print(json.dumps(row), flush=True)
    if args.append:
        with open("BENCH_CORE.jsonl", "a") as fh:
            fh.write(json.dumps(row) + "\n")
    if ratio > 1.05:
        raise SystemExit(f"overhead ratio {ratio:.4f} exceeds budget 1.05")


if __name__ == "__main__":
    main()
