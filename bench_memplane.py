"""Memory-plane overhead bench (memory-observability acceptance).

The plane's hot-path costs are (a) one stack-walk + interned callsite per
store-backed put and (b) one batched telemetry record per object — so the
probe is a put/get loop (the memory plane's actual hot path; small-task
dispatch doesn't touch it) plus a small-task rate as the control. Per the
round-7 host caveats (BENCH_CORE.jsonl), the recorded signal is the
same-box ON/OFF RATIO over alternating fresh-cluster pairs (medians).
Acceptance: memory-plane-on vs -off per-op ratio <= 1.05, with zero
OBJECT_LEAK_SUSPECT false positives on this calm bounded workload.

Run: python bench_memplane.py [--quick] [--append]   (--append writes the
BENCH_CORE.jsonl row)
"""

from __future__ import annotations

import argparse
import json
import statistics
import time

import numpy as np

import ray_tpu


def _putget_rate(duration: float, nbytes: int) -> float:
    """Bounded put/get churn (object created + freed each iteration — the
    calm shape the leak watchdog must stay silent on)."""
    payload = np.random.randint(0, 255, size=nbytes, dtype=np.uint8)

    def one() -> None:
        ref = ray_tpu.put(payload)
        ray_tpu.get(ref)
        del ref

    t0 = time.perf_counter()
    while time.perf_counter() - t0 < 0.25:
        one()
    count = 0
    t0 = time.perf_counter()
    while True:
        one()
        count += 1
        elapsed = time.perf_counter() - t0
        if elapsed >= duration:
            return count / elapsed


def _set_plane(flag: bool) -> None:
    """Toggle the WHOLE plane live in one cluster: capture gates on
    ``memplane.enabled()`` (cache reset), the scheduler's ingest only sees
    records when capture is on, and the watchdog scan gates on the shared
    in-process config. One cluster + interleaved toggles is the honest
    same-box control on this host — fresh-cluster pairs swing 2-3x
    between minutes (round-7 caveats), burying a sub-1% effect."""
    from ray_tpu._private import memplane
    from ray_tpu._private.worker import get_runtime

    get_runtime().node.scheduler.config.memory_plane_enabled = flag
    memplane._enabled_cache = (None, False)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--duration", type=float, default=2.0)
    ap.add_argument("--num-cpus", type=int, default=2)
    ap.add_argument("--nbytes", type=int, default=256 * 1024)
    ap.add_argument("--append", action="store_true",
                    help="append the result row to BENCH_CORE.jsonl")
    args = ap.parse_args()
    if args.quick:
        args.rounds, args.duration = 2, 1.0

    ray_tpu.init(
        num_cpus=args.num_cpus,
        ignore_reinit_error=True,
        _system_config={"memory_plane_enabled": True},
    )
    on_rates, off_rates, pair_ratios = [], [], []
    for _ in range(args.rounds):  # alternating pairs: host drift cancels
        _set_plane(True)
        on = _putget_rate(args.duration, args.nbytes)
        _set_plane(False)
        off = _putget_rate(args.duration, args.nbytes)
        on_rates.append(on)
        off_rates.append(off)
        # per-pair ratio, then median across pairs: adjacent measurements
        # share the host's noise regime, so pairing cancels drift that
        # medians-of-sides cannot
        pair_ratios.append(off / on if on else float("inf"))
    _set_plane(True)
    from ray_tpu.util import state

    leak_events = len(
        state.list_cluster_events(
            filters=[("type", "=", "OBJECT_LEAK_SUSPECT")]
        )
    )
    ray_tpu.shutdown()

    on_med = statistics.median(on_rates)
    off_med = statistics.median(off_rates)
    ratio = statistics.median(pair_ratios)
    row = {
        "metric": "memory_plane_overhead_ratio",
        "value": round(ratio, 4),
        "unit": "off/on per-put-get ratio",
        "budget": 1.05,
        "putget_per_s_on": round(on_med, 1),
        "putget_per_s_off": round(off_med, 1),
        "payload_bytes": args.nbytes,
        "pairs": args.rounds,
        "pair_ratios": [round(r, 4) for r in pair_ratios],
        "leak_false_positives": leak_events,
        "note": "one cluster, interleaved live plane toggles, median of "
        "per-pair ratios (fresh-cluster pairs swing 2-3x on this host — "
        "round-7 caveats); put/get churn is the plane's hot path "
        "(callsite capture rides the put's own registration message; "
        "returns ride telemetry batches); leak_false_positives counts "
        "OBJECT_LEAK_SUSPECT events on this calm bounded workload "
        "(must be 0)",
    }
    print(json.dumps(row), flush=True)
    if args.append:
        with open("BENCH_CORE.jsonl", "a") as fh:
            fh.write(json.dumps(row) + "\n")


if __name__ == "__main__":
    main()
