"""Tracing/profiler overhead bench (request-tracing plane acceptance).

Per the round-7 host caveats (BENCH_CORE.jsonl), absolute percentages are
unresolvable on these noisy sandbox boxes — the recorded signal is the
same-box ON/OFF RATIO over alternating fresh-cluster pairs (medians), which
cancels slow-host drift. Acceptance: tracing-on vs tracing-off per-call
overhead ratio <= 1.05.

Also records a span-tree completeness probe: a nested task graph's root
stage decomposition must sum to its measured wall time within 10% (the
`ray_tpu trace` acceptance bar; test_tracing.py asserts the same).

Run: python bench_trace.py [--quick] [--append]   (--append writes the
BENCH_CORE.jsonl rows)
"""

from __future__ import annotations

import argparse
import json
import statistics
import time

import ray_tpu


@ray_tpu.remote
def _noop():
    return None


def _tasks_async_rate(duration: float) -> float:
    """Small-task async throughput (the per-call overhead probe: submit +
    dispatch + execute + result for a no-op)."""

    def batch():
        ray_tpu.get([_noop.remote() for _ in range(100)])

    t0 = time.perf_counter()
    while time.perf_counter() - t0 < 0.25:
        batch()
    count = 0
    t0 = time.perf_counter()
    while True:
        batch()
        count += 1
        elapsed = time.perf_counter() - t0
        if elapsed >= duration:
            return count * 100 / elapsed


def measure(flag: bool, duration: float, num_cpus: int, profiler: bool) -> float:
    ray_tpu.shutdown()
    cfg = {"tracing_enabled": flag}
    if profiler and flag:
        cfg["profiler_hz"] = 19.0  # steady-state sampling ON with tracing
    ray_tpu.init(num_cpus=num_cpus, ignore_reinit_error=True, _system_config=cfg)
    ray_tpu.get([_noop.remote() for _ in range(20)], timeout=60)
    return _tasks_async_rate(duration)


def stage_sum_probe() -> dict:
    """Nested-graph completeness: stages must cover root wall within 10%."""
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)

    @ray_tpu.remote
    def leaf(x):
        time.sleep(0.03)
        return x

    @ray_tpu.remote
    def root(x):
        return ray_tpu.get(leaf.remote(x))

    ray_tpu.get(root.remote(1))
    tid = next(
        t["trace_id"]
        for t in ray_tpu.recent_traces(limit=10)
        if t["root"] == "root"
    )
    tr = ray_tpu.trace(tid)
    r = tr.roots[0]
    bd = r.stage_breakdown()
    covered = sum(bd.values())
    wall = r.duration_ms
    return {
        "spans": tr.span_count(),
        "wall_ms": round(wall, 3),
        "stage_sum_ms": round(covered, 3),
        "coverage": round(covered / wall, 4) if wall else None,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--duration", type=float, default=2.0)
    ap.add_argument("--num-cpus", type=int, default=4)
    ap.add_argument("--profiler", action="store_true",
                    help="also enable steady-state profiler_hz on the ON side")
    ap.add_argument("--append", action="store_true",
                    help="append the result rows to BENCH_CORE.jsonl")
    args = ap.parse_args()
    if args.quick:
        args.rounds, args.duration = 2, 1.0

    on_rates, off_rates = [], []
    for _ in range(args.rounds):  # alternating pairs: host drift cancels
        on_rates.append(measure(True, args.duration, args.num_cpus, args.profiler))
        off_rates.append(measure(False, args.duration, args.num_cpus, args.profiler))
    probe = stage_sum_probe()
    ray_tpu.shutdown()

    on_med = statistics.median(on_rates)
    off_med = statistics.median(off_rates)
    ratio = off_med / on_med if on_med else float("inf")
    rows = [
        {
            "metric": "tracing_overhead_ratio",
            "value": round(ratio, 4),
            "unit": "off/on per-call ratio",
            "budget": 1.05,
            "tasks_async_on": round(on_med, 1),
            "tasks_async_off": round(off_med, 1),
            "pairs": args.rounds,
            "profiler_on_side": bool(args.profiler),
            "note": "alternating fresh-cluster pairs, medians; ratio is the "
            "host-stable signal (round-7 caveats)",
        },
        {
            "metric": "trace_stage_coverage",
            "value": probe["coverage"],
            "unit": "stage_sum/wall",
            "budget": "within 0.10 of 1.0",
            **probe,
        },
    ]
    for row in rows:
        print(json.dumps(row), flush=True)
    if args.append:
        with open("BENCH_CORE.jsonl", "a") as fh:
            for row in rows:
                fh.write(json.dumps(row) + "\n")


if __name__ == "__main__":
    main()
