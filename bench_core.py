"""Core runtime microbenchmarks, mirrored from the reference's harness.

Parity: ``python/ray/_private/ray_perf.py:93`` — same workload shapes, same
metric names where applicable, so numbers are directly comparable with
BASELINE.md's core table (reference values from
``release/release_logs/2.9.3/microbenchmark.json``, m4.16xlarge/64 vCPU).

Run: python bench_core.py [--quick]
Prints one JSON line per metric: {"metric", "value", "unit", "reference", "ratio"}.
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np

import ray_tpu

# reference numbers (BASELINE.md core table)
REFERENCE = {
    "single_client_get_calls": 10_182.0,
    "single_client_put_calls": 5_545.0,
    "single_client_put_gigabytes": 20.9,
    "multi_client_put_gigabytes": 35.9,
    "single_client_tasks_sync": 1_007.0,
    "single_client_tasks_async": 8_444.0,
    "actor_calls_1_1_sync": 2_033.0,
    "actor_calls_1_1_async": 8_886.0,
    "actor_calls_n_n_async": 27_667.0,
}


def timeit(name, fn, multiplier=1, duration=2.0, warmup=0.25):
    """ray_perf-style: run fn repeatedly for ~duration, report ops/s."""
    start = time.perf_counter()
    while time.perf_counter() - start < warmup:
        fn()
    count = 0
    start = time.perf_counter()
    while True:
        fn()
        count += 1
        elapsed = time.perf_counter() - start
        if elapsed >= duration:
            break
    return name, count * multiplier / elapsed


def report(name, value, unit="ops/s"):
    ref = REFERENCE.get(name)
    row = {
        "metric": name,
        "value": round(value, 1),
        "unit": unit,
        "reference": ref,
        "ratio": round(value / ref, 3) if ref else None,
    }
    print(json.dumps(row), flush=True)
    return row


@ray_tpu.remote
def _noop():
    return None


@ray_tpu.remote
def _noop_arg(x):
    return None


@ray_tpu.remote
class _Actor:
    def noop(self):
        return None


@ray_tpu.remote
def _print_heavy(n: int):
    """Log-plane load shape: one task, n log lines. Prints to stderr so the
    driver-side echo never interleaves with the bench's stdout JSON."""
    import sys

    for i in range(n):
        print(f"bench-log-line-{i}", file=sys.stderr)
    return None


@ray_tpu.remote
def _print_burst(n: int):
    """Worker-local per-line timing: n prints, returns elapsed seconds."""
    import sys
    import time as _t

    t0 = _t.perf_counter()
    for _ in range(n):
        print("bench-burst-line", file=sys.stderr)
    return _t.perf_counter() - t0


@ray_tpu.remote
class _PutClient:
    """One concurrent putter for the multi-client put shape (parity:
    ray_perf's multi_client_put_gigabytes worker actors)."""

    def __init__(self, mib: int):
        self._arr = np.zeros(mib * 1024 * 1024 // 8)

    def put_for(self, seconds: float):
        end = time.perf_counter() + 0.25  # warmup outside the window
        while time.perf_counter() < end:
            r = ray_tpu.put(self._arr)
            del r
        count = 0
        start = time.perf_counter()
        while True:
            r = ray_tpu.put(self._arr)
            del r
            count += 1
            elapsed = time.perf_counter() - start
            if elapsed >= seconds:
                return count, elapsed


@ray_tpu.remote
class _GetClient:
    """One concurrent getter hammering a shared large object (zero-copy
    reads of the same sealed arena buffer from several processes)."""

    def get_for(self, refs, seconds: float):
        ref = refs[0]  # nested so the arg arrives as a ref, not a value
        end = time.perf_counter() + 0.25
        while time.perf_counter() < end:
            v = ray_tpu.get(ref, timeout=60)
            del v
        count = 0
        start = time.perf_counter()
        while True:
            v = ray_tpu.get(ref, timeout=60)
            del v
            count += 1
            elapsed = time.perf_counter() - start
            if elapsed >= seconds:
                return count, elapsed


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true", help="shorter windows")
    parser.add_argument("--num-cpus", type=int, default=8)
    args = parser.parse_args()
    duration = 0.6 if args.quick else 2.0

    ray_tpu.init(num_cpus=args.num_cpus, ignore_reinit_error=True)
    rows = []

    # warm the worker pool so spawn latency isn't measured
    ray_tpu.get([_noop.remote() for _ in range(20)], timeout=60)

    # --- tasks --- (before the multi-GB object phases: on small hosts
    # the 128MiB put churn triggers OS memory-compaction stalls that
    # contaminate the latency-sensitive sync shapes measured after it)
    name, v = timeit(
        "single_client_tasks_sync",
        lambda: ray_tpu.get(_noop.remote(), timeout=60),
        duration=duration,
    )
    rows.append(report(name, v))

    def tasks_async():
        ray_tpu.get([_noop.remote() for _ in range(100)], timeout=120)

    name, v = timeit(
        "single_client_tasks_async", tasks_async, multiplier=100, duration=duration
    )
    rows.append(report(name, v))

    # --- actor calls ---
    a = _Actor.remote()
    ray_tpu.get(a.noop.remote(), timeout=60)
    name, v = timeit(
        "actor_calls_1_1_sync",
        lambda: ray_tpu.get(a.noop.remote(), timeout=60),
        duration=duration,
    )
    rows.append(report(name, v))

    def actor_async():
        ray_tpu.get([a.noop.remote() for _ in range(100)], timeout=120)

    name, v = timeit(
        "actor_calls_1_1_async", actor_async, multiplier=100, duration=duration
    )
    rows.append(report(name, v))

    n = 4
    actors = [_Actor.remote() for _ in range(n)]
    ray_tpu.get([b.noop.remote() for b in actors], timeout=60)

    def actors_nn():
        refs = []
        for b in actors:
            refs.extend(b.noop.remote() for _ in range(25))
        ray_tpu.get(refs, timeout=120)

    name, v = timeit(
        "actor_calls_n_n_async", actors_nn, multiplier=25 * n, duration=duration
    )
    rows.append(report(name, v))

    # --- puts / gets (plasma path: value large enough to hit the store) ---
    # inline limit is 100 KiB: 200 KiB puts exercise the shm store
    arr = np.zeros(200 * 1024 // 8)

    name, v = timeit(
        "single_client_put_calls", lambda: ray_tpu.put(arr), duration=duration
    )
    rows.append(report(name, v))

    ref = ray_tpu.put(arr)
    name, v = timeit(
        "single_client_get_calls",
        lambda: ray_tpu.get(ref, timeout=60),
        duration=duration,
    )
    rows.append(report(name, v))

    big = np.zeros(1024 * 1024 * 128 // 8)  # 128 MiB of float64
    gib = big.nbytes / 1024**3

    def put_big():
        r = ray_tpu.put(big)
        del r

    # long warmup: the first pass over the arena pays one-time costs the
    # steady state never sees again (background prefault of the 2 GiB
    # region, first-touch faults on whatever it hasn't reached) — on
    # fault-slow kernels that transient runs ~20x below steady state
    name, v = timeit(
        "single_client_put_gigabytes",
        put_big,
        multiplier=gib,
        duration=duration,
        warmup=(4.0 if args.quick else 14.0),
    )
    rows.append(report(name, v, unit="GiB/s"))

    # --- multi-client put/get shapes (VERDICT top_next: the reference's
    # true multi-caller workloads, measured honestly) ---
    n_clients = max(2, min(4, os.cpu_count() or 2))
    putters = [_PutClient.remote(128) for _ in range(n_clients)]
    # spawn+settle round so every client exists before the measured window
    ray_tpu.get([p.put_for.remote(0.05) for p in putters], timeout=120)
    results = ray_tpu.get(
        [p.put_for.remote(duration) for p in putters], timeout=600
    )
    agg = sum(c * gib / e for c, e in results)
    rows.append(report("multi_client_put_gigabytes", agg, unit="GiB/s"))

    big_ref = ray_tpu.put(big)
    getters = [_GetClient.remote() for _ in range(n_clients)]
    ray_tpu.get([g.get_for.remote([big_ref], 0.05) for g in getters], timeout=120)
    results = ray_tpu.get(
        [g.get_for.remote([big_ref], duration) for g in getters], timeout=600
    )
    agg = sum(c * gib / e for c, e in results)
    rows.append(report("multi_client_get_gigabytes", agg, unit="GiB/s"))

    # --- telemetry overhead (tracked budget: event pipeline <= 5%) ---
    # back-to-back fresh clusters so worker-pool age doesn't bias either
    # side: small-task throughput with the telemetry plane on vs off
    tele = {}
    for flag in (True, False):
        ray_tpu.shutdown()
        ray_tpu.init(
            num_cpus=args.num_cpus,
            ignore_reinit_error=True,
            _system_config={"telemetry_enabled": flag},
        )
        ray_tpu.get([_noop.remote() for _ in range(20)], timeout=60)
        _, v = timeit(
            "tasks_async_telemetry", tasks_async, multiplier=100, duration=duration
        )
        tele[flag] = v
        label = "on" if flag else "off"
        rows.append(report(f"single_client_tasks_async_telemetry_{label}", v))
    overhead_pct = (
        (1 - tele[True] / tele[False]) * 100 if tele.get(False) else 0.0
    )
    print(
        json.dumps(
            {
                "metric": "telemetry_overhead_pct",
                "value": round(overhead_pct, 2),
                "unit": "%",
                "budget_pct": 5.0,
            }
        ),
        flush=True,
    )

    # --- log-plane overhead (tracked budget: structured logs <= 5%) ---
    # print-heavy task loop (10 lines/task) with log_to_driver on vs off:
    # "on" pays the tee + per-line tagging + batched shipping + head-side
    # echo/persist; "off" has no tee installed at all. Alternating pairs +
    # medians because fresh-cluster throughput swings 2x+ on small shared
    # boxes; the per-line burst microbench below is the stable signal.
    import statistics

    logp = {True: [], False: []}
    line_us = {}
    for _ in range(3 if not args.quick else 1):
        for flag in (True, False):
            ray_tpu.shutdown()
            ray_tpu.init(
                num_cpus=args.num_cpus,
                ignore_reinit_error=True,
                log_to_driver=flag,
                # "off" = whole log plane off (no tee): persistence alone
                # would otherwise keep the tee installed
                _system_config={"persist_worker_logs": flag},
            )
            ray_tpu.get([_noop.remote() for _ in range(20)], timeout=60)

            def print_tasks():
                ray_tpu.get(
                    [_print_heavy.remote(10) for _ in range(50)], timeout=120
                )

            _, v = timeit(
                "print_heavy_tasks_log",
                print_tasks,
                multiplier=50,
                duration=duration,
            )
            logp[flag].append(v)
            # per-line cost INSIDE one worker (20k-line burst): within-
            # process, so box-level throughput noise divides out
            t = ray_tpu.get(_print_burst.remote(20_000), timeout=120)
            line_us.setdefault(flag, []).append(t / 20_000 * 1e6)
    for flag, label in ((True, "on"), (False, "off")):
        rows.append(
            report(
                f"print_heavy_tasks_log_to_driver_{label}",
                statistics.median(logp[flag]),
            )
        )
        print(
            json.dumps(
                {
                    "metric": f"log_line_cost_us_log_to_driver_{label}",
                    "value": round(statistics.median(line_us[flag]), 2),
                    "unit": "us/line",
                }
            ),
            flush=True,
        )
    log_overhead_pct = (
        1 - statistics.median(logp[True]) / statistics.median(logp[False])
    ) * 100
    line_overhead_pct = (
        statistics.median(line_us[True]) / statistics.median(line_us[False])
        - 1
    ) * 100
    print(
        json.dumps(
            {
                "metric": "log_plane_overhead_pct",
                "value": round(log_overhead_pct, 2),
                "per_line_overhead_pct": round(line_overhead_pct, 2),
                "unit": "%",
                "budget_pct": 5.0,
            }
        ),
        flush=True,
    )

    # --- checkpoint-plane goodput (tentpole acceptance: async save
    # overhead per train step < 20% of the blocking-save overhead) ---
    # One simulated train loop, three variants over identical local
    # snapshots: no upload (baseline), blocking commit per step (the
    # seed's behavior), and the manager's background commit. The storage
    # backend is throttled (fixed per-object latency) so the bench models
    # a remote store instead of the local page cache.
    import shutil
    import tempfile

    from ray_tpu._private import external_storage as xstorage
    from ray_tpu.train import checkpointing as ckpt_plane
    from ray_tpu.train._checkpoint import Checkpoint

    class _ThrottledStore(xstorage.FileBackend):
        DELAY_S = 0.05  # per-object round-trip latency (remote-store model)

        def write_bytes(self, path, data):
            time.sleep(self.DELAY_S)
            super().write_bytes(path, data)

        def write_stream(self, path, chunks):
            # commit_dir_to_uri uploads payload through write_stream — the
            # throttle must cover it or only the 2 marker files pay latency
            time.sleep(self.DELAY_S)
            super().write_stream(path, chunks)

        def read_bytes(self, path):
            time.sleep(self.DELAY_S)
            return super().read_bytes(path)

        def read_into(self, path, make_dest):
            time.sleep(self.DELAY_S)
            return super().read_into(path, make_dest)

    xstorage.register_backend("benchstore", _ThrottledStore)
    ck_root = tempfile.mkdtemp(prefix="bench_ckpt_")
    src = os.path.join(ck_root, "src")
    os.makedirs(src)
    ckpt_mb = 4 if args.quick else 16
    with open(os.path.join(src, "model.bin"), "wb") as fh:
        fh.write(os.urandom(ckpt_mb * 1024 * 1024))
    with open(os.path.join(src, "meta.json"), "w") as fh:
        fh.write('{"bench": true}')
    ck_steps = 4 if args.quick else 8
    step_compute_s = 0.05

    def ckpt_loop(base, on_step):
        """steps x (simulated compute + local snapshot + on_step hook);
        returns wall seconds."""
        os.makedirs(base, exist_ok=True)
        t0 = time.perf_counter()
        for step in range(1, ck_steps + 1):
            time.sleep(step_compute_s)
            sd = os.path.join(base, ckpt_plane.step_dir_name(step))
            shutil.copytree(src, sd, dirs_exist_ok=True)
            on_step(step, sd)
        return time.perf_counter() - t0

    t_base = ckpt_loop(os.path.join(ck_root, "base"), lambda s, d: None)

    sync_uri = f"benchstore://{ck_root}/sync_mirror"
    t_sync = ckpt_loop(
        os.path.join(ck_root, "sync"),
        lambda s, d: xstorage.commit_dir_to_uri(
            d, xstorage.join(sync_uri, ckpt_plane.step_dir_name(s))
        ),
    )

    async_uri = f"benchstore://{ck_root}/async_mirror"
    mgr = ckpt_plane.CheckpointManager(
        os.path.join(ck_root, "async"),
        storage_uri=async_uri,
        world_size=1,
        run_name="bench",
    )
    t_async = ckpt_loop(
        os.path.join(ck_root, "async"), lambda s, d: mgr.note_shard(0, s, d)
    )
    drain_t0 = time.perf_counter()
    mgr.wait(timeout=300)
    drain_s = time.perf_counter() - drain_t0
    mgr.shutdown()

    sync_ms = (t_sync - t_base) / ck_steps * 1e3
    async_ms = (t_async - t_base) / ck_steps * 1e3
    ratio_pct = (async_ms / sync_ms * 100) if sync_ms > 0 else None
    print(
        json.dumps(
            {
                "metric": "checkpoint_save_overhead_ms_per_step",
                "sync_blocking": round(sync_ms, 2),
                "async_manager": round(async_ms, 2),
                "async_vs_sync_pct": round(ratio_pct, 1) if ratio_pct is not None else None,
                "budget_pct": 20.0,
                "unit": "ms/step",
                "ckpt_mb": ckpt_mb,
                "steps": ck_steps,
                "uploader_drain_s": round(drain_s, 2),
            }
        ),
        flush=True,
    )

    # restore latency: cold (real download + digest verify) and cached
    latest = ckpt_plane.latest_step(async_uri)
    latest_uri = xstorage.join(async_uri, ckpt_plane.step_dir_name(latest))
    ckpt_plane.clear_restore_cache()
    t0 = time.perf_counter()
    Checkpoint.from_uri(latest_uri)
    cold_ms = (time.perf_counter() - t0) * 1e3
    t0 = time.perf_counter()
    Checkpoint.from_uri(latest_uri)
    cached_ms = (time.perf_counter() - t0) * 1e3
    print(
        json.dumps(
            {
                "metric": "checkpoint_restore_latency_ms",
                "cold_verified": round(cold_ms, 2),
                "cached": round(cached_ms, 2),
                "unit": "ms",
                "ckpt_mb": ckpt_mb,
            }
        ),
        flush=True,
    )
    ckpt_plane.clear_restore_cache()
    shutil.rmtree(ck_root, ignore_errors=True)

    # --- elastic-training goodput under churn (ROADMAP item 4
    # acceptance: a run that loses and regains workers converges to the
    # same loss as an uninterrupted one, with goodput reported) ---
    # A 2-worker deterministic SGD run checkpointing elastically every
    # step, once calm and once with a seeded killer SIGKILLing train
    # workers mid-epoch; in-run replacement re-forms the group and every
    # resume is an N→M-capable restore from committed shards.
    import sys as _sys

    from ray_tpu.train import FailureConfig, JaxTrainer, RunConfig, ScalingConfig

    # the seeded killer AND the deterministic workload are the SAME
    # harness the chaos tests use (one implementation of victim choice,
    # arming, and the convergence loop — not a bench-local fork)
    _sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tests"))
    from chaos import ChaosMonkey, elastic_sgd_loop

    el_steps = 20 if args.quick else 30
    el_sleep = 0.08
    el_root = tempfile.mkdtemp(prefix="bench_elastic_")

    def _elastic_fit(name):
        return JaxTrainer(
            elastic_sgd_loop(el_steps, el_sleep),
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(
                storage_path=el_root,
                name=name,
                failure_config=FailureConfig(
                    max_failures=8,
                    retry_backoff_s=0.2,
                    retry_backoff_jitter=0.0,
                    replacement_timeout_s=60.0,
                ),
            ),
        ).fit()

    t0 = time.perf_counter()
    calm = _elastic_fit("calm")
    calm_wall = time.perf_counter() - t0
    # arm only once a committed step exists, so every kill provably
    # forces a resume-from-committed (not a restart-from-scratch)
    monkey = ChaosMonkey(
        seed=1729,
        interval_s=(1.0, 1.8),
        max_kills=2,
        arm_when=lambda: (
            ckpt_plane.latest_step(os.path.join(el_root, "churned")) or 0
        )
        >= 2,
    )
    t0 = time.perf_counter()
    monkey.start()
    churned = _elastic_fit("churned")
    churn_wall = time.perf_counter() - t0
    kills = monkey.kills
    monkey.stop()
    converged = (
        calm.error is None
        and churned.error is None
        and churned.metrics.get("loss") == calm.metrics.get("loss")
        and churned.metrics.get("training_iteration") == el_steps
    )
    print(
        json.dumps(
            {
                "metric": "elastic_train_goodput",
                "goodput_churned": round(
                    (churned.goodput or {}).get("goodput", 0.0), 3
                ),
                "goodput_calm": round((calm.goodput or {}).get("goodput", 0.0), 3),
                "wall_calm_s": round(calm_wall, 2),
                "wall_churned_s": round(churn_wall, 2),
                "kills": len(kills),
                "steps_redone": (churned.goodput or {}).get("steps_redone"),
                "steps": el_steps,
                "workers": 2,
                "converged_identically": converged,
                "unit": "fraction",
            }
        ),
        flush=True,
    )
    shutil.rmtree(el_root, ignore_errors=True)

    # per-stage attribution of the driver's put pipeline (serialize /
    # alloc / copy / seal — the same registry event_stats exports)
    from ray_tpu._private import fastcopy

    stages = {
        k: {
            "count": c,
            "total_s": round(t, 4),
            "gib_per_s": round(b / t / 2**30, 2) if t > 0 and b else None,
        }
        for k, (c, t, b) in sorted(fastcopy.stage_stats().items())
    }
    print(json.dumps({"metric": "put_stage_timings", "stages": stages}), flush=True)

    geo = 1.0
    cnt = 0
    for r in rows:
        if r["ratio"]:
            geo *= r["ratio"]
            cnt += 1
    summary = {
        "metric": "core_microbench_geomean_vs_reference",
        "value": round(geo ** (1 / cnt), 3) if cnt else None,
        "unit": "x",
    }
    print(json.dumps(summary), flush=True)
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
