# Unified build/test/bench entry point (parity role: the reference's
# top-level Bazel workspace + CI scripts — SURVEY §2.1 row "Build").
#
#   make native     build the C++ object-store runtime (.so)
#   make cpp        build the C++ client API (+ demo binary)
#   make sanitize   build + run the TSAN/ASAN store-chaos harnesses
#   make test       full pytest suite (virtual 8-device CPU mesh)
#   make test-fast  the quick core slice (smoke for iteration)
#   make bench      the flagship MFU benchmark (one JSON line)
#   make ci         everything CI runs: native + cpp + sanitize + test

PY ?= python
# deterministic chaos schedules: export CHAOS_SEED=<n> (or set here) to
# reproduce a failing chaos run kill-for-kill
CHAOS_SEED ?= 1729

.PHONY: all native cpp sanitize test test-fast chaos chaos-serve bench bench-isolation bench-trace trace-demo train-obs-demo bench-train-obs bench-net bench-launch bench-incidents bench-lm-decode bench-gate ci clean

all: native cpp

native:
	$(MAKE) -C ray_tpu/native

cpp:
	$(MAKE) -C ray_tpu/cpp

sanitize:
	$(MAKE) -C ray_tpu/native tsan asan
	./ray_tpu/native/store_chaos_tsan /dev/shm/ray_tpu_chaos_tsan 8 200
	./ray_tpu/native/store_chaos_asan /dev/shm/ray_tpu_chaos_asan 8 200

test: native
	$(PY) -m pytest tests/ -x -q -m "not slow"

test-fast: native
	$(PY) -m pytest tests/test_core_basic.py tests/test_actors.py \
		tests/test_direct_actor.py tests/test_data.py -q

# slow-marked fault-injection suite: worker/node SIGKILLs mid-run, elastic
# resume convergence, priority-preemption resume. Excluded from tier-1;
# seeded via CHAOS_SEED.
chaos:
	CHAOS_SEED=$(CHAOS_SEED) $(PY) -m pytest tests/test_chaos.py \
		tests/test_elastic_chaos.py tests/test_preempt_chaos.py \
		tests/test_serve_chaos.py tests/test_llm_chaos.py \
		tests/test_incident_chaos.py -m slow -q

# serve-plane churn suite: replica + controller SIGKILLs under sustained
# mixed unary/streaming load, graceful-redeploy zero-drop proof — plus the
# LLM variant with live decode streams (kills mid-decode fail typed or
# pre-first-token; drain finishes in-flight decodes). Seeded via
# CHAOS_SEED like the rest of the chaos group; on-demand for CI.
chaos-serve:
	CHAOS_SEED=$(CHAOS_SEED) $(PY) -m pytest tests/test_serve_chaos.py \
		tests/test_llm_chaos.py -m slow -q

bench:
	$(PY) bench.py

# request-tracing plane smoke: nested task graph + streaming serve request
# reconstructed via ray_tpu.trace (stage sum within 10% of wall, TTFT span
# present), plus a profiler flame-graph export. Fails non-zero on any
# violation.
trace-demo:
	JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) examples/trace_demo.py

# tracing/profiler overhead: same-box alternating on/off pairs; the
# recorded acceptance signal is the per-call ratio (budget <= 1.05).
# --append writes the rows to BENCH_CORE.jsonl
bench-trace:
	JAX_PLATFORMS=cpu $(PY) bench_trace.py

# training step-plane smoke: 2-rank run with throttled ingest + per-step
# checkpoints -> per-rank step waterfall (stage sums within 10% of wall),
# then a seeded-kill rerun whose goodput gap must be attributed by the
# downtime ledger. Fails non-zero on any coverage/attribution violation.
train-obs-demo:
	JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) examples/train_obs_demo.py

# step-plane overhead: alternating fresh-cluster on/off pairs over a tight
# report loop; the recorded acceptance signal is the per-step ratio
# (budget <= 1.05). --append writes the row to BENCH_CORE.jsonl
bench-train-obs:
	JAX_PLATFORMS=cpu $(PY) bench_train_obs.py --append

# transfer-plane overhead + per-path GiB/s: socket-plane broadcast with the
# plane toggled in alternating pairs (median per-pair ratio, budget <= 1.05)
# plus the link ledger's per-path EWMAs and the stage-coverage ratio.
# --append writes the rows to BENCH_SCALE.jsonl. Fails non-zero on budget
# violation.
bench-net:
	JAX_PLATFORMS=cpu $(PY) bench_netplane.py --append

# control-plane (actor-launch) observability: launch-rate overhead with the
# plane toggled in alternating pairs (budget <= 1.05) plus the 1000-actor
# per-stage launch decomposition and stage-coverage ratio. --append writes
# the rows to BENCH_SCALE.jsonl. Fails non-zero on budget violation.
bench-launch:
	JAX_PLATFORMS=cpu $(PY) bench_launch_obs.py --append

# incident/alerting-plane overhead: small-task rate with the plane (1 Hz
# SLO scan + event intake) toggled live in alternating pairs, 3 SLOs
# registered while ON (budget <= 1.05). --append writes the row to
# BENCH_CORE.jsonl.
bench-incidents:
	JAX_PLATFORMS=cpu $(PY) bench_incidents.py --append

# LM decode: static vs continuous batching tokens/s, serve-deployed TTFT
# p50/p99 (tracing-plane stream spans via the controller fold, registers
# the deployment_ttft_p99 SLO), and the >=100-stream KV saturation run.
# Appends rows to BENCH_LM_DECODE.jsonl.
bench-lm-decode:
	$(PY) bench_lm_decode.py --mode all

# bench regression gate: re-reads the BENCH_*.jsonl ledgers and fails
# non-zero if the newest row of any *_overhead_ratio metric exceeds its
# budget (default 1.05), any *_stage_coverage row is below 0.9, any
# *_ttft_p99_ms row exceeds its budget (default 5000 ms), any
# *_floor_ratio row is below its floor (default 1.0), or any
# *_untyped_failures row exceeds its budget (default 0).
bench-gate:
	$(PY) tools/bench_check.py

# multi-tenant acceptance: a noisy-neighbor job (task spam + large puts)
# must not degrade a high-priority job's p99 probe latency beyond 2x its
# calm baseline. Slow; excluded from tier-1.
bench-isolation:
	$(PY) bench_isolation.py

ci: native cpp sanitize test

clean:
	$(MAKE) -C ray_tpu/native clean
	$(MAKE) -C ray_tpu/cpp clean
