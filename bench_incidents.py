"""Incident-plane overhead bench (alerting/forensics acceptance).

The plane adds NO new hot-path messages: its only per-event cost is one
bounded enqueue in ``_ingest_cluster_event`` (cluster events are rare),
and its steady cost is the 1 Hz SLO/incident scan inside the scheduler's
existing maintenance pass.  The honest probe is therefore small-task
dispatch rate — the scheduler-loop hot path the 1 Hz scan shares a thread
with — measured with real SLOs registered so the scan does its full
sampling/burn-rate work while ON.  Per the round-7 host caveats
(BENCH_CORE.jsonl), the recorded signal is the same-box ON/OFF RATIO over
alternating toggles in ONE cluster (median of per-pair ratios).
Acceptance: incident-plane-on vs -off per-task ratio <= 1.05, with zero
incidents opened on this calm workload.

Run: python bench_incidents.py [--quick] [--append]   (--append writes the
BENCH_CORE.jsonl row)
"""

from __future__ import annotations

import argparse
import json
import statistics
import time

import ray_tpu


@ray_tpu.remote
def _noop() -> int:
    return 0


def _task_rate(duration: float) -> float:
    """Small-task churn: submit/drain waves sized to keep the scheduler
    loop busy without unbounded backlog."""
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < 0.25:
        ray_tpu.get([_noop.remote() for _ in range(20)])
    count = 0
    t0 = time.perf_counter()
    while True:
        ray_tpu.get([_noop.remote() for _ in range(20)])
        count += 20
        elapsed = time.perf_counter() - t0
        if elapsed >= duration:
            return count / elapsed


def _set_plane(flag: bool) -> None:
    """Toggle the whole plane live in one cluster: every consumer
    (``_ingest_cluster_event`` intake, the 1 Hz ``_maybe_incident_scan``,
    the metric series) gates on ``sch._incident_mgr is not None``, so
    parking/restoring the manager instance is a complete on/off switch.
    One cluster + interleaved toggles is the honest same-box control on
    this host — fresh-cluster pairs swing 2-3x between minutes (round-7
    caveats), burying a sub-1% effect."""
    from ray_tpu._private.worker import get_runtime

    sch = get_runtime().node.scheduler
    if flag:
        if sch._incident_mgr is None:
            sch._incident_mgr = _set_plane._parked  # type: ignore[attr-defined]
    else:
        if sch._incident_mgr is not None:
            _set_plane._parked = sch._incident_mgr  # type: ignore[attr-defined]
            sch._incident_mgr = None


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--duration", type=float, default=2.0)
    ap.add_argument("--num-cpus", type=int, default=2)
    ap.add_argument("--append", action="store_true",
                    help="append the result row to BENCH_CORE.jsonl")
    args = ap.parse_args()
    if args.quick:
        args.rounds, args.duration = 2, 1.0

    ray_tpu.init(
        num_cpus=args.num_cpus,
        ignore_reinit_error=True,
        _system_config={"incident_plane_enabled": True},
    )
    from ray_tpu.util import state

    # Real SLOs registered so the ON scans run the full sampling + burn
    # evaluation path (a scan over an empty registry would flatter the
    # plane).
    state.register_slo("bench-job-lat", "job_latency_p99", 60_000.0)
    state.register_slo("bench-launch", "actor_launch_rate_floor", 0.1)
    state.register_slo("bench-link", "link_throughput_floor", 0.001)

    on_rates, off_rates, pair_ratios = [], [], []
    for _ in range(args.rounds):  # alternating pairs: host drift cancels
        _set_plane(True)
        on = _task_rate(args.duration)
        _set_plane(False)
        off = _task_rate(args.duration)
        on_rates.append(on)
        off_rates.append(off)
        pair_ratios.append(off / on if on else float("inf"))
    _set_plane(True)
    time.sleep(1.5)  # let one final scan run with the plane back on
    incidents = state.list_incidents()
    ray_tpu.shutdown()

    on_med = statistics.median(on_rates)
    off_med = statistics.median(off_rates)
    ratio = statistics.median(pair_ratios)
    row = {
        "metric": "incident_plane_overhead_ratio",
        "value": round(ratio, 4),
        "unit": "off/on per-task ratio",
        "budget": 1.05,
        "tasks_per_s_on": round(on_med, 1),
        "tasks_per_s_off": round(off_med, 1),
        "pairs": args.rounds,
        "pair_ratios": [round(r, 4) for r in pair_ratios],
        "slos_registered": 3,
        "incident_false_positives": len(incidents),
        "note": "one cluster, interleaved live plane toggles, median of "
        "per-pair ratios (fresh-cluster pairs swing 2-3x on this host — "
        "round-7 caveats); small-task rate is the shared-thread probe "
        "(the plane adds no hot-path messages; its cost is the 1 Hz "
        "scan on the scheduler loop, run here with 3 live SLOs); "
        "incident_false_positives counts incidents opened on this calm "
        "workload (must be 0)",
    }
    print(json.dumps(row), flush=True)
    if args.append:
        with open("BENCH_CORE.jsonl", "a") as fh:
            fh.write(json.dumps(row) + "\n")


if __name__ == "__main__":
    main()
