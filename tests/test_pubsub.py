"""General pubsub channels (parity: GCS pubsub, src/ray/pubsub/)."""

import queue

import pytest

import ray_tpu
from ray_tpu.util.pubsub import publish, subscribe


@pytest.fixture
def ray_start():
    rt = ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    yield rt
    ray_tpu.shutdown()


def test_driver_pub_driver_sub(ray_start):
    with subscribe("alpha") as sub:
        publish("alpha", {"n": 1})
        publish("alpha", [1, 2, 3])
        assert sub.get(timeout=10) == {"n": 1}
        assert sub.get(timeout=10) == [1, 2, 3]


def test_worker_pub_driver_sub_and_fanout(ray_start):
    @ray_tpu.remote
    def announce(i):
        publish("beta", {"from_task": i})
        return i

    sub1 = subscribe("beta")
    sub2 = subscribe("beta")
    ray_tpu.get([announce.remote(i) for i in range(3)], timeout=60)
    got1 = sorted(sub1.get(timeout=10)["from_task"] for _ in range(3))
    got2 = sorted(sub2.get(timeout=10)["from_task"] for _ in range(3))
    assert got1 == got2 == [0, 1, 2]
    sub1.close()
    sub2.close()
    # closed: a later publish is not delivered to sub1
    publish("beta", {"late": True})
    with pytest.raises(queue.Empty):
        sub1.get(timeout=0.5)


def test_actor_subscriber_receives_driver_publishes(ray_start):
    @ray_tpu.remote
    class Listener:
        def __init__(self):
            self.sub = subscribe("gamma")

        def ready(self):
            return True

        def next(self):
            return self.sub.get(timeout=30)

    lis = Listener.remote()
    ray_tpu.get(lis.ready.remote(), timeout=60)
    publish("gamma", "hello-actor")
    assert ray_tpu.get(lis.next.remote(), timeout=60) == "hello-actor"
    ray_tpu.kill(lis)


def test_worker_to_worker_channel(ray_start):
    @ray_tpu.remote
    class Consumer:
        def __init__(self):
            self.sub = subscribe("delta")

        def ready(self):
            return True

        def take(self, n):
            return sorted(self.sub.get(timeout=30) for _ in range(n))

    @ray_tpu.remote
    def producer(i):
        publish("delta", i * 10)
        return i

    c = Consumer.remote()
    ray_tpu.get(c.ready.remote(), timeout=60)
    fut = c.take.remote(3)
    ray_tpu.get([producer.remote(i) for i in range(3)], timeout=60)
    assert ray_tpu.get(fut, timeout=60) == [0, 10, 20]
    ray_tpu.kill(c)


def test_no_replay_for_late_subscriber(ray_start):
    publish("epsilon", "before")  # nobody listening: dropped
    with subscribe("epsilon") as sub:
        publish("epsilon", "after")
        assert sub.get(timeout=10) == "after"
        with pytest.raises(queue.Empty):
            sub.get(timeout=0.3)


def test_dead_subscriber_pruned_and_others_unaffected(ray_start):
    import time

    @ray_tpu.remote
    class Listener:
        def __init__(self):
            self.sub = subscribe("zeta")

        def ready(self):
            return True

        def next(self):
            return self.sub.get(timeout=30)

    a, b = Listener.remote(), Listener.remote()
    ray_tpu.get([a.ready.remote(), b.ready.remote()], timeout=60)
    ray_tpu.kill(a)
    # deterministic: wait until the cluster actually sees a as dead
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            ray_tpu.get(a.ready.remote(), timeout=5)
        except Exception:
            break
        time.sleep(0.1)
    else:
        raise AssertionError("killed actor never died")
    # publish with one dead subscriber: must not error, and the survivor
    # still receives (the head prunes the dead channel during fanout)
    publish("zeta", "still-works")
    assert ray_tpu.get(b.next.remote(), timeout=60) == "still-works"
    from ray_tpu._private.worker import get_runtime

    ch = get_runtime().scheduler._pubsub.get("zeta")
    assert ch is not None and len(ch["workers"]) == 1, ch
    ray_tpu.kill(b)
