"""Multi-host SPMD training: JaxTrainer workers on separate daemon nodes
joining one ``jax.distributed`` coordination service.

Parity: the reference's multi-worker process-group path
(``python/ray/train/torch/config.py:65`` via
``_internal/backend_executor.py:129``), redesigned TPU-first: after the
KV rendezvous, the *mesh spans the worker processes* and one jitted train
step runs over all of them (SURVEY.md §7 step 5, the "aha" milestone).
Virtual multi-host: 2 worker processes x 4 forced CPU devices = one
8-device global mesh, per SURVEY.md §4(e).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.train import FailureConfig, JaxTrainer, ScalingConfig, RunConfig, report

N_STEPS = 3
SEQ = 64
BATCH = 8


def _tiny_cfg():
    import jax.numpy as jnp

    from ray_tpu.models.transformer import TransformerConfig

    return TransformerConfig(
        vocab_size=256,
        d_model=64,
        n_layers=2,
        n_heads=4,
        d_ff=256,
        max_seq_len=SEQ,
        parallel_block=True,
        use_swiglu=False,
        remat=False,
        dtype=jnp.float32,
    )


def _fixed_batches():
    rng = np.random.default_rng(7)
    toks = rng.integers(0, 255, (N_STEPS, BATCH, SEQ), dtype=np.int32)
    tgts = np.roll(toks, -1, axis=2)
    return toks, tgts


def _run_steps(mesh_devices_expected: int):
    """Build the tiny flagship over an fsdp mesh on all visible devices and
    run N_STEPS on fixed data; returns the per-step losses."""
    import jax

    from ray_tpu.parallel.mesh import MeshConfig, create_mesh
    from ray_tpu.parallel.spmd import build_lm_train_step

    devices = jax.devices()
    assert len(devices) == mesh_devices_expected, (
        f"expected global mesh of {mesh_devices_expected}, got {len(devices)}"
    )
    mesh = create_mesh(MeshConfig(fsdp=mesh_devices_expected), devices=devices)
    bundle = build_lm_train_step(_tiny_cfg(), mesh, learning_rate=1e-2)
    state = bundle.init_state(seed=0)
    toks, tgts = _fixed_batches()
    losses = []
    for i in range(N_STEPS):
        tok, tgt = bundle.shard_batch(toks[i], tgts[i])
        state, metrics = bundle.step_fn(state, tok, tgt)
        losses.append(float(metrics["loss"]))
    return losses


@pytest.fixture
def two_node_cluster():
    # head has no CPUs: train workers are forced onto the two daemon nodes
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 0})
    cluster.add_node(num_cpus=1)
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()
    yield cluster
    cluster.shutdown()


def test_jax_distributed_spans_daemon_nodes(two_node_cluster):
    """2 worker processes x 4 virtual devices train over ONE 8-device fsdp
    mesh; losses match a single-process 8-device run of the same program."""

    # self-contained closure: cloudpickle ships it by value (the tests module
    # is not importable from daemon-node worker processes)
    def train_loop(config):
        import numpy as np

        import jax
        import ray_tpu.train as train
        from ray_tpu.models.transformer import TransformerConfig
        from ray_tpu.parallel.mesh import MeshConfig, create_mesh
        from ray_tpu.parallel.spmd import build_lm_train_step

        n_steps, seq, batch = config["n_steps"], config["seq"], config["batch"]
        devices = jax.devices()
        assert len(devices) == 8, f"global mesh should be 8, got {len(devices)}"
        mesh = create_mesh(MeshConfig(fsdp=8), devices=devices)
        import jax.numpy as jnp

        # f32 so cross-process (gloo) vs in-process collective reduction
        # order stays below the comparison tolerance
        cfg = TransformerConfig(
            vocab_size=256,
            d_model=64,
            n_layers=2,
            n_heads=4,
            d_ff=256,
            max_seq_len=seq,
            parallel_block=True,
            use_swiglu=False,
            remat=False,
            dtype=jnp.float32,
        )
        bundle = build_lm_train_step(cfg, mesh, learning_rate=1e-2)
        state = bundle.init_state(seed=0)
        rng = np.random.default_rng(7)
        toks = rng.integers(0, 255, (n_steps, batch, seq), dtype=np.int32)
        tgts = np.roll(toks, -1, axis=2)
        losses = []
        for i in range(n_steps):
            tok, tgt = bundle.shard_batch(toks[i], tgts[i])
            state, metrics = bundle.step_fn(state, tok, tgt)
            losses.append(float(metrics["loss"]))
        train.report({"losses": losses})

    trainer = JaxTrainer(
        train_loop,
        train_loop_config={"n_steps": N_STEPS, "seq": SEQ, "batch": BATCH},
        scaling_config=ScalingConfig(
            num_workers=2,
            use_jax_distributed=True,
            worker_runtime_env={
                "env_vars": {
                    "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
                }
            },
        ),
        run_config=RunConfig(name="jaxdist_test"),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    distributed_losses = result.metrics["losses"]
    assert len(distributed_losses) == N_STEPS
    assert all(np.isfinite(l) for l in distributed_losses)
    # training must actually make progress
    assert distributed_losses[-1] < distributed_losses[0]

    # reference: the identical program on this process's own 8 cpu devices
    single_losses = _run_steps(mesh_devices_expected=8)
    np.testing.assert_allclose(
        distributed_losses, single_losses, rtol=2e-5, atol=1e-6
    )


@pytest.mark.parametrize("die_phase", ["rendezvous", "midstep"])
def test_worker_death_rejoins_fresh_coordinator(two_node_cluster, die_phase):
    """Failure injection (parity: backend_executor restart path): rank 1 dies
    either right after joining the coordination service ("rendezvous" — the
    peer is entering its first collective) or after one optimizer step
    ("midstep"). The retry must rendezvous against a FRESH attempt-suffixed
    coordinator key (a stale coordinator address must not be reused) and
    train to completion."""
    import os as _os
    import uuid as _uuid

    marker = f"/tmp/jaxdist_die_{_uuid.uuid4().hex[:8]}"

    def train_loop(config):
        import os

        import numpy as np
        import jax
        import jax.numpy as jnp
        import ray_tpu.train as train
        from ray_tpu.train import get_context

        rank = get_context().get_world_rank()
        phase = config["die_phase"]
        first_attempt = not os.path.exists(config["marker"])
        if rank == 1 and first_attempt and phase == "rendezvous":
            # die right after jax.distributed.initialize returned (the
            # wrapper ran before this loop): rank 0 is heading into its
            # first collective against a doomed peer
            open(config["marker"], "w").close()
            os._exit(1)
        devices = jax.devices()
        assert len(devices) == 8, f"global mesh should be 8, got {len(devices)}"
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(devices), ("data",))
        sharded = NamedSharding(mesh, P("data"))

        @jax.jit
        def step(x):
            return jnp.sum(x * 2.0)

        losses = []
        for i in range(3):
            x = jax.make_array_from_process_local_data(
                sharded, np.full(4, i + 1.0, np.float32)
            )
            losses.append(float(step(x)))
            if rank == 1 and first_attempt and phase == "midstep" and i == 1:
                open(config["marker"], "w").close()
                os._exit(1)
        train.report({"losses": losses})

    trainer = JaxTrainer(
        train_loop,
        train_loop_config={"marker": marker, "die_phase": die_phase},
        scaling_config=ScalingConfig(
            num_workers=2,
            use_jax_distributed=True,
            worker_runtime_env={
                "env_vars": {
                    "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
                }
            },
        ),
        run_config=RunConfig(
            name=f"jaxdist_failure_{die_phase}",
            failure_config=FailureConfig(max_failures=2),
        ),
    )
    try:
        result = trainer.fit()
        assert result.error is None, result.error
        # every step summed 8 devices' worth of 2*(i+1)
        assert result.metrics["losses"] == [16.0, 32.0, 48.0]
        assert _os.path.exists(marker), "the injected death never happened"
    finally:
        if _os.path.exists(marker):
            _os.unlink(marker)


def test_pipeline_axis_spans_processes(two_node_cluster):
    """pipeline >= 2 across OS processes: a 4-stage GPipe ring whose
    ``pipeline`` mesh axis spans 2 worker processes (2 virtual devices each);
    the ppermute stage-to-stage hops cross the process boundary. Output must
    match a sequential host evaluation of the same 4 stages."""

    def train_loop(config):
        import numpy as np
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        import ray_tpu.train as train
        from ray_tpu.parallel.pipeline import make_pipeline_fn

        devices = jax.devices()
        assert len(devices) == 4, f"expected 4 global devices, got {len(devices)}"
        mesh = Mesh(np.array(devices), ("pipeline",))

        def stage_fn(p, x):
            return jnp.tanh(x @ p["w"] + p["b"])

        rng = np.random.default_rng(3)
        d = 8
        stacked = {
            "w": jnp.asarray(rng.normal(0, 0.5, (4, d, d)), jnp.float32),
            "b": jnp.asarray(rng.normal(0, 0.1, (4, d)), jnp.float32),
        }
        micro = jnp.asarray(rng.normal(0, 1, (3, 2, d)), jnp.float32)  # (M, mb, d)
        pipeline = make_pipeline_fn(stage_fn, mesh)
        out = jax.jit(pipeline)(
            jax.device_put(stacked, NamedSharding(mesh, P("pipeline"))),
            jax.device_put(micro, NamedSharding(mesh, P())),
        )
        # replicate (allgather) so every process can read the full result
        full = jax.jit(lambda x: x, out_shardings=NamedSharding(mesh, P()))(out)
        got = np.asarray(jax.device_get(full))

        # host reference: apply the 4 stages sequentially
        ref = np.asarray(micro)
        for s in range(4):
            w = np.asarray(stacked["w"][s])
            b = np.asarray(stacked["b"][s])
            ref = np.tanh(ref @ w + b)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        train.report({"ok": True, "mesh_pipeline": 4})

    trainer = JaxTrainer(
        train_loop,
        scaling_config=ScalingConfig(
            num_workers=2,
            use_jax_distributed=True,
            worker_runtime_env={
                "env_vars": {
                    "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                }
            },
        ),
        run_config=RunConfig(name="pipeline_multihost"),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["ok"] is True
    assert result.metrics["mesh_pipeline"] == 4
