"""Sharded ready-queue dispatch core tests (fast tier-1 smoke).

Parity targets: ``ClusterTaskManager`` scheduling classes + locality-aware
leasing (SURVEY L4). Covers: sharded dispatch correctness on a small
simulated fleet, the starvation regression (feasible small tasks behind a
deep infeasible queue), the work-steal gate with an infeasible head queue,
per-shape backlog surfaces (state API + /metrics), and locality-aware
placement of big-arg tasks. Heavy depth/locality benches live in
``bench_scale.py`` (slow); these stay well under the tier-1 budget.
"""

import json
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


def _sch():
    from ray_tpu._private.worker import get_runtime

    return get_runtime().node.scheduler


@pytest.fixture
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 0})
    yield c
    c.shutdown()


def test_sharded_dispatch_smoke_mixed_shapes(cluster):
    """Correctness smoke on 4 simulated nodes: a few thousand tasks of
    mixed resource shapes all complete, every shard drains, and no node
    ledger leaks."""
    for _ in range(2):
        cluster.add_node(num_cpus=1)
    for _ in range(2):
        cluster.add_node(num_cpus=1, resources={"gadget": 1.0})
    cluster.wait_for_nodes()

    @ray_tpu.remote
    def cpu_task(i):
        return i

    @ray_tpu.remote(num_cpus=0, resources={"gadget": 0.5})
    def gadget_task(i):
        return -i

    n = 1500
    refs = [cpu_task.remote(i) for i in range(n)]
    grefs = [gadget_task.remote(i) for i in range(60)]
    assert ray_tpu.get(refs, timeout=600) == list(range(n))
    assert ray_tpu.get(grefs, timeout=600) == [-i for i in range(60)]

    sch = _sch()
    assert sch._ready_count == 0
    assert all(not s.queue for s in sch._ready_shards.values())
    # the dispatch-pass histogram actually observed ticks
    assert sch._tick_hist["count"] > 0
    time.sleep(1.5)  # trailing lease_done batches
    for node in ray_tpu.nodes():
        if not node["alive"]:
            continue
        for k, total in node["total"].items():
            assert abs(node["available"][k] - total) < 1e-6


def test_small_tasks_keep_dispatching_behind_infeasible_pile():
    """Starvation regression (the old flat deque + rotate path): 10k
    queued tasks of an infeasible shape must not slow feasible small-shape
    dispatch — the infeasible shard costs zero scans per tick."""
    ray_tpu.init(num_cpus=2)
    try:
        sch = _sch()

        @ray_tpu.remote(num_cpus=0, resources={"TPU": 4.0})
        def impossible(i):
            return i

        @ray_tpu.remote
        def small(i):
            return i * 2

        pile = [impossible.remote(i) for i in range(10_000)]
        deadline = time.monotonic() + 60
        while sch._ready_count < 10_000 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sch._ready_count >= 10_000

        t0 = time.monotonic()
        out = ray_tpu.get([small.remote(i) for i in range(300)], timeout=120)
        small_dt = time.monotonic() - t0
        assert out == [i * 2 for i in range(300)]
        # 300 no-op tasks through 2 warm CPUs: generous bound that the old
        # O(queue) deferral scans blew through
        assert small_dt < 60, f"small tasks starved behind pile ({small_dt:.1f}s)"
        # the infeasible pile is intact, still queued, and attributed to
        # its own shard
        assert sch._ready_count >= 10_000
        depths = {
            (s.demand or {}).get("TPU"): len(s.queue)
            for s in sch._ready_shards.values()
            if s.demand is not None and "TPU" in s.demand
        }
        assert depths.get(4.0, 0) >= 10_000
        del pile
    finally:
        ray_tpu.shutdown()


def test_backlog_summary_and_metrics_surface():
    ray_tpu.init(num_cpus=1)
    try:
        from ray_tpu.util import state

        @ray_tpu.remote(num_cpus=0, resources={"TPU": 4.0})
        def impossible():
            return 1

        refs = [impossible.remote() for _ in range(25)]
        sch = _sch()
        deadline = time.monotonic() + 30
        while sch._ready_count < 25 and time.monotonic() < deadline:
            time.sleep(0.02)

        summary = state.backlog_summary()
        rows = {
            json.dumps(r["shape"], sort_keys=True): r for r in summary["shapes"]
        }
        key = json.dumps({"TPU": 4.0}, sort_keys=True)
        assert key in rows, summary
        assert rows[key]["queued"] == 25

        from ray_tpu.util.metrics import prometheus_text

        text = prometheus_text()
        assert "ray_tpu_sched_ready_shard_depth" in text
        assert "ray_tpu_sched_tick_seconds_bucket" in text
        assert "ray_tpu_object_transfer_bytes_total" in text
        del refs
    finally:
        ray_tpu.shutdown()


def test_steal_triggers_with_infeasible_head_queue(cluster):
    """Work stealing must fire even while the head queue is non-empty, when
    everything in it is infeasible (the old gate early-outed on ANY pending
    work and parked feasible node backlogs behind it)."""
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()

    @ray_tpu.remote
    def hold(sec):
        time.sleep(sec)
        return "held"

    @ray_tpu.remote
    def quick(i):
        return i

    @ray_tpu.remote(num_cpus=0, resources={"TPU": 4.0})
    def impossible():
        return 1

    # an infeasible pile occupies the head queue
    pile = [impossible.remote() for _ in range(1_000)]
    # one long task occupies the only node; quick tasks park in its backlog
    long_ref = hold.remote(20)
    time.sleep(1.0)
    quick_refs = [quick.remote(i) for i in range(3)]
    time.sleep(0.5)
    # capacity appears elsewhere: the parked tasks must be stolen to it
    # long before the 20s blocker frees the first node
    cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    t0 = time.monotonic()
    assert ray_tpu.get(quick_refs, timeout=60) == [0, 1, 2]
    assert time.monotonic() - t0 < 15, "backlog not stolen past infeasible head queue"
    ray_tpu.cancel(long_ref, force=True)
    del pile


def test_locality_prefers_node_holding_big_args(cluster):
    """Big-arg tasks follow their data: with free capacity everywhere, the
    second and later consumers land on the node that already pulled the
    argument, and exactly one transfer happens (counter-based)."""
    import numpy as np

    cluster.add_node(num_cpus=1)
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()
    sch = _sch()
    # force the socket plane so residency is explicit (same-host shm reads
    # never register remote copies; a real fleet pays the socket path)
    sch.config.same_host_shm_transfer = False
    try:
        blob = ray_tpu.put(np.ones(1_000_000 // 8))  # ~1 MB, size known

        @ray_tpu.remote(num_cpus=1)
        def consume(x):
            from ray_tpu._private.worker import get_runtime

            assert float(x[0]) == 1.0
            return get_runtime().shm_dir

        homes = [
            ray_tpu.get(consume.remote(blob), timeout=120) for _ in range(5)
        ]
        # first consumer pulled the object somewhere; the rest follow it
        assert len(set(homes[1:])) == 1
        assert homes[1] == homes[0]
        assert sum(sch._xfer_done_count) == 1, sch._xfer_done_count
        assert sum(sch._xfer_done_bytes) >= 1_000_000
        assert sch._locality_hits >= 4
    finally:
        sch.config.same_host_shm_transfer = True


def test_locality_does_not_override_feasibility(cluster):
    """A resident-but-full node must not capture the task: locality scores
    only runnable candidates."""
    import numpy as np

    cluster.add_node(num_cpus=1)
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()
    sch = _sch()
    sch.config.same_host_shm_transfer = False
    try:
        blob = ray_tpu.put(np.ones(1_000_000 // 8))

        @ray_tpu.remote(num_cpus=1)
        def consume_slow(x, sec):
            import time as _t

            _t.sleep(sec)
            from ray_tpu._private.worker import get_runtime

            return get_runtime().shm_dir

        # pin the object's home busy, then submit another consumer: it must
        # run elsewhere rather than queue behind the resident node
        first = consume_slow.remote(blob, 8.0)
        time.sleep(2.0)  # first consumer is running where the blob landed
        t0 = time.monotonic()
        second = ray_tpu.get(consume_slow.remote(blob, 0.0), timeout=60)
        assert time.monotonic() - t0 < 6.0, "task queued behind resident node"
        assert ray_tpu.get(first, timeout=60) != second
    finally:
        sch.config.same_host_shm_transfer = True
