"""Tests: the unified telemetry plane.

Batched task-event pipeline (``_private/telemetry.py``), runtime metrics
exporter (``util/metrics.prometheus_text`` + ``runtime_metrics`` rpc), and
the cross-process chrome-trace timeline (``ray_tpu.timeline``). Parity:
``python/ray/tests/test_task_events*.py``, ``test_metrics_agent.py``,
``test_tracing.py``.
"""

import json
import re
import time

import pytest

import ray_tpu


# -- chrome-trace timeline ---------------------------------------------------


def test_timeline_chrome_trace_schema(ray_start_regular, tmp_path):
    """timeline(filename=) writes a valid chrome://tracing JSON array whose
    spans cover the full task lifecycle with stable tids."""

    @ray_tpu.remote
    def work(x):
        return x + 1

    assert ray_tpu.get([work.remote(i) for i in range(3)], timeout=60) == [1, 2, 3]

    out = tmp_path / "trace.json"
    events = ray_tpu.timeline(filename=str(out))
    on_disk = json.loads(out.read_text())
    assert on_disk == events and isinstance(on_disk, list)

    for e in events:
        # chrome trace event schema: required keys, numeric timestamps
        assert {"ph", "pid", "tid", "ts", "name", "args"} <= set(e)
        assert isinstance(e["ts"], (int, float))
        assert "state" in e["args"]

    states = {e["args"]["state"] for e in events}
    assert {"SUBMITTED", "QUEUED", "DISPATCHED", "RUNNING", "FINISHED"} <= states

    # lifecycle phase spans are "X" complete events with durations
    phases = [e for e in events if e.get("cat") == "TASK_PHASE"]
    assert any(e["name"].endswith(":run") for e in phases)
    assert all(e["ph"] == "X" and e["dur"] >= 0 for e in phases)

    # stable tid registry: every event of one task shares one tid, and tids
    # are small sequential ints (the seed's hash(task_id) % 1000 collided
    # and changed across runs)
    by_task = {}
    for e in events:
        tid_key = e["args"].get("task_id")
        if tid_key:
            by_task.setdefault(tid_key, set()).add(e["tid"])
    assert by_task and all(len(tids) == 1 for tids in by_task.values())
    all_tids = {next(iter(t)) for t in by_task.values()}
    assert all_tids <= set(range(1, len(by_task) + 2))


def test_timeline_worker_events_cross_process(ray_start_regular):
    """Worker-side RUNNING/FINISHED events carry real worker pids, so the
    run phases of concurrent tasks land on >= 2 distinct processes."""
    import os

    @ray_tpu.remote
    def hold():
        time.sleep(0.2)
        return os.getpid()

    pids = set(ray_tpu.get([hold.remote() for _ in range(4)], timeout=60))
    events = ray_tpu.timeline()
    run_pids = {
        e["pid"]
        for e in events
        if e.get("cat") == "TASK_PHASE" and e["args"]["state"] == "FINISHED"
    }
    assert len(run_pids & pids) >= min(2, len(pids))


def test_trace_parent_links_nested_task_actor(ray_start_regular):
    """Trace context propagates driver -> task -> actor method; the
    timeline's spans reconstruct one parent-linked tree across processes."""
    from ray_tpu.util import tracing

    tracing.enable_tracing()
    try:

        @ray_tpu.remote
        class Leaf:
            def ping(self):
                return tracing.get_current_context().to_dict()

        @ray_tpu.remote
        def mid(leaf):
            ctx = tracing.get_current_context()
            inner = ray_tpu.get(leaf.ping.remote(), timeout=60)
            return ctx.to_dict(), inner

        leaf = Leaf.remote()
        root = tracing.start_span()
        outer, inner = ray_tpu.get(mid.remote(leaf), timeout=60)
        assert outer["trace_id"] == root.trace_id == inner["trace_id"]
        assert outer["parent_id"] == root.span_id
        assert inner["parent_id"] == outer["span_id"]

        events = ray_tpu.timeline()
        spans = [e for e in events if e.get("cat") == "PROFILE"]
        by_span = {
            e["args"]["span_id"]: e for e in spans if e["args"].get("span_id")
        }
        # the actor-method span links to the mid-task span, which executed
        # in a different process: a cross-process parent edge
        child = by_span[inner["span_id"]]
        parent = by_span[child["args"]["parent_id"]]
        assert parent["args"]["span_id"] == outer["span_id"]
        assert parent["pid"] != child["pid"]
        # chrome flow events bind the edge visually
        flow_ids = {e.get("id") for e in events if e.get("ph") in ("s", "f")}
        assert inner["span_id"] in flow_ids
    finally:
        tracing.reset_tracing()  # back to config-driven (default-on) tracing
        tracing.deactivate()


# -- prometheus exposition ---------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [0-9.+-eE]+$"
)


def test_prometheus_text_parses(ray_start_regular):
    """Counter/gauge/histogram lines follow the exposition format and the
    runtime-internal series are present (>= 10 of them)."""
    from ray_tpu.util.metrics import Counter, Gauge, Histogram, prometheus_text

    Counter("tp_requests_total", tag_keys=("route",)).inc(3.0, tags={"route": "/x"})
    Gauge("tp_depth").set(4.0)
    h = Histogram("tp_latency_ms", boundaries=[1, 10])
    h.observe(0.5)
    h.observe(20.0)

    text = prometheus_text()
    lines = text.strip().splitlines()
    types = {}
    for line in lines:
        if line.startswith("# TYPE"):
            _, _, name, kind = line.split()
            types[name] = kind
        elif not line.startswith("#"):
            assert _SAMPLE_RE.match(line), f"bad sample line: {line!r}"

    assert types["tp_requests_total"] == "counter"
    assert types["tp_depth"] == "gauge"
    assert types["tp_latency_ms"] == "histogram"
    assert 'tp_requests_total{route="/x"} 3.0' in text
    assert "tp_latency_ms_count 2" in text
    assert 'tp_latency_ms_bucket{le="1"} 1' in text
    assert 'tp_latency_ms_bucket{le="+Inf"} 2' in text

    runtime = {n for n in types if n.startswith("ray_tpu_")}
    assert len(runtime) >= 10, sorted(runtime)
    assert "ray_tpu_scheduler_queue_depth" in runtime
    assert "ray_tpu_telemetry_dropped_total" in runtime
    assert "ray_tpu_object_store_bytes_used" in runtime


def test_metrics_merge_across_processes(ray_start_regular):
    """Counter increments from several worker processes SUM in the
    exposition (the seed's per-record KV flush was last-writer-wins)."""
    from ray_tpu.util.metrics import prometheus_text

    @ray_tpu.remote
    class Recorder:
        def bump(self):
            import os

            from ray_tpu.util.metrics import Counter

            Counter("tp_merge_total").inc(5.0)
            return os.getpid()

    recorders = [Recorder.remote() for _ in range(2)]
    pids = set(ray_tpu.get([r.bump.remote() for r in recorders], timeout=60))
    text = prometheus_text()
    line = next(l for l in text.splitlines() if l.startswith("tp_merge_total"))
    assert float(line.split()[-1]) == 5.0 * len(pids)


# -- batched flush -----------------------------------------------------------


def test_batched_metric_flush_interval_50ms():
    """Under metrics_report_interval_ms=50, N records coalesce into a few
    interval batches — one KV write per interval per metric, not one
    blocking RPC per record — and nothing is silently lost."""
    import ray_tpu as rt

    rt.init(num_cpus=2, _system_config={"metrics_report_interval_ms": 50},
            ignore_reinit_error=True)
    try:
        from ray_tpu._private import telemetry
        from ray_tpu.util.metrics import Counter, prometheus_text

        c = Counter("tp_bulk_total")
        n = 400
        for _ in range(n):
            c.inc()
        text = prometheus_text()  # forces the final flush: read-your-writes
        assert f"tp_bulk_total {float(n)}" in text
        stats = rt.get_runtime().rpc("event_stats")
        batches = stats.get("cmd.telemetry_batch", {}).get("count", 0)
        assert 0 < batches < n / 4, batches
        assert telemetry.dropped_total() == 0
    finally:
        rt.shutdown()


def test_telemetry_disabled_drops_pipeline():
    """telemetry_enabled=False turns the event pipeline off end to end:
    no task events, no metric forwarding (the overhead-budget escape hatch
    measured by bench_core's telemetry row)."""
    import ray_tpu as rt

    rt.init(num_cpus=1, _system_config={"telemetry_enabled": False},
            ignore_reinit_error=True)
    try:

        @rt.remote
        def f():
            return 1

        assert rt.get(f.remote(), timeout=60) == 1
        assert rt.timeline() == []
    finally:
        rt.shutdown()


def test_telemetry_buffer_drop_accounting():
    """Overflow beyond capacity is counted, never silent."""
    from ray_tpu._private.telemetry import TelemetryBuffer

    buf = TelemetryBuffer(capacity=10)
    for i in range(25):
        buf.record_event({"i": i})
    assert buf.dropped_total == 15
    batch = buf._drain()
    assert len(batch["events"]) == 10
    assert batch["dropped"] == 15


# -- state API operators + limit pushdown ------------------------------------


def test_state_api_comparison_operators(ray_start_regular):
    from ray_tpu.util import state

    @ray_tpu.remote
    def g():
        return 1

    ray_tpu.get([g.remote() for _ in range(3)], timeout=60)
    rows = state.list_tasks(filters=[("retries_left", ">=", 0)])
    assert len(rows) >= 3
    assert state.list_tasks(filters=[("retries_left", "<", 0)]) == []
    assert state.list_tasks(filters=[("retries_left", ">", -1), ("state", "=", "FINISHED")])
    # non-numeric fields never match ordering filters
    assert state.list_tasks(filters=[("name", "<", 5)]) == []
    with pytest.raises(ValueError):
        state.list_tasks(filters=[("name", "~", "g")])


def test_state_api_limit_pushdown(ray_start_regular):
    from ray_tpu.util import state

    @ray_tpu.remote
    def h():
        return 1

    ray_tpu.get([h.remote() for _ in range(6)], timeout=60)
    assert len(state.list_tasks(limit=2)) == 2
    # the server truncates at the limit: the capped fetch is what filters see
    drv = ray_tpu.get_runtime()
    assert len(drv.rpc("list_tasks", 3)) == 3
