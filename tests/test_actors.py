"""Actor tests: lifecycle, ordering, restart, named actors.

Test strategy parity: ``python/ray/tests/test_actor*.py`` (SURVEY.md §4).
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.v = start

    def inc(self, k=1):
        self.v += k
        return self.v

    def value(self):
        return self.v


def test_actor_basic(ray_start_regular):
    c = Counter.remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    assert ray_tpu.get(c.inc.remote(10)) == 11


def test_actor_init_args(ray_start_regular):
    c = Counter.remote(100)
    assert ray_tpu.get(c.value.remote()) == 100


def test_actor_call_ordering(ray_start_regular):
    c = Counter.remote()
    refs = [c.inc.remote() for _ in range(20)]
    assert ray_tpu.get(refs) == list(range(1, 21))


def test_actor_init_failure(ray_start_regular):
    @ray_tpu.remote
    class Bad:
        def __init__(self):
            raise RuntimeError("bad init")

        def ping(self):
            return "pong"

    b = Bad.remote()
    with pytest.raises(Exception):
        ray_tpu.get(b.ping.remote(), timeout=30)


def test_actor_method_error(ray_start_regular):
    @ray_tpu.remote
    class Erratic:
        def boom(self):
            raise KeyError("nope")

        def fine(self):
            return "ok"

    e = Erratic.remote()
    with pytest.raises(Exception):
        ray_tpu.get(e.boom.remote())
    # actor survives a user exception
    assert ray_tpu.get(e.fine.remote()) == "ok"


def test_actor_death_and_restart(ray_start_regular):
    @ray_tpu.remote(max_restarts=1)
    class Fragile:
        def __init__(self):
            self.n = 0

        def die(self):
            os._exit(1)

        def ping(self):
            self.n += 1
            return self.n

    f = Fragile.remote()
    assert ray_tpu.get(f.ping.remote()) == 1
    with pytest.raises(exc.ActorDiedError):
        ray_tpu.get(f.die.remote(), timeout=30)
    # state reset after restart
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            assert ray_tpu.get(f.ping.remote(), timeout=30) == 1
            break
        except exc.ActorDiedError:
            time.sleep(0.1)
    else:
        pytest.fail("actor did not restart")


def test_actor_no_restart_stays_dead(ray_start_regular):
    @ray_tpu.remote
    class Once:
        def die(self):
            os._exit(1)

        def ping(self):
            return "pong"

    o = Once.remote()
    with pytest.raises(exc.ActorDiedError):
        ray_tpu.get(o.die.remote(), timeout=30)
    with pytest.raises(exc.ActorDiedError):
        ray_tpu.get(o.ping.remote(), timeout=30)


def test_kill_actor(ray_start_regular):
    c = Counter.remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    ray_tpu.kill(c)
    with pytest.raises(exc.ActorDiedError):
        ray_tpu.get(c.inc.remote(), timeout=30)


def test_named_actor(ray_start_regular):
    c = Counter.options(name="global_counter").remote()
    ray_tpu.get(c.inc.remote())
    h = ray_tpu.get_actor("global_counter")
    assert ray_tpu.get(h.inc.remote()) == 2


def test_named_actor_duplicate_rejected(ray_start_regular):
    Counter.options(name="dup").remote()
    with pytest.raises(ValueError):
        Counter.options(name="dup").remote()


def test_get_actor_missing(ray_start_regular):
    with pytest.raises(ValueError):
        ray_tpu.get_actor("no_such_actor")


def test_actor_handle_passed_to_task(ray_start_regular):
    c = Counter.remote()

    @ray_tpu.remote
    def bump(handle):
        return ray_tpu.get(handle.inc.remote())

    assert ray_tpu.get(bump.remote(c), timeout=60) == 1
    assert ray_tpu.get(c.value.remote()) == 1


def test_actor_ref_arg(ray_start_regular):
    c = Counter.remote()
    ref = ray_tpu.put(5)
    assert ray_tpu.get(c.inc.remote(ref)) == 5


def test_many_actors(ray_start_regular):
    # actors consume 0 CPU while idle -> more actors than cores
    counters = [Counter.remote() for _ in range(8)]
    out = ray_tpu.get([c.inc.remote() for c in counters], timeout=120)
    assert out == [1] * 8
