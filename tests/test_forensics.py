"""Tests: the failure-forensics plane.

Task-attributed structured logs (``_TeeStream`` -> telemetry batches ->
persisted session logs), the cluster event log (``list_cluster_events`` /
``ray_tpu events``), TaskError provenance, and the straggler / hung-get
watchdogs. Parity: ``python/ray/tests/test_output.py`` (log attribution),
the exported event stream, and RayTaskError's origin fields.
"""

import os
import pickle
import signal
import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.util import state


def _events_of(type_, timeout=10.0):
    """Poll list_cluster_events for a type (batches land asynchronously)."""
    deadline = time.monotonic() + timeout
    while True:
        rows = [e for e in state.list_cluster_events() if e["type"] == type_]
        if rows or time.monotonic() >= deadline:
            return rows
        time.sleep(0.2)


# -- structured logs ---------------------------------------------------------


def test_log_lines_attributed_to_tasks(ray_start_regular):
    """Worker prints persist under <session>/logs tagged with the printing
    task's id; get_log(task_id=) returns exactly that task's lines."""

    @ray_tpu.remote
    def speak(i):
        print(f"voice-{i}")
        return i

    refs = [speak.remote(i) for i in range(3)]
    assert ray_tpu.get(refs, timeout=60) == [0, 1, 2]

    rows = {r.hex(): i for i, r in ((i, refs[i].id().task_id()) for i in range(3))}
    for tid_hex, i in rows.items():
        txt = state.get_log(task_id=tid_hex)
        assert f"voice-{i}" in txt, (tid_hex, txt)
        # only this task's lines match
        for j in range(3):
            if j != i:
                assert f"voice-{j}" not in txt


def test_log_attribution_threaded_actor(ray_start_regular):
    """Concurrent method calls on a threaded actor attribute their prints to
    the right task (per-thread TLS, not a process-global)."""

    @ray_tpu.remote(max_concurrency=4)
    class Chorus:
        def sing(self, i):
            time.sleep(0.05)  # force overlap
            print(f"note-{i}")
            return i

    c = Chorus.remote()
    refs = [c.sing.remote(i) for i in range(4)]
    assert sorted(ray_tpu.get(refs, timeout=60)) == [0, 1, 2, 3]
    for i, ref in enumerate(refs):
        txt = state.get_log(task_id=ref.id().task_id().hex())
        assert f"note-{i}" in txt
        assert f"note-{(i + 1) % 4}" not in txt


def test_tee_stream_flushes_partial_line():
    """Text without a trailing newline must not vanish (the seed buffered it
    forever); flush() ships the residue as a line."""
    from ray_tpu._private.worker_process import _TeeStream

    sent = []

    class FakeRt:
        current_task_id = None
        _actor_id = None

        def _send(self, msg):
            sent.append(msg)

    import io

    tee = _TeeStream(io.StringIO(), FakeRt(), "stdout")
    tee.write("no newline here")
    assert sent == []  # still buffered
    tee.flush()
    # unconnected process: telemetry disabled -> legacy pipe fallback
    assert sent == [("log", "stdout", os.getpid(), "no newline here")]
    tee.flush()
    assert len(sent) == 1  # residue shipped exactly once


def test_list_logs_skips_directories_and_limits(ray_start_regular):
    """list_logs must not count subdirectories against the limit (the seed
    applied [:limit] before filtering) and must skip them entirely."""

    @ray_tpu.remote
    def ping():
        print("logged-line")
        return 1

    assert ray_tpu.get(ping.remote(), timeout=60) == 1
    # force the batched log through and give a directory a low sort key
    drv = ray_tpu.get_runtime()
    drv.scheduler.request_telemetry_flush()
    time.sleep(0.2)
    logs_dir = os.path.join(drv.node.session_dir, "logs")
    os.makedirs(os.path.join(logs_dir, "aaa-subdir"), exist_ok=True)
    os.makedirs(os.path.join(logs_dir, "aab-subdir"), exist_ok=True)
    rows = state.list_logs(limit=1)
    assert len(rows) == 1
    assert rows[0]["filename"] not in ("aaa-subdir", "aab-subdir")
    assert os.path.isfile(rows[0]["path"])


# -- cluster event log -------------------------------------------------------


def test_worker_died_event_and_task_provenance(ray_start_regular):
    """Killing a worker mid-task yields a WORKER_DIED event, and the failed
    task's list_tasks row carries error_type, attempt, node, and pid."""

    @ray_tpu.remote(max_retries=0)
    def hang():
        time.sleep(60)

    ref = hang.remote()
    deadline = time.monotonic() + 30
    row = None
    while time.monotonic() < deadline:
        rows = [
            r
            for r in state.list_tasks()
            if r["name"] == "hang" and r["state"] == "RUNNING" and r["pid"]
        ]
        if rows:
            row = rows[0]
            break
        time.sleep(0.1)
    assert row is not None
    os.kill(row["pid"], signal.SIGKILL)
    with pytest.raises(exc.WorkerCrashedError):
        ray_tpu.get(ref, timeout=60)

    died = _events_of("WORKER_DIED")
    assert any(e["severity"] == "ERROR" and e.get("pid") == row["pid"] for e in died)
    failed = [r for r in state.list_tasks() if r["name"] == "hang"][0]
    assert failed["state"] == "FAILED"
    assert failed["error_type"] == "WorkerCrashedError"
    assert failed["attempt"] == 1
    assert failed["pid"] == row["pid"]
    assert failed["node_id"]
    # the TASK_FAILED event links the same provenance
    tf = [e for e in _events_of("TASK_FAILED") if e.get("name") == "hang"]
    assert tf and tf[0]["error_type"] == "WorkerCrashedError"


def test_task_retry_events_on_worker_kill(ray_start_regular):
    """A retriable task killed mid-run emits TASK_RETRY and completes; its
    row records the successful attempt number."""

    @ray_tpu.remote(max_retries=5)
    def phoenix():
        time.sleep(0.8)
        return "risen"

    ref = phoenix.remote()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        rows = [
            r
            for r in state.list_tasks()
            if r["name"] == "phoenix" and r["state"] == "RUNNING" and r["pid"]
        ]
        if rows:
            os.kill(rows[0]["pid"], signal.SIGKILL)
            break
        time.sleep(0.05)
    assert ray_tpu.get(ref, timeout=120) == "risen"
    assert _events_of("TASK_RETRY")
    row = [r for r in state.list_tasks() if r["name"] == "phoenix"][0]
    assert row["attempt"] >= 2


def test_app_error_provenance_in_events_and_rows(ray_start_regular):
    """An application exception surfaces its cause type (not just TaskError)
    in the TASK_FAILED event and the task row, and the raised error carries
    task_id + pid provenance through pickling."""

    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ZeroDivisionError("1/0")

    ref = boom.remote()
    with pytest.raises(ZeroDivisionError) as ei:
        ray_tpu.get(ref, timeout=60)
    err = ei.value
    assert isinstance(err, exc.TaskError)
    assert err.task_id == ref.id().task_id().hex()
    assert err.pid is not None
    # provenance survives another pickling round-trip (returns/args)
    err2 = pickle.loads(pickle.dumps(err))
    assert isinstance(err2, ZeroDivisionError)
    assert (err2.task_id, err2.pid) == (err.task_id, err.pid)

    tf = [e for e in _events_of("TASK_FAILED") if e.get("name") == "boom"]
    assert tf and tf[0]["error_type"] == "ZeroDivisionError"
    row = [r for r in state.list_tasks() if r["name"] == "boom"][0]
    assert row["error_type"] == "ZeroDivisionError"
    assert row["pid"] is not None


def test_taskerror_provenance_defaults():
    """Constructing/pickling TaskError without provenance stays compatible."""
    e = exc.TaskError("f", "tb")
    e2 = pickle.loads(pickle.dumps(e))
    assert (e2.task_id, e2.attempt, e2.node_id, e2.pid) == (None,) * 4
    w = exc.TaskError(
        "f", "tb", ValueError("x"), task_id="t", attempt=3, node_id="n", pid=9
    ).as_instanceof_cause()
    w2 = pickle.loads(pickle.dumps(w))
    assert isinstance(w2, ValueError) and isinstance(w2, exc.TaskError)
    assert (w2.task_id, w2.attempt, w2.node_id, w2.pid) == ("t", 3, "n", 9)


def test_list_cluster_events_filters_and_limit(ray_start_regular):
    @ray_tpu.remote(max_retries=0)
    def die():
        raise RuntimeError("no")

    with pytest.raises(RuntimeError):
        ray_tpu.get(die.remote(), timeout=60)
    assert _events_of("TASK_FAILED")
    errs = state.list_cluster_events(filters=[("severity", "=", "ERROR")])
    assert errs and all(e["severity"] == "ERROR" for e in errs)
    assert len(state.list_cluster_events(limit=1)) == 1
    # event ids are assigned in arrival order
    rows = state.list_cluster_events()
    ids = [e["event_id"] for e in rows]
    assert ids == sorted(ids)


# -- watchdogs ---------------------------------------------------------------


@pytest.fixture
def watchdog_runtime():
    rt = ray_tpu.init(
        num_cpus=4,
        ignore_reinit_error=True,
        _system_config={
            "straggler_detect_factor": 2.0,
            "straggler_min_samples": 3,
            "straggler_min_runtime_s": 0.3,
            "hung_get_warn_s": 1.0,
        },
    )
    yield rt
    ray_tpu.shutdown()


def test_straggler_warn_event_deterministic(watchdog_runtime):
    """With a lowered threshold, a 10x-slow task is flagged: one STRAGGLER
    WARN event + the ray_tpu_stragglers_total counter."""

    @ray_tpu.remote
    def work(d):
        time.sleep(d)
        return d

    ray_tpu.get([work.remote(0.01) for _ in range(5)], timeout=60)
    slow = work.remote(8.0)
    evs = _events_of("STRAGGLER", timeout=15.0)
    assert evs, "straggler watchdog never fired"
    ev = evs[0]
    assert ev["severity"] == "WARNING"
    assert ev["name"] == "work"
    assert ev["elapsed_s"] > 2.0 * ev["p95_s"]
    # one attempt is flagged at most once
    time.sleep(2.5)
    assert len(_events_of("STRAGGLER")) == 1
    from ray_tpu.util.metrics import prometheus_text

    line = next(
        l
        for l in prometheus_text().splitlines()
        if l.startswith("ray_tpu_stragglers_total")
    )
    assert float(line.split()[-1]) >= 1
    ray_tpu.cancel(slow, force=True)


def test_hung_get_digest(watchdog_runtime, capfd):
    """A get() blocked past hung_get_warn_s prints the pending task chain
    and records a HUNG_GET event, then still honors its timeout."""

    @ray_tpu.remote
    def sleepy():
        time.sleep(30)

    ref = sleepy.remote()
    with pytest.raises(exc.GetTimeoutError):
        ray_tpu.get(ref, timeout=2.5)
    out, err = capfd.readouterr()
    assert "get() has been blocked" in err
    assert "sleepy" in err  # the pending task chain names the producer
    assert _events_of("HUNG_GET", timeout=5.0)
    ray_tpu.cancel(ref, force=True)


# -- serve path --------------------------------------------------------------


def test_serve_replica_failure_event(ray_start_regular):
    from ray_tpu import serve

    @serve.deployment
    class Fragile:
        def __call__(self, x):
            if x == "boom":
                raise ValueError("bad input")
            return x

    h = serve.run(Fragile.bind(), name="fragile")
    try:
        assert h.remote("ok").result(timeout_s=60) == "ok"
        with pytest.raises(Exception):
            h.remote("boom").result(timeout_s=60)
        evs = _events_of("REPLICA_REQUEST_FAILED")
        assert evs
        ev = evs[0]
        assert ev["source"] == "SERVE"
        assert ev["deployment"] == "Fragile"
        assert ev["error_type"] == "ValueError"
        assert ev["replica_id"]
    finally:
        serve.shutdown()


# -- regression guards: PR 2 surfaces unchanged ------------------------------


def test_timeline_and_prometheus_unaffected(ray_start_regular):
    """The forensics plane must not disturb the PR 2 telemetry outputs:
    timeline() still renders the lifecycle spans and /metrics still parses
    (log records and cluster events ride the same batches but never enter
    the task-event log)."""

    @ray_tpu.remote
    def noisy():
        print("timeline-noise")
        return 1

    assert ray_tpu.get([noisy.remote() for _ in range(3)], timeout=60) == [1, 1, 1]
    events = ray_tpu.timeline()
    states = {e["args"]["state"] for e in events}
    assert {"SUBMITTED", "QUEUED", "DISPATCHED", "RUNNING", "FINISHED"} <= states
    # no log/cluster-event record leaked into the chrome trace
    assert all("line" not in e.get("args", {}) for e in events)
    from ray_tpu.util.metrics import prometheus_text

    text = prometheus_text()
    assert "ray_tpu_scheduler_queue_depth" in text
    assert "ray_tpu_cluster_events_total" in text
