"""RL tests. Parity: RLlib learning tests — ``tuned_examples/ppo/
cartpole-ppo.yaml`` asserts return >= 150 within 100k steps (SURVEY.md §4)."""

import numpy as np
import pytest

import ray_tpu

from ray_tpu.rl import CartPoleEnv, PPOConfig, make_env, register_env


def test_cartpole_env_contract():
    env = CartPoleEnv(seed=0)
    obs, info = env.reset()
    assert obs.shape == (4,)
    total = 0.0
    for _ in range(10):
        obs, r, term, trunc, _ = env.step(1)
        total += r
        if term or trunc:
            break
    assert total >= 1.0


def test_register_custom_env():
    class Tiny:
        spec = CartPoleEnv.spec

        def reset(self, seed=None):
            return np.zeros(4, np.float32), {}

        def step(self, a):
            return np.zeros(4, np.float32), 1.0, True, False, {}

    register_env("Tiny-v0", lambda seed=None: Tiny())
    env = make_env("Tiny-v0")
    assert env.reset()[0].shape == (4,)


def test_unknown_env_rejected():
    with pytest.raises(ValueError):
        make_env("DoesNotExist-v99")


def test_ppo_learns_cartpole():
    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                     rollout_fragment_length=128)
        .debugging(seed=0)
    )
    algo = cfg.build()
    best = 0.0
    for _ in range(49):  # <= ~100k env steps, the reference's budget
        result = algo.train()
        best = max(best, result["episode_return_mean"])
        if best >= 150:
            break
    assert best >= 150, f"PPO failed to reach 150 (best {best})"
    assert result["num_env_steps_sampled_lifetime"] <= 101_000


def test_ppo_remote_env_runners(ray_start_regular):
    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                     rollout_fragment_length=32)
        .debugging(seed=0)
    )
    algo = cfg.build()
    result = algo.train()
    assert result["num_env_steps_sampled_lifetime"] == 2 * 4 * 32
    assert "total_loss" in result
    algo.stop()


def test_ppo_save_restore(tmp_path):
    cfg = PPOConfig().env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                                  rollout_fragment_length=32)
    algo = cfg.build()
    algo.train()
    path = algo.save(str(tmp_path / "ckpt"))

    algo2 = cfg.build()
    algo2.restore(path)
    assert algo2.iteration == 1
    r = algo2.train()
    assert r["training_iteration"] == 2


def test_config_rejects_unknown_option():
    with pytest.raises(ValueError):
        PPOConfig().training(nonexistent_option=1)


def test_impala_cartpole_learns_spmd(ray_start_regular):
    """IMPALA with an 8-device SPMD learner (CPU mesh) + remote env runners
    learns CartPole; a runner killed mid-train is replaced (elastic)."""
    import jax

    from ray_tpu.rl import IMPALAConfig

    assert len(jax.devices()) >= 8
    config = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=16,
                     rollout_fragment_length=64)
        .training(lr=1e-3, entropy_coeff=0.005)
        .learners(num_learner_devices=8)
        .debugging(seed=0)
    )
    algo = config.build()
    best = 0.0
    killed = False
    for i in range(400):
        result = algo.training_step()
        best = max(best, result["episode_return_mean"])
        if i == 10 and not killed:
            # kill one env runner mid-train: sampling must stay elastic
            ray_tpu.kill(algo.runners.remote[0])
            killed = True
        if i > 12 and killed:
            assert result["num_healthy_workers"] == 2  # replaced
        if best >= 150.0:
            break
    algo.stop()
    assert best >= 150.0, f"IMPALA did not learn (best {best})"


def test_dqn_learns_cartpole():
    from ray_tpu.rl import DQNConfig

    cfg = (
        DQNConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=8)
        .debugging(seed=0)
    )
    algo = cfg.build()
    best = 0.0
    for _ in range(120):  # <= ~60k env steps
        result = algo.train()
        best = max(best, result["episode_return_mean"])
        if best >= 150:
            break
    assert best >= 150, f"DQN failed to reach 150 (best {best})"


def _expert_cartpole_dataset(n_episodes=40, seed=0):
    """Scripted balancing expert: (obs, action, reward-to-go) rows."""
    rows = []
    for ep in range(n_episodes):
        env = CartPoleEnv(seed=seed + ep)
        obs, _ = env.reset()
        traj = []
        done = False
        while not done:
            a = 1 if (obs[2] + 0.25 * obs[3]) > 0 else 0
            nobs, r, term, trunc, _ = env.step(a)
            traj.append((obs.copy(), a, r))
            obs = nobs
            done = term or trunc
        rtg = 0.0
        for o, a, r in reversed(traj):
            rtg = r + 0.99 * rtg
            rows.append({"obs": o, "actions": a, "returns": rtg})
    return ray_tpu.data.from_items(rows)


def test_bc_imitates_expert(ray_start_regular):
    from ray_tpu.rl import BCConfig

    ds = _expert_cartpole_dataset()
    algo = (
        BCConfig().environment("CartPole-v1").offline_data(ds).debugging(seed=0)
    ).build()
    for _ in range(50):
        result = algo.train()
    assert result["policy_loss"] < 0.5
    ret = algo.evaluate(num_episodes=5)
    assert ret >= 150, f"BC policy return {ret}"


def test_marwil_trains(ray_start_regular):
    from ray_tpu.rl import MARWILConfig

    ds = _expert_cartpole_dataset(n_episodes=10)
    algo = (
        MARWILConfig().environment("CartPole-v1").offline_data(ds).debugging(seed=0)
    ).build()
    first = algo.train()["total_loss"]
    for _ in range(10):
        last = algo.train()["total_loss"]
    assert last < first


def test_sac_learns_cartpole():
    """Discrete SAC (twin soft critics + auto-tuned alpha) reaches the
    tuned-example CartPole threshold (parity: rllib/algorithms/sac)."""
    from ray_tpu.rl import SACConfig

    cfg = (
        SACConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=8)
        .debugging(seed=0)
    )
    algo = cfg.build()
    best = 0.0
    for _ in range(150):
        result = algo.train()
        best = max(best, result["episode_return_mean"])
        if best >= 150:
            break
    assert best >= 150, f"SAC failed to reach 150 (best {best})"
    # the temperature is live (alpha adapted away from its initial value)
    assert result["alpha"] > 0


def test_multi_agent_env_contract():
    from ray_tpu.rl import MultiAgentCartPole

    env = MultiAgentCartPole(num_agents=3, seed=0)
    obs, _ = env.reset()
    assert set(obs) == {"agent_0", "agent_1", "agent_2"}
    obs, rewards, terms, truncs, _ = env.step({a: 0 for a in obs})
    assert set(rewards) == {"agent_0", "agent_1", "agent_2"}
    assert "__all__" in terms and "__all__" in truncs
    # drive until every pole falls: __all__ flips exactly then
    for _ in range(600):
        if terms["__all__"] or truncs["__all__"]:
            break
        obs, rewards, terms, truncs, _ = env.step({a: 0 for a in obs})
    assert terms["__all__"] or truncs["__all__"]


def test_multi_agent_ppo_two_policies_learn():
    """Two independent policies (one per agent via policy_mapping_fn) both
    learn CartPole through the per-policy learner (parity:
    multi_agent_env_runner + MultiRLModule)."""
    from ray_tpu.rl import MultiAgentCartPole, MultiAgentPPOConfig

    cfg = (
        MultiAgentPPOConfig()
        .environment(lambda seed=None: MultiAgentCartPole(num_agents=2, seed=seed))
        .env_runners(num_env_runners=0, num_envs_per_env_runner=8,
                     rollout_fragment_length=64)
        .training(lr=1e-3)
        .multi_agent(
            policies=["p0", "p1"],
            policy_mapping_fn=lambda aid: "p0" if aid == "agent_0" else "p1",
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    best = {"p0": 0.0, "p1": 0.0}
    for _ in range(400):
        result = algo.train()
        for p in best:
            best[p] = max(best[p], result.get(f"{p}/episode_return_mean", 0.0))
        if all(v >= 150.0 for v in best.values()):
            break
    assert all(v >= 150.0 for v in best.values()), f"policies stalled: {best}"
    # the two policies are genuinely distinct modules with distinct weights
    import jax
    import numpy as np

    state = algo.get_state()
    assert state["params"].keys() == {"p0", "p1"}
    p0_leaves = jax.tree.leaves(state["params"]["p0"])
    p1_leaves = jax.tree.leaves(state["params"]["p1"])
    assert any(
        not np.array_equal(a, b) for a, b in zip(p0_leaves, p1_leaves)
    ), "p0 and p1 share identical weights"


def test_appo_learns_cartpole(ray_start_regular):
    """APPO (IMPALA architecture + PPO clipped surrogate on V-trace
    advantages; parity: rllib/algorithms/appo) reaches the CartPole
    threshold with the same learner plane as IMPALA."""
    from ray_tpu.rl import APPOConfig

    cfg = (
        APPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                     rollout_fragment_length=64)
        .training(lr=1e-3, entropy_coeff=0.005, clip_param=0.3)
        .debugging(seed=0)
    )
    algo = cfg.build()
    best = 0.0
    for _ in range(400):
        result = algo.train()
        best = max(best, result["episode_return_mean"])
        if best >= 150:
            break
    assert best >= 150, f"APPO best return {best}"


def _transition_cartpole_dataset(n_episodes=30, seed=0, noise=0.3):
    """(obs, action, reward, next_obs, done) rows from a decent-but-noisy
    behavior policy — the offline-RL setting CQL is built for."""
    rng = np.random.default_rng(seed)
    rows = []
    for ep in range(n_episodes):
        env = CartPoleEnv(seed=seed + ep)
        obs, _ = env.reset()
        done = False
        while not done:
            expert = 1 if (obs[2] + 0.25 * obs[3]) > 0 else 0
            a = int(rng.integers(0, 2)) if rng.random() < noise else expert
            nobs, r, term, trunc, _ = env.step(a)
            done = term or trunc
            rows.append(
                {
                    "obs": obs.copy(),
                    "actions": a,
                    "rewards": r,
                    "next_obs": nobs.copy(),
                    "dones": float(done),
                }
            )
            obs = nobs
    return ray_tpu.data.from_items(rows)


def test_cql_conservative_offline(ray_start_regular):
    """CQL (parity: rllib/algorithms/cql, discrete CQL(H)): trains from a
    fixed transition dataset, the conservative term keeps out-of-dataset
    action values below data support, and the greedy policy beats the
    noisy behavior policy's return."""
    from ray_tpu.rl import CQLConfig

    ds = _transition_cartpole_dataset()
    algo = (
        CQLConfig().environment("CartPole-v1").offline_data(ds).debugging(seed=0)
    ).build()
    for _ in range(40):
        result = algo.train()
    assert np.isfinite(result["total_loss"])
    # the conservative regularizer must actually bind: logsumexp-Q minus
    # data-action Q stays small (OOD actions are not overestimated)
    assert result["cql_loss"] < 1.5, result
    ret = algo.evaluate(num_episodes=5)
    assert ret >= 120, f"CQL policy return {ret}"


def test_connector_pipeline_env_to_module(ray_start_regular):
    """Connector pipelines (parity: rllib/connectors ConnectorV2):
    observations flow through NormalizeObservations + FrameStack before the
    module sees or stores them; the policy net is sized for the pipeline
    OUTPUT, and PPO still learns CartPole through the transformed stream."""
    from ray_tpu.rl import FrameStack, NormalizeObservations, PPOConfig

    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(
            num_env_runners=0,
            num_envs_per_env_runner=16,
            env_to_module_connector=lambda: [
                NormalizeObservations(),
                FrameStack(k=2),
            ],
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    # module input is widened by the stack: 4 obs dims * k=2
    assert algo.params["w0"].shape[0] == 8 if "w0" in algo.params else True
    best = 0.0
    for _ in range(120):
        result = algo.train()
        best = max(best, result["episode_return_mean"])
        if best >= 150:
            break
    assert best >= 150, f"PPO-with-connectors best return {best}"


def test_compute_single_action_and_evaluate():
    """Parity surface: Algorithm.compute_single_action + evaluate() —
    greedy rollouts on a trained PPO return a sane CartPole score."""
    from ray_tpu.rl.ppo import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=8,
                     rollout_fragment_length=64)
        .debugging(seed=0)
        .build()
    )
    for _ in range(12):
        algo.train()
    a = algo.compute_single_action([0.0, 0.0, 0.0, 0.0])
    assert a in (0, 1)
    out = algo.evaluate(num_episodes=3)["evaluation"]
    assert out["episodes_this_iter"] == 3
    assert out["episode_return_mean"] > 40, out  # far above random (~20)
    algo.stop()


def test_evaluate_uses_trained_connector_state_without_mutating_it():
    """evaluate() must snapshot the training runners' connector pipeline
    (running normalize stats) rather than restarting it at zero — and must
    not advance the training copy while evaluating."""
    import copy

    import numpy as np

    from ray_tpu.rl.connectors import NormalizeObservations
    from ray_tpu.rl.ppo import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                     rollout_fragment_length=32,
                     env_to_module_connector=lambda: [NormalizeObservations()])
        .debugging(seed=0)
        .build()
    )
    algo.train()
    trained_pipe = algo.runners.local.connectors
    before = copy.deepcopy(trained_pipe.get_state())
    out = algo.evaluate(num_episodes=2)["evaluation"]
    assert out["episodes_this_iter"] == 2
    after = trained_pipe.get_state()
    flat_b = np.concatenate([np.ravel(np.asarray(v, dtype=np.float64))
                             for v in _flatten_state(before)])
    flat_a = np.concatenate([np.ravel(np.asarray(v, dtype=np.float64))
                             for v in _flatten_state(after)])
    assert np.allclose(flat_b, flat_a), "evaluation mutated training stats"
    algo.stop()


def _flatten_state(state):
    out = []

    def rec(x):
        if isinstance(x, dict):
            for v in x.values():
                rec(v)
        elif isinstance(x, (list, tuple)):
            for v in x:
                rec(v)
        elif isinstance(x, (int, float)) or hasattr(x, "ndim"):
            out.append(x)

    rec(state)
    return out or [0.0]
