"""Elastic training fast tests: N→M re-shard corner cases, the report
collector's bounded buffer, retry backoff, and drain-timeout surfacing.
(The kill-driven convergence tests live in test_elastic_chaos.py, slow.)"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import external_storage as storage
from ray_tpu.train import checkpointing, elastic


# --------------------------------------------------------------------------
# partition + re-shard corner cases (pure, no cluster)
# --------------------------------------------------------------------------


def test_partition_rows_balanced_and_total():
    for total in (0, 1, 5, 8, 23):
        for world in (1, 2, 3, 7, 10):
            parts = elastic.partition_rows(total, world)
            assert len(parts) == world
            assert parts[0][0] == 0 and parts[-1][1] == total
            sizes = [hi - lo for lo, hi in parts]
            assert sum(sizes) == total
            assert max(sizes) - min(sizes) <= 1  # balanced
            # contiguous, ordered
            for (_, a), (b, _) in zip(parts, parts[1:]):
                assert a == b


def _commit_elastic_step(base, step, arrays, save_world, *, extra=None):
    step_dir = os.path.join(base, checkpointing.step_dir_name(step))
    for r in range(save_world):
        shard = os.path.join(
            step_dir, checkpointing.shard_dir_name(r, save_world)
        )
        elastic.save_elastic_shard(
            shard or step_dir,
            arrays,
            rank=r,
            world_size=save_world,
            extra=extra or {"step": step},
        )
    manifest = storage.build_manifest(step_dir, step=step, world_size=save_world)
    storage.write_commit_markers(step_dir, manifest)
    return step_dir


@pytest.mark.parametrize("save_world,load_world", [(3, 1), (1, 4), (2, 3), (4, 2)])
def test_reshard_n_to_m_roundtrip(tmp_path, save_world, load_world):
    """N→1, 1→M, and both directions of N→M: concatenating every new
    rank's slice reproduces the original arrays bitwise."""
    g = {
        "w": np.arange(20 * 5, dtype=np.float32).reshape(20, 5),
        "b": np.linspace(-1, 1, 7),
    }
    step_dir = _commit_elastic_step(str(tmp_path), 1, g, save_world)
    for name, ref in g.items():
        slices = []
        for r in range(load_world):
            arrays, extra = elastic.load_elastic_state(
                step_dir, rank=r, world_size=load_world, arrays=[name]
            )
            assert extra == {"step": 1}
            slices.append(arrays[name])
        assert np.array_equal(np.concatenate(slices), ref)


def test_reshard_m_greater_than_rows_empty_slices(tmp_path):
    """M > row count: trailing ranks own empty (zero-row) slices and the
    concatenation is still exact."""
    g = {"tiny": np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])}
    step_dir = _commit_elastic_step(str(tmp_path), 2, g, 2)
    world = 7  # > 3 rows
    slices = [
        elastic.load_elastic_state(step_dir, rank=r, world_size=world)[0]["tiny"]
        for r in range(world)
    ]
    assert [s.shape[0] for s in slices] == [1, 1, 1, 0, 0, 0, 0]
    assert np.array_equal(np.concatenate(slices), g["tiny"])


def test_rank0_only_checkpoint_into_multirank_world(tmp_path):
    """The reference's gather pattern — one shard holding the FULL state,
    committed under a multi-rank world — re-shards into any world."""
    g = {"w": np.random.default_rng(0).normal(size=(11, 3))}
    step_dir = os.path.join(str(tmp_path), checkpointing.step_dir_name(3))
    # only rank 0 saved, and it saved everything (world_size=1 slicing)
    elastic.save_elastic_shard(
        os.path.join(step_dir, checkpointing.shard_dir_name(0, 4)),
        g,
        rank=0,
        world_size=1,
    )
    storage.write_commit_markers(
        step_dir, storage.build_manifest(step_dir, step=3, world_size=4)
    )
    slices = [
        elastic.load_elastic_state(step_dir, rank=r, world_size=3)[0]["w"]
        for r in range(3)
    ]
    assert np.array_equal(np.concatenate(slices), g["w"])


def test_presliced_zero_style_save(tmp_path):
    """Callers holding only their slice (ZeRO-sharded state) pass
    (local, row_offset, global_rows) tuples; restore is identical."""
    full = np.arange(12.0).reshape(6, 2)
    base = str(tmp_path)
    step_dir = os.path.join(base, checkpointing.step_dir_name(1))
    for r, (lo, hi) in enumerate(elastic.partition_rows(6, 2)):
        elastic.save_elastic_shard(
            os.path.join(step_dir, checkpointing.shard_dir_name(r, 2)),
            {"opt": (full[lo:hi], lo, 6)},
            rank=r,
            world_size=2,
        )
    storage.write_commit_markers(step_dir, storage.build_manifest(step_dir))
    got, _ = elastic.load_elastic_full(step_dir)
    assert np.array_equal(got["opt"], full)


def test_digest_mismatch_shard_refused_mid_reshard(tmp_path):
    """A corrupted shard is refused by the chunk digests — but only ranks
    whose row range touches the corrupt bytes fail; others re-shard
    cleanly (ranged reads never even see the bad shard)."""
    g = {"w": np.arange(30.0).reshape(10, 3)}
    step_dir = _commit_elastic_step(str(tmp_path), 1, g, 2)
    # corrupt one byte in rank 1's shard payload
    victim = os.path.join(
        step_dir, checkpointing.shard_dir_name(1, 2), "w.bin"
    )
    with open(victim, "r+b") as fh:
        fh.seek(4)
        fh.write(b"\xff")
    with pytest.raises(storage.IntegrityError, match="digest mismatch"):
        elastic.load_elastic_full(step_dir)
    # rank 0 of 2 owns rows 0..5 — entirely inside the intact shard 0
    ok, _ = elastic.load_elastic_state(step_dir, rank=0, world_size=2)
    assert np.array_equal(ok["w"], g["w"][:5])
    # tampering with the INDEX is caught by the committed manifest
    idx = os.path.join(
        step_dir, checkpointing.shard_dir_name(0, 2), elastic.ELASTIC_INDEX
    )
    with open(idx, "a") as fh:
        fh.write(" ")
    with pytest.raises(storage.IntegrityError):
        elastic.load_elastic_state(step_dir, rank=0, world_size=2)


def test_reshard_from_memory_uri_backend(tmp_path):
    """Re-shard straight off a scheme:// mirror: ranged reads go through
    the backend (base-class read_range fallback), no local staging of the
    whole checkpoint."""
    g = {"w": np.arange(40.0).reshape(8, 5)}
    step_dir = _commit_elastic_step(str(tmp_path), 4, g, 2)
    uri = "memory://elastic_test/checkpoint_000004"
    storage.commit_dir_to_uri(step_dir, uri)
    slices = [
        elastic.load_elastic_state(uri, rank=r, world_size=4)[0]["w"]
        for r in range(4)
    ]
    assert np.array_equal(np.concatenate(slices), g["w"])


def test_mixed_world_step_refused(tmp_path):
    """Shards from two world sizes under one step prefix are a torn mix
    of save generations — the loader must refuse, not interleave rows."""
    g = {"w": np.arange(12.0).reshape(6, 2)}
    step_dir = os.path.join(str(tmp_path), checkpointing.step_dir_name(1))
    for r in range(2):
        elastic.save_elastic_shard(
            os.path.join(step_dir, checkpointing.shard_dir_name(r, 2)),
            g, rank=r, world_size=2,
        )
    elastic.save_elastic_shard(
        os.path.join(step_dir, checkpointing.shard_dir_name(0, 3)),
        g, rank=0, world_size=3,
    )
    storage.write_commit_markers(step_dir, storage.build_manifest(step_dir))
    with pytest.raises(storage.IntegrityError, match="multiple world sizes"):
        elastic.load_elastic_full(step_dir)


def test_resize_report_clears_stale_layout(tmp_path):
    """A rank snapshotting a step dir left over from another world size
    (a dead attempt's shards, or a flat world-1 residue) must clear the
    stale layout — otherwise the commit would manifest a mixed dir."""
    from ray_tpu.train._session import _clear_stale_layouts

    step_dir = str(tmp_path / "checkpoint_000003")
    g = {"w": np.arange(8.0).reshape(4, 2)}
    # dead world-4 attempt left two shards; a flat file rides along too
    for r in (0, 2):
        elastic.save_elastic_shard(
            os.path.join(step_dir, checkpointing.shard_dir_name(r, 4)),
            g, rank=r, world_size=4,
        )
    # current world 2: rank 0's fresh shard already landed
    elastic.save_elastic_shard(
        os.path.join(step_dir, checkpointing.shard_dir_name(0, 2)),
        g, rank=0, world_size=2,
    )
    open(os.path.join(step_dir, "stale_flat.bin"), "w").close()
    _clear_stale_layouts(step_dir, 2)
    assert sorted(os.listdir(step_dir)) == ["shard-00000-of-00002"]
    # shrink to world 1: ALL shard dirs are stale (flat layout expected)
    elastic.save_elastic_shard(
        os.path.join(step_dir, checkpointing.shard_dir_name(1, 2)),
        g, rank=1, world_size=2,
    )
    _clear_stale_layouts(step_dir, 1)
    assert os.listdir(step_dir) == []


def test_pick_shard_cross_world_rules(tmp_path):
    """_pick_shard: exact (rank, world) match; a SOLE rank-0 shard (the
    gather pattern, full state) restores into any world; a truly
    partitioned other-world layout falls back to the step dir (a
    different world's slice is the wrong rows)."""
    from ray_tpu.train._session import _pick_shard

    step = str(tmp_path / "checkpoint_000001")
    for r in range(2):
        os.makedirs(os.path.join(step, checkpointing.shard_dir_name(r, 2)))
    # same world: exact match
    assert _pick_shard(step, 1, 2).endswith("shard-00001-of-00002")
    # world changed, multi-shard layout: step dir (elastic loader's job)
    assert _pick_shard(step, 0, 3) is None
    assert _pick_shard(step, 0, 1) is None
    # sole rank-0 shard = gathered full state: safe at any world
    step2 = str(tmp_path / "checkpoint_000002")
    os.makedirs(os.path.join(step2, checkpointing.shard_dir_name(0, 4)))
    assert _pick_shard(step2, 2, 3).endswith("shard-00000-of-00004")
    assert _pick_shard(step2, 0, 1).endswith("shard-00000-of-00004")
    # flat world-1 layout: no shard dirs at all
    step3 = str(tmp_path / "checkpoint_000003")
    os.makedirs(step3)
    assert _pick_shard(step3, 0, 1) is None


def test_uncovered_rows_refused(tmp_path):
    """A checkpoint missing a shard (lost rows) must refuse ranks whose
    partition needs them, not zero-fill."""
    g = {"w": np.arange(12.0).reshape(6, 2)}
    step_dir = _commit_elastic_step(str(tmp_path), 1, g, 3)
    import shutil

    shutil.rmtree(os.path.join(step_dir, checkpointing.shard_dir_name(1, 3)))
    # re-commit so the manifest matches what's on disk (the shard was
    # legitimately lost, not torn)
    storage.write_commit_markers(step_dir, storage.build_manifest(step_dir))
    with pytest.raises(storage.IntegrityError, match="not covered"):
        elastic.load_elastic_full(step_dir)


# --------------------------------------------------------------------------
# trainer-level N→M resume (real worker group, tiny workload)
# --------------------------------------------------------------------------


def _sgd_loop(total_steps):
    def loop(config=None):
        from ray_tpu import train

        rng = np.random.default_rng(7)
        X = rng.normal(size=(16, 4))
        y = X @ np.array([1.0, -2.0, 3.0, 0.5])
        state = train.load_elastic(full=True)
        if state is not None:
            arrays, extra = state
            w, start = arrays["w"], int(extra["step"])
        else:
            w, start = np.zeros(4), 0
        for step in range(start, total_steps):
            w = w - 0.05 * (2.0 * X.T @ (X @ w - y) / len(y))
            train.report_elastic(
                {"loss": float(np.mean((X @ w - y) ** 2))},
                {"w": w},
                extra={"step": step + 1},
            )

    return loop


def test_trainer_resume_across_world_sizes(ray_start_regular, tmp_path):
    """fit at world 2, stop, resume the SAME run at world 3: ranks restore
    re-sharded slices of the 2-shard checkpoint, continue the step
    numbering, and land on the loss an uninterrupted world-2 run gets."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    calm = JaxTrainer(
        _sgd_loop(6),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path), name="calm"),
    ).fit()
    assert calm.error is None, calm.error

    first = JaxTrainer(
        _sgd_loop(3),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path), name="grow"),
    ).fit()
    assert first.error is None, first.error
    manifest = storage.read_committed_manifest(
        os.path.join(str(tmp_path / "grow"), checkpointing.step_dir_name(3))
    )
    assert manifest is not None and manifest["world_size"] == 2

    second = JaxTrainer(
        _sgd_loop(6),
        scaling_config=ScalingConfig(num_workers=3),
        run_config=RunConfig(storage_path=str(tmp_path), name="grow"),
    ).fit()
    assert second.error is None, second.error
    assert second.metrics["training_iteration"] == 6
    assert second.metrics["loss"] == calm.metrics["loss"]
    manifest = storage.read_committed_manifest(
        os.path.join(str(tmp_path / "grow"), checkpointing.step_dir_name(6))
    )
    assert manifest is not None and manifest["world_size"] == 3


# --------------------------------------------------------------------------
# satellites: collector trim, backoff, drain timeout
# --------------------------------------------------------------------------


def test_report_collector_drops_drained_entries(ray_start_regular):
    """Regression: drain(start) must trim the buffered history (the seed
    kept every report forever — a long run's metrics accumulated in the
    collector actor unbounded)."""
    from ray_tpu.train._backend_executor import _ReportCollector

    c = _ReportCollector.remote()
    for i in range(40):
        ray_tpu.get(c.report.remote(0, i, {"i": i}, None), timeout=30)
    assert ray_tpu.get(c.buffered.remote(), timeout=30) == 40
    out = ray_tpu.get(c.drain.remote(0), timeout=30)
    assert [r[1] for r in out] == list(range(40))
    # drained entries are gone from the actor...
    assert ray_tpu.get(c.buffered.remote(), timeout=30) == 0
    # ...and the offset keeps subsequent drains consistent
    for i in range(40, 45):
        ray_tpu.get(c.report.remote(0, i, {"i": i}, None), timeout=30)
    out2 = ray_tpu.get(c.drain.remote(40), timeout=30)
    assert [r[1] for r in out2] == [40, 41, 42, 43, 44]
    assert ray_tpu.get(c.drain.remote(45), timeout=30) == []


def test_retry_backoff_schedule():
    from ray_tpu.train import FailureConfig
    from ray_tpu.train.jax_trainer import _retry_backoff

    cfg = FailureConfig(
        retry_backoff_s=0.5, retry_backoff_max_s=4.0, retry_backoff_jitter=0.0
    )
    assert [_retry_backoff(a, cfg) for a in (1, 2, 3, 4, 5)] == [
        0.5,
        1.0,
        2.0,
        4.0,
        4.0,  # capped
    ]
    jittered = FailureConfig(
        retry_backoff_s=1.0, retry_backoff_max_s=8.0, retry_backoff_jitter=0.5
    )
    for attempt in (1, 3):
        base = min(8.0, 1.0 * 2 ** (attempt - 1))
        for _ in range(20):
            d = _retry_backoff(attempt, jittered)
            assert 0.5 * base <= d <= 1.5 * base


def test_drain_timeout_surfaces_undrained_steps(ray_start_regular, tmp_path):
    """Satellite: a drain timeout in fit()'s finally must emit a
    CHECKPOINT_FAILED event and put the undrained steps on Result.error —
    never return as if everything committed."""

    class _HangBackend(storage.StorageBackend):
        def __init__(self):
            self._inner = storage.MemoryBackend()

        def write_bytes(self, path, data):
            time.sleep(8.0)  # the mirror is wedged
            self._inner.write_bytes(path, data)

        def write_stream(self, path, chunks):
            time.sleep(8.0)
            self._inner.write_stream(path, chunks)

        def read_bytes(self, path):
            return self._inner.read_bytes(path)

        def exists(self, path):
            return self._inner.exists(path)

        def delete(self, path):
            return self._inner.delete(path)

        def list(self, prefix):
            return self._inner.list(prefix)

    storage.register_backend("hangstore", _HangBackend)
    from ray_tpu.train import (
        CheckpointConfig,
        Checkpoint,
        JaxTrainer,
        RunConfig,
        ScalingConfig,
    )
    from ray_tpu import train

    def loop(config=None):
        import tempfile

        d = tempfile.mkdtemp()
        with open(os.path.join(d, "m.txt"), "w") as fh:
            fh.write("x")
        train.report({"ok": 1.0}, checkpoint=Checkpoint.from_directory(d))

    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            storage_path="hangstore://drainbase",
            name="draintest",
            checkpoint_config=CheckpointConfig(drain_timeout_s=0.5),
        ),
    ).fit()
    assert isinstance(result.error, checkpointing.CheckpointDrainError), result.error
    assert result.error.undrained_steps == [1]
    # the local commit landed before the wedged mirror: resume point exists
    assert result.checkpoint is not None
    from ray_tpu.util import state as state_api

    failed = [
        e
        for e in state_api.list_cluster_events()
        if e["type"] == "CHECKPOINT_FAILED" and e.get("run") == "draintest"
    ]
    assert failed and failed[-1].get("undrained_steps") == [1], failed
