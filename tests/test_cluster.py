"""Virtual multi-node cluster tests: scheduling policies, PGs, node failure.

Test strategy parity: ``python/ray/tests/test_scheduling*.py``,
``test_placement_group*.py``, chaos killers (SURVEY.md §4 item 3).
"""

import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.util.placement_group import (
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)


def test_custom_resource_routing(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2, resources={"special": 2})
    cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"special": 1})
    def f():
        return "routed"

    assert ray_tpu.get(f.remote(), timeout=60) == "routed"


def test_node_affinity(ray_start_cluster):
    cluster = ray_start_cluster
    node = cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()

    @ray_tpu.remote
    def f():
        return 1

    strat = NodeAffinitySchedulingStrategy(node_id=node.hex)
    assert ray_tpu.get(f.options(scheduling_strategy=strat).remote(), timeout=60) == 1


def test_infeasible_task_waits(ray_start_cluster):
    @ray_tpu.remote(resources={"nonexistent": 1})
    def f():
        return 1

    ref = f.remote()
    ready, not_ready = ray_tpu.wait([ref], num_returns=1, timeout=0.5)
    assert ready == []


def test_pg_strict_spread_needs_nodes(ray_start_cluster):
    cluster = ray_start_cluster
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert not pg.wait(0.5)  # only one node
    cluster.add_node(num_cpus=2)
    deadline = time.monotonic() + 10
    # PENDING PGs retry when nodes change: re-create for now
    pg2 = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert pg2.wait(10)


def test_pg_pack_and_task(ray_start_cluster):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.wait(10)

    @ray_tpu.remote(num_cpus=1)
    def f():
        return "ok"

    strat = PlacementGroupSchedulingStrategy(placement_group=pg, placement_group_bundle_index=0)
    assert ray_tpu.get(f.options(scheduling_strategy=strat).remote(), timeout=60) == "ok"
    table = placement_group_table()
    assert any(v["state"] == "CREATED" for v in table.values())
    remove_placement_group(pg)


def test_pg_gang_actors(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="SPREAD")
    assert pg.wait(10)

    @ray_tpu.remote(num_cpus=1)
    class W:
        def ping(self):
            return "pong"

    actors = [
        W.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                placement_group=pg, placement_group_bundle_index=i
            )
        ).remote()
        for i in range(2)
    ]
    assert ray_tpu.get([a.ping.remote() for a in actors], timeout=60) == ["pong", "pong"]


def test_node_failure_task_retry(ray_start_cluster):
    cluster = ray_start_cluster
    node = cluster.add_node(num_cpus=1, resources={"doomed": 1})
    cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"doomed": 1}, max_retries=0)
    def stuck():
        time.sleep(60)
        return 1

    ref = stuck.remote()
    # wait until it is actually executing on the doomed node, then kill it
    from ray_tpu.util import state as state_api

    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        rows = [t for t in state_api.list_tasks() if t["name"] == "stuck"]
        if rows and rows[0]["state"] == "RUNNING":
            break
        time.sleep(0.05)
    else:
        raise TimeoutError("task never started on the doomed node")
    cluster.remove_node(node)
    with pytest.raises((exc.WorkerCrashedError, exc.TaskError)):
        ray_tpu.get(ref, timeout=60)


def test_spread_strategy(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()

    @ray_tpu.remote(num_cpus=1, scheduling_strategy="SPREAD")
    def f():
        time.sleep(0.2)
        return 1

    assert sum(ray_tpu.get([f.remote() for _ in range(4)], timeout=120)) == 4


def test_device_instances_across_dispatch_planes():
    """One per-device ledger per node (daemon-authoritative): head-relayed
    actors and daemon-leased tasks must never share a chip, kills recycle
    indices, and TPU_VISIBLE_CHIPS reaches the worker (parity:
    resource_instance_set.h + accelerator env isolation)."""
    import time

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    cluster.add_node(num_cpus=2, resources={"TPU": 2})
    cluster.wait_for_nodes()
    try:
        @ray_tpu.remote(num_cpus=0, resources={"TPU": 1})
        class Chip:
            def which(self):
                import os

                return os.environ.get("TPU_VISIBLE_CHIPS")

        a, b = Chip.remote(), Chip.remote()
        got = {
            ray_tpu.get(a.which.remote(), timeout=120),
            ray_tpu.get(b.which.remote(), timeout=120),
        }
        assert got == {"0", "1"}, got

        c = Chip.remote()  # pending: both chips held
        ray_tpu.kill(a)
        assert ray_tpu.get(c.which.remote(), timeout=120) in ("0", "1")

        ray_tpu.kill(b)
        ray_tpu.kill(c)
        time.sleep(1.0)

        @ray_tpu.remote(num_cpus=0, resources={"TPU": 1})
        def probe():
            import os
            import time as _t

            _t.sleep(0.8)
            return os.environ.get("TPU_VISIBLE_CHIPS")

        xs = ray_tpu.get([probe.remote(), probe.remote()], timeout=120)
        assert set(xs) == {"0", "1"}, xs
    finally:
        cluster.shutdown()
