"""Virtual multi-node cluster tests: scheduling policies, PGs, node failure.

Test strategy parity: ``python/ray/tests/test_scheduling*.py``,
``test_placement_group*.py``, chaos killers (SURVEY.md §4 item 3).
"""

import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.util.placement_group import (
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)


def test_custom_resource_routing(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2, resources={"special": 2})
    cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"special": 1})
    def f():
        return "routed"

    assert ray_tpu.get(f.remote(), timeout=60) == "routed"


def test_node_affinity(ray_start_cluster):
    cluster = ray_start_cluster
    node = cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()

    @ray_tpu.remote
    def f():
        return 1

    strat = NodeAffinitySchedulingStrategy(node_id=node.hex)
    assert ray_tpu.get(f.options(scheduling_strategy=strat).remote(), timeout=60) == 1


def test_infeasible_task_waits(ray_start_cluster):
    @ray_tpu.remote(resources={"nonexistent": 1})
    def f():
        return 1

    ref = f.remote()
    ready, not_ready = ray_tpu.wait([ref], num_returns=1, timeout=0.5)
    assert ready == []


def test_pg_strict_spread_needs_nodes(ray_start_cluster):
    cluster = ray_start_cluster
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert not pg.wait(0.5)  # only one node
    cluster.add_node(num_cpus=2)
    deadline = time.monotonic() + 10
    # PENDING PGs retry when nodes change: re-create for now
    pg2 = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert pg2.wait(10)


def test_pg_pack_and_task(ray_start_cluster):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.wait(10)

    @ray_tpu.remote(num_cpus=1)
    def f():
        return "ok"

    strat = PlacementGroupSchedulingStrategy(placement_group=pg, placement_group_bundle_index=0)
    assert ray_tpu.get(f.options(scheduling_strategy=strat).remote(), timeout=60) == "ok"
    table = placement_group_table()
    assert any(v["state"] == "CREATED" for v in table.values())
    remove_placement_group(pg)


def test_pg_gang_actors(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="SPREAD")
    assert pg.wait(10)

    @ray_tpu.remote(num_cpus=1)
    class W:
        def ping(self):
            return "pong"

    actors = [
        W.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                placement_group=pg, placement_group_bundle_index=i
            )
        ).remote()
        for i in range(2)
    ]
    assert ray_tpu.get([a.ping.remote() for a in actors], timeout=60) == ["pong", "pong"]


def test_node_failure_task_retry(ray_start_cluster):
    cluster = ray_start_cluster
    node = cluster.add_node(num_cpus=1, resources={"doomed": 1})
    cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"doomed": 1}, max_retries=0)
    def stuck():
        time.sleep(60)
        return 1

    ref = stuck.remote()
    # wait until it is actually executing on the doomed node, then kill it
    from ray_tpu.util import state as state_api

    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        rows = [t for t in state_api.list_tasks() if t["name"] == "stuck"]
        if rows and rows[0]["state"] == "RUNNING":
            break
        time.sleep(0.05)
    else:
        raise TimeoutError("task never started on the doomed node")
    cluster.remove_node(node)
    with pytest.raises((exc.WorkerCrashedError, exc.TaskError)):
        ray_tpu.get(ref, timeout=60)


def test_spread_strategy(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()

    @ray_tpu.remote(num_cpus=1, scheduling_strategy="SPREAD")
    def f():
        time.sleep(0.2)
        return 1

    assert sum(ray_tpu.get([f.remote() for _ in range(4)], timeout=120)) == 4
