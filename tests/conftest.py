"""Test fixtures.

Parity: ``python/ray/tests/conftest.py`` (``ray_start_regular:419``,
``ray_start_cluster:500``). TPU tests run on a virtual 8-device CPU mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``), the JAX analogue of
the reference's fake-GPU configs (SURVEY.md §4). The environment may pin
``JAX_PLATFORMS`` to a real TPU plugin before we run, so we override both the
env (for spawned worker processes) and the live jax config (this process).
"""

import os

# Env first: worker processes and any not-yet-initialized jax in this process
# inherit these. Force-set (not setdefault): the surrounding environment may
# pin JAX_PLATFORMS to a hardware plugin.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The interpreter may have imported jax already (site customization); update
# the live config too. Backends must not be initialized yet at conftest time.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

import pytest  # noqa: E402


@pytest.fixture
def ray_start_regular():
    import ray_tpu

    rt = ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield rt
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    yield cluster
    cluster.shutdown()


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    import jax

    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs


def pytest_sessionfinish(session, exitstatus):
    """Post-suite hygiene: a test that leaks a BLOCKED non-daemon thread
    (e.g. a pool worker parked in an unbounded get after its cluster died)
    would wedge interpreter shutdown forever. Print the evidence, then arm
    a watchdog that bounds the exit at 90s — the suite's verdict is already
    decided at this point."""
    import faulthandler
    import os
    import sys
    import threading
    import time

    stragglers = [
        t
        for t in threading.enumerate()
        if t is not threading.main_thread() and not t.daemon and t.is_alive()
    ]
    if stragglers:
        sys.stderr.write(
            f"\n[conftest] {len(stragglers)} non-daemon thread(s) still "
            f"alive at exit: {[t.name for t in stragglers]}\n"
        )
        faulthandler.dump_traceback(file=sys.stderr)

    status = int(exitstatus)

    def _watchdog():
        time.sleep(90)
        sys.stderr.write("[conftest] exit watchdog fired: hard-exiting\n")
        sys.stderr.flush()
        os._exit(status)

    threading.Thread(target=_watchdog, name="exit-watchdog", daemon=True).start()
