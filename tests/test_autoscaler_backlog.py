"""Backlog-driven autoscaler unit tests (no live cluster).

Parity: ``python/ray/tests/test_autoscaler.py`` MockProvider pattern — a
pure-python NodeProvider plus a fake ClusterStateSource feed the reconciler
synthetic backlog ramps, so scale-up request counts, the scale-down
utilization floor / empty-backlog rule, and the no-flap hysteresis are all
asserted without spawning a cluster.
"""

import time

from ray_tpu.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    ClusterStateSource,
    NodeProvider,
    NodeType,
)


class MockProvider(NodeProvider):
    def __init__(self):
        self._nodes = {}
        self._seq = 0
        self.created = []
        self.terminated = []

    def create_node(self, node_type, resources):
        self._seq += 1
        nid = f"n{self._seq}"
        self._nodes[nid] = {
            "node_id": nid,
            "node_type": node_type,
            "resources": dict(resources),
            "launched_at": time.time(),
        }
        self.created.append(nid)
        return nid

    def terminate_node(self, node_id):
        self._nodes.pop(node_id, None)
        self.terminated.append(node_id)

    def non_terminated_nodes(self):
        return list(self._nodes.values())


class FakeState(ClusterStateSource):
    def __init__(self):
        self.shapes = []  # [{"shape", "queued", "leased", "node_backlog"}]
        self.pg_pending = []
        self.util = {}  # node_id -> fraction

    def backlog(self):
        return {"shapes": self.shapes, "pg_pending": self.pg_pending}

    def utilization(self):
        return dict(self.util)


def _mk(config, provider=None, state=None):
    provider = provider or MockProvider()
    state = state or FakeState()
    return Autoscaler(config, provider, state=state), provider, state


def test_scale_up_request_count_matches_binpack():
    auto, provider, state = _mk(
        AutoscalerConfig(
            node_types=[NodeType("cpu2", {"CPU": 2}, max_workers=8)],
            upscaling_speed=100.0,  # don't throttle: count the bin-pack
        )
    )
    state.shapes = [
        {"shape": {"CPU": 1.0}, "queued": 5, "leased": 0, "node_backlog": 0}
    ]
    report = auto.update()
    # 5 one-CPU tasks pack 2-per-node onto 2-CPU nodes -> 3 launches
    assert report["launched"] == 3
    assert len(provider.non_terminated_nodes()) == 3


def test_scale_up_threshold_gates_demand():
    auto, provider, state = _mk(
        AutoscalerConfig(
            node_types=[NodeType("cpu1", {"CPU": 1}, max_workers=8)],
            scale_up_backlog_threshold=10,
        )
    )
    state.shapes = [
        {"shape": {"CPU": 1.0}, "queued": 5, "leased": 0, "node_backlog": 0}
    ]
    assert auto.update()["launched"] == 0
    state.shapes[0]["queued"] = 10
    assert auto.update()["launched"] >= 1


def test_node_backlog_counts_as_pressure():
    auto, provider, state = _mk(
        AutoscalerConfig(
            node_types=[NodeType("cpu1", {"CPU": 1}, max_workers=8)],
            scale_up_backlog_threshold=4,
        )
    )
    # tasks parked in node-local dispatch backlogs are queue pressure too
    state.shapes = [
        {"shape": {"CPU": 1.0}, "queued": 1, "leased": 5, "node_backlog": 3}
    ]
    assert auto.update()["launched"] >= 1


def test_scale_down_requires_util_floor_and_empty_backlog():
    cfg = AutoscalerConfig(
        node_types=[NodeType("cpu1", {"CPU": 1}, max_workers=4)],
        idle_timeout_s=0.0,
        scale_down_util_floor=0.1,
        scale_down_cooldown_s=0.0,
    )
    auto, provider, state = _mk(cfg)
    nid = provider.create_node("cpu1", {"CPU": 1})

    # busy node: never terminated
    state.util = {nid: 0.5}
    auto.update()
    assert auto.update()["terminated"] == 0

    # idle node BUT a backlogged shape this node type could serve: kept
    state.util = {nid: 0.0}
    state.shapes = [
        {"shape": {"CPU": 1.0}, "queued": 2, "leased": 0, "node_backlog": 0}
    ]
    auto.update()
    assert nid in [n["node_id"] for n in provider.non_terminated_nodes()]

    # a backlogged shape the node CANNOT serve does not pin it
    state.shapes = [
        {"shape": {"TPU": 4.0}, "queued": 2, "leased": 0, "node_backlog": 0}
    ]
    auto.update()  # records idle
    report = auto.update()
    assert report["terminated"] == 1 or nid in provider.terminated


def test_min_workers_respected_on_scale_down():
    cfg = AutoscalerConfig(
        node_types=[
            NodeType("cpu1", {"CPU": 1}, min_workers=1, max_workers=4)
        ],
        idle_timeout_s=0.0,
        scale_down_cooldown_s=0.0,
    )
    auto, provider, state = _mk(cfg)
    auto.update()  # launches min_workers
    auto.update()
    auto.update()
    assert len(provider.non_terminated_nodes()) == 1


def test_backlog_ramp_up_and_down_without_flapping():
    """Synthetic ramp: backlog appears, fleet scales up; backlog drains,
    the cooldown holds the fleet, then idle-drain shrinks it — with no
    launch/terminate oscillation in between."""
    cfg = AutoscalerConfig(
        node_types=[NodeType("cpu1", {"CPU": 1}, max_workers=4)],
        idle_timeout_s=0.0,
        scale_down_cooldown_s=60.0,
        upscaling_speed=100.0,
    )
    auto, provider, state = _mk(cfg)

    # ramp up
    state.shapes = [
        {"shape": {"CPU": 1.0}, "queued": 3, "leased": 0, "node_backlog": 0}
    ]
    report = auto.update()
    assert report["launched"] == 3
    fleet = {n["node_id"] for n in provider.non_terminated_nodes()}

    # backlog drained, nodes idle — cooldown suppresses the down-swing
    state.shapes = []
    state.util = {nid: 0.0 for nid in fleet}
    for _ in range(3):
        report = auto.update()
        assert report == {"launched": 0, "terminated": 0}
    assert {n["node_id"] for n in provider.non_terminated_nodes()} == fleet

    # cooldown expires -> idle-drain scale-down, once, with no relaunch
    auto._last_scale_up = time.monotonic() - cfg.scale_down_cooldown_s - 1
    report = auto.update()
    assert report["launched"] == 0 and report["terminated"] == 3
    assert provider.non_terminated_nodes() == []
    assert auto.update() == {"launched": 0, "terminated": 0}


def test_pg_pending_bundles_drive_scale_up():
    auto, provider, state = _mk(
        AutoscalerConfig(
            node_types=[NodeType("cpu2", {"CPU": 2}, max_workers=4)],
            upscaling_speed=100.0,
        )
    )
    state.pg_pending = [{"CPU": 2.0}, {"CPU": 2.0}]
    assert auto.update()["launched"] == 2
