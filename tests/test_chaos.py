"""Chaos tests: retry/restart machinery under concurrent fault injection.

Parity: the reference's chaos fixtures (``_ray_start_chaos_cluster``,
``python/ray/tests/conftest.py:900``; killer actors
``python/ray/_private/test_utils.py:1500``) — components die *while* a
workload runs, repeatedly, not once.
"""

import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc

# chaos runs are heavy (continuous kill/respawn churn) and the tier-1
# budget is marginal on slow hosts: the whole module is slow-marked and
# runs via `make chaos` (CHAOS_SEED reproduces a given schedule)
pytestmark = pytest.mark.slow


@pytest.fixture
def chaos_runtime():
    rt = ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield rt
    ray_tpu.shutdown()


def test_tasks_survive_continuous_worker_kills(chaos_runtime):
    """100 retriable tasks all complete while a killer SIGKILLs busy workers
    every 300ms for the duration."""
    from ray_tpu.util.test_utils import WorkerKillerActor

    killer = WorkerKillerActor.options(max_concurrency=2).remote(
        kill_interval_s=0.3, seed=1
    )
    kill_run = killer.run.remote(duration_s=12.0)

    @ray_tpu.remote(max_retries=20)
    def work(i):
        time.sleep(0.15)
        return i * i

    refs = [work.remote(i) for i in range(100)]
    results = ray_tpu.get(refs, timeout=240)
    assert results == [i * i for i in range(100)]
    killed = ray_tpu.get(killer.stop.remote(), timeout=60)
    ray_tpu.get(kill_run, timeout=60)
    assert killed >= 1, "the killer never actually killed a worker"


def test_actor_restart_under_fire(chaos_runtime):
    """A restartable actor keeps serving (with task retries) while its worker
    is killed several times mid-stream."""
    from ray_tpu.util import state as state_api

    @ray_tpu.remote(max_restarts=-1, max_task_retries=-1)
    class Survivor:
        def __init__(self):
            self.calls = 0

        def work(self):
            self.calls += 1
            time.sleep(0.05)
            return "ok"

        def pid(self):
            import os

            return os.getpid()

    s = Survivor.remote()
    assert ray_tpu.get(s.work.remote(), timeout=60) == "ok"
    import os
    import signal

    kills = 0
    deadline = time.monotonic() + 30
    while kills < 3 and time.monotonic() < deadline:
        pid = ray_tpu.get(s.pid.remote(), timeout=60)
        # fire a batch of calls, kill mid-flight
        refs = [s.work.remote() for _ in range(10)]
        try:
            os.kill(pid, signal.SIGKILL)
            kills += 1
        except ProcessLookupError:
            pass
        assert ray_tpu.get(refs, timeout=120) == ["ok"] * 10
    assert kills == 3
    assert ray_tpu.get(s.work.remote(), timeout=60) == "ok"


def test_many_processes_hammer_native_store(chaos_runtime):
    """The shared-memory arena's robust mutex + orphan reclaim hold up under
    concurrent multi-process puts/gets with worker kills mixed in."""
    import numpy as np

    from ray_tpu.util.test_utils import WorkerKillerActor

    killer = WorkerKillerActor.options(max_concurrency=2).remote(
        kill_interval_s=0.5, seed=2
    )
    kill_run = killer.run.remote(duration_s=8.0)

    @ray_tpu.remote(max_retries=20)
    def churn(i):
        arr = np.full(120_000, float(i))  # large: goes through the shm arena
        ref = ray_tpu.put(arr)
        out = ray_tpu.get(ref, timeout=60)
        return float(out.sum())

    refs = [churn.remote(i) for i in range(60)]
    results = ray_tpu.get(refs, timeout=240)
    assert results == [120_000.0 * i for i in range(60)]
    ray_tpu.get(killer.stop.remote(), timeout=60)
    ray_tpu.get(kill_run, timeout=60)
