"""Object store unit tests, run against BOTH backends (file + native arena).

Parity: ``src/ray/object_manager/plasma/test/`` (SURVEY.md §4 tier 1).
"""

import os
import shutil
import uuid

import numpy as np
import pytest

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import ObjectStoreClient


def _file_store(tmp):
    return ObjectStoreClient(str(tmp / "shm"), str(tmp / "fb"), 1 << 24)


def _native_store(tmp):
    from ray_tpu.native import load_native
    from ray_tpu._private.native_store import NativeStoreClient

    lib = load_native()
    if lib is None:
        pytest.skip("native store not built")
    shm_dir = f"/dev/shm/rt_test_{uuid.uuid4().hex[:8]}"
    os.makedirs(shm_dir, exist_ok=True)
    fb = ObjectStoreClient(os.path.join(shm_dir, "files"), str(tmp / "fb"), 1 << 20)
    client = NativeStoreClient(lib, os.path.join(shm_dir, "arena"), fb, 1 << 24)
    client._test_cleanup_dir = shm_dir
    return client


@pytest.fixture(params=["file", "native"])
def store(request, tmp_path):
    client = _file_store(tmp_path) if request.param == "file" else _native_store(tmp_path)
    yield client
    client.close()
    d = getattr(client, "_test_cleanup_dir", None)
    if d:
        shutil.rmtree(d, ignore_errors=True)


def test_put_get_roundtrip(store):
    oid = ObjectID.from_random()
    store.put_bytes(oid, b"hello world")
    assert bytes(store.get(oid, timeout=1)) == b"hello world"


def test_get_missing_times_out(store):
    assert store.get(ObjectID.from_random(), timeout=0.05) is None


def test_unsealed_not_visible(store):
    oid = ObjectID.from_random()
    store.create(oid, 10)
    assert not store.contains(oid)
    assert store.get(oid, timeout=0.05) is None
    store.seal(oid)
    assert store.contains(oid)


def test_duplicate_create_rejected(store):
    oid = ObjectID.from_random()
    store.put_bytes(oid, b"x")
    with pytest.raises(ValueError):
        store.create(oid, 5)


def test_delete_frees(store):
    oid = ObjectID.from_random()
    store.put_bytes(oid, b"y" * 1000)
    store.delete(oid)
    assert not store.contains(oid)


def test_many_objects_reuse(store):
    for _ in range(100):
        oid = ObjectID.from_random()
        store.put_bytes(oid, b"z" * 10_000)
        store.delete(oid)
    # allocator reuses space: usage returns to (near) baseline
    assert store.usage_bytes() < 1 << 22


def test_large_numpy_zero_copy(store):
    oid = ObjectID.from_random()
    arr = np.arange(100_000, dtype=np.float32)
    store.put_bytes(oid, arr.tobytes())
    mv = store.get(oid, timeout=1)
    out = np.frombuffer(mv, dtype=np.float32)
    np.testing.assert_array_equal(arr, out)


def test_put_bytes_idempotent(store):
    # task retries re-store the same deterministic return id
    oid = ObjectID.from_random()
    store.put_bytes(oid, b"first")
    store.put_bytes(oid, b"second")  # must not raise; first copy wins
    assert bytes(store.get(oid, timeout=1)) == b"first"


def test_delete_with_live_view_is_safe(store):
    oid = ObjectID.from_random()
    data = np.arange(5000, dtype=np.float64)
    store.put_bytes(oid, data.tobytes())
    mv = store.get(oid, timeout=1)
    view = np.frombuffer(mv, dtype=np.float64)
    store.delete(oid)
    # churn allocations that would reuse the freed block
    for _ in range(10):
        o = ObjectID.from_random()
        store.put_bytes(o, b"B" * 40_000)
    np.testing.assert_array_equal(view, data)


def test_fragmentation_coalescing(store):
    ids = [ObjectID.from_random() for _ in range(50)]
    for o in ids:
        store.put_bytes(o, b"s" * 50_000)
    for o in ids:
        store.delete(o)
    big = ObjectID.from_random()
    store.put_bytes(big, b"L" * 2_000_000)  # needs coalesced space in arena
    assert store.contains(big)


def test_native_store_lru_eviction(tmp_path):
    """When the arena fills, sealed+unpinned objects evict LRU-first instead
    of failing the create (parity: plasma EvictionPolicy)."""
    from ray_tpu._private.ids import JobID, ObjectID, TaskID
    from ray_tpu._private.native_store import NativeStoreClient, create_store_client

    store = create_store_client(
        str(tmp_path / "shm"), str(tmp_path / "spill"), 8 * 1024 * 1024
    )
    if not isinstance(store, NativeStoreClient):
        import pytest

        pytest.skip("native store unavailable")
    tid = TaskID.for_driver(JobID.from_int(7))
    oids = [ObjectID.for_put(tid, i) for i in range(10)]
    blob = bytes(1024 * 1024)  # 1 MiB each into an ~8 MiB arena
    for i, oid in enumerate(oids):
        store.put_bytes(oid, blob)  # later puts evict-to-disk the oldest
    # every object remains readable: evicted ones were spilled to the file
    # store first (plasma eviction + LocalObjectManager spilling)
    for oid in oids:
        mv = store.get(oid, timeout=5)
        assert mv is not None and mv.nbytes == len(blob)
        store.release(oid)
    # pinned objects are not evictable: pin one, then fill again
    mv = store.get(oids[-1], timeout=1)
    assert mv is not None
    for i in range(10, 18):
        store.put_bytes(ObjectID.for_put(tid, i), blob)
    assert store.contains(oids[-1])  # survived: it was pinned
    store.release(oids[-1])
    store.close()


@pytest.mark.parametrize("variant", ["tsan", "asan"])
def test_store_chaos_sanitized(variant, tmp_path):
    """Build the store chaos driver under TSAN/ASAN and hammer the arena
    from 4 threads (parity: reference .bazelrc sanitizer CI configs)."""
    import subprocess

    native_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "ray_tpu",
        "native",
    )
    build = subprocess.run(
        ["make", "-s", variant], cwd=native_dir, capture_output=True, timeout=180
    )
    if build.returncode != 0:
        err = build.stderr.decode()
        # only a genuinely absent sanitizer runtime is a skip; an ordinary
        # compile error must fail the test, not mask the coverage
        if "unsupported option" in err or "ltsan" in err or "lasan" in err:
            pytest.skip(f"{variant} toolchain unavailable: {err[-200:]}")
        pytest.fail(f"sanitizer build failed:\n{err[-2000:]}")
    arena = str(tmp_path / f"chaos_{variant}")
    run = subprocess.run(
        [os.path.join(native_dir, f"store_chaos_{variant}"), arena, "4", "2000"],
        capture_output=True,
        timeout=300,
    )
    assert run.returncode == 0, run.stderr.decode()[-2000:]
    assert b"WARNING: ThreadSanitizer" not in run.stderr
    assert b"ERROR: AddressSanitizer" not in run.stderr


def test_abort_unsealed_object(store):
    """A failed transfer aborts its creation (plasma Abort): the id becomes
    creatable again instead of wedging every retry."""
    from ray_tpu._private.ids import JobID, TaskID

    tid = TaskID.for_driver(JobID.from_int(9))
    oid = ObjectID.for_put(tid, 0)
    buf = store.create(oid, 128)
    buf[:4] = b"dead"
    assert store.abort(oid)
    assert not store.contains(oid)
    # creatable again, and the normal path still works
    buf = store.create(oid, 64)
    buf[:] = bytes(range(64))
    store.seal(oid)
    mv = store.get(oid, timeout=5)
    assert bytes(mv) == bytes(range(64))
    # aborting a sealed object is refused
    assert not store.abort(oid)


# ---- large-object data path (zero-copy parallel put/get pipeline) ------


def test_large_object_threshold_roundtrip(store):
    """Pattern round-trips at every copy-strategy boundary: below/at the
    slice-assignment cutoff, below/at/above the parallel fan-out threshold
    (the +odd size leaves an uneven tail chunk for the copy pool)."""
    from ray_tpu._private import fastcopy

    sizes = [
        fastcopy._SLICE_MAX - 1,
        fastcopy._SLICE_MAX,
        fastcopy.LARGE_OBJECT_MIN - 1,
        fastcopy.LARGE_OBJECT_MIN,
        fastcopy.LARGE_OBJECT_MIN + 65_537,
    ]
    for size in sizes:
        oid = ObjectID.from_random()
        data = (np.arange(size, dtype=np.uint64) % 251).astype(np.uint8)
        store.put_bytes(oid, data.tobytes())
        mv = store.get(oid, timeout=5)
        assert mv is not None and mv.nbytes == size, size
        np.testing.assert_array_equal(np.frombuffer(mv, dtype=np.uint8), data)
        del mv  # drop the pin so delete reclaims the block immediately
        store.delete(oid)


def test_concurrent_multiclient_puts(tmp_path):
    """Several clients over the same store, putting concurrently: identical
    puts of ONE oid never corrupt (losing a create race may raise ValueError
    while the winner is mid-copy — that is the documented loud path — but
    the sealed object must equal the payload), and puts of DIFFERENT oids
    all land intact."""
    import threading

    from ray_tpu._private.native_store import create_store_client

    shm, fb = str(tmp_path / "shm"), str(tmp_path / "fb")
    clients = [create_store_client(shm, fb, 64 * 1024 * 1024) for _ in range(4)]
    size = 5 * 1024 * 1024  # above the parallel fan-out threshold
    payload = bytes(np.full(size, 0xA7, dtype=np.uint8))
    same = ObjectID.from_random()
    unexpected = []

    def put_same(c):
        try:
            c.put_bytes(same, payload)
        except ValueError:
            pass  # a live creator owned it: loud, but not corruption
        except Exception as e:  # noqa: BLE001
            unexpected.append(e)

    threads = [threading.Thread(target=put_same, args=(c,)) for c in clients]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not unexpected, unexpected
    for c in clients:
        mv = c.get(same, timeout=5)
        assert mv is not None and bytes(mv) == payload
        del mv

    oids = [ObjectID.from_random() for _ in range(len(clients))]
    payloads = [bytes([17 * (i + 1) % 256]) * size for i in range(len(clients))]

    def put_own(c, o, p):
        try:
            c.put_bytes(o, p)
        except Exception as e:  # noqa: BLE001
            unexpected.append(e)

    threads = [
        threading.Thread(target=put_own, args=(c, o, p))
        for c, o, p in zip(clients, oids, payloads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not unexpected, unexpected
    for o, p in zip(oids, payloads):
        mv = clients[0].get(o, timeout=5)
        assert mv is not None and bytes(mv) == p
        del mv
    for c in clients:
        c.close()


def test_spill_restore_chunk_streamed(tmp_path):
    """LRU spill streams the sealed arena buffer chunk-by-chunk to external
    storage (no ``bytes()`` staging copy) and restore streams straight back
    into a fresh arena allocation; a multi-chunk object survives the round
    trip bit-exact."""
    from ray_tpu._private import fastcopy
    from ray_tpu._private.native_store import NativeStoreClient
    from ray_tpu.native import load_native

    lib = load_native()
    if lib is None:
        pytest.skip("native store not built")
    shm_dir = f"/dev/shm/rt_test_{uuid.uuid4().hex[:8]}"
    os.makedirs(shm_dir, exist_ok=True)
    fb = ObjectStoreClient(
        os.path.join(shm_dir, "files"), str(tmp_path / "fb"), 1 << 20
    )
    spill_dir = tmp_path / "ext_spill"
    client = NativeStoreClient(
        lib,
        os.path.join(shm_dir, "arena"),
        fb,
        32 * 1024 * 1024,
        spill_uri=f"file://{spill_dir}",
    )
    client._test_cleanup_dir = shm_dir
    try:
        big = ObjectID.from_random()
        size = 2 * fastcopy.CHUNK_BYTES + 65_537  # 3 chunks, uneven tail
        data = (np.arange(size, dtype=np.uint64) % 249).astype(np.uint8)
        client.put_bytes(big, data.tobytes())
        # fill the arena until the LRU evicts (spills) the big object
        for i in range(8):
            client.put_bytes(ObjectID.from_random(), b"f" * (8 * 1024 * 1024))
            if not lib.rt_store_contains(client._h, big.binary()):
                break
        assert not lib.rt_store_contains(client._h, big.binary())
        assert os.path.exists(spill_dir / f"{big.hex()}.obj")
        assert client.contains(big)  # reachable via the external copy
        mv = client.get(big, timeout=10)  # restore: streamed back in
        assert mv is not None and mv.nbytes == size
        np.testing.assert_array_equal(np.frombuffer(mv, dtype=np.uint8), data)
        del mv
    finally:
        client.close()
        shutil.rmtree(shm_dir, ignore_errors=True)


def test_zero_copy_get_readonly_aliasing(store):
    """get() views are READ-ONLY: neither the raw view nor an array
    deserialized from it can mutate the sealed shared copy, and failed
    mutation attempts leave the object byte-identical."""
    oid = ObjectID.from_random()
    size = 5 * 1024 * 1024  # large path: view aliases the shared map
    src = (np.arange(size, dtype=np.uint64) % 253).astype(np.uint8)
    store.put_bytes(oid, src.tobytes())
    mv = store.get(oid, timeout=5)
    assert mv.readonly
    with pytest.raises(TypeError):
        mv[0] = 1
    arr = np.frombuffer(mv, dtype=np.uint8)
    assert not arr.flags.writeable
    with pytest.raises((ValueError, RuntimeError)):
        arr[0] = 99
    del arr, mv
    again = store.get(oid, timeout=5)
    np.testing.assert_array_equal(np.frombuffer(again, dtype=np.uint8), src)
    del again


def test_spilled_object_reput_then_delete_leaves_no_files():
    """A retried put of a spilled object re-stores into the arena (create is
    the arbiter); delete must purge EVERY tier — arena, shm file, fallback
    file — or the spill copy leaks (round-5 review finding)."""
    import glob
    import os

    import ray_tpu
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.worker import get_runtime

    ray_tpu.init(num_cpus=2, object_store_memory=32 * 1024 * 1024,
                 ignore_reinit_error=True)
    store = get_runtime().store
    if not hasattr(store, "_lib"):
        import pytest as _pytest

        _pytest.skip("native store unavailable")
    data = b"x" * (4 * 1024 * 1024)
    oid = ObjectID(os.urandom(28))
    store.put_bytes(oid, data)
    for _ in range(12):
        store.put_bytes(ObjectID(os.urandom(28)), b"y" * (4 * 1024 * 1024))
    assert not store._lib.rt_store_contains(store._h, oid.binary())
    assert store.contains(oid)  # reachable via the spill copy
    store.put_bytes(oid, data)  # task-retry shape
    assert bytes(store.get(oid, timeout=5)) == data
    store.delete(oid)
    assert not store.contains(oid)
    leaks = [
        p
        for base in (store._fallback._fallback_dir, store._fallback._shm_dir)
        for p in glob.glob(os.path.join(base, "*"))
        if oid.hex() in p
    ]
    assert not leaks, leaks
    ray_tpu.shutdown()
