"""JaxTrainer tests. Parity: ``python/ray/train/tests`` patterns (SURVEY.md §4):
real worker-group actors, gloo-free CPU execution, checkpoint/restore."""

import os

import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (
    Checkpoint,
    CheckpointConfig,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)


def test_single_worker_fit(ray_start_regular, tmp_path):
    def loop(config):
        for i in range(3):
            train.report({"loss": 10.0 - i, "lr": config["lr"]})

    trainer = JaxTrainer(
        loop,
        train_loop_config={"lr": 0.1},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path), name="t1"),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["loss"] == 8.0
    assert result.metrics["training_iteration"] == 3
    assert result.metrics["lr"] == 0.1


def test_multi_worker_context(ray_start_regular, tmp_path):
    def loop():
        ctx = train.get_context()
        train.report({"rank": ctx.get_world_rank(), "world": ctx.get_world_size()})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path), name="t2"),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["rank"] == 0
    assert result.metrics["world"] == 2


def test_checkpoint_reported_and_kept(ray_start_regular, tmp_path):
    def loop():
        import tempfile

        for i in range(4):
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "model.txt"), "w") as fh:
                fh.write(f"iter-{i}")
            train.report({"score": float(i)}, checkpoint=Checkpoint.from_directory(d))

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            storage_path=str(tmp_path),
            name="t3",
            checkpoint_config=CheckpointConfig(num_to_keep=2),
        ),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.checkpoint is not None
    with open(os.path.join(result.checkpoint.path, "model.txt")) as fh:
        assert fh.read() == "iter-3"


def test_failure_restart_from_checkpoint(ray_start_regular, tmp_path):
    marker = str(tmp_path / "fail_once")

    def loop():
        import tempfile

        ckpt = train.get_checkpoint()
        start = 0
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "it.txt")) as fh:
                start = int(fh.read()) + 1
        for i in range(start, 3):
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "it.txt"), "w") as fh:
                fh.write(str(i))
            train.report({"it": float(i)}, checkpoint=Checkpoint.from_directory(d))
            if i == 1 and not os.path.exists(marker):
                open(marker, "w").close()
                raise RuntimeError("injected failure")

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            storage_path=str(tmp_path),
            name="t4",
            failure_config=FailureConfig(max_failures=1),
        ),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["it"] == 2.0  # resumed from it=1 checkpoint, not from 0


def test_worker_error_surfaces(ray_start_regular, tmp_path):
    def loop():
        raise ValueError("bad train fn")

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path), name="t5"),
    )
    result = trainer.fit()
    assert result.error is not None


def test_gang_schedule_too_big_fails_fast(ray_start_regular, tmp_path):
    def loop():
        pass

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(
            num_workers=2, resources_per_worker={"CPU": 100}
        ),
        run_config=RunConfig(storage_path=str(tmp_path), name="t6"),
    )
    result = trainer.fit()
    assert result.error is not None


def test_torch_trainer_ddp_gloo(ray_start_regular, tmp_path):
    """2-worker TorchTrainer: gloo process group via the KV rendezvous, DDP
    grad sync proven by rank-identical weights after divergent data."""
    from ray_tpu.train import RunConfig, ScalingConfig, TorchTrainer

    def train_fn():
        import numpy as np
        import torch
        import torch.nn as nn

        from ray_tpu.train import get_context, prepare_model, report

        ctx = get_context()
        rank = ctx.get_world_rank()
        torch.manual_seed(0)  # same init on both ranks
        model = prepare_model(nn.Linear(4, 1))
        opt = torch.optim.SGD(model.parameters(), lr=0.05)
        rng = np.random.default_rng(rank)  # DIFFERENT data per rank
        w_true = np.array([1.0, -2.0, 3.0, 0.5], dtype=np.float32)
        losses = []
        for step in range(30):
            x = torch.tensor(rng.normal(size=(16, 4)).astype(np.float32))
            y = x @ torch.tensor(w_true)[:, None]
            opt.zero_grad()
            loss = ((model(x) - y) ** 2).mean()
            loss.backward()  # DDP averages grads across ranks here
            opt.step()
            losses.append(float(loss))
        params = torch.nn.utils.parameters_to_vector(model.parameters()).detach()
        # prove DDP actually synchronized: every rank must hold identical
        # weights despite training on different data
        import torch.distributed as dist

        assert dist.is_initialized() and dist.get_world_size() == 2
        pmax = params.clone(); dist.all_reduce(pmax, op=dist.ReduceOp.MAX)
        pmin = params.clone(); dist.all_reduce(pmin, op=dist.ReduceOp.MIN)
        assert torch.allclose(pmax, pmin, atol=1e-6), "ranks diverged: DDP broken"
        report(
            {
                "final_loss": losses[-1],
                "first_loss": losses[0],
                "param_sum": float(params.sum()),
            }
        )

    result = TorchTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path), name="tt"),
    ).fit()
    assert result.error is None
    assert result.metrics["final_loss"] < result.metrics["first_loss"] * 0.2


def test_tensorflow_trainer_multiworker(ray_start_regular, tmp_path):
    """2-worker TensorflowTrainer: TF_CONFIG rendezvous via the cluster KV,
    MultiWorkerMirroredStrategy grad sync proven by rank-identical weights
    after divergent per-rank data."""
    from ray_tpu.train import RunConfig, ScalingConfig, TensorflowTrainer

    def train_fn(config):
        import os

        import numpy as np

        from ray_tpu.train import get_context, report

        os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
        import tensorflow as tf

        ctx = get_context()
        rank = ctx.get_world_rank()
        assert "TF_CONFIG" in os.environ
        strategy = tf.distribute.MultiWorkerMirroredStrategy()
        assert strategy.num_replicas_in_sync == 2
        with strategy.scope():
            w = tf.Variable(tf.zeros((4, 1)), name="w")
            opt = tf.keras.optimizers.SGD(0.05)

        @tf.function
        def train_step(x, y):
            def step_fn(x, y):
                with tf.GradientTape() as tape:
                    loss = tf.reduce_mean((tf.matmul(x, w) - y) ** 2)
                grads = tape.gradient(loss, [w])
                opt.apply_gradients(zip(grads, [w]))  # allreduced here
                return loss

            per = strategy.run(step_fn, args=(x, y))
            return strategy.reduce(tf.distribute.ReduceOp.MEAN, per, axis=None)

        rng = np.random.default_rng(rank)  # DIFFERENT data per rank
        w_true = np.array([1.0, -2.0, 3.0, 0.5], dtype=np.float32)
        loss = None
        for _ in range(20):
            x = tf.constant(rng.normal(size=(16, 4)).astype(np.float32))
            y = tf.matmul(x, tf.constant(w_true[:, None]))
            loss = float(train_step(x, y))
        import json

        weights = [float(v) for v in w.numpy().reshape(-1)]
        with open(config["out_dir"] + f"/rank{rank}.json", "w") as fh:
            json.dump({"weights": weights, "final_loss": loss}, fh)
        report({"final_loss": loss, "rank": rank})

    out_dir = tmp_path / "out"
    out_dir.mkdir()
    result = TensorflowTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path), name="tf_test"),
        train_loop_config={"out_dir": str(out_dir)},
    ).fit()
    assert result.error is None, result.error
    import json as _json

    r0 = _json.load(open(out_dir / "rank0.json"))
    r1 = _json.load(open(out_dir / "rank1.json"))
    # grad allreduce => rank-identical weights despite divergent data
    assert all(abs(a - b) < 1e-5 for a, b in zip(r0["weights"], r1["weights"]))
    assert r0["final_loss"] < 1.0
