"""Pluggable external storage (scheme:// URI API) behind spill, Data IO and
Train checkpoints.

Parity: ``python/ray/_private/external_storage.py`` (spill backends) + the
pyarrow-fs URI resolution of Data/Train storage paths. Tests swap schemes:
``file://`` (cross-process) and ``memory://`` (in-process fake).
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import external_storage as storage
from ray_tpu._private.ids import JobID, ObjectID, TaskID


@pytest.mark.parametrize("scheme", ["file", "memory"])
def test_backend_roundtrip(scheme, tmp_path):
    base = f"{scheme}://{tmp_path}/store" if scheme == "file" else "memory://teststore"
    uri = storage.join(base, "a/b.bin")
    assert not storage.exists(uri)
    storage.write_bytes(uri, b"\x00payload\xff")
    assert storage.exists(uri)
    assert storage.read_bytes(uri) == b"\x00payload\xff"
    storage.write_bytes(storage.join(base, "a/c.bin"), b"2")
    listed = storage.list_uri(base + "/a/")
    assert len(listed) == 2
    assert storage.delete(uri)
    assert not storage.exists(uri)
    assert storage.read_bytes(uri) is None


def test_unknown_scheme_raises():
    with pytest.raises(ValueError):
        storage.resolve("s3-not-registered://bucket/key")


def test_custom_backend_registration(tmp_path):
    calls = []

    class Recording(storage.FileBackend):
        def write_bytes(self, path, data):
            calls.append(path)
            super().write_bytes(path, data)

    storage.register_backend("rec", Recording)
    try:
        storage.write_bytes(f"rec://{tmp_path}/x.bin", b"hi")
        assert calls == [f"{tmp_path}/x.bin"]
        assert storage.read_bytes(f"rec://{tmp_path}/x.bin") == b"hi"
    finally:
        storage._FACTORIES.pop("rec", None)
        storage._BACKENDS.pop("rec", None)


@pytest.mark.parametrize("scheme", ["file", "memory"])
def test_spill_to_external_storage(scheme, tmp_path):
    """Arena eviction spills through the storage API; spilled objects stay
    readable and deletable (parity: spill to external storage + restore)."""
    from ray_tpu._private.native_store import NativeStoreClient, create_store_client

    spill_uri = (
        f"file://{tmp_path}/spill" if scheme == "file" else "memory://spilltest"
    )
    shm = str(tmp_path / "shm")
    store = create_store_client(
        shm, str(tmp_path / "fb"), 8 * 1024 * 1024, spill_uri=spill_uri
    )
    if not isinstance(store, NativeStoreClient):
        pytest.skip("native store unavailable")
    tid = TaskID.for_driver(JobID.from_int(11))
    oids = [ObjectID.for_put(tid, i) for i in range(10)]
    blob = bytes(range(256)) * 4096  # 1 MiB
    for oid in oids:
        store.put_bytes(oid, blob)  # later puts evict the oldest externally
    # something actually spilled through the backend
    spilled = storage.list_uri(spill_uri + "/")
    assert spilled, "nothing spilled externally"
    # every object still readable (arena or external restore)
    for oid in oids:
        mv = store.get(oid, timeout=5)
        assert mv is not None and bytes(mv) == blob
        store.release(oid)
    # delete purges the external copy + marker
    victim = next(
        o for o in oids if os.path.exists(store._spill_marker(o))
    )
    uri = store._external_spilled_uri(victim)
    store.delete(victim)
    assert not storage.exists(uri)
    assert not store.contains(victim)
    store.close()


def test_data_write_read_via_uri(tmp_path):
    """Dataset write/read through scheme'd URIs (worker tasks resolve the
    backend themselves)."""
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    try:
        import ray_tpu.data as rdata

        ds = rdata.from_items([{"v": i} for i in range(20)])
        uri = f"file://{tmp_path}/out"
        paths = ds.write_json(uri)
        assert all(p.startswith("file://") for p in paths)
        assert storage.list_uri(uri + "/")
        back = rdata.read_json(uri)
        got = sorted(r["v"] for r in back.take_all())
        assert got == list(range(20))
    finally:
        ray_tpu.shutdown()


def test_train_checkpoint_to_uri_roundtrip(tmp_path):
    """Checkpoint.to_uri / from_uri through both schemes."""
    from ray_tpu.train import Checkpoint

    src = tmp_path / "ckpt"
    (src / "sub").mkdir(parents=True)
    (src / "weights.bin").write_bytes(b"W" * 1000)
    (src / "sub" / "meta.json").write_text('{"step": 3}')
    for uri in (f"file://{tmp_path}/up", "memory://ckpts/run1"):
        Checkpoint(str(src)).to_uri(uri)
        restored = Checkpoint.from_uri(uri)
        with open(os.path.join(restored.path, "weights.bin"), "rb") as fh:
            assert fh.read() == b"W" * 1000
        with open(os.path.join(restored.path, "sub", "meta.json")) as fh:
            assert fh.read() == '{"step": 3}'


def test_jax_trainer_uploads_checkpoints_to_uri(tmp_path):
    """JaxTrainer(storage_path='memory://...') mirrors every checkpoint out
    through the backend; Checkpoint.from_uri restores it."""
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    try:
        import ray_tpu.train as train
        from ray_tpu.train import Checkpoint, JaxTrainer, RunConfig, ScalingConfig

        def loop(config=None):
            import json
            import os as _os
            import tempfile

            d = tempfile.mkdtemp()
            with open(_os.path.join(d, "state.json"), "w") as fh:
                json.dump({"value": 42}, fh)
            train.report({"loss": 1.0}, checkpoint=Checkpoint(d))

        result = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(name="uri_run", storage_path="memory://results"),
        ).fit()
        assert result.error is None, result.error
        uploaded = storage.list_uri("memory://results/uri_run/")
        assert any("state.json" in u for u in uploaded), uploaded
        restored = Checkpoint.from_uri("memory://results/uri_run/checkpoint_000001")
        import json

        with open(os.path.join(restored.path, "state.json")) as fh:
            assert json.load(fh) == {"value": 42}
    finally:
        ray_tpu.shutdown()
