"""Multi-host plane tests: real node-daemon processes over sockets.

Parity: the reference's multi-node tests run real raylet+GCS processes on one
machine via ``ray.cluster_utils.Cluster`` (``python/ray/cluster_utils.py:135``);
these tests do the same with ``ray_tpu`` node daemons — real processes, real
socket RPC, real inter-node object transfer.
"""

import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def real_cluster():
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    yield cluster
    cluster.shutdown()


def test_daemon_node_registration(real_cluster):
    real_cluster.add_node(num_cpus=2)
    real_cluster.add_node(num_cpus=2)
    real_cluster.add_node(num_cpus=2)
    real_cluster.wait_for_nodes()
    alive = [n for n in ray_tpu.nodes() if n["alive"]]
    assert len(alive) == 4  # head + 3 real daemons


def test_task_spillback_to_daemon_nodes(real_cluster, tmp_path):
    """With the head saturated, tasks spill to daemon nodes (hybrid policy).

    Each task holds its CPU slot until all 5 have started: the cluster has
    1 (head) + 2 + 2 CPUs, so completion is only possible if tasks spilled
    onto both daemon nodes.
    """
    real_cluster.add_node(num_cpus=2)
    real_cluster.add_node(num_cpus=2)
    rendezvous = str(tmp_path / "started")
    os.makedirs(rendezvous, exist_ok=True)

    @ray_tpu.remote
    def hold(i, rendezvous):
        import os
        import time

        open(os.path.join(rendezvous, str(i)), "w").close()
        deadline = time.monotonic() + 60
        while len(os.listdir(rendezvous)) < 5:
            if time.monotonic() > deadline:
                raise TimeoutError("peers never started: no spillback")
            time.sleep(0.02)
        return os.getpid()

    pids = ray_tpu.get([hold.remote(i, rendezvous) for i in range(5)], timeout=120)
    assert len(set(pids)) == 5  # five concurrent slots -> five workers


def test_remote_object_fetched_over_wire(real_cluster):
    node = real_cluster.add_node(num_cpus=1, resources={"far": 1})

    @ray_tpu.remote(resources={"far": 0.1})
    def produce():
        return np.arange(500_000)  # too big to inline: lives in the far store

    arr = ray_tpu.get(produce.remote(), timeout=60)
    assert arr.sum() == sum(range(500_000))


def test_node_to_node_arg_transfer(real_cluster):
    real_cluster.add_node(num_cpus=1, resources={"a": 1})
    real_cluster.add_node(num_cpus=1, resources={"b": 1})

    @ray_tpu.remote(resources={"a": 0.1})
    def produce():
        return np.full(300_000, 3.0)

    @ray_tpu.remote(resources={"b": 0.1})
    def consume(x):
        return float(x.sum())

    assert ray_tpu.get(consume.remote(produce.remote()), timeout=60) == 900_000.0


def test_driver_put_consumed_on_daemon_node(real_cluster):
    real_cluster.add_node(num_cpus=1, resources={"b": 1})

    @ray_tpu.remote(resources={"b": 0.1})
    def consume(x):
        return float(x.sum())

    ref = ray_tpu.put(np.full(250_000, 2.0))
    assert ray_tpu.get(consume.remote(ref), timeout=60) == 500_000.0


def test_actor_on_daemon_node(real_cluster):
    real_cluster.add_node(num_cpus=2, resources={"far": 1})

    @ray_tpu.remote(resources={"far": 0.1})
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert ray_tpu.get([c.inc.remote() for _ in range(3)], timeout=60) == [1, 2, 3]


def test_node_death_task_retry(real_cluster):
    doomed = real_cluster.add_node(num_cpus=2, resources={"doomed": 1})
    real_cluster.add_node(num_cpus=2)

    @ray_tpu.remote(max_retries=2)
    def slow():
        time.sleep(3)
        return "done"

    refs = [slow.remote() for _ in range(4)]
    time.sleep(0.8)
    real_cluster.remove_node(doomed)  # SIGKILL: socket drops, node declared dead
    assert ray_tpu.get(refs, timeout=180) == ["done"] * 4
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        alive = [n for n in ray_tpu.nodes() if n["alive"]]
        if len(alive) == 2:
            break
        time.sleep(0.2)
    assert len(alive) == 2


def test_actor_restart_after_node_death(real_cluster):
    doomed = real_cluster.add_node(num_cpus=2, resources={"doomed": 1})

    @ray_tpu.remote(max_restarts=1, max_task_retries=1, resources={"doomed": 0.1})
    class Sticky:
        def ping(self):
            return "pong"

    # schedulable only on the doomed node first; after its death the actor
    # becomes infeasible, so give the restart somewhere to go
    s = Sticky.remote()
    assert ray_tpu.get(s.ping.remote(), timeout=60) == "pong"
    real_cluster.add_node(num_cpus=2, resources={"doomed": 1})
    real_cluster.remove_node(doomed)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            assert ray_tpu.get(s.ping.remote(), timeout=30) == "pong"
            break
        except ray_tpu.exceptions.ActorDiedError:
            pytest.fail("actor died despite max_restarts")
    else:
        pytest.fail("actor did not come back")


def test_remote_driver_connect(real_cluster):
    real_cluster.add_node(num_cpus=2, resources={"r1": 1})
    host, port = real_cluster.address
    from ray_tpu._private.worker import get_driver

    script = textwrap.dedent(
        f"""
        import numpy as np
        import ray_tpu
        ray_tpu.init(address="{host}:{port}")

        @ray_tpu.remote
        def f(x):
            return x * 2

        assert ray_tpu.get(f.remote(21), timeout=60) == 42

        @ray_tpu.remote(resources={{"r1": 0.1}})
        def big():
            return np.ones(200_000)

        assert ray_tpu.get(big.remote(), timeout=60).sum() == 200_000
        ray_tpu.shutdown()
        print("REMOTE-DRIVER-OK")
        """
    )
    env = dict(os.environ)
    env["RAY_TPU_AUTH"] = get_driver().config.cluster_auth_key
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
        + os.pathsep
        + env.get("PYTHONPATH", "")
    )
    r = subprocess.run(
        [sys.executable, "-c", script],
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "REMOTE-DRIVER-OK" in r.stdout

    # the cluster survives the driver's disconnect
    @ray_tpu.remote
    def still_alive():
        return 1

    assert ray_tpu.get(still_alive.remote(), timeout=60) == 1


def _wait_task_finished(name, timeout=30):
    from ray_tpu.util import state as state_api

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        rows = [t for t in state_api.list_tasks() if t["name"] == name]
        if rows and all(t["state"] == "FINISHED" for t in rows):
            return
        time.sleep(0.05)
    raise TimeoutError(f"task {name} never finished")


def test_lineage_reconstruction_after_node_loss(real_cluster):
    """Kill the node holding the only copy -> get() succeeds via re-execution.

    Parity: ObjectRecoveryManager (object_recovery_manager.h:70-84)."""
    doomed = real_cluster.add_node(num_cpus=2, resources={"doomed": 1})

    @ray_tpu.remote(resources={"doomed": 0.1}, max_retries=2)
    def produce():
        return np.arange(300_000)

    ref = produce.remote()
    _wait_task_finished("produce")
    # second node so the re-execution has somewhere feasible to run
    real_cluster.add_node(num_cpus=2, resources={"doomed": 1})
    real_cluster.remove_node(doomed)  # the only copy dies with the node
    arr = ray_tpu.get(ref, timeout=90)
    assert arr.sum() == sum(range(300_000))


def test_recursive_lineage_reconstruction(real_cluster):
    """A lost object whose lost arg must also be reconstructed."""
    doomed = real_cluster.add_node(num_cpus=2, resources={"doomed": 1})

    @ray_tpu.remote(resources={"doomed": 0.1}, max_retries=2)
    def produce():
        return np.ones(300_000)

    @ray_tpu.remote(resources={"doomed": 0.1}, max_retries=2)
    def double(x):
        return x * 2

    a = produce.remote()
    b = double.remote(a)
    _wait_task_finished("double")
    real_cluster.add_node(num_cpus=2, resources={"doomed": 1})
    real_cluster.remove_node(doomed)
    out = ray_tpu.get(b, timeout=120)
    assert float(out.sum()) == 600_000.0


def test_put_object_lost_is_terminal(real_cluster):
    """Driver puts have no lineage: loss surfaces as ObjectLostError —
    but only for copies that actually lived on the dead node."""
    doomed = real_cluster.add_node(num_cpus=2, resources={"doomed": 1})

    @ray_tpu.remote(resources={"doomed": 0.1}, max_retries=2)
    def produce_put():
        import ray_tpu as rt

        return rt.put(np.ones(200_000))  # put lives in the doomed node store

    inner_ref = ray_tpu.get(produce_put.remote(), timeout=60)
    _wait_task_finished("produce_put")
    real_cluster.remove_node(doomed)
    time.sleep(0.3)
    with pytest.raises(ray_tpu.exceptions.ObjectLostError):
        ray_tpu.get(inner_ref, timeout=20)


def test_cross_machine_remote_driver(real_cluster):
    """A driver that cannot see the head's shm (simulated via
    RAY_TPU_FORCE_REMOTE_CLIENT) works through the object plane: puts ride
    the control socket into the head store, gets pull from the head's object
    server (Ray-Client parity, util/client/ARCHITECTURE.md)."""
    real_cluster.add_node(num_cpus=2, resources={"rc": 1})
    host, port = real_cluster.address
    from ray_tpu._private.worker import get_driver

    script = textwrap.dedent(
        f"""
        import numpy as np
        import ray_tpu
        ray_tpu.init(address="{host}:{port}")
        from ray_tpu._private.worker import get_driver
        assert get_driver()._cross_machine

        @ray_tpu.remote(resources={{"rc": 0.1}})
        def consume(x):
            return float(x.sum())

        # upload path: driver put -> head store -> remote node
        ref = ray_tpu.put(np.full(300_000, 2.0))
        assert ray_tpu.get(consume.remote(ref), timeout=90) == 600_000.0

        # download path: big result produced on the far node -> driver
        @ray_tpu.remote(resources={{"rc": 0.1}})
        def produce():
            return np.arange(250_000)

        arr = ray_tpu.get(produce.remote(), timeout=90)
        assert arr.sum() == sum(range(250_000))
        ray_tpu.shutdown()
        print("CROSS-MACHINE-OK")
        """
    )
    env = dict(os.environ)
    env["RAY_TPU_AUTH"] = get_driver().config.cluster_auth_key
    env["RAY_TPU_FORCE_REMOTE_CLIENT"] = "1"
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
        + os.pathsep
        + env.get("PYTHONPATH", "")
    )
    r = subprocess.run(
        [sys.executable, "-c", script],
        env=env,
        capture_output=True,
        text=True,
        timeout=180,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "CROSS-MACHINE-OK" in r.stdout


def test_node_churn_under_load(real_cluster):
    """Chaos: nodes join and are SIGKILLed repeatedly while a retriable task
    load runs; every task must eventually complete (parity: the reference's
    node-killer chaos, test_utils.py NodeKillerBase)."""

    @ray_tpu.remote(max_retries=8, resources={"churn": 0.1})
    def work(i):
        time.sleep(0.05)
        return i

    nodes = [real_cluster.add_node(num_cpus=2, resources={"churn": 4})]
    real_cluster.wait_for_nodes()
    refs = [work.remote(i) for i in range(60)]
    for cycle in range(2):
        time.sleep(1.0)
        # kill the newest node mid-load, then replace it
        real_cluster.remove_node(nodes.pop())
        nodes.append(real_cluster.add_node(num_cpus=2, resources={"churn": 4}))
    out = ray_tpu.get(refs, timeout=180)
    assert sorted(out) == list(range(60))


def test_daemon_stack_dump(real_cluster):
    """Per-daemon thread-stack dumps (dashboard /api/stacks plumbing; the
    reporter-agent py-spy role, reporter_agent.py:314)."""
    from ray_tpu._private.worker import get_driver

    real_cluster.add_node(num_cpus=1)
    real_cluster.wait_for_nodes()
    stacks = get_driver().node.scheduler.request_node_stacks(timeout=30)
    assert len(stacks) == 1
    text = next(iter(stacks.values()))
    assert "thread" in text and "MainThread" in text
