"""Control-plane observability tests (fast tier-1).

Covers: the actor-launch lifecycle decomposition (creation-trace stage sum
vs the submit→ready wall, the placement/worker_spawn split replacing the
coarse queue_wait, worker-reported runtime_env/actor_class_load stages and
boot-stage telemetry), `state.list_actors` lifecycle rows + the pending
stage view, the launch-profile aggregate, the decision flight recorder
(bounds/eviction, placement records, autoscaler records explaining a
seeded backlog ramp), spawn-failure forensics (typed WORKER_SPAWN_FAILED
+ fast fail with provenance), the ACTOR_LAUNCH_STALLED watchdog
(seeded positive + calm silence), worker-pool metric series, and a
regression guard that PR-11 call traces are unchanged.
"""

import time

import pytest

import ray_tpu
from ray_tpu.util import state


def _sch():
    from ray_tpu._private.worker import get_runtime

    return get_runtime().node.scheduler


def _events_of(etype, timeout=0.0):
    deadline = time.monotonic() + timeout
    while True:
        evs = [
            e
            for e in state.list_cluster_events()
            if e.get("type") == etype
        ]
        if evs or time.monotonic() >= deadline:
            return evs
        time.sleep(0.25)


@pytest.fixture
def launch_runtime():
    rt = ray_tpu.init(
        num_cpus=4,
        ignore_reinit_error=True,
        _system_config={
            "actor_launch_warn_s": 1.0,
            "decision_log_max": 8,
        },
    )
    yield rt
    ray_tpu.shutdown()


class _Probe:
    def __init__(self):
        self.ready = True

    def ping(self):
        return self.ready


# ---------------------------------------------------------------------------
# lifecycle decomposition
# ---------------------------------------------------------------------------


def test_creation_trace_stage_sum_vs_wall(launch_runtime):
    """ray_tpu.trace on an Actor.remote() shows the creation span with the
    placement/worker_spawn split swapped in for queue_wait, and the stage
    sum stays within 10% of the span's submit→ready wall (acceptance)."""
    Probe = ray_tpu.remote(_Probe)
    h = Probe.remote()
    assert ray_tpu.get(h.ping.remote(), timeout=60)

    rows = [
        r for r in state.list_actors() if r.get("class_name") == "_Probe"
    ]
    assert rows and rows[0]["trace_id"], "creation carries no trace id"
    # worker-side stages (actor_class_load) lag one telemetry flush
    state.launch_profile()
    tr = ray_tpu.trace(rows[0]["trace_id"])
    creation = next(
        s for s in tr.spans.values() if "__init__" in (s.name or "")
    )
    bd = creation.stage_breakdown()
    # the scheduler's finer cut replaces the coarse gap
    assert "placement_ms" in bd and "worker_spawn_ms" in bd
    assert "queue_wait_ms" not in bd
    assert "actor_class_load_ms" in bd
    wall = creation.duration_ms
    assert wall and wall > 0
    sum_ms = sum(bd.values())
    assert abs(sum_ms - wall) <= 0.10 * wall, (bd, wall)


def test_lifecycle_ms_partitions_wall(launch_runtime):
    """The settled lifecycle_ms decomposition exactly partitions total_ms
    (submit + placement + worker_spawn + execute), and total_ms stays
    within the driver-observed Actor.remote()→ready wall."""
    Probe = ray_tpu.remote(_Probe)
    t0 = time.perf_counter()
    h = Probe.remote()
    assert ray_tpu.get(h.ping.remote(), timeout=60)
    driver_wall_ms = (time.perf_counter() - t0) * 1e3

    row = next(
        r for r in state.list_actors() if r.get("class_name") == "_Probe"
    )
    assert row["launch_stage"] == "ready"
    lc = row["lifecycle_ms"]
    head_stages = ("submit_ms", "placement_ms", "worker_spawn_ms", "execute_ms")
    assert all(k in lc for k in head_stages), lc
    part = sum(lc[k] for k in head_stages)
    assert abs(part - lc["total_ms"]) <= max(1.0, 0.01 * lc["total_ms"])
    # submit→ready wall is inside the driver's remote()→get() wall
    assert lc["total_ms"] <= driver_wall_ms + 5.0
    # ordered wall-clock stamps for every stage crossed
    ts = row["stage_ts"]
    order = ["submitted", "placing", "executing", "ready"]
    stamps = [ts[s] for s in order if s in ts]
    assert stamps == sorted(stamps) and len(stamps) >= 3
    # first settled method call lands on the head a beat after get()
    deadline = time.monotonic() + 10
    fmts = row["first_method_ts"]
    while fmts is None and time.monotonic() < deadline:
        time.sleep(0.2)
        state.launch_profile()  # forces a cluster-wide telemetry flush
        fmts = next(
            r
            for r in state.list_actors()
            if r.get("class_name") == "_Probe"
        )["first_method_ts"]
    assert fmts is not None


def test_launch_profile_and_boot_stages(launch_runtime):
    """launch_profile aggregates per-stage stats over settled creations and
    carries the worker boot-stage split riding the ready ack."""
    Probe = ray_tpu.remote(_Probe)
    hs = [Probe.remote() for _ in range(3)]
    ray_tpu.get([h.ping.remote() for h in hs], timeout=60)
    prof = state.launch_profile()
    assert prof["launched_total"] >= 3
    assert prof["window"] >= 3
    for stage in ("placement_ms", "execute_ms"):
        assert prof["stages"][stage]["count"] >= 3
        assert prof["stages"][stage]["p95_ms"] >= prof["stages"][stage]["p50_ms"]
    # worker-side creation stages late-merged through telemetry
    assert "actor_class_load_ms" in prof["stages"]
    # boot split: import / store_connect / runtime_init / serve_bind
    boot = prof["worker_boot_stage_seconds"]
    assert set(boot) >= {"import_ms", "store_connect_ms", "runtime_init_ms"}
    assert all(v >= 0 for v in boot.values())
    recent = prof["recent"]
    assert recent and all("stages" in r and "trace" in r for r in recent)


def test_pending_actor_shows_blocked_stage(launch_runtime):
    """A creation that cannot place stays PENDING in launch_stage=placing
    with a wall-clock stamp — the `ray_tpu actors --pending` feed."""
    Probe = ray_tpu.remote(_Probe)
    h = Probe.options(resources={"nonexistent_resource": 1}).remote()
    time.sleep(0.3)
    row = next(
        r
        for r in state.list_actors()
        if r.get("class_name") == "_Probe" and r["state"] == "PENDING"
    )
    assert row["launch_stage"] == "placing"
    assert "placing" in row["stage_ts"]
    assert row["lifecycle_ms"] == {}  # not settled
    ray_tpu.kill(h)


# ---------------------------------------------------------------------------
# decision flight recorder
# ---------------------------------------------------------------------------


def test_placement_decision_recorded_for_creation(launch_runtime):
    Probe = ray_tpu.remote(_Probe)
    h = Probe.remote()
    ray_tpu.get(h.ping.remote(), timeout=60)
    decs = state.list_decisions(kind="placement")
    assert decs, "no placement decision recorded"
    d = decs[-1]
    assert d["reason"] in ("idle_worker", "spawned_worker")
    assert d["node"] and d["queue_wait_ms"] >= 0
    assert d["trace"], "placement decision lost the creation's trace id"


def test_decision_ring_bounds_and_eviction(launch_runtime):
    """The recorder is a bounded ring (decision_log_max=8 here): old rows
    evict, seq keeps increasing, and the kind filter runs server-side."""
    from ray_tpu._private.worker import get_driver

    drv = get_driver()
    for i in range(20):
        drv.rpc("record_decision", {"kind": "autoscaler", "i": i})
    rows = state.list_decisions(kind="autoscaler")
    assert len(rows) <= 8
    assert [r["i"] for r in rows] == list(range(12, 20))
    seqs = [r["seq"] for r in rows]
    assert seqs == sorted(seqs)
    assert all(r["kind"] == "autoscaler" for r in rows)
    # limit applies after the kind filter, keeping the newest rows
    assert [r["i"] for r in state.list_decisions(kind="autoscaler", limit=3)] == [
        17,
        18,
        19,
    ]


def test_autoscaler_decisions_explain_backlog_ramp():
    """A seeded backlog ramp: scale-up and the later idle scale-down are
    each attributed to a recorded autoscaler decision (acceptance)."""
    from ray_tpu.autoscaler import (
        Autoscaler,
        AutoscalerConfig,
        ClusterStateSource,
        NodeProvider,
        NodeType,
    )

    class MockProvider(NodeProvider):
        def __init__(self):
            self._nodes = {}
            self._seq = 0

        def create_node(self, node_type, resources):
            self._seq += 1
            nid = f"n{self._seq}"
            self._nodes[nid] = {
                "node_id": nid,
                "node_type": node_type,
                "resources": dict(resources),
                "launched_at": time.time(),
            }
            return nid

        def terminate_node(self, node_id):
            self._nodes.pop(node_id, None)

        def non_terminated_nodes(self):
            return list(self._nodes.values())

    class FakeState(ClusterStateSource):
        def __init__(self):
            self.shapes = []
            self.util = {}
            self.decisions = []

        def backlog(self):
            return {"shapes": self.shapes, "pg_pending": []}

        def utilization(self):
            return dict(self.util)

        def record_decision(self, dec):
            self.decisions.append(dec)

    st = FakeState()
    asc = Autoscaler(
        AutoscalerConfig(
            node_types=[NodeType("cpu_4", {"CPU": 4}, max_workers=4)],
            idle_timeout_s=0.0,
            scale_down_cooldown_s=0.0,
            upscaling_speed=100.0,
        ),
        MockProvider(),
        state=st,
    )
    # ramp up: 8 queued 1-CPU tasks -> 2 nodes
    st.shapes = [{"shape": {"CPU": 1}, "queued": 8, "leased": 0,
                  "node_backlog": 0}]
    asc.update()
    up = [d for d in st.decisions if d["launched"] > 0]
    assert up and up[-1]["kind"] == "autoscaler"
    assert "backlog_demand" in up[-1]["reasons"]
    assert up[-1]["demand"] == 8 and up[-1]["to_launch"] == {"cpu_4": 2}
    # ramp down: backlog gone, nodes idle -> terminate, attributed
    st.shapes = []
    st.util = {n["node_id"]: 0.0 for n in asc.provider.non_terminated_nodes()}
    asc.update()  # marks idle_since
    asc.update()  # drains (idle_timeout_s=0)
    down = [d for d in st.decisions if d["terminated"] > 0]
    assert down and "idle_timeout" in down[-1]["reasons"]
    # a pure no-op pass records nothing
    n = len(st.decisions)
    asc.update()
    assert len(st.decisions) == n


# ---------------------------------------------------------------------------
# spawn-failure forensics
# ---------------------------------------------------------------------------


def test_runtime_env_failure_fails_creation_fast_with_event(launch_runtime):
    """A creation whose runtime_env apply fails surfaces as a fast typed
    error AND a WORKER_SPAWN_FAILED cluster event with the exception
    chained (not a hung lease)."""
    Probe = ray_tpu.remote(_Probe)
    h = Probe.options(
        runtime_env={"working_dir_uri": "deadbeef-no-such-package"}
    ).remote()
    with pytest.raises(Exception) as ei:
        ray_tpu.get(h.ping.remote(), timeout=30)
    assert "runtime" in str(ei.value).lower() or "deadbeef" in str(ei.value)
    evs = _events_of("WORKER_SPAWN_FAILED", timeout=10.0)
    assert evs, "no WORKER_SPAWN_FAILED event for runtime_env failure"
    ev = evs[-1]
    assert ev["severity"] == "ERROR"
    assert ev.get("stderr_tail"), "event lost the exception provenance"
    row = next(
        r for r in state.list_actors() if r.get("class_name") == "_Probe"
    )
    assert row["state"] == "DEAD" and row["launch_stage"] == "dead"


def test_spawn_failure_streak_and_fail_fast(launch_runtime):
    """Worker deaths before the ready ack emit typed WORKER_SPAWN_FAILED
    events with a consecutive-failure streak, and crossing the threshold
    fails creations parked in the spawning stage with that provenance."""
    from ray_tpu import exceptions as exc
    from ray_tpu._private.scheduler import WorkerState
    from ray_tpu._private.ids import WorkerID

    sch = _sch()
    time.sleep(0.5)  # let the initial worker pool settle (clears streaks)
    node_id = next(iter(sch.nodes))
    # park a creation in the spawning stage: unplaceable keeps it PENDING,
    # the stage flip mimics a dispatch that found the node but no worker
    Probe = ray_tpu.remote(_Probe)
    h = Probe.options(resources={"nonexistent_resource": 1}).remote()
    pending = h._actor_id
    time.sleep(0.3)
    actor = sch.actors[pending]
    assert actor.state == "PENDING"
    actor.launch_stage = "spawning"
    actor.stage_ts["spawning"] = time.time()

    threshold = int(sch.config.spawn_fail_fast_threshold)
    for i in range(threshold):
        w = WorkerState(
            worker_id=WorkerID.from_random(),
            conn=None,
            proc=None,
            node_id=node_id,
        )
        sch._note_spawn_failure(w, w.worker_id, None)
    evs = _events_of("WORKER_SPAWN_FAILED", timeout=5.0)
    assert len(evs) >= threshold
    streaks = [e["consecutive_failures"] for e in evs[-threshold:]]
    assert streaks == list(range(1, threshold + 1))
    # fail-fast: the parked creation died with the provenance chained
    with pytest.raises(exc.ActorDiedError) as ei:
        ray_tpu.get(h.ping.remote(), timeout=10)
    assert "consecutive worker spawn failures" in str(ei.value)
    assert sch.actors[pending].state == "DEAD"
    # the node is not poisoned: later launches still succeed (an idle
    # worker serves them; only a successful SPAWN resets the streak)
    h2 = ray_tpu.remote(_Probe).remote()
    assert ray_tpu.get(h2.ping.remote(), timeout=60)


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------


def test_launch_stalled_watchdog_seeded_and_calm(launch_runtime):
    """A creation stuck past actor_launch_warn_s (=1s here) fires exactly
    one ACTOR_LAUNCH_STALLED per (actor, stage) with stage + trace id;
    normal launches stay silent (calm-silence)."""
    Probe = ray_tpu.remote(_Probe)
    # calm: healthy creations never flag
    ok = Probe.remote()
    ray_tpu.get(ok.ping.remote(), timeout=60)
    # seeded: unplaceable creation parks in 'placing'
    h = Probe.options(resources={"nonexistent_resource": 1}).remote()
    evs = _events_of("ACTOR_LAUNCH_STALLED", timeout=10.0)
    assert evs, "launch watchdog never fired"
    ev = evs[0]
    assert ev["severity"] == "WARNING"
    assert ev["stage"] == "placing"
    assert ev["stalled_s"] >= 1.0
    assert ev["trace_id"]
    # dedup: one flag per (actor, stage)
    time.sleep(2.5)
    assert len(_events_of("ACTOR_LAUNCH_STALLED")) == 1
    # the healthy actor was never flagged
    assert all(
        e["actor_id"] != ok._actor_id.hex()
        for e in _events_of("ACTOR_LAUNCH_STALLED")
    )
    ray_tpu.kill(h)


# ---------------------------------------------------------------------------
# worker-pool telemetry + metric series
# ---------------------------------------------------------------------------


def test_worker_pool_and_launch_metric_series(launch_runtime):
    """The new ray_tpu_* series are live: spawn histogram counts real
    spawns, launch counters/stage-seconds accumulate, pool gauges track
    worker states."""
    Probe = ray_tpu.remote(_Probe)
    h = Probe.remote()
    ray_tpu.get(h.ping.remote(), timeout=60)
    state.launch_profile()  # flush worker-side stages
    from ray_tpu._private.worker import get_driver

    series = {s["name"]: s for s in get_driver().rpc("runtime_metrics")}
    spawns = series["ray_tpu_worker_spawns_total"]["data"]
    assert sum(v for v in spawns.values()) >= 1
    hist = next(iter(series["ray_tpu_worker_spawn_seconds"]["data"].values()))
    assert hist["count"] >= 1 and len(hist["buckets"]) == len(hist["boundaries"]) + 1
    assert (
        sum(series["ray_tpu_actor_launches_total"]["data"].values()) >= 1
    )
    stage_secs = series["ray_tpu_actor_launch_stage_seconds_total"]["data"]
    assert any("worker_spawn" in k or "execute" in k for k in stage_secs)
    boot_secs = series["ray_tpu_worker_boot_stage_seconds_total"]["data"]
    assert any("import" in k for k in boot_secs)
    pool = series["ray_tpu_worker_pool"]["data"]
    assert sum(pool.values()) >= 1
    assert "ray_tpu_decisions_total" in series
    # and they reach the Prometheus exposition
    from ray_tpu.util.metrics import prometheus_text

    text = prometheus_text()
    assert "ray_tpu_actor_launches_total" in text
    assert "ray_tpu_worker_spawn_seconds" in text


def test_prestart_accounting_on_lease_path(tmp_path):
    """Daemon lease dispatch counts prestart hits (idle worker reused) vs
    misses (spawn forced), riding heartbeats into head-side series."""
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 0})
    try:
        c.add_node(num_cpus=2)
        c.wait_for_nodes()

        @ray_tpu.remote
        def one():
            return 1

        # first wave forces spawns (misses); second wave reuses idle
        # workers (hits)
        assert ray_tpu.get([one.remote() for _ in range(4)], timeout=120) == [1] * 4
        time.sleep(1.0)
        assert ray_tpu.get([one.remote() for _ in range(4)], timeout=120) == [1] * 4
        deadline = time.monotonic() + 10
        prestart = {}
        while time.monotonic() < deadline:
            from ray_tpu._private.worker import get_driver

            series = {
                s["name"]: s for s in get_driver().rpc("runtime_metrics")
            }
            prestart = series["ray_tpu_prestart_total"]["data"]
            if any("hit" in k for k in prestart) and any(
                "miss" in k for k in prestart
            ):
                break
            time.sleep(0.5)
        hits = sum(v for k, v in prestart.items() if "hit" in k)
        misses = sum(v for k, v in prestart.items() if "miss" in k)
        assert misses >= 1, prestart
        assert hits >= 1, prestart
        # lease pool gauges rode the same heartbeat
        assert "ray_tpu_lease_pool" in series
    finally:
        c.shutdown()


# ---------------------------------------------------------------------------
# regression guard: PR-11 call traces unchanged
# ---------------------------------------------------------------------------


def test_plain_task_trace_unchanged(launch_runtime):
    """Non-creation spans keep the PR-11 decomposition: queue_wait stays
    (no placement/worker_spawn split), measured worker stages present,
    stage sum within 10% of the span wall."""
    @ray_tpu.remote
    def work():
        time.sleep(0.05)
        return 7

    ref = work.remote()
    assert ray_tpu.get(ref, timeout=60) == 7
    tid = None
    for _ in range(20):
        tid = next(
            (
                t["trace_id"]
                for t in ray_tpu.recent_traces(limit=50)
                if t["root"] == "work"
            ),
            None,
        )
        if tid:
            break
        time.sleep(0.25)
    assert tid, "plain task minted no trace"
    tr = ray_tpu.trace(tid)
    span = next(s for s in tr.spans.values() if s.name == "work")
    bd = span.stage_breakdown()
    assert "queue_wait_ms" in bd
    assert "placement_ms" not in bd and "worker_spawn_ms" not in bd
    assert "execute_ms" in bd
    wall = span.duration_ms
    assert abs(sum(bd.values()) - wall) <= 0.10 * wall, (bd, wall)


# ---------------------------------------------------------------------------
# CLI surfaces
# ---------------------------------------------------------------------------


def test_actors_and_decisions_cli(launch_runtime, capsys):
    from ray_tpu.scripts.cli import main

    Probe = ray_tpu.remote(_Probe)
    h = Probe.remote()
    ray_tpu.get(h.ping.remote(), timeout=60)
    main(["actors", "launch-profile"])
    out = capsys.readouterr().out
    assert "actor launches:" in out and "worker_spawn" in out
    main(["actors"])
    out = capsys.readouterr().out
    assert "stage=ready" in out
    stuck = Probe.options(resources={"nonexistent_resource": 1}).remote()
    time.sleep(0.3)
    main(["actors", "--pending"])
    out = capsys.readouterr().out
    assert "stage=placing" in out and "blocked" in out
    main(["decisions", "--kind", "placement"])
    out = capsys.readouterr().out
    assert "placement" in out and "reason=" in out
    ray_tpu.kill(stuck)
