"""Alerting & incident-forensics plane tests (fast tier-1).

Covers: the shared ``EventDeduper`` gate semantics + bounds (the unified
replacement for the watchdogs' hand-rolled stamp dicts), SLO spec
validation and burn-rate math, the incident lifecycle (watchdog trigger →
open → merge → quiet-close with duration + verdict), WORKER_DIED burst
gating (single deaths are churn; a storm is ONE incident), the SLO
breach → incident path on a live cluster, the cross-plane digest and
the shape contracts it joins (memory snapshot, link rows, launch ring,
decision log), the `after_event_id`/`since_ts` server-side event cursor,
the `ray_tpu doctor` / `ray_tpu incidents` / `ray_tpu events --since`
CLI surfaces, the dashboard `/api/incidents` + `/api/doctor` endpoints,
and the new ``ray_tpu_incidents_*`` / ``ray_tpu_slo_*`` metric series.
"""

import json
import time

import pytest

import ray_tpu
from ray_tpu._private.ids import NodeID, ObjectID
from ray_tpu._private.incidents import SLOSpec, _SLOState, _hist_p99
from ray_tpu._private.telemetry import EventDeduper
from ray_tpu.util import state


def _sch():
    from ray_tpu._private.worker import get_runtime

    return get_runtime().node.scheduler


def _mgr():
    return _sch()._incident_mgr


@pytest.fixture
def incident_cluster():
    """Two-cpu cluster with a tight quiet-close so lifecycle tests don't
    wait out the production 120s window."""
    rt = ray_tpu.init(
        num_cpus=2,
        _system_config={
            "incident_quiet_close_s": 2.0,
            "incident_event_window_s": 60.0,
        },
    )
    yield rt
    ray_tpu.shutdown()


def _wait(pred, timeout=30.0, interval=0.2, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = pred()
        if got:
            return got
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# EventDeduper: the unified watchdog gate
# ---------------------------------------------------------------------------


def test_deduper_fire_once_semantics():
    """rearm_s=None keys fire exactly once, ever (the straggler/launch
    per-(subject, attempt) rule)."""
    d = EventDeduper(rearm_s=None, max_keys=8)
    assert d.should_fire("k")
    assert not d.should_fire("k")
    assert not d.should_fire("k", now=1e9)  # no rearm, no matter how late
    assert "k" in d and len(d) == 1
    d.discard("k")
    assert "k" not in d and d.should_fire("k")


def test_deduper_rearm_window():
    d = EventDeduper(rearm_s=10.0)
    assert d.should_fire("k", now=100.0)
    assert not d.should_fire("k", now=105.0)  # inside the window
    assert d.should_fire("k", now=110.5)  # re-armed
    assert not d.should_fire("k", now=111.0)  # stamp refreshed on re-fire


def test_deduper_mark_check_split():
    """`in` + `mark` is the check-early/stamp-on-emit split the straggler
    scan uses — membership alone must not stamp."""
    d = EventDeduper(rearm_s=None)
    assert "k" not in d
    assert "k" not in d  # repeated checks don't create state
    d.mark("k", now=1.0)
    assert "k" in d


def test_deduper_eviction_bounds_adversarial_keys():
    """mark past max_keys evicts the OLDEST stamp, so an unbounded key
    stream (e.g. ever-new callsites) cannot grow the table."""
    d = EventDeduper(rearm_s=None, max_keys=4)
    for i in range(4):
        d.mark(i, now=float(i))
    d.mark(99, now=99.0)
    assert len(d) == 4
    assert 0 not in d  # oldest evicted
    assert 99 in d and 3 in d
    # a re-mark refreshes the stamp: key 1 moves to newest, key 2 becomes
    # the eviction victim
    d.mark(1, now=100.0)
    d.mark(100, now=101.0)
    assert 2 not in d and 1 in d


def test_deduper_prune_liveness_and_staleness():
    d = EventDeduper(rearm_s=None, max_keys=64)
    for i in range(6):
        d.mark(i, now=float(i))
    # keep-rule prune: drop settled subjects (odd keys), regardless of age
    dropped = d.prune(keep=lambda k: k % 2 == 0, now=100.0)
    assert dropped == 3 and sorted(d._stamps) == [0, 2, 4]
    # stale_s guard: young stamps for absent subjects survive the sweep
    d.mark(7, now=99.9)
    dropped = d.prune(keep=lambda k: False, stale_s=50.0, now=100.0)
    assert dropped == 3 and 7 in d and len(d) == 1
    # over= threshold: sweep skipped entirely below the size floor
    assert d.prune(keep=lambda k: False, now=200.0, over=10) == 0
    assert 7 in d


# ---------------------------------------------------------------------------
# SLO spec + burn math
# ---------------------------------------------------------------------------


def test_slospec_validation():
    with pytest.raises(ValueError, match="needs a name"):
        SLOSpec.from_dict({"kind": "job_latency_p99", "target": 1.0})
    with pytest.raises(ValueError, match="unknown SLO kind"):
        SLOSpec.from_dict({"name": "x", "kind": "nope", "target": 1.0})
    with pytest.raises(ValueError, match="needs a target"):
        SLOSpec.from_dict({"name": "x", "kind": "job_latency_p99"})
    with pytest.raises(ValueError, match="unknown SLO spec fields"):
        SLOSpec.from_dict(
            {"name": "x", "kind": "job_latency_p99", "target": 1.0,
             "tresh": 2}
        )
    spec = SLOSpec.from_dict(
        {"name": "x", "kind": "deployment_latency_p99", "target": 250,
         "subject": "chat", "budget": 0.2}
    )
    assert spec.threshold == 1.0 and spec.fast_window_s == 60.0
    assert spec.subject == "chat" and spec.budget == 0.2
    assert spec.to_dict()["target"] == 250.0


def test_slo_burn_math():
    st = _SLOState(max_samples=100)
    now = 1000.0
    # under min_samples: no burn verdict at all (prevents 1-sample pages)
    st.samples.append((now - 1, 1.0))
    assert st.burn(60.0, 0.1, now) is None
    st.samples.clear()
    # half the window bad, budget 10% -> burn 5x
    for i in range(10):
        st.samples.append((now - 10 + i, 1.0 if i % 2 == 0 else 0.0))
    assert st.burn(60.0, 0.1, now) == pytest.approx(5.0)
    # a tight window sees only the newest samples
    for i in range(5):
        st.samples.append((now - 0.5 + i * 0.1, 0.0))
    assert st.burn(1.0, 0.1, now) == pytest.approx(0.0)


def test_hist_p99_bucket_upper_bound():
    # 100 obs: 99 in the first bucket (<=10), 1 in (10, 100]
    boundaries = [10.0, 100.0]
    buckets = [99.0, 1.0, 0.0]  # +inf bucket empty
    assert _hist_p99(100, buckets, boundaries) == pytest.approx(10.0)
    buckets = [50.0, 0.0, 50.0]  # half in +inf: p99 pins to last boundary
    assert _hist_p99(100, buckets, boundaries) == pytest.approx(100.0)
    assert _hist_p99(0, [0, 0, 0], boundaries) is None


# ---------------------------------------------------------------------------
# incident lifecycle on a live cluster
# ---------------------------------------------------------------------------


def test_calm_cluster_stays_clean(incident_cluster):
    """Normal task traffic opens nothing; doctor says healthy."""

    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get([f.remote(i) for i in range(20)]) == list(range(1, 21))
    time.sleep(1.5)  # at least one full scan
    assert state.list_incidents() == []
    doc = state.doctor()
    assert doc["healthy"] is True
    assert doc["open_incidents"] == []
    assert isinstance(doc["watchdogs"], dict)
    assert doc["watchdogs"]["stragglers"] == 0


def test_slo_registry_roundtrip(incident_cluster):
    row = state.register_slo(
        "chat-p99", "deployment_latency_p99", 250.0, subject="chat",
        budget=0.2,
    )
    assert row["name"] == "chat-p99" and row["budget"] == 0.2
    slos = {s["name"]: s for s in state.list_slos()}
    assert "chat-p99" in slos
    assert slos["chat-p99"]["ok"] is True  # no subjects yet -> not breached
    with pytest.raises(Exception, match="unknown SLO kind"):
        state.register_slo("bad", "not_a_kind", 1.0)
    assert state.remove_slo("chat-p99") is True
    assert state.remove_slo("chat-p99") is False
    assert all(s["name"] != "chat-p99" for s in state.list_slos())


def test_watchdog_trigger_opens_merges_and_closes(incident_cluster):
    """A watchdog event opens ONE incident; repeats merge (count bumps,
    no second page); quiet + recovery closes it with duration + verdict."""
    sch = _sch()
    sch.record_cluster_event(
        "STRAGGLER", "f_slow 40x over p95", severity="WARNING",
        source="WATCHDOG", name="f_slow", elapsed_s=40.0, p95_s=1.0,
    )
    inc = _wait(
        lambda: next(iter(state.list_incidents(kind="STRAGGLER")), None),
        msg="STRAGGLER incident to open",
    )
    assert inc["state"] == "open" and inc["subject"] == "f_slow"
    assert inc["count"] == 1
    # repeat trigger merges into the SAME incident
    sch.record_cluster_event(
        "STRAGGLER", "f_slow still over", severity="WARNING",
        source="WATCHDOG", name="f_slow", elapsed_s=50.0, p95_s=1.0,
    )
    merged = _wait(
        lambda: next(
            (r for r in state.list_incidents(kind="STRAGGLER")
             if r["count"] >= 2), None),
        msg="trigger merge",
    )
    assert merged["id"] == inc["id"]
    assert len(state.list_incidents(kind="STRAGGLER")) == 1
    # the lifecycle reaches the cluster event log
    opened = state.list_cluster_events(
        filters=[("type", "=", "INCIDENT_OPENED")]
    )
    assert any(e.get("incident_id") == inc["id"] for e in opened)
    # full record: digest joined at least events + memory planes
    full = state.get_incident(inc["id"])
    assert full["digest"]["planes"], full["digest"]
    assert "memory" in full["digest"]["planes"]
    assert any(
        e["type"] == "STRAGGLER" for e in full["digest"]["events"]
    )
    # quiet (no new triggers) past incident_quiet_close_s=2 closes it
    closed = _wait(
        lambda: next(
            (r for r in state.list_incidents(kind="STRAGGLER")
             if r["state"] == "closed"), None),
        msg="incident close",
    )
    assert closed["duration_s"] is not None and closed["duration_s"] >= 0
    assert closed["verdict"] and "f_slow" in closed["verdict"]
    assert any(
        e.get("incident_id") == inc["id"]
        for e in state.list_cluster_events(
            filters=[("type", "=", "INCIDENT_CLOSED")]
        )
    )


def test_worker_died_burst_gating(incident_cluster):
    """One death is elastic churn (no incident); a >=3-death burst on one
    node collapses into exactly ONE WORKER_KILL_STORM."""
    sch = _sch()
    node = NodeID.from_random().hex()[:12]
    sch.record_cluster_event(
        "WORKER_DIED", "exitcode -9", severity="ERROR",
        source="SCHEDULER", node_id=node,
    )
    time.sleep(2.0)  # two scans: a lone death must never page
    assert state.list_incidents(kind="WORKER_KILL_STORM") == []
    for _ in range(3):
        sch.record_cluster_event(
            "WORKER_DIED", "exitcode -9", severity="ERROR",
            source="SCHEDULER", node_id=node,
        )
    storm = _wait(
        lambda: state.list_incidents(kind="WORKER_KILL_STORM"),
        msg="kill-storm incident",
    )
    assert len(storm) == 1
    assert storm[0]["subject"] == node


def test_slo_breach_opens_incident(incident_cluster):
    """A registered job-latency SLO with an impossible target breaches
    (both windows burning) and opens an SLO_BREACH incident."""

    @ray_tpu.remote
    def work():
        time.sleep(0.02)
        return 1

    state.register_slo(
        "job-p99", "job_latency_p99", 0.001,  # 1us target: always bad
        budget=0.5, threshold=1.0, fast_window_s=5.0, slow_window_s=10.0,
    )
    # keep latency samples flowing while the 1 Hz evaluator accumulates
    deadline = time.monotonic() + 30.0
    breach = None
    while time.monotonic() < deadline and not breach:
        ray_tpu.get([work.remote() for _ in range(4)])
        breach = next(
            iter(state.list_incidents(kind="SLO_BREACH")), None
        )
    assert breach, "SLO breach never opened"
    assert breach["slo"] == "job-p99"
    assert breach["subject"].startswith("job-p99:")
    slos = {s["name"]: s for s in state.list_slos()}
    row = slos["job-p99"]
    assert row["ok"] is False and row["breaches_total"] >= 1
    assert row["worst"]["burn_fast"] >= 1.0
    evs = state.list_cluster_events(filters=[("type", "=", "SLO_BREACH")])
    assert evs and evs[0]["slo"] == "job-p99"
    doc = state.doctor()
    assert doc["healthy"] is False
    state.remove_slo("job-p99")


# ---------------------------------------------------------------------------
# event-log cursor (ray_tpu events --since/--follow backend)
# ---------------------------------------------------------------------------


def test_event_cursor_after_event_id_and_since_ts(incident_cluster):
    sch = _sch()
    sch.record_cluster_event("OOM", "marker-a", severity="WARNING",
                             source="TEST", node_id="aaaa")
    evs = _wait(
        lambda: state.list_cluster_events(filters=[("type", "=", "OOM")]),
        msg="first marker event",
    )
    cursor = max(e["event_id"] for e in evs)
    t_mid = time.time()
    assert state.list_cluster_events(after_event_id=cursor) == []
    time.sleep(0.05)
    sch.record_cluster_event("OOM", "marker-b", severity="WARNING",
                             source="TEST", node_id="bbbb")
    newer = _wait(
        lambda: state.list_cluster_events(after_event_id=cursor),
        msg="cursor-filtered tail",
    )
    assert all(e["event_id"] > cursor for e in newer)
    assert any(e["message"] == "marker-b" for e in newer)
    assert not any(e["message"] == "marker-a" for e in newer)
    # since_ts: wall-clock variant used by `events --since`
    recent = state.list_cluster_events(since_ts=t_mid)
    assert any(e["message"] == "marker-b" for e in recent)
    assert not any(e["message"] == "marker-a" for e in recent)


# ---------------------------------------------------------------------------
# CLI + dashboard + metric surfaces
# ---------------------------------------------------------------------------


def test_cli_doctor_and_incidents(incident_cluster, capsys):
    from ray_tpu.scripts.cli import main

    sch = _sch()
    sch.record_cluster_event(
        "STRAGGLER", "f_cli 10x over p95", severity="WARNING",
        source="WATCHDOG", name="f_cli", elapsed_s=10.0, p95_s=1.0,
    )
    inc = _wait(
        lambda: next(iter(state.list_incidents(kind="STRAGGLER")), None),
        msg="incident for the CLI",
    )
    main(["doctor"])
    out = capsys.readouterr().out
    assert "cluster health" in out and "incident" in out.lower()
    main(["doctor", "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert doc["healthy"] is False and doc["open_incidents"]
    main(["incidents"])
    out = capsys.readouterr().out
    assert inc["id"] in out and "STRAGGLER" in out
    main(["incidents", "--json"])
    rows = json.loads(capsys.readouterr().out)
    assert any(r["id"] == inc["id"] for r in rows)
    main(["incidents", "show", inc["id"]])
    out = capsys.readouterr().out
    assert "STRAGGLER" in out and "f_cli" in out
    main(["incidents", inc["id"], "--json"])  # "show" prefix is optional
    full = json.loads(capsys.readouterr().out)
    assert full["id"] == inc["id"] and full["digest"]["planes"]
    with pytest.raises(SystemExit):
        main(["incidents", "show", "inc-does-not-exist"])


def test_cli_events_since(incident_cluster, capsys):
    from ray_tpu.scripts.cli import main

    sch = _sch()
    sch.record_cluster_event("OOM", "cli-marker", severity="WARNING",
                             source="TEST", node_id="cccc")
    _wait(
        lambda: state.list_cluster_events(filters=[("type", "=", "OOM")]),
        msg="marker event",
    )
    main(["events", "--since", "10m", "--json"])
    rows = json.loads(capsys.readouterr().out)
    assert any(e.get("message") == "cli-marker" for e in rows)
    # a since-window in the future excludes everything
    main(["events", "--since", "0s", "--json"])
    out = capsys.readouterr().out.strip()
    assert "cli-marker" not in out


def test_dashboard_incidents_endpoints(incident_cluster):
    import urllib.request

    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    sch = _sch()
    sch.record_cluster_event(
        "STRAGGLER", "f_dash over p95", severity="WARNING",
        source="WATCHDOG", name="f_dash", elapsed_s=9.0, p95_s=1.0,
    )
    _wait(
        lambda: state.list_incidents(kind="STRAGGLER"),
        msg="incident for the dashboard",
    )
    port = start_dashboard(port=0)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/incidents", timeout=10
        ) as resp:
            body = json.loads(resp.read())
        assert any(r["kind"] == "STRAGGLER" for r in body["incidents"])
        assert "slos" in body
        inc_id = body["incidents"][0]["id"]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/incidents?id={inc_id}", timeout=10
        ) as resp:
            full = json.loads(resp.read())
        assert full["id"] == inc_id and "digest" in full
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/doctor", timeout=10
        ) as resp:
            doc = json.loads(resp.read())
        assert "healthy" in doc and "watchdogs" in doc
    finally:
        stop_dashboard()


def test_incident_metric_series(incident_cluster):
    from ray_tpu._private.worker import get_driver

    sch = _sch()
    sch.record_cluster_event(
        "STRAGGLER", "f_m over p95", severity="WARNING",
        source="WATCHDOG", name="f_m", elapsed_s=9.0, p95_s=1.0,
    )
    _wait(
        lambda: state.list_incidents(kind="STRAGGLER"),
        msg="incident for metrics",
    )
    series = {s["name"]: s for s in get_driver().rpc("runtime_metrics")}
    for name in (
        "ray_tpu_incidents_open",
        "ray_tpu_incidents_total",
        "ray_tpu_incidents_closed_total",
        "ray_tpu_incident_open_seconds_max",
        "ray_tpu_slo_breaches_total",
        "ray_tpu_alerts_emitted_total",
    ):
        assert name in series, name
    assert sum(series["ray_tpu_incidents_open"]["data"].values()) >= 1
    assert sum(series["ray_tpu_incidents_total"]["data"].values()) >= 1
    assert any(
        "STRAGGLER" in k for k in series["ray_tpu_incidents_open"]["data"]
    )
    from ray_tpu.util.metrics import prometheus_text

    text = prometheus_text()
    assert "ray_tpu_incidents_open" in text
    # HELP descriptions ship with every series (satellite of this plane)
    assert "# HELP ray_tpu_incidents_open" in text


# ---------------------------------------------------------------------------
# cross-plane shape guard
# ---------------------------------------------------------------------------


def test_digest_source_shapes_hold(incident_cluster):
    """The digest joins other planes by reaching into their row shapes;
    if any of those shapes drifts, fail HERE with a named contract, not
    inside a best-effort digest assembly that would silently go empty."""
    sch = _sch()
    # memory plane: forensics snapshot keys the digest copies
    mem = sch.memory_forensics_snapshot(top=3)
    for key in ("store_capacity_bytes", "top_callsites"):
        assert key in mem, f"memory_forensics_snapshot lost {key!r}"
    # net plane: link ledger rows (feed one synthetic completed transfer)
    dst = NodeID.from_random()
    oid = ObjectID.from_random()
    sch._fetching[(oid, dst)] = (sch._node.head_node_id, True)
    sch._xfer_complete(
        oid, dst, True,
        stats={"path": "socket", "bytes": 1 << 20, "wire_ms": 5.0,
               "total_ms": 5.0, "t0": time.time()},
    )
    rows = sch._net_link_rows()
    assert rows, "link ledger empty after a completed transfer"
    for key in ("src", "dst", "path", "bytes"):
        assert key in rows[0], f"_net_link_rows lost {key!r}"
    # train plane: run listing stays a list of dicts with the keys the
    # goodput digest slice reads (empty on this cluster, shape still held)
    runs = sch._train_index.list_runs()
    assert isinstance(runs, list)
    # control plane: decision ring + lock and the launch ring the digest
    # slices by time window
    assert hasattr(sch, "_decisions") and hasattr(sch, "_decision_lock")
    assert hasattr(sch, "_launch_recent")
    # events: every recorded event carries the id the cursor pages on
    sch.record_cluster_event("OOM", "shape probe", severity="WARNING",
                             source="TEST", node_id="dddd")
    evs = _wait(
        lambda: state.list_cluster_events(filters=[("type", "=", "OOM")]),
        msg="shape-probe event",
    )
    assert all("event_id" in e and "time" in e for e in evs)
