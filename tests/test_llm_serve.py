"""LLM serving plane on a real cluster: deploy the continuous-batching
engine, stream tokens through handle + HTTP, watch TTFT/KV telemetry,
shed typed 503s on KV exhaustion, scale on TTFT."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

pytest.importorskip("jax")

import ray_tpu  # noqa: E402
from ray_tpu import serve  # noqa: E402
from ray_tpu.serve.llm import EngineConfig, InferenceEngine, TINY_MODEL, llm_deployment  # noqa: E402

SMALL_ENGINE = dict(
    block_size=4,
    num_blocks=128,
    max_batch=3,
    max_blocks_per_seq=16,
    max_waiting=16,
)


@pytest.fixture
def serve_cluster():
    rt = ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield rt
    serve.shutdown()
    ray_tpu.shutdown()


def _deploy(name="llmapp", engine_cfg=None, route_prefix=None, **opts):
    app = llm_deployment(
        TINY_MODEL, engine_cfg or SMALL_ENGINE, deployment_name="llm", **opts
    )
    serve.run(app, name=name, route_prefix=route_prefix)
    return serve.get_app_handle(name)


def test_deploy_and_stream_matches_local_engine(serve_cluster):
    """Tokens streamed through the serve stack equal a local engine run on
    the same weights/config — the transport adds nothing and drops
    nothing. KV + batching gauges appear in the metrics surface."""
    h = _deploy()
    prompt = [5, 11, 23, 42]
    via_serve = list(
        h.options(stream=True).generate.remote(prompt, max_new_tokens=8)
    )
    assert len(via_serve) == 8

    import jax

    from ray_tpu.models.transformer import init_params
    from ray_tpu.serve.llm.deployment import _resolve_model_cfg

    cfg = _resolve_model_cfg(TINY_MODEL)
    params = init_params(jax.random.PRNGKey(0), cfg)
    local = InferenceEngine(
        params, cfg, EngineConfig(**SMALL_ENGINE), deployment="local"
    )
    try:
        assert local.submit(prompt, max_new_tokens=8).tokens() == via_serve
    finally:
        local.shutdown()

    # unary convenience path returns the same completion
    assert h.remote(prompt, max_new_tokens=8).result(timeout_s=60) == via_serve

    # replica-side kv stats are live and consistent
    stats = h.kv_stats.remote().result(timeout_s=60)
    assert stats["blocks_total"] == 127
    assert stats["blocks_free"] == stats["blocks_total"]

    from ray_tpu.util.metrics import prometheus_text

    text = prometheus_text()
    for series in (
        "ray_tpu_kv_blocks_total",
        "ray_tpu_kv_blocks_free",
        "ray_tpu_kv_occupancy_ratio",
        "ray_tpu_llm_running_seqs",
        "ray_tpu_llm_tokens_total",
        "ray_tpu_serve_ttft_ms",
    ):
        assert series in text, f"{series} missing from metrics"
    serve.delete("llmapp")


def test_ttft_surfaces_in_serve_status(serve_cluster):
    """The replica's stream-TTFT samples fold into a per-deployment window
    visible in serve.status() — the SLO + autoscaling input."""
    h = _deploy(health_check_period_s=0.5)
    for _ in range(4):
        list(h.options(stream=True).generate.remote([3, 1, 4], max_new_tokens=4))
    deadline = time.time() + 20
    snap = None
    while time.time() < deadline:
        snap = (
            serve.status().get("llmapp", {}).get("llm", {}).get("ttft")
        )
        if snap and snap.get("count", 0) >= 1 and snap.get("p99") is not None:
            break
        time.sleep(0.25)
    assert snap and snap.get("p99") is not None, f"no TTFT fold: {snap}"
    assert snap["p99"] < 60_000
    serve.delete("llmapp")


def test_kv_exhaustion_typed_503_through_handle(serve_cluster):
    """KV-aware admission inside the replica sheds with the SAME typed
    error the handle-level bound uses — callers can't tell (and shouldn't)
    which layer shed them. Nothing hangs."""
    h = _deploy(
        name="tiny",
        engine_cfg=dict(
            block_size=4,
            num_blocks=9,
            max_batch=2,
            max_blocks_per_seq=8,
            max_waiting=0,
            retry_after_s=2.0,
        ),
        # let concurrency reach the ENGINE: the replica gate must not
        # serialize requests ahead of the KV-aware admission under test
        max_ongoing_requests=32,
    ).options(stream=True)
    prompt = [7, 9, 2, 4, 6, 8]
    ok, shed, other = 0, 0, []
    lock = threading.Lock()

    def client():
        nonlocal ok, shed
        try:
            out = list(h.generate.remote(prompt, max_new_tokens=8))
            with lock:
                ok += 1
            assert len(out) == 8
        except serve.DeploymentOverloadedError as e:
            assert getattr(e, "retry_after_s", 0) > 0
            with lock:
                shed += 1
        except Exception as e:  # noqa: BLE001
            other.append(e)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client) for _ in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    elapsed = time.perf_counter() - t0
    assert not other, f"untyped failures: {other[:3]}"
    assert shed > 0, "tiny KV pool never shed"
    assert ok > 0, "everything shed"
    assert elapsed < 80, f"sheds must be fast, took {elapsed:.1f}s"
    serve.delete("tiny")


def test_kv_exhaustion_503_with_retry_after_over_http(serve_cluster):
    """Over the HTTP proxy a replica-side KV shed is a 503 with a
    Retry-After header — same surface as handle-level admission sheds."""
    _deploy(
        name="tinyhttp",
        engine_cfg=dict(
            block_size=4,
            num_blocks=9,
            max_batch=1,
            max_blocks_per_seq=8,
            max_waiting=0,
            retry_after_s=3.0,
        ),
        route_prefix="/tinyhttp",
    )
    body = json.dumps(
        {"prompt": [5, 3, 1, 2, 4, 6], "max_new_tokens": 6}
    ).encode()

    def post():
        req = urllib.request.Request(
            "http://127.0.0.1:8700/tinyhttp",
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                return r.status, dict(r.headers)
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers)

    results = []
    lock = threading.Lock()

    def worker():
        r = post()
        with lock:
            results.append(r)

    threads = [threading.Thread(target=worker) for _ in range(10)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    statuses = [s for s, _ in results]
    sheds = [(s, h) for s, h in results if s == 503]
    assert any(s == 200 for s in statuses), statuses
    assert sheds, f"no 503 sheds over HTTP: {statuses}"
    for s, hdrs in sheds:
        retry = {k.lower(): v for k, v in hdrs.items()}.get("retry-after")
        assert retry is not None and int(retry) >= 1
    assert all(s in (200, 503) for s in statuses), statuses
    serve.delete("tinyhttp")


def test_ttft_autoscaling_scales_up(serve_cluster):
    """A deployment breaching target_ttft_ms scales up even though queue
    depth alone would not ask for more replicas."""

    @serve.deployment(
        num_replicas=1,
        health_check_period_s=0.5,
        max_ongoing_requests=8,
        autoscaling_config={
            "min_replicas": 1,
            "max_replicas": 2,
            "target_ongoing_requests": 100,  # depth signal never triggers
            "target_ttft_ms": 10.0,
            "ttft_min_samples": 3,
        },
    )
    class SlowFirstToken:
        def stream(self, n):
            time.sleep(0.2)  # TTFT ~200ms >> 10ms target
            for i in range(n):
                yield i

    serve.run(SlowFirstToken.bind(), name="slowttft")
    h = serve.get_app_handle("slowttft").options(stream=True)
    stop = threading.Event()

    def load():
        while not stop.is_set():
            try:
                list(h.stream.remote(3))
            except Exception:
                pass

    threads = [threading.Thread(target=load) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        deadline = time.time() + 45
        scaled = False
        while time.time() < deadline:
            row = serve.status().get("slowttft", {}).get("SlowFirstToken", {})
            if row.get("target", 1) >= 2:
                scaled = True
                break
            time.sleep(0.5)
        assert scaled, f"TTFT breach never scaled up: {row}"
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    serve.delete("slowttft")
