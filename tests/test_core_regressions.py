"""Regression tests for review findings on the core runtime."""

import time

import pytest

import ray_tpu


def test_get_duplicate_refs(ray_start_regular):
    @ray_tpu.remote
    def f():
        time.sleep(0.2)
        return 7

    r = f.remote()
    assert ray_tpu.get([r, r, r], timeout=60) == [7, 7, 7]


def test_exception_value_roundtrip(ray_start_regular):
    err = ValueError("stored, not raised")
    ref = ray_tpu.put(err)
    out = ray_tpu.get(ref)
    assert isinstance(out, ValueError)
    assert str(out) == "stored, not raised"


def test_task_returning_exception_object(ray_start_regular):
    @ray_tpu.remote
    def collect():
        return [KeyError("a"), 42]

    errs = ray_tpu.get(collect.remote(), timeout=60)
    assert isinstance(errs[0], KeyError)
    assert errs[1] == 42


def test_arg_pinned_after_driver_ref_dropped(ray_start_regular):
    import numpy as np

    @ray_tpu.remote
    def total(x, delay):
        time.sleep(delay)
        return float(x.sum())

    big = np.ones(300_000, dtype=np.float64)  # large enough to live in shm
    ref = ray_tpu.put(big)
    result = total.remote(ref, 0.5)
    del ref  # must not free the object out from under the running task
    assert ray_tpu.get(result, timeout=60) == 300_000.0


def test_failed_actor_init_releases_resources(ray_start_regular):
    @ray_tpu.remote(num_cpus=1)
    class Bad:
        def __init__(self):
            raise RuntimeError("nope")

        def ping(self):
            return 1

    handles = [Bad.remote() for _ in range(4)]  # would exhaust all 4 CPUs if leaked
    for h in handles:
        with pytest.raises(Exception):
            ray_tpu.get(h.ping.remote(), timeout=60)

    @ray_tpu.remote
    def still_works():
        return "yes"

    assert ray_tpu.get(still_works.remote(), timeout=60) == "yes"


def test_pending_pg_created_after_node_added(ray_start_cluster):
    from ray_tpu.util.placement_group import placement_group

    cluster = ray_start_cluster
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert not pg.wait(0.3)  # only one node: infeasible
    cluster.add_node(num_cpus=2)
    assert pg.wait(10)  # retried once the node joined


def test_actor_method_num_returns(ray_start_regular):
    @ray_tpu.remote
    class Splitter:
        @ray_tpu.method(num_returns=2)
        def pair(self):
            return "a", "b"

    s = Splitter.remote()
    r1, r2 = s.pair.remote()
    assert ray_tpu.get([r1, r2], timeout=60) == ["a", "b"]
