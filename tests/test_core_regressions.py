"""Regression tests for review findings on the core runtime."""

import time

import pytest

import ray_tpu


def test_get_duplicate_refs(ray_start_regular):
    @ray_tpu.remote
    def f():
        time.sleep(0.2)
        return 7

    r = f.remote()
    assert ray_tpu.get([r, r, r], timeout=60) == [7, 7, 7]


def test_exception_value_roundtrip(ray_start_regular):
    err = ValueError("stored, not raised")
    ref = ray_tpu.put(err)
    out = ray_tpu.get(ref)
    assert isinstance(out, ValueError)
    assert str(out) == "stored, not raised"


def test_task_returning_exception_object(ray_start_regular):
    @ray_tpu.remote
    def collect():
        return [KeyError("a"), 42]

    errs = ray_tpu.get(collect.remote(), timeout=60)
    assert isinstance(errs[0], KeyError)
    assert errs[1] == 42


def test_arg_pinned_after_driver_ref_dropped(ray_start_regular):
    import numpy as np

    @ray_tpu.remote
    def total(x, delay):
        time.sleep(delay)
        return float(x.sum())

    big = np.ones(300_000, dtype=np.float64)  # large enough to live in shm
    ref = ray_tpu.put(big)
    result = total.remote(ref, 0.5)
    del ref  # must not free the object out from under the running task
    assert ray_tpu.get(result, timeout=60) == 300_000.0


def test_failed_actor_init_releases_resources(ray_start_regular):
    @ray_tpu.remote(num_cpus=1)
    class Bad:
        def __init__(self):
            raise RuntimeError("nope")

        def ping(self):
            return 1

    handles = [Bad.remote() for _ in range(4)]  # would exhaust all 4 CPUs if leaked
    for h in handles:
        with pytest.raises(Exception):
            ray_tpu.get(h.ping.remote(), timeout=60)

    @ray_tpu.remote
    def still_works():
        return "yes"

    assert ray_tpu.get(still_works.remote(), timeout=60) == "yes"


def test_pending_pg_created_after_node_added(ray_start_cluster):
    from ray_tpu.util.placement_group import placement_group

    cluster = ray_start_cluster
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert not pg.wait(0.3)  # only one node: infeasible
    cluster.add_node(num_cpus=2)
    assert pg.wait(10)  # retried once the node joined


def test_actor_method_num_returns(ray_start_regular):
    @ray_tpu.remote
    class Splitter:
        @ray_tpu.method(num_returns=2)
        def pair(self):
            return "a", "b"

    s = Splitter.remote()
    r1, r2 = s.pair.remote()
    assert ray_tpu.get([r1, r2], timeout=60) == ["a", "b"]


def test_retry_exceptions_true(ray_start_regular, tmp_path):
    marker = tmp_path / "attempts"

    @ray_tpu.remote(max_retries=3, retry_exceptions=True)
    def flaky():
        n = int(marker.read_text()) if marker.exists() else 0
        marker.write_text(str(n + 1))
        if n < 2:
            raise RuntimeError(f"attempt {n}")
        return n

    assert ray_tpu.get(flaky.remote(), timeout=60) == 2


def test_retry_exceptions_list_no_match(ray_start_regular, tmp_path):
    marker = tmp_path / "attempts"

    @ray_tpu.remote(max_retries=3, retry_exceptions=[KeyError])
    def flaky():
        n = int(marker.read_text()) if marker.exists() else 0
        marker.write_text(str(n + 1))
        raise ValueError("not retryable")

    with pytest.raises(ValueError):
        ray_tpu.get(flaky.remote(), timeout=60)
    assert marker.read_text() == "1"  # no retries on a non-matching type


def test_retry_exceptions_list_match(ray_start_regular, tmp_path):
    marker = tmp_path / "attempts"

    @ray_tpu.remote(max_retries=3, retry_exceptions=[ValueError])
    def flaky():
        n = int(marker.read_text()) if marker.exists() else 0
        marker.write_text(str(n + 1))
        if n == 0:
            raise ValueError("retry me")
        return "ok"

    assert ray_tpu.get(flaky.remote(), timeout=60) == "ok"


def test_detached_actor_survives_handle_drop(ray_start_regular):
    import gc

    @ray_tpu.remote(lifetime="detached", name="det1")
    class Holder:
        def __init__(self):
            self.v = 41

        def bump(self):
            self.v += 1
            return self.v

    h = Holder.remote()
    assert ray_tpu.get(h.bump.remote(), timeout=60) == 42
    aid = h._actor_id
    del h
    gc.collect()
    time.sleep(0.3)
    h2 = ray_tpu.get_actor("det1")
    assert ray_tpu.get(h2.bump.remote(), timeout=60) == 43
    ray_tpu.kill(h2)


def test_actor_max_task_retries_on_restart(ray_start_regular, tmp_path):
    marker = tmp_path / "attempts"

    @ray_tpu.remote(max_restarts=1, max_task_retries=1)
    class Crashy:
        def work(self):
            import os

            n = int(marker.read_text()) if marker.exists() else 0
            marker.write_text(str(n + 1))
            if n == 0:
                os._exit(1)  # kill the actor worker mid-call
            return n

    c = Crashy.remote()
    assert ray_tpu.get(c.work.remote(), timeout=60) == 1


def test_custom_serializer_scoped_and_deregisterable(ray_start_regular):
    import cloudpickle

    from ray_tpu._private.serialization import get_context

    class Odd:
        def __init__(self, x):
            self.x = x

    ctx = get_context()
    ctx.register_serializer(
        Odd, serializer=lambda o: o.x * 10, deserializer=lambda p: Odd(p)
    )
    try:
        blob = ctx.serialize_to_bytes(Odd(3))
        out = ctx.deserialize_from(memoryview(blob))
        assert isinstance(out, Odd) and out.x == 30
        # the registration must not leak into plain cloudpickle
        plain = cloudpickle.loads(cloudpickle.dumps(Odd(5)))
        assert plain.x == 5
    finally:
        ctx.deregister_serializer(Odd)
    blob = ctx.serialize_to_bytes(Odd(7))
    out = ctx.deserialize_from(memoryview(blob))
    assert out.x == 7  # default path after deregistration


def test_log_to_driver(ray_start_regular, capfd):
    @ray_tpu.remote
    def chatty():
        print("marker-from-worker-xyz")
        return 1

    assert ray_tpu.get(chatty.remote(), timeout=60) == 1
    deadline = time.monotonic() + 10
    seen = ""
    while time.monotonic() < deadline:
        out, err = capfd.readouterr()
        seen += out + err
        if "marker-from-worker-xyz" in seen:
            break
        time.sleep(0.1)
    assert "marker-from-worker-xyz" in seen


def test_idle_worker_reaping(tmp_path):
    """Idle workers beyond the keep-warm floor exit after the timeout
    (parity: WorkerPool idle killing)."""
    import ray_tpu as rt
    from ray_tpu.util import state as state_api

    rt.init(
        num_cpus=4,
        ignore_reinit_error=True,
        _system_config={"worker_idle_timeout_s": 1.0},
    )
    try:
        @rt.remote
        def burst(i):
            time.sleep(0.1)
            return i

        rt.get([burst.remote(i) for i in range(8)], timeout=60)
        # several workers spawned; after the timeout only the floor remains
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            idle = [
                w for w in state_api.list_workers()
                if w["state"] == "idle" and not w["actor_id"]
            ]
            if len(idle) <= 2:
                break
            time.sleep(0.3)
        assert len(idle) <= 2, idle

        @rt.remote
        def again():
            return "ok"

        assert rt.get(again.remote(), timeout=60) == "ok"  # pool respawns fine
    finally:
        rt.shutdown()


def test_borrowed_ref_keeps_object_alive(ray_start_regular):
    """Parity: borrower tracking (reference_count.h:61) — an actor holding a
    deserialized ObjectRef keeps the object alive after the driver drops its
    own handle."""
    import gc
    import time

    import numpy as np

    import ray_tpu

    @ray_tpu.remote
    class Holder:
        def __init__(self, refs):
            self.refs = refs

        def read(self):
            return float(ray_tpu.get(self.refs[0], timeout=30).sum())

    arr = np.arange(50_000, dtype=np.float64)  # large enough to live in shm
    expect = float(arr.sum())
    ref = ray_tpu.put(arr)
    h = Holder.remote([ref])
    assert ray_tpu.get(h.read.remote(), timeout=60) == expect

    del ref, arr
    gc.collect()
    time.sleep(1.0)  # let the driver's remove_ref drain through the loop
    # the borrow held by the actor must keep the bytes fetchable
    assert ray_tpu.get(h.read.remote(), timeout=60) == expect


def test_object_freed_after_all_borrowers_drop(ray_start_regular):
    import gc
    import time

    import numpy as np

    import ray_tpu
    from ray_tpu.util import state

    @ray_tpu.remote
    class Holder:
        def __init__(self, refs):
            self.refs = refs

        def drop(self):
            self.refs = []
            import gc as _gc

            _gc.collect()
            return True

    ref = ray_tpu.put(np.arange(50_000, dtype=np.float64))
    oid_hex = ref.hex()
    h = Holder.remote([ref])
    ray_tpu.get(h.drop.remote(), timeout=60)
    del ref
    gc.collect()
    # every holder is gone and the transit pin was acked at deserialization,
    # so the free lands promptly (no TTL to wait out)
    deadline = time.monotonic() + 45
    while time.monotonic() < deadline:
        if all(o["object_id"] != oid_hex for o in state.list_objects()):
            break
        time.sleep(0.25)
    assert all(o["object_id"] != oid_hex for o in state.list_objects())


def test_borrowed_ref_survives_transit_pin_expiry(ray_start_regular):
    """A driver-held ref deserialized from a task result must outlive the
    sender's transit pin: the borrow flushes with the get, not lazily."""
    import time

    import numpy as np

    import ray_tpu
    from ray_tpu._private.worker import get_driver

    @ray_tpu.remote
    def producer():
        return ray_tpu.put(np.full(30_000, 7.0))

    inner = ray_tpu.get(producer.remote(), timeout=60)
    # idle longer than the old 10 s TTL cliff: with acknowledged handoff the
    # borrow was registered at deserialization, so no clock can free it
    assert get_driver().config.transit_pin_backstop_s > 60
    time.sleep(12.0)
    assert float(ray_tpu.get(inner, timeout=30).sum()) == 7.0 * 30_000


def test_ref_parked_in_blob_past_old_ttl(ray_start_regular):
    """Adversarial handoff: a serialized ref blob parked for longer than the
    old 10 s TTL cliff, with the sender's handle long gone, must still
    deserialize to a live object (acknowledged handoff has no clock)."""
    import gc
    import time

    import cloudpickle
    import numpy as np

    import ray_tpu

    ref = ray_tpu.put(np.full(20_000, 3.0))
    blob = cloudpickle.dumps(ref)  # takes the token transit pin
    del ref
    gc.collect()
    time.sleep(12.0)  # park past the old cliff; nothing else holds the object
    ref2 = cloudpickle.loads(blob)  # borrow + ack
    assert float(ray_tpu.get(ref2, timeout=30).sum()) == 3.0 * 20_000


def test_borrower_death_releases_refs(ray_start_regular):
    """A borrower whose worker dies mid-borrow must not leak its borrow: the
    scheduler releases dead holders' refs, so the object frees once every
    live handle is gone (the reference owner notices borrower death)."""
    import gc
    import time

    import numpy as np

    import ray_tpu
    from ray_tpu._private.worker import get_driver

    @ray_tpu.remote
    class Borrower:
        def __init__(self):
            self.held = None

        def hold(self, box):
            self.held = box["ref"]  # registers this worker as a borrower
            return True

    ref = ray_tpu.put(np.arange(30_000, dtype=np.float64))
    oid = ref.id()
    b = Borrower.remote()
    assert ray_tpu.get(b.hold.remote({"ref": ref}), timeout=60)
    ray_tpu.kill(b)  # borrower dies holding the borrow
    del b
    del ref
    gc.collect()
    sched = get_driver().node.scheduler
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if sched._ref_counts.get(oid, 0) <= 0:
            break
        time.sleep(0.2)
    assert sched._ref_counts.get(oid, 0) <= 0, (
        f"borrow leaked: count={sched._ref_counts.get(oid)}"
    )


def test_nested_borrow_chain(ray_start_regular):
    """A ref nested inside containers through two task hops (each re-pickling
    it) survives each handoff and resolves at the end."""
    import numpy as np

    import ray_tpu

    @ray_tpu.remote
    def wrap(box):
        import time

        time.sleep(0.5)
        return {"inner": box["ref"], "hop": box.get("hop", 0) + 1}

    ref = ray_tpu.put(np.full(10_000, 5.0))
    hop1 = ray_tpu.get(wrap.remote({"ref": ref}), timeout=60)
    del ref
    import gc

    gc.collect()
    hop2 = ray_tpu.get(wrap.remote({"ref": hop1["inner"], "hop": hop1["hop"]}), timeout=60)
    del hop1
    gc.collect()
    assert hop2["hop"] == 2
    assert float(ray_tpu.get(hop2["inner"], timeout=30).sum()) == 5.0 * 10_000


def test_generator_refs_borrowed_cross_actor(ray_start_regular):
    """Streaming-generator return refs handed to another actor resolve there
    (generator refs flow through the same borrower protocol)."""
    import numpy as np

    import ray_tpu

    @ray_tpu.remote(num_returns="streaming")
    def gen():
        for i in range(3):
            yield np.full(5_000, float(i))

    @ray_tpu.remote
    class Consumer:
        def consume(self, box):
            import time

            time.sleep(0.3)
            # the nested ref is a genuine borrow (top-level args would be
            # auto-resolved before the method runs)
            return float(ray_tpu.get(box["r"], timeout=30).sum())

    c = Consumer.remote()
    totals = []
    for item_ref in gen.remote():
        totals.append(c.consume.remote({"r": item_ref}))
        del item_ref
    import gc

    gc.collect()
    assert ray_tpu.get(totals, timeout=120) == [0.0, 5_000.0, 10_000.0]
