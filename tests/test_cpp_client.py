"""C++ API frontend tests: build the client with g++ and drive a live
cluster from a C++ process (parity: the reference's ``cpp/`` frontend and its
cluster tests, ``cpp/src/ray/test/``)."""

import os
import subprocess

import pytest

import ray_tpu

CPP_DIR = os.path.join(os.path.dirname(__file__), "..", "ray_tpu", "cpp")


@pytest.fixture(scope="module")
def cpp_demo_binary():
    proc = subprocess.run(
        ["make", "-C", CPP_DIR], capture_output=True, text=True, timeout=120
    )
    assert proc.returncode == 0, proc.stderr
    path = os.path.join(CPP_DIR, "build", "ray_tpu_cpp_demo")
    assert os.path.exists(path)
    return path


def test_cpp_client_end_to_end(cpp_demo_binary):
    rt = ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    try:
        host, port = rt.node.start_head_server()
        auth = rt.config.cluster_auth_key

        @ray_tpu.remote
        class Adder:
            def add(self, a, b):
                return a + b

            def dup(self):
                # same dict twice: the pickled reply memoizes the container
                # and references it (BINGET) — regression for the by-value
                # memo bug where the second copy decoded empty
                d = {"k": [1, 2, 3]}
                return [d, d]

        actor = Adder.options(name="cpp_demo").remote()
        # make sure the actor is live before the C++ process calls it
        assert ray_tpu.get(actor.add.remote(1, 1), timeout=60) == 2

        proc = subprocess.run(
            [cpp_demo_binary, str(host), str(port), auth],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        out = proc.stdout
        assert "OK connect" in out
        assert "OK cluster_resources" in out
        assert "OK put_get" in out
        # zero-copy shm data plane: the demo runs on the head's machine, so
        # the 1MiB payload MUST come back via the arena read, not a SKIP
        assert "OK shm_get 1048576 bytes" in out, out
        assert "OK call_actor 42" in out
        assert "OK memo_roundtrip" in out
        assert "OK done" in out
    finally:
        ray_tpu.shutdown()
