"""C++ API frontend tests: build the client with g++ and drive a live
cluster from a C++ process (parity: the reference's ``cpp/`` frontend and its
cluster tests, ``cpp/src/ray/test/``)."""

import os
import shutil
import subprocess

import pytest

import ray_tpu

CPP_DIR = os.path.join(os.path.dirname(__file__), "..", "ray_tpu", "cpp")


def _cheap_skip_reason():
    """Collection-time checks only (no subprocesses — every pytest run
    collects this module). The two known baseline reds polluting tier-1
    (CHANGES PR 4 / PR 11): a missing g++ on slow hosts, and a CPython
    whose multiprocessing auth predates the sha256 challenge the client
    implements."""
    import sys

    if sys.version_info < (3, 12):
        # CPython < 3.12 deliver_challenge() speaks legacy md5-HMAC with no
        # {digest} prefix; the C++ client implements the 3.12 sha256
        # protocol and refuses ("unsupported auth digest md5")
        return (
            f"python {sys.version_info.major}.{sys.version_info.minor} "
            "multiprocessing auth is md5-only (client needs >= 3.12 sha256)"
        )
    if shutil.which("g++") is None:
        return "no g++ on PATH"
    return None


_SKIP_REASON = _cheap_skip_reason()
pytestmark = pytest.mark.skipif(
    _SKIP_REASON is not None,
    reason=f"C++ client tests cannot run here ({_SKIP_REASON})",
)


def _assert_gxx_works():
    """Run-time (selected-tests-only) probe: a g++ that exists but cannot
    compile a trivial program skips with the reason; a g++ that works but
    fails the REAL client build below still FAILS loudly (that would be a
    build regression, not an environment gap)."""
    try:
        proc = subprocess.run(
            ["g++", "-x", "c++", "-", "-fsyntax-only"],
            input="int main() { return 0; }\n",
            capture_output=True,
            text=True,
            timeout=60,
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        pytest.skip(f"g++ not runnable: {e}")
    if proc.returncode != 0:
        pytest.skip(
            f"g++ cannot compile a trivial program: {proc.stderr[:200]}"
        )


@pytest.fixture(scope="module")
def cpp_demo_binary():
    _assert_gxx_works()
    proc = subprocess.run(
        ["make", "-C", CPP_DIR], capture_output=True, text=True, timeout=120
    )
    assert proc.returncode == 0, proc.stderr
    path = os.path.join(CPP_DIR, "build", "ray_tpu_cpp_demo")
    assert os.path.exists(path)
    return path


def test_cpp_client_end_to_end(cpp_demo_binary):
    rt = ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    try:
        host, port = rt.node.start_head_server()
        auth = rt.config.cluster_auth_key

        @ray_tpu.remote
        class Adder:
            def add(self, a, b):
                return a + b

            def dup(self):
                # same dict twice: the pickled reply memoizes the container
                # and references it (BINGET) — regression for the by-value
                # memo bug where the second copy decoded empty
                d = {"k": [1, 2, 3]}
                return [d, d]

        actor = Adder.options(name="cpp_demo").remote()
        # make sure the actor is live before the C++ process calls it
        assert ray_tpu.get(actor.add.remote(1, 1), timeout=60) == 2

        proc = subprocess.run(
            [cpp_demo_binary, str(host), str(port), auth],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        out = proc.stdout
        assert "OK connect" in out
        assert "OK cluster_resources" in out
        assert "OK put_get" in out
        # zero-copy shm data plane: the demo runs on the head's machine, so
        # the 1MiB payload MUST come back via the arena read, not a SKIP
        assert "OK shm_get 1048576 bytes" in out, out
        assert "OK call_actor 42" in out
        assert "OK memo_roundtrip" in out
        assert "OK done" in out
    finally:
        ray_tpu.shutdown()
