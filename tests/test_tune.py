"""Tuner tests. Parity: ``python/ray/tune/tests`` patterns (SURVEY.md §4)."""

import pytest

import ray_tpu
from ray_tpu import train, tune
from ray_tpu.train import RunConfig
from ray_tpu.tune import ASHAScheduler, MedianStoppingRule, TuneConfig, Tuner


def test_grid_search(ray_start_regular, tmp_path):
    def objective(config):
        train.report({"score": config["a"] * 10 + config["b"]})

    tuner = Tuner(
        objective,
        param_space={"a": tune.grid_search([1, 2]), "b": tune.grid_search([3, 4])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 4
    best = grid.get_best_result(metric="score", mode="max")
    assert best.metrics["score"] == 24
    assert best.metrics["config"] == {"a": 2, "b": 4}


def test_random_sampling(ray_start_regular, tmp_path):
    def objective(config):
        train.report({"val": config["x"]})

    tuner = Tuner(
        objective,
        param_space={"x": tune.uniform(0, 1)},
        tune_config=TuneConfig(num_samples=3, seed=42),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 3
    vals = [r.metrics["val"] for r in grid]
    assert all(0 <= v <= 1 for v in vals)
    assert len(set(vals)) == 3  # distinct samples


def test_trial_error_isolated(ray_start_regular, tmp_path):
    def objective(config):
        if config["i"] == 1:
            raise RuntimeError("trial exploded")
        train.report({"ok": 1})

    tuner = Tuner(
        objective,
        param_space={"i": tune.grid_search([0, 1, 2])},
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid.errors) == 1
    ok = [r for r in grid if r.error is None]
    assert len(ok) == 2


def test_asha_stops_bad_trials(ray_start_regular, tmp_path):
    import time

    def objective(config):
        for i in range(1, 21):
            # bad trials have high loss and would run long if not stopped
            train.report({"loss": config["q"] + i * 0.0})
            time.sleep(0.02)

    tuner = Tuner(
        objective,
        param_space={"q": tune.grid_search([1.0, 2.0, 3.0, 4.0])},
        tune_config=TuneConfig(
            metric="loss",
            mode="min",
            scheduler=ASHAScheduler(
                metric="loss", mode="min", grace_period=2, reduction_factor=4, max_t=20
            ),
            max_concurrent_trials=4,
        ),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    best = grid.get_best_result(metric="loss", mode="min")
    assert best.metrics["loss"] == 1.0
    # at least one of the worse trials was cut before max_t
    iters = [r.metrics["training_iteration"] for r in grid]
    assert min(iters) < 20


def test_tuner_wraps_jax_trainer(ray_start_regular, tmp_path):
    from ray_tpu.train import JaxTrainer, ScalingConfig

    def loop(config):
        train.report({"loss": 100.0 - config["lr"]})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path), name="inner"),
    )
    tuner = Tuner(
        trainer,
        param_space={"lr": tune.grid_search([1.0, 2.0])},
        tune_config=TuneConfig(metric="loss", mode="min", max_concurrent_trials=1),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    best = grid.get_best_result(metric="loss", mode="min")
    assert best.metrics["loss"] == 98.0


def test_median_stopping_rule():
    rule = MedianStoppingRule(metric="loss", mode="min", grace_period=0, min_samples_required=2)
    assert rule.on_result("a", 1, {"loss": 1.0}) == "CONTINUE"
    assert rule.on_result("b", 1, {"loss": 1.2}) == "CONTINUE"
    assert rule.on_result("c", 1, {"loss": 50.0}) == "STOP"


def test_pbt_exploits_better_config(ray_start_regular, tmp_path):
    from ray_tpu import tune
    from ray_tpu.train import RunConfig
    from ray_tpu.tune import PopulationBasedTraining, TuneConfig

    def trainable(config):
        from ray_tpu.train import report

        import time as _t

        # score is simply the lr: PBT must migrate lr=0 trials to lr=1
        # (slow iterations so the controller can interject exploits)
        for _ in range(14):
            report({"score": config["lr"]})
            _t.sleep(0.25)

    tuner = tune.Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.0, 0.0, 1.0])},
        tune_config=TuneConfig(
            num_samples=1,
            scheduler=PopulationBasedTraining(
                metric="score",
                mode="max",
                perturbation_interval=2,
                hyperparam_mutations={"lr": [0.0, 1.0]},
                quantile_fraction=0.4,
                seed=0,
            ),
        ),
        run_config=RunConfig(storage_path=str(tmp_path), name="pbt"),
    )
    results = tuner.fit()
    finals = [r.metrics["score"] for r in results]
    # every surviving trial converges onto the winning config
    assert max(finals) == 1.0
    assert sum(1 for s in finals if s == 1.0) >= 2, finals


def test_tuner_restore_resumes_experiment(ray_start_regular, tmp_path):
    import os
    import signal
    import subprocess
    import sys
    import textwrap
    import time as _time

    import ray_tpu as rt
    from ray_tpu import tune

    exp_dir = str(tmp_path / "exp")
    script = textwrap.dedent(f"""
        import ray_tpu, time
        from ray_tpu import tune
        from ray_tpu.train import RunConfig, report
        ray_tpu.init(num_cpus=2)

        def slow_trial(config):
            for i in range(40):
                report({{"step": i, "tag": config["tag"]}})
                time.sleep(0.5)

        tune.Tuner(
            slow_trial,
            param_space={{"tag": tune.grid_search([1, 2])}},
            tune_config=tune.TuneConfig(num_samples=1, max_concurrent_trials=2),
            run_config=RunConfig(storage_path={str(tmp_path)!r}, name="exp"),
        ).fit()
    """)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(rt.__file__)))
    proc = subprocess.Popen([sys.executable, "-c", script], env=env)
    # wait for the snapshot to appear, then kill the driver mid-sweep
    deadline = _time.monotonic() + 60
    state_file = os.path.join(exp_dir, "experiment_state.pkl")
    while _time.monotonic() < deadline:
        if os.path.exists(state_file):
            break
        _time.sleep(0.2)
    else:
        proc.kill()
        raise TimeoutError("snapshot never appeared")
    _time.sleep(1.0)
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=15)

    def fast_trial(config):
        from ray_tpu.train import report

        for i in range(3):
            report({"step": i, "tag": config["tag"]})

    tuner = tune.Tuner.restore(exp_dir, trainable=fast_trial)
    results = tuner.fit()
    tags = sorted(r.metrics["tag"] for r in results)
    assert tags == [1, 2]  # both trials resumed and completed
    assert all(r.error is None for r in results)


def test_stoppers_and_loggers(ray_start_regular, tmp_path):
    import json
    import os

    def objective(config):
        for i in range(50):
            train.report({"score": i})

    tuner = Tuner(
        objective,
        param_space={"a": tune.grid_search([1, 2])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(
            storage_path=str(tmp_path), name="stoptest",
            stop=tune.MaximumIterationStopper(5),
        ),
    )
    grid = tuner.fit()
    assert all(r.metrics["training_iteration"] <= 6 for r in grid)
    # result.json + progress.csv written into each trial dir
    trial_dirs = [r.path for r in grid]
    for d in trial_dirs:
        lines = open(os.path.join(d, "result.json")).read().splitlines()
        assert 1 <= len(lines) <= 6
        assert "score" in json.loads(lines[0])
        csv_text = open(os.path.join(d, "progress.csv")).read()
        assert csv_text.startswith("score")


def test_plateau_stopper(ray_start_regular, tmp_path):
    def objective(config):
        for i in range(40):
            train.report({"loss": 1.0 if i > 5 else 10.0 - i})

    grid = Tuner(
        objective,
        tune_config=TuneConfig(metric="loss", mode="min"),
        run_config=RunConfig(
            storage_path=str(tmp_path),
            stop=tune.TrialPlateauStopper("loss", std=1e-6, num_results=4),
        ),
    ).fit()
    assert grid[0].metrics["training_iteration"] < 40


def test_dict_stop_criteria(ray_start_regular, tmp_path):
    def objective(config):
        for i in range(100):
            train.report({"score": i})

    grid = Tuner(
        objective,
        run_config=RunConfig(storage_path=str(tmp_path), stop={"score": 7}),
    ).fit()
    assert grid[0].metrics["score"] <= 8


def test_bayesopt_beats_random_on_quadratic(ray_start_regular, tmp_path):
    """GP search should concentrate samples near the optimum of a smooth
    1-d objective and find a better best-value than coarse random search."""
    from ray_tpu.tune import BayesOptSearch, bayesopt

    def objective(config):
        x = config["x"]
        train.report({"neg_loss": -((x - 0.73) ** 2)})

    tuner = Tuner(
        objective,
        param_space={"x": bayesopt.uniform(0.0, 1.0)},
        tune_config=TuneConfig(
            num_samples=16,
            max_concurrent_trials=1,  # sequential: each suggest sees history
            search_alg=BayesOptSearch(metric="neg_loss", mode="max", seed=0,
                                      n_initial_points=4),
        ),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    best = grid.get_best_result(metric="neg_loss", mode="max")
    assert best.metrics["neg_loss"] > -0.01  # within 0.1 of the optimum
    assert abs(best.metrics["config"]["x"] - 0.73) < 0.1


def test_hyperband_brackets_stop_bad_trials():
    """HyperBand: bracketed halving stops weak trials at rungs (before
    exhausting max_t) while the best survive, with bracket diversity in
    grace periods."""
    from ray_tpu.tune.schedulers import STOP, HyperBandScheduler

    sched = HyperBandScheduler(metric="score", mode="max", max_t=27, reduction_factor=3)
    graces = sorted({b.grace for b in sched._brackets})
    assert len(graces) > 1, "expected multiple bracket budgets"

    # 12 trials; good trials report first so rungs are populated when the
    # weak ones arrive (async halving judges against filled rungs)
    order = sorted(range(12), reverse=True)
    stopped_at = {}
    for it in range(1, 28):
        for i in order:
            tid = f"t{i}"
            if tid in stopped_at:
                continue
            if sched.on_result(tid, it, {"score": float(i)}) == STOP:
                stopped_at[tid] = it
    assert stopped_at.get("t11", 27) >= 27, "best trial must reach max_t"
    early = {t for t, it in stopped_at.items() if it < 27}
    assert len(early) >= 3, f"halving never stopped weak trials early: {stopped_at}"
    assert all(int(t[1:]) < 11 for t in early)


def test_with_parameters_shares_payload(ray_start_regular, tmp_path):
    """tune.with_parameters: one object-store copy feeds every trial."""
    import numpy as np

    from ray_tpu import tune

    payload = np.arange(20000.0)  # too big to want per-trial pickling

    def train_fn(config, data=None):
        from ray_tpu import train as _train

        _train.report({"loss": float(config["x"] + data.sum() * 0)})

    from ray_tpu.train import RunConfig as _RC

    tuner = tune.Tuner(
        tune.with_parameters(train_fn, data=payload),
        param_space={"x": tune.grid_search([1.0, 2.0, 3.0])},
        run_config=_RC(storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert sorted(r.metrics["loss"] for r in grid) == [1.0, 2.0, 3.0]
