"""Incident-plane chaos acceptance (slow; part of `make chaos`).

Seeded fault injections, each of which must produce EXACTLY ONE incident
whose cross-plane digest joins >= 3 planes and whose close verdict names
the true injected cause:

  * a throttled link among healthy peers  -> one SLOW_LINK incident
    (events + memory + net), verdict naming the degraded link;
  * a worker SIGKILL storm               -> one WORKER_KILL_STORM
    incident (events + memory + control) — burst-gated, not one page
    per death — verdict naming the kill burst on the node;
  * a grow-only object leak              -> one OBJECT_LEAK_SUSPECT
    incident (events + traces + memory), verdict naming the leaking
    callsite.

Plus the calm-run control: the same cluster under sustained mixed load
opens ZERO incidents (no alert noise on healthy clusters).
"""

import os
import signal
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.ids import NodeID, ObjectID
from ray_tpu.util import state

pytestmark = pytest.mark.slow


def _sch():
    from ray_tpu._private.worker import get_runtime

    return get_runtime().node.scheduler


@pytest.fixture
def chaos_cluster():
    """Incident knobs tightened to converge inside a test budget: 3s
    quiet-close, leak watchdog at 0.1s scans with small growth floors."""
    rt = ray_tpu.init(
        num_cpus=4,
        ignore_reinit_error=True,
        _system_config={
            "incident_quiet_close_s": 3.0,
            "incident_event_window_s": 60.0,
            "leak_watchdog_interval_s": 0.1,
            "leak_watchdog_window": 5,
            "leak_watchdog_min_growth_bytes": 50_000,
            "leak_watchdog_min_count_growth": 3,
            "metrics_report_interval_ms": 50,
        },
    )
    yield rt
    ray_tpu.shutdown()


def _wait(pred, timeout=60.0, interval=0.25, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = pred()
        if got:
            return got
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def _feed_link(sch, src, dst, gibps, n=4, nbytes=8 * 1024 * 1024):
    """Synthesize n completed socket transfers at a given rate (the
    netplane test harness's injection point: the scheduler's own
    transfer-completion bookkeeping)."""
    wire_ms = nbytes / 2**30 / gibps * 1e3
    for _ in range(n):
        oid = ObjectID.from_random()
        sch._fetching[(oid, dst)] = (src, True)
        sch._xfer_complete(
            oid, dst, True,
            stats={"path": "socket", "bytes": nbytes, "wire_ms": wire_ms,
                   "total_ms": wire_ms, "t0": time.time()},
        )


def test_slow_link_incident_lifecycle(chaos_cluster):
    """Throttled link among healthy peers: ONE SLOW_LINK incident opens
    with a >=3-plane digest, and recovering the link closes it with a
    verdict naming the degraded wire throughput."""
    sch = _sch()
    head = sch._node.head_node_id
    nodes = [NodeID.from_random() for _ in range(4)]
    for dst in nodes[:3]:
        _feed_link(sch, head, dst, gibps=2.0)
    _feed_link(sch, nodes[0], nodes[3], gibps=0.05, n=6)  # ~40x slower

    inc = _wait(
        lambda: next(iter(state.list_incidents(kind="SLOW_LINK")), None),
        msg="SLOW_LINK incident",
    )
    assert len(state.list_incidents(kind="SLOW_LINK")) == 1
    slow_label = sch._node_label(nodes[3])
    assert inc["subject"].endswith(slow_label)

    full = state.get_incident(inc["id"])
    digest = full["digest"]
    assert len(digest["planes"]) >= 3, digest["planes"]
    assert {"events", "memory", "net"} <= set(digest["planes"])
    assert any(e["type"] == "SLOW_LINK" for e in digest["events"])
    link_rows = digest["net"]["links"]
    assert link_rows and all(
        f"{r['src']}->{r['dst']}" == inc["subject"] for r in link_rows
    )
    assert digest["net"]["recent_transfers"]

    # recovery: pull the link's EWMA back up until the watchdog clears
    # the slow flag, then the incident quiet-closes
    def recovered_and_closed():
        _feed_link(sch, nodes[0], nodes[3], gibps=2.0, n=4)
        rows = state.list_incidents(kind="SLOW_LINK")
        return next((r for r in rows if r["state"] == "closed"), None)

    closed = _wait(recovered_and_closed, timeout=90.0, interval=1.0,
                   msg="SLOW_LINK close after recovery")
    assert closed["duration_s"] > 0
    assert "degraded wire throughput" in closed["verdict"]
    assert closed["verdict"].count(slow_label) >= 1
    # still exactly one incident: repeats merged, never re-paged
    assert len(state.list_incidents(kind="SLOW_LINK")) == 1


def test_worker_kill_storm_one_incident(chaos_cluster):
    """SIGKILLing several workers in a burst yields exactly ONE
    WORKER_KILL_STORM incident (not one per death) whose digest joins the
    control plane and whose verdict names the kill burst."""

    @ray_tpu.remote
    class Victim:
        def pid(self):
            return os.getpid()

    actors = [Victim.remote() for _ in range(3)]
    pids = ray_tpu.get([a.pid.remote() for a in actors], timeout=120)
    assert len(set(pids)) == 3
    for pid in pids:
        try:
            os.kill(pid, signal.SIGKILL)
        except ProcessLookupError:
            pass

    storms = _wait(
        lambda: state.list_incidents(kind="WORKER_KILL_STORM"),
        msg="kill-storm incident",
    )
    assert len(storms) == 1, storms
    inc = storms[0]
    node_label = inc["subject"]

    full = state.get_incident(inc["id"])
    digest = full["digest"]
    assert len(digest["planes"]) >= 3, digest["planes"]
    assert {"events", "memory", "control"} <= set(digest["planes"])
    deaths = [e for e in digest["events"] if e["type"] == "WORKER_DIED"]
    assert len(deaths) >= 3
    # the control slice carries the victims' launch entries
    assert digest["control"].get("launches") or digest["control"].get(
        "decisions"
    )

    closed = _wait(
        lambda: next(
            (r for r in state.list_incidents(kind="WORKER_KILL_STORM")
             if r["state"] == "closed"), None),
        msg="storm close",
    )
    assert "kill/crash burst" in closed["verdict"]
    assert node_label in closed["verdict"]
    assert len(state.list_incidents(kind="WORKER_KILL_STORM")) == 1


def test_leak_incident_names_callsite(chaos_cluster):
    """A grow-only ref hoard of task-return objects opens ONE
    OBJECT_LEAK_SUSPECT incident; the digest joins traces (creation
    provenance of exemplar leaked objects) + memory (suspect row), and
    releasing the hoard closes it with the callsite named in the
    verdict."""
    from ray_tpu._private import telemetry

    @ray_tpu.remote
    def make_block():
        # 200 KB: big enough to be store-backed (inlined returns never
        # reach the provenance index, so a hoard of them can't be a
        # store leak)
        return np.zeros(200_000, dtype=np.uint8)

    hoard = []
    inc = None
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and inc is None:
        ref = make_block.remote()
        ray_tpu.get(ref)  # sealed + live; the ref in the hoard pins it
        hoard.append(ref)
        telemetry.flush()
        inc = next(
            iter(state.list_incidents(kind="OBJECT_LEAK_SUSPECT")), None
        )
    assert inc, "leak incident never opened"
    assert "make_block" in inc["subject"]
    assert len(state.list_incidents(kind="OBJECT_LEAK_SUSPECT")) == 1

    full = state.get_incident(inc["id"])
    digest = full["digest"]
    assert len(digest["planes"]) >= 3, digest["planes"]
    assert {"events", "traces", "memory"} <= set(digest["planes"])
    assert digest["memory"]["leak_suspect"]["callsite"] == inc["subject"]
    assert digest["memory"]["leak_suspect"]["growth_bytes"] > 0
    assert digest["traces"], "no exemplar trace joined via provenance"
    assert digest["traces"][0]["spans"] >= 1

    # release the hoard: the suspect clears, the incident quiet-closes
    hoard.clear()
    closed = _wait(
        lambda: next(
            (r for r in state.list_incidents(kind="OBJECT_LEAK_SUSPECT")
             if r["state"] == "closed"), None),
        timeout=90.0,
        msg="leak incident close after release",
    )
    assert "unreleased references" in closed["verdict"]
    assert inc["subject"] in closed["verdict"]


def test_calm_cluster_under_load_zero_incidents(chaos_cluster):
    """The control run: sustained mixed load (tasks + bounded put/get
    churn + actor calls) with the plane fully on opens ZERO incidents —
    the alerting plane must be silent on healthy clusters."""

    @ray_tpu.remote
    def work(i):
        return i * 2

    @ray_tpu.remote
    class Worker:
        def ping(self):
            return "ok"

    actors = [Worker.remote() for _ in range(2)]
    payload = np.zeros(100_000, dtype=np.uint8)
    deadline = time.monotonic() + 8.0
    while time.monotonic() < deadline:
        refs = [work.remote(i) for i in range(10)]
        ref = ray_tpu.put(payload)
        ray_tpu.get(ref)
        del ref  # bounded churn: created and released each round
        assert ray_tpu.get(refs, timeout=60) == [i * 2 for i in range(10)]
        assert ray_tpu.get(
            [a.ping.remote() for a in actors], timeout=60
        ) == ["ok", "ok"]
    time.sleep(1.5)  # one more full scan
    assert state.list_incidents() == [], state.list_incidents()
    doc = state.doctor()
    assert doc["healthy"] is True, doc
