"""Elastic-training chaos tests: worker loss mid-epoch, in-run replacement,
N→M re-sharded resume — a churned run must converge to EXACTLY the same
loss and step count as an uninterrupted one, resuming from committed
checkpoints only.

Slow-marked (tier-1 budget is marginal on slow hosts); run via
``make chaos``. Kill schedules are seeded — ``CHAOS_SEED=<n>`` reproduces
a failing run kill-for-kill.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu._private import external_storage as storage
from ray_tpu.train import checkpointing

# pytest's prepend import mode puts tests/ on sys.path (no tests/__init__),
# so the chaos harness package imports as a top-level name
from chaos import ChaosMonkey, chaos_seed, elastic_sgd_loop

pytestmark = pytest.mark.slow


@pytest.fixture
def chaos_cluster():
    rt = ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield rt
    ray_tpu.shutdown()


def _fit(tmp_path, name, total_steps, *, num_workers, min_workers=None,
         step_sleep=0.0, max_failures=8):
    from ray_tpu.train import FailureConfig, JaxTrainer, RunConfig, ScalingConfig

    return JaxTrainer(
        elastic_sgd_loop(total_steps, step_sleep),
        scaling_config=ScalingConfig(
            num_workers=num_workers, min_workers=min_workers
        ),
        run_config=RunConfig(
            storage_path=str(tmp_path),
            name=name,
            failure_config=FailureConfig(
                max_failures=max_failures,
                retry_backoff_s=0.2,
                retry_backoff_jitter=0.0,
                replacement_timeout_s=60.0,
                abort_drain_timeout_s=60.0,
            ),
        ),
    ).fit()


def test_churned_run_converges_like_uninterrupted(chaos_cluster, tmp_path):
    """SIGKILL train workers mid-epoch (seeded schedule); the run must
    keep going via in-run replacement, resume every rank from committed
    steps only, and land on the exact loss/step of a calm run."""
    total = 30
    calm = _fit(tmp_path, "calm", total, num_workers=2)
    assert calm.error is None, calm.error
    assert calm.metrics["training_iteration"] == total

    # arm only once a committed step exists: every kill then provably
    # forces a resume-from-committed, not a restart-from-scratch
    trial = str(tmp_path / "churned")
    monkey = ChaosMonkey(
        seed=chaos_seed(),
        interval_s=(1.0, 2.0),
        max_kills=2,
        arm_when=lambda: (checkpointing.latest_step(trial) or 0) >= 2,
    ).start()
    try:
        churned = _fit(tmp_path, "churned", total, num_workers=2, step_sleep=0.12)
    finally:
        kills = monkey.stop()
    assert churned.error is None, churned.error
    assert kills >= 1, "the chaos monkey never landed a kill (schedule too slow?)"
    # exact convergence: resumed-from-committed replay is bitwise identical
    assert churned.metrics["training_iteration"] == total
    assert churned.metrics["loss"] == calm.metrics["loss"], (
        f"churned run diverged: {churned.metrics['loss']} != "
        f"{calm.metrics['loss']} after {kills} kills (CHAOS_SEED={chaos_seed()})"
    )
    # the churned run actually resumed (not a lucky single pass)
    assert churned.metrics["resumed_at"] > 0
    # goodput accounting: some wall time was lost to redone steps/recovery
    assert churned.goodput is not None
    assert 0.0 < churned.goodput["goodput"] <= 1.0
    # the first report of each dispatch has no inter-report dt sample, so
    # each recovery can cost one counted step on top of the first
    assert churned.goodput["steps_useful"] >= total - 1 - kills
    # forensics: the in-run path fired (worker died, group re-formed)
    from ray_tpu.util import state as state_api

    events = state_api.list_cluster_events()
    types = {e["type"] for e in events}
    assert "TRAIN_WORKER_DIED" in types, sorted(types)
    assert "TRAIN_WORKER_REPLACED" in types, sorted(types)
    # the final step is committed and digest-valid (resume/readers never
    # saw a torn step; mid-kill uncommitted garbage may remain until GC)
    assert checkpointing.latest_step(trial) == total
    checkpointing.verify_checkpoint(
        checkpointing.discover_steps(trial)[total]
    )


def test_shrink_to_min_workers_resumes_n_to_m(chaos_cluster, tmp_path, monkeypatch):
    """Replacement provisioning is forced to fail, so losing a rank
    shrinks the group 2→1 inside the elasticity band: the sole survivor
    re-shards the world-2 committed checkpoint into world 1 (N→M resume)
    and finishes with the exact calm-run loss."""
    from ray_tpu.train import _backend_executor as be

    total = 24
    calm = _fit(tmp_path, "calm1", total, num_workers=2)
    assert calm.error is None, calm.error

    # no capacity for replacements: recovery must shrink, not stall
    monkeypatch.setattr(
        be.BackendExecutor, "_provision", lambda self, want, free: []
    )
    trial = str(tmp_path / "shrunk")
    monkey = ChaosMonkey(
        seed=chaos_seed() + 1,
        interval_s=(0.8, 1.4),
        max_kills=1,
        arm_when=lambda: (checkpointing.latest_step(trial) or 0) >= 2,
    ).start()
    try:
        result = _fit(
            tmp_path, "shrunk", total, num_workers=2, min_workers=1,
            step_sleep=0.12,
        )
    finally:
        kills = monkey.stop()
    assert result.error is None, result.error
    assert kills == 1
    assert result.metrics["training_iteration"] == total
    assert result.metrics["loss"] == calm.metrics["loss"]
    assert result.metrics["resumed_at"] > 0

    from ray_tpu.util import state as state_api

    resized = [
        e for e in state_api.list_cluster_events() if e["type"] == "TRAIN_RESIZED"
    ]
    assert resized and resized[-1]["new_world"] == 1, resized

    # the world-size change is visible in the committed manifests: early
    # steps committed by 2 ranks, post-shrink steps by 1
    worlds = {}
    for step, prefix in sorted(checkpointing.discover_steps(trial).items()):
        manifest = storage.read_committed_manifest(prefix)
        if manifest is not None:
            worlds[step] = manifest["world_size"]
    assert 2 in worlds.values(), worlds
    assert worlds[max(worlds)] == 1, worlds


def test_deterministic_crasher_bounded_not_infinite(chaos_cluster, tmp_path):
    """A rank that dies at the same step every attempt (no progress ever)
    must NOT kill/replace/resume forever: the progress-aware recovery
    budget fails over to the gang restart, max_failures caps that, and
    fit() returns with the error in bounded time."""
    from ray_tpu.train import FailureConfig, JaxTrainer, RunConfig, ScalingConfig

    def suicidal(config=None):
        import os as _os
        import signal as _signal

        from ray_tpu import train

        if train.get_context().get_world_rank() == 1:
            _os.kill(_os.getpid(), _signal.SIGKILL)
        train.report({"ok": 1.0})

    t0 = time.monotonic()
    result = JaxTrainer(
        suicidal,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            storage_path=str(tmp_path),
            name="crashloop",
            failure_config=FailureConfig(
                max_failures=1,
                retry_backoff_s=0.05,
                retry_backoff_jitter=0.0,
                max_recoveries_without_progress=1,
                replacement_timeout_s=20.0,
            ),
        ),
    ).fit()
    assert result.error is not None
    # bounded: (1 + max_recoveries) in-run recoveries per attempt, 2
    # attempts, small backoffs — minutes would mean a hot loop regression
    assert time.monotonic() - t0 < 180


def test_node_kill_mid_run_recovers(chaos_cluster, tmp_path):
    """Whole-host preemption modeled by killing both train workers in one
    schedule tick burst: the group re-forms from scratch capacity and the
    run still converges exactly."""
    total = 20
    calm = _fit(tmp_path, "calm2", total, num_workers=2)
    assert calm.error is None, calm.error

    from chaos import train_worker_pids

    def kill_all_once():
        # one burst: SIGKILL every live train worker (a node dying takes
        # all of its ranks at once)
        import signal as _signal
        import time as _time

        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline:
            pids = train_worker_pids()
            if len(pids) >= 2:
                for pid in pids:
                    try:
                        os.kill(pid, _signal.SIGKILL)
                    except ProcessLookupError:
                        pass
                return True
            _time.sleep(0.2)
        return False

    import threading

    burst_done = {}
    t = threading.Thread(
        target=lambda: burst_done.setdefault("ok", kill_all_once()), daemon=True
    )
    t.start()
    result = _fit(tmp_path, "nodekill", total, num_workers=2, step_sleep=0.12)
    t.join(timeout=35)
    assert burst_done.get("ok"), "burst killer never saw 2 live train workers"
    assert result.error is None, result.error
    assert result.metrics["training_iteration"] == total
    assert result.metrics["loss"] == calm.metrics["loss"]
