"""Head restart continuity: SIGKILL the head, restart it, cluster resumes.

Parity: the reference's GCS rebuilds all tables from Redis on restart and
raylets re-attach (``src/ray/gcs/store_client/redis_store_client.h:33``,
``gcs_init_data.h``). Here the snapshot in the session dir plays Redis's
role: a restarted head (``auto_restore``) adopts the crashed head's auth
key + listener port, restores the KV/name tables, recreates detached
actors, and surviving node daemons re-attach on their own.
"""

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HEAD1 = """
import json, os, sys, time
sys.path.insert(0, {repo!r})
import ray_tpu

rt = ray_tpu.init(num_cpus=1)
addr = rt.node.start_head_server()
print("ADDR " + json.dumps(
    {{"addr": list(addr), "auth": rt.config.cluster_auth_key,
      "session": rt.node.session_dir}}), flush=True)

# wait for the daemon node to join
deadline = time.monotonic() + 60
while time.monotonic() < deadline:
    if any(n["alive"] and "dnode" in n["total"] for n in ray_tpu.nodes()):
        break
    time.sleep(0.2)
else:
    raise TimeoutError("daemon never joined")

@ray_tpu.remote(num_cpus=0)
class Keeper:
    def __init__(self):
        self.tag = "alive"

    def ping(self):
        return self.tag

k = Keeper.options(name="keeper", lifetime="detached").remote()
assert ray_tpu.get(k.ping.remote(), timeout=60) == "alive"
print("ACTOR_UP", flush=True)

# wait until the periodic snapshot includes the detached actor
snap = os.path.join(rt.node.session_dir, "gcs_snapshot.pkl")
start = os.path.getmtime(snap) if os.path.exists(snap) else 0
deadline = time.monotonic() + 30
while time.monotonic() < deadline:
    if os.path.exists(snap) and os.path.getmtime(snap) > start:
        break
    time.sleep(0.5)
print("SNAPSHOTTED", flush=True)
while True:
    time.sleep(1)
"""

HEAD2 = """
import json, os, sys, time
sys.path.insert(0, {repo!r})
os.environ["RAY_TPU_AUTO_RESTORE"] = "1"
import ray_tpu

rt = ray_tpu.init(num_cpus=1)
# restored head must be listening on the crashed head's port already
assert rt.node.head_server is not None, "auto-restore did not restart the head server"
addr = rt.node.head_server.address
print("ADDR2 " + json.dumps(list(addr)), flush=True)
assert list(addr) == {old_addr!r}, (addr, {old_addr!r})

# the surviving daemon re-attaches by itself
deadline = time.monotonic() + 90
while time.monotonic() < deadline:
    if any(n["alive"] and "dnode" in n["total"] for n in ray_tpu.nodes()):
        break
    time.sleep(0.5)
else:
    raise TimeoutError("daemon did not re-attach")
print("DAEMON_BACK", flush=True)

# the detached actor is back under its name (recreated by restore)
deadline = time.monotonic() + 60
keeper = None
while time.monotonic() < deadline:
    try:
        keeper = ray_tpu.get_actor("keeper")
        break
    except ValueError:
        time.sleep(0.5)
assert keeper is not None, "detached actor not restored"
assert ray_tpu.get(keeper.ping.remote(), timeout=60) == "alive"
print("ACTOR_BACK", flush=True)

# new work lands on the re-attached daemon
@ray_tpu.remote(resources={{"dnode": 0.5}})
def on_daemon():
    return os.getpid()

pid = ray_tpu.get(on_daemon.remote(), timeout=120)
assert pid > 0
print("OK", flush=True)
ray_tpu.shutdown()
"""


def _wait_line(proc, marker, timeout):
    deadline = time.monotonic() + timeout
    lines = []
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise AssertionError(
                    f"process died waiting for {marker!r}: {''.join(lines)[-3000:]}"
                )
            time.sleep(0.05)
            continue
        lines.append(line)
        if marker in line:
            return line
    raise AssertionError(f"timed out waiting for {marker!r}: {''.join(lines)[-3000:]}")


def test_head_sigkill_restart_cluster_resumes(tmp_path):
    env = dict(os.environ)
    env["RAY_TPU_SESSION_DIR_ROOT"] = str(tmp_path / "sessions")
    env.pop("RAY_TPU_AUTO_RESTORE", None)

    head1 = subprocess.Popen(
        [sys.executable, "-u", "-c", HEAD1.format(repo=REPO)],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=env,
        cwd=REPO,
    )
    daemon = None
    head2 = None
    try:
        info = json.loads(_wait_line(head1, "ADDR ", 120).split("ADDR ", 1)[1])
        host, port = info["addr"]

        denv = dict(env)
        denv["RAY_TPU_AUTH"] = info["auth"]
        daemon = subprocess.Popen(
            [
                sys.executable,
                "-u",
                "-m",
                "ray_tpu._private.raylet",
                "--address",
                f"{host}:{port}",
                "--num-cpus",
                "1",
                "--resources",
                '{"dnode": 1.0}',
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=denv,
            cwd=REPO,
        )
        _wait_line(head1, "ACTOR_UP", 180)
        _wait_line(head1, "SNAPSHOTTED", 60)

        # crash the head ungracefully (no clean-shutdown marker)
        os.kill(head1.pid, signal.SIGKILL)
        head1.wait(timeout=30)

        assert daemon.poll() is None, "daemon died with the head"

        head2 = subprocess.Popen(
            [
                sys.executable,
                "-u",
                "-c",
                HEAD2.format(repo=REPO, old_addr=[host, port]),
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=REPO,
        )
        _wait_line(head2, "DAEMON_BACK", 180)
        _wait_line(head2, "ACTOR_BACK", 120)
        _wait_line(head2, "OK", 180)
        head2.wait(timeout=60)
        assert head2.returncode == 0
    finally:
        for p in (head1, daemon, head2):
            if p is not None and p.poll() is None:
                p.kill()
        for p in (head1, daemon, head2):
            if p is not None:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
