"""runtime_env env_vars, memory monitor, multiprocessing Pool shim."""

import os
import time

import pytest

import ray_tpu


def test_runtime_env_env_vars(ray_start_regular):
    @ray_tpu.remote(runtime_env={"env_vars": {"MY_FLAG": "on"}})
    def read_flag():
        return os.environ.get("MY_FLAG")

    @ray_tpu.remote
    def read_plain():
        return os.environ.get("MY_FLAG")

    assert ray_tpu.get(read_flag.remote(), timeout=60) == "on"
    # env restored after the task
    assert ray_tpu.get(read_plain.remote(), timeout=60) is None


def test_runtime_env_on_actor(ray_start_regular):
    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_FLAG": "yes"}})
    class A:
        def __init__(self):
            self.at_init = os.environ.get("ACTOR_FLAG")

        def get(self):
            return self.at_init

    a = A.remote()
    assert ray_tpu.get(a.get.remote(), timeout=60) == "yes"


def test_memory_monitor_threshold_and_kill():
    from ray_tpu._private.memory_monitor import MemoryMonitor

    readings = iter([0.5, 0.97, 0.5])
    kills = []
    mon = MemoryMonitor(
        threshold=0.9,
        usage_fn=lambda: next(readings),
        kill_fn=lambda: kills.append(1) or True,
    )
    assert not mon.check_once()
    assert mon.check_once()
    assert not mon.check_once()
    assert kills == [1]
    assert mon.kills == 1


def test_memory_monitor_system_reading():
    from ray_tpu._private.memory_monitor import system_memory_fraction

    frac = system_memory_fraction()
    assert 0.0 <= frac <= 1.0


def test_memory_monitor_kill_policy(ray_start_regular):
    from ray_tpu._private.memory_monitor import make_scheduler_kill_policy

    rt = ray_tpu.get_runtime()

    @ray_tpu.remote(max_retries=2)
    def hog():
        time.sleep(60)
        return 1

    ref = hog.remote()
    time.sleep(1.0)  # let it start
    kill = make_scheduler_kill_policy(rt.scheduler)
    assert kill()  # terminates the running retriable worker
    # task retries and would eventually run again; just assert no crash here
    ready, not_ready = ray_tpu.wait([ref], num_returns=1, timeout=0.5)
    assert not_ready  # still pending/retrying


def test_multiprocessing_pool(ray_start_regular):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=2) as pool:
        assert pool.map(lambda x: x * x, range(10)) == [x * x for x in range(10)]
        assert pool.starmap(lambda a, b: a + b, [(1, 2), (3, 4)]) == [3, 7]
        r = pool.apply_async(lambda a: a * 10, (7,))
        assert r.get(timeout=60) == 70
        assert list(pool.imap(lambda x: x + 1, range(5))) == [1, 2, 3, 4, 5]
    with pytest.raises(ValueError):
        pool.map(lambda x: x, [1])
