"""runtime_env env_vars, memory monitor, multiprocessing Pool shim."""

import os
import time

import pytest

import ray_tpu


def test_runtime_env_env_vars(ray_start_regular):
    @ray_tpu.remote(runtime_env={"env_vars": {"MY_FLAG": "on"}})
    def read_flag():
        return os.environ.get("MY_FLAG")

    @ray_tpu.remote
    def read_plain():
        return os.environ.get("MY_FLAG")

    assert ray_tpu.get(read_flag.remote(), timeout=60) == "on"
    # env restored after the task
    assert ray_tpu.get(read_plain.remote(), timeout=60) is None


def test_runtime_env_on_actor(ray_start_regular):
    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_FLAG": "yes"}})
    class A:
        def __init__(self):
            self.at_init = os.environ.get("ACTOR_FLAG")

        def get(self):
            return self.at_init

    a = A.remote()
    assert ray_tpu.get(a.get.remote(), timeout=60) == "yes"


def test_memory_monitor_threshold_and_kill():
    from ray_tpu._private.memory_monitor import MemoryMonitor

    readings = iter([0.5, 0.97, 0.5])
    kills = []
    mon = MemoryMonitor(
        threshold=0.9,
        usage_fn=lambda: next(readings),
        kill_fn=lambda: kills.append(1) or True,
    )
    assert not mon.check_once()
    assert mon.check_once()
    assert not mon.check_once()
    assert kills == [1]
    assert mon.kills == 1


def test_memory_monitor_system_reading():
    from ray_tpu._private.memory_monitor import system_memory_fraction

    frac = system_memory_fraction()
    assert 0.0 <= frac <= 1.0


def test_memory_monitor_kill_policy(ray_start_regular):
    from ray_tpu._private.memory_monitor import make_scheduler_kill_policy

    rt = ray_tpu.get_runtime()

    @ray_tpu.remote(max_retries=2)
    def hog():
        time.sleep(60)
        return 1

    ref = hog.remote()
    time.sleep(1.0)  # let it start
    kill = make_scheduler_kill_policy(rt.scheduler)
    assert kill()  # terminates the running retriable worker
    # task retries and would eventually run again; just assert no crash here
    ready, not_ready = ray_tpu.wait([ref], num_returns=1, timeout=0.5)
    assert not_ready  # still pending/retrying


def test_multiprocessing_pool(ray_start_regular):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=2) as pool:
        assert pool.map(lambda x: x * x, range(10)) == [x * x for x in range(10)]
        assert pool.starmap(lambda a, b: a + b, [(1, 2), (3, 4)]) == [3, 7]
        r = pool.apply_async(lambda a: a * 10, (7,))
        assert r.get(timeout=60) == 70
        assert list(pool.imap(lambda x: x + 1, range(5))) == [1, 2, 3, 4, 5]
    with pytest.raises(ValueError):
        pool.map(lambda x: x, [1])


def test_runtime_env_working_dir_and_py_modules(ray_start_regular, tmp_path):
    """Parity: runtime_env working_dir/py_modules as content-addressed
    packages (python/ray/_private/runtime_env/working_dir.py:1)."""
    wd = tmp_path / "project"
    wd.mkdir()
    (wd / "data.txt").write_text("payload-42")
    mod = tmp_path / "mymodule"
    mod.mkdir()
    (mod / "__init__.py").write_text("MAGIC = 'xyzzy'\n")

    @ray_tpu.remote(
        runtime_env={"working_dir": str(wd), "py_modules": [str(mod)]}
    )
    def uses_env():
        import os

        import mymodule  # extracted + on sys.path only via the runtime env

        return open("data.txt").read(), mymodule.MAGIC, os.getcwd()

    data, magic, cwd = ray_tpu.get(uses_env.remote(), timeout=60)
    assert data == "payload-42"
    assert magic == "xyzzy"
    assert "ray_tpu_pkgs" in cwd

    # after the task, the worker is back in its original cwd
    @ray_tpu.remote
    def plain():
        import os

        return os.getcwd()

    assert "ray_tpu_pkgs" not in ray_tpu.get(plain.remote(), timeout=60)


def test_gcs_snapshot_restore_head_restart(tmp_path):
    """Restart the control plane from its snapshot: KV entries and detached
    named actors survive (recreated under their names — head-owned workers
    die with the head, unlike the reference where they outlive the GCS)."""
    import time

    import ray_tpu as rt
    from ray_tpu._private.worker import get_driver

    drv = rt.init(num_cpus=2, ignore_reinit_error=True)
    session_dir = drv.node.session_dir

    rt.experimental_kv_put = drv.rpc  # not public API; use rpc directly
    drv.rpc("kv_put", "app", b"setting", b"v1", True)

    @rt.remote(lifetime="detached", name="survivor")
    class Counter:
        def ping(self):
            return "alive"

    c = Counter.remote()
    assert rt.get(c.ping.remote(), timeout=60) == "alive"
    # force a snapshot now (the loop writes every 5s)
    drv.scheduler._write_gcs_snapshot()
    snap = session_dir + "/gcs_snapshot.pkl"
    import shutil

    saved = str(tmp_path / "gcs_snapshot.pkl")
    shutil.copy(snap, saved)
    rt.shutdown()

    drv2 = rt.init(num_cpus=2, _restore_from=saved)
    try:
        assert drv2.rpc("kv_get", "app", b"setting") == b"v1"
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                h = rt.get_actor("survivor")
                assert rt.get(h.ping.remote(), timeout=30) == "alive"
                break
            except Exception:
                time.sleep(0.2)
        else:
            raise AssertionError("detached actor did not come back")
    finally:
        rt.shutdown()


def test_compiled_dag_channel_pipeline(ray_start_regular):
    """Linear actor pipeline lowered to mutable shm channels: stages run
    resident loops, repeated executes reuse actors and buffers, no per-hop
    task submission (parity: compiled DAGs / aDAG)."""
    from ray_tpu.dag import ChannelCompiledDAG, InputNode

    @ray_tpu.remote
    class Doubler:
        def __init__(self):
            self.calls = 0

        def step(self, x):
            self.calls += 1
            return x * 2

    @ray_tpu.remote
    class AddCount:
        def __init__(self):
            self.calls = 0

        def step(self, x):
            self.calls += 1
            return x + self.calls  # stateful: proves actor reuse

    with InputNode() as inp:
        mid = Doubler.bind().step.bind(inp)
        out = AddCount.bind().step.bind(mid)
    dag = out.experimental_compile()
    assert isinstance(dag, ChannelCompiledDAG)
    try:
        # sequential executes through the SAME resident actors
        assert dag.execute(1).get() == 3  # 1*2 + 1
        assert dag.execute(1).get() == 4  # 1*2 + 2 (state advanced)
        assert dag.execute(5).get() == 13  # 5*2 + 3
    finally:
        dag.teardown()


def test_channel_acquire_release_semantics(ray_start_regular, tmp_path):
    """Writer blocks until the reader consumes (one-slot mutable object)."""
    import threading
    import time as _t

    from ray_tpu.experimental.channel import Channel

    path = str(tmp_path / "ch")
    writer = Channel(path, capacity=1 << 16, create=True)
    reader = Channel(path, capacity=1 << 16)
    writer.write("a")
    blocked = threading.Event()
    done = threading.Event()

    def second_write():
        blocked.set()
        writer.write("b", timeout=30)  # must wait for the reader
        done.set()

    t = threading.Thread(target=second_write, daemon=True)
    t.start()
    blocked.wait(5)
    _t.sleep(0.2)
    assert not done.is_set(), "writer overran the unconsumed slot"
    assert reader.read(timeout=5) == "a"
    assert done.wait(5), "writer never unblocked after consumption"
    assert reader.read(timeout=5) == "b"
    writer.close()


def test_runtime_env_pip_wheelhouse(ray_start_regular, tmp_path, monkeypatch):
    """pip runtime env from a local wheelhouse (offline --no-index mode;
    parity: runtime_env/pip.py)."""
    import subprocess
    import sys

    # build a tiny wheel offline
    src = tmp_path / "tinypkg_src"
    (src / "tinypkg").mkdir(parents=True)
    (src / "tinypkg" / "__init__.py").write_text("MAGIC = 'wheelhouse-ok'\n")
    (src / "pyproject.toml").write_text(
        '[build-system]\nrequires=["setuptools"]\n'
        'build-backend="setuptools.build_meta"\n'
        '[project]\nname="tinypkg"\nversion="0.1"\n'
    )
    wheelhouse = tmp_path / "wheelhouse"
    wheelhouse.mkdir()
    proc = subprocess.run(
        [sys.executable, "-m", "pip", "wheel", "--no-deps", "--no-index",
         "--no-build-isolation", "-w", str(wheelhouse), str(src)],
        capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr

    monkeypatch.setenv("RAY_TPU_WHEELHOUSE", str(wheelhouse))

    @ray_tpu.remote(runtime_env={"pip": ["tinypkg"],
                                 "env_vars": {"RAY_TPU_WHEELHOUSE": str(wheelhouse)}})
    def use_pkg():
        import tinypkg

        return tinypkg.MAGIC

    assert ray_tpu.get(use_pkg.remote(), timeout=120) == "wheelhouse-ok"

    # a missing package surfaces as a task error, not a dead worker
    @ray_tpu.remote(runtime_env={"pip": ["definitely-not-a-package-xyz"],
                                 "env_vars": {"RAY_TPU_WHEELHOUSE": str(wheelhouse)}})
    def bad():
        return 1

    with pytest.raises(Exception, match="pip runtime_env install failed"):
        ray_tpu.get(bad.remote(), timeout=120)

    # a failed env application must not leak its env_vars into the worker
    @ray_tpu.remote
    def check_clean():
        import os

        return os.environ.get("RAY_TPU_WHEELHOUSE")

    leaked = ray_tpu.get([check_clean.remote() for _ in range(4)], timeout=60)
    # none of the workers may carry the failed task's env var
    assert str(wheelhouse) not in [v for v in leaked if v is not None]


# ---- fixed-point resources + per-instance accounting (SURVEY row 6) ----


def test_fixed_point_no_drift():
    from ray_tpu._private.resources import quantize

    v = 1.0
    for _ in range(10000):
        v = quantize(v - 0.0001)
    assert v == 0.0  # a float loop would land at ~1e-13, not exact zero


def test_resource_instance_set_rules():
    from ray_tpu._private.resources import ResourceInstanceSet

    s = ResourceInstanceSet(4)
    # whole demands take whole devices
    a = s.allocate(2.0)
    assert sorted(i for i, _ in a) == [0, 1]
    # fractional demands pack onto one device (best-fit on partial first)
    b = s.allocate(0.5)
    c = s.allocate(0.25)
    assert b[0][0] == c[0][0] == 2  # packs the same device
    d = s.allocate(0.5)
    assert d[0][0] == 3
    # nothing left for a whole device
    assert s.allocate(1.0) is None
    # >1 must be whole
    assert s.allocate(1.5) is None
    s.free(a)
    assert s.allocate(1.0) is not None
    # free restores fractional capacity exactly
    s.free(b)
    s.free(c)
    assert s.allocate(1.0) is not None  # device 2 whole again


def test_instance_ledger_all_or_nothing():
    from ray_tpu._private.resources import InstanceLedger

    led = InstanceLedger({"TPU": 2.0, "GPU": 1.0, "CPU": 8.0})
    ok = led.allocate({"TPU": 2.0, "GPU": 1.0, "CPU": 4.0})
    assert set(ok) == {"TPU", "GPU"}  # CPU is not indexed
    # GPU exhausted: a combined demand must roll back its TPU part too
    led.free(ok)
    led.allocate({"GPU": 1.0})
    failed = led.allocate({"TPU": 1.0, "GPU": 1.0})
    assert failed is None
    assert led.allocate({"TPU": 2.0}) is not None  # TPU was rolled back


def test_task_sees_assigned_accelerator_ids():
    import ray_tpu

    ray_tpu.init(num_cpus=2, resources={"TPU": 2}, ignore_reinit_error=True)

    @ray_tpu.remote(resources={"TPU": 1})
    def which():
        import os

        ctx = ray_tpu.get_runtime_context()
        return ctx.get_accelerator_ids()["TPU"], os.environ.get("TPU_VISIBLE_CHIPS")

    try:
        ids, env = ray_tpu.get(which.remote(), timeout=120)
        assert len(ids) == 1 and env == ids[0]
        # two concurrent 1-chip tasks must get DIFFERENT devices
        import time as _time

        @ray_tpu.remote(resources={"TPU": 1})
        def hold():
            import os

            _time.sleep(1.0)
            return os.environ.get("TPU_VISIBLE_CHIPS")

        a, b = ray_tpu.get([hold.remote(), hold.remote()], timeout=120)
        assert {a, b} == {"0", "1"}
    finally:
        ray_tpu.shutdown()
