"""Direct worker-to-worker actor transport tests.

Parity: the reference's caller-side actor task submitter + receiver ordering
(``src/ray/core_worker/transport/actor_task_submitter.h:73``,
``.../task_receiver.h:51``) — calls bypass the head; the head sees only
lifecycle events. These tests cover the ownership/escape protocol the direct
plane adds (caller-owned results escalated to the head when they leave the
process) and failure semantics (restart replay, kill, relay fallback).
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc


@pytest.fixture
def ray_start():
    rt = ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield rt
    ray_tpu.shutdown()


@ray_tpu.remote
class Counter:
    def __init__(self):
        self.n = 0

    def inc(self, k=1):
        self.n += k
        return self.n

    def get(self):
        return self.n

    def pid(self):
        return os.getpid()

    def big(self, mib):
        import numpy as np

        return np.ones(mib * 1024 * 1024 // 8)

    def die(self):
        os._exit(1)


def test_direct_calls_skip_head_task_table(ray_start):
    """Method calls ride the direct plane: the head's task table records the
    creation but NOT the calls (lifecycle-only visibility)."""
    c = Counter.remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    for _ in range(20):
        c.inc.remote()
    assert ray_tpu.get(c.get.remote()) == 21
    from ray_tpu._private.worker import get_runtime

    tasks = get_runtime().rpc("list_tasks")
    names = [t["name"] for t in tasks]
    assert any("__init__" in n for n in names)
    assert not any(n == "inc" for n in names), "calls leaked to the head"


def test_per_caller_ordering_under_load(ray_start):
    c = Counter.remote()
    refs = [c.inc.remote() for _ in range(500)]
    assert ray_tpu.get(refs, timeout=120) == list(range(1, 501))


def test_result_escapes_to_normal_task(ray_start):
    """A caller-owned direct result passed into a head-routed task must be
    escalated (published + refcount transfer) so the task resolves it."""
    c = Counter.remote()

    @ray_tpu.remote
    def double(x):
        return 2 * x

    r = c.inc.remote(21)
    assert ray_tpu.get(double.remote(r), timeout=60) == 42


def test_result_chains_between_actors(ray_start):
    a = Counter.remote()
    b = Counter.remote()
    # b's argument is a pending direct result from a
    assert ray_tpu.get(b.inc.remote(a.inc.remote(5)), timeout=60) == 5


def test_result_escapes_via_put_roundtrip(ray_start):
    """Pickling a direct-result ref (here: inside a put value) escalates
    ownership; a fresh task can deserialize and resolve it."""
    c = Counter.remote()
    ref = c.inc.remote(7)
    holder = ray_tpu.put({"inner": ref})

    @ray_tpu.remote
    def read(box):
        return ray_tpu.get(box["inner"])

    assert ray_tpu.get(read.remote(holder), timeout=60) == 7


def test_large_direct_result_stored_and_locatable(ray_start):
    """Stored (non-inline) direct returns register their location at the
    head, so any process can fetch them."""
    c = Counter.remote()
    r = c.big.remote(2)  # 2 MiB >> inline limit

    @ray_tpu.remote
    def total(x):
        return float(x.sum())

    assert ray_tpu.get(total.remote(r), timeout=60) == 2 * 1024 * 1024 / 8
    assert ray_tpu.get(r, timeout=60).nbytes == 2 * 1024 * 1024


def test_restart_invalidates_location_cache(ray_start):
    """After a restart the caller re-resolves to the NEW worker address."""
    a = Counter.options(max_restarts=1).remote()
    p1 = ray_tpu.get(a.pid.remote(), timeout=60)
    with pytest.raises(exc.ActorDiedError):
        ray_tpu.get(a.die.remote(), timeout=60)
    p2 = ray_tpu.get(a.pid.remote(), timeout=60)
    assert p1 != p2


def test_retry_replays_queued_calls_in_order(ray_start):
    """Calls queued behind a killer survive via caller-side replay within
    max_task_retries, preserving submission order."""
    a = Counter.options(max_restarts=1, max_task_retries=1).remote()
    assert ray_tpu.get(a.inc.remote(), timeout=60) == 1
    refs = [a.inc.remote() for _ in range(5)]
    assert ray_tpu.get(refs, timeout=60) == [2, 3, 4, 5, 6]


def test_kill_fails_fast_locally(ray_start):
    c = Counter.remote()
    ray_tpu.get(c.inc.remote(), timeout=60)
    ray_tpu.kill(c)
    with pytest.raises(exc.ActorDiedError):
        ray_tpu.get(c.inc.remote(), timeout=60)


def test_worker_to_worker_calls(ray_start):
    """Caller is itself a worker process: the direct plane spans worker
    processes, not just the driver."""
    c = Counter.remote()

    @ray_tpu.remote
    def caller(h, n):
        return ray_tpu.get([h.inc.remote() for _ in range(n)])[-1]

    outs = ray_tpu.get([caller.remote(c, 5) for _ in range(4)], timeout=120)
    assert sorted(outs)[-1] == 20


def test_relay_fallback_when_direct_disabled(ray_start):
    """With the kill switch off, calls take the head relay and still work."""
    # a fresh actor whose worker has no listener: simulate by disabling the
    # caller side (the resolution returns an addr, but the client is absent)
    from ray_tpu._private.worker import get_runtime

    rt = get_runtime()
    saved = rt._direct
    rt._direct = None
    try:
        c = Counter.remote()
        assert ray_tpu.get(c.inc.remote(), timeout=60) == 1
        assert ray_tpu.get([c.inc.remote() for _ in range(10)], timeout=60) == list(
            range(2, 12)
        )
    finally:
        rt._direct = saved


def test_streaming_over_direct_plane(ray_start):
    @ray_tpu.remote
    class Gen:
        def stream(self, n):
            for i in range(n):
                yield i * 3

    g = Gen.remote()
    got = [
        ray_tpu.get(r, timeout=60)
        for r in g.stream.options(num_returns="streaming").remote(6)
    ]
    assert got == [0, 3, 6, 9, 12, 15]


def test_fleet_launch_rate_floor(ray_start):
    """Regression floor for the fleet-launch path (prestart + adaptive spawn
    width + preloaded forkserver): 100 zero-CPU actors must launch and
    answer one call each at >=15/s even on a loaded 1-core box."""

    @ray_tpu.remote(num_cpus=0)
    class Member:
        def pid(self):
            return os.getpid()

    t0 = time.perf_counter()
    actors = [Member.remote() for _ in range(100)]
    pids = ray_tpu.get([a.pid.remote() for a in actors], timeout=300)
    rate = 100 / (time.perf_counter() - t0)
    assert len(set(pids)) == 100
    for a in actors:
        ray_tpu.kill(a)
    assert rate >= 15.0, f"fleet launch regressed: {rate:.1f}/s"
