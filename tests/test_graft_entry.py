"""Regression tests for the driver entry points (``__graft_entry__.py``).

The multichip dry run must be hermetic: it runs on the virtual CPU host
platform regardless of what hardware backend is visible or already
initialized (VERDICT r2: the r1/r2 artifacts went red because eager ops
dispatched to a flaky TPU tunnel). These tests run the dry run in
subprocesses *without* forcing ``JAX_PLATFORMS``, so whatever hardware
plugin the environment exposes stays visible — exactly the driver's setup.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(code: str, timeout: float = 600.0) -> subprocess.CompletedProcess:
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    return subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def test_dryrun_multichip_hermetic_fresh_process():
    proc = _run(
        "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)"
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dryrun_multichip ok" in proc.stdout


def test_dryrun_multichip_multiprocess():
    # multi-host SPMD shape on virtual devices: 2 processes x 4 cpu devices
    # joined via jax.distributed = one 8-device global mesh
    proc = _run(
        "from __graft_entry__ import dryrun_multichip; "
        "dryrun_multichip(8, n_processes=2)"
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "processes=2" in proc.stdout


def test_dryrun_multichip_after_default_backend_initialized():
    # Even if the caller initialized the default (possibly hardware) backend
    # first, the dry run must still complete on 8 virtual CPU devices.
    proc = _run(
        "import jax\n"
        "try:\n"
        "    jax.devices()\n"
        "except Exception:\n"
        "    pass\n"  # no backend at all is fine too
        "from __graft_entry__ import dryrun_multichip\n"
        "dryrun_multichip(8)\n"
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
