"""Compiled DAG tests: general (branching / multi-output) graphs and
cross-node channel edges.

Parity: the reference compiles arbitrary multi-actor DAGs with typed
cross-node channels (``python/ray/dag/compiled_dag_node.py:391``,
``python/ray/experimental/channel/``); here same-node edges are mutable shm
channels and cross-node edges are authenticated one-slot socket channels.
"""

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.dag import GeneralCompiledDAG, InputNode, MultiOutputNode


@ray_tpu.remote
class _Add:
    def __init__(self, k=1):
        self.k = k

    def add(self, x):
        return x + self.k


@ray_tpu.remote
class _Mul:
    def mul(self, x):
        return x * 2


@ray_tpu.remote
class _Join:
    def join(self, a, b):
        return (a, b)


def test_compiled_diamond_matches_eager(ray_start_regular):
    with InputNode() as inp:
        a = _Add.bind().add.bind(inp)
        b = _Mul.bind().mul.bind(inp)
        dag = _Join.bind().join.bind(a, b)

    eager = ray_tpu.get(dag.execute(7), timeout=60)
    compiled = dag.experimental_compile()
    assert isinstance(compiled, GeneralCompiledDAG)
    try:
        for v in (7, 0, -3):
            got = compiled.execute(v).get(timeout=60)
            assert got == (v + 1, v * 2)
        assert compiled.execute(7).get(timeout=60) == eager
    finally:
        compiled.teardown()


def test_compiled_multi_output(ray_start_regular):
    with InputNode() as inp:
        shared = _Add.bind().add.bind(inp)
        left = _Mul.bind().mul.bind(shared)
        dag = MultiOutputNode([shared, left])
    compiled = dag.experimental_compile()
    try:
        # pipelined executions with out-of-order result consumption
        r1 = compiled.execute(1)
        r2 = compiled.execute(10)
        assert r2.get(timeout=60) == [11, 22]
        assert r1.get(timeout=60) == [2, 4]
    finally:
        compiled.teardown()


def test_compiled_dag_exception_propagation(ray_start_regular):
    @ray_tpu.remote
    class _Boom:
        def f(self, x):
            raise ValueError("kapow")

    with InputNode() as inp:
        a = _Boom.bind().f.bind(inp)
        b = _Mul.bind().mul.bind(inp)
        dag = _Join.bind().join.bind(a, b)
    compiled = dag.experimental_compile()
    try:
        with pytest.raises(RuntimeError, match="kapow"):
            compiled.execute(1).get(timeout=60)
        # the pipeline survives the error and keeps serving
        with pytest.raises(RuntimeError, match="kapow"):
            compiled.execute(2).get(timeout=60)
    finally:
        compiled.teardown()


def test_compiled_diamond_across_daemon_nodes():
    """Diamond with its branch stages pinned to two daemon nodes: the edges
    to/from those stages are cross-node socket channels, and the compiled
    result matches eager execution."""
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        cluster.add_node(num_cpus=1, resources={"left": 1.0})
        cluster.add_node(num_cpus=1, resources={"right": 1.0})
        cluster.wait_for_nodes()

        with InputNode() as inp:
            a = _Add.options(resources={"left": 0.5}).bind().add.bind(inp)
            b = _Mul.options(resources={"right": 0.5}).bind().mul.bind(inp)
            dag = _Join.bind().join.bind(a, b)

        eager = ray_tpu.get(dag.execute(5), timeout=120)
        assert eager == (6, 10)

        compiled = dag.experimental_compile()
        assert isinstance(compiled, GeneralCompiledDAG)
        try:
            # at least the driver->branch and branch->join edges cross nodes
            kinds = {
                type(w).__name__ for w, _ in compiled._input_writers
            }
            assert "SocketChannelWriter" in kinds
            for v in (5, 12):
                got = compiled.execute(v).get(timeout=120)
                assert got == (v + 1, v * 2), got
            assert compiled.execute(5).get(timeout=120) == eager
        finally:
            compiled.teardown()
    finally:
        cluster.shutdown()


def test_compiled_output_stage_on_remote_node():
    """The OUTPUT stage lives on a daemon node, so the driver's result
    reader is a cross-node socket channel — compile must not block waiting
    for it (readers open lazily at first get)."""
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        cluster.add_node(num_cpus=1, resources={"out": 1.0})
        cluster.wait_for_nodes()

        with InputNode() as inp:
            a = _Add.bind().add.bind(inp)
            dag = _Join.options(resources={"out": 0.5}).bind().join.bind(
                a, _Mul.bind().mul.bind(inp)
            )
        compiled = dag.experimental_compile()
        assert isinstance(compiled, GeneralCompiledDAG)
        try:
            assert compiled.execute(4).get(timeout=120) == (5, 8)
            assert compiled.execute(9).get(timeout=120) == (10, 18)
        finally:
            compiled.teardown()
    finally:
        cluster.shutdown()


def test_compiled_dag_rejects_inputless_stage(ray_start_regular):
    """A method node with only constant args cannot be channel-compiled
    (its loop would run eagerly, decoupled from execute()); such graphs
    keep the pre-planned actor-call path."""
    from ray_tpu.dag import CompiledDAG

    @ray_tpu.remote
    class Tick:
        def __init__(self):
            self.n = 0

        def tick(self, step):
            self.n += step
            return self.n

    dag = Tick.bind().tick.bind(2)  # constant arg only, no InputNode
    compiled = dag.experimental_compile()
    assert isinstance(compiled, CompiledDAG)
    assert ray_tpu.get(compiled.execute(), timeout=60) == 2
    assert ray_tpu.get(compiled.execute(), timeout=60) == 4
    compiled.teardown()


def test_out_of_scope_actor_finishes_queued_calls(ray_start_regular):
    """An actor whose last handle is dropped must finish already-submitted
    calls before termination (reference GcsActorManager semantics)."""
    import gc

    @ray_tpu.remote
    class Slow:
        def work(self, x):
            import time

            time.sleep(0.3)
            return x * 2

    a = Slow.remote()
    refs = [a.work.remote(i) for i in range(4)]
    del a
    gc.collect()
    assert ray_tpu.get(refs, timeout=60) == [0, 2, 4, 6]
