"""Chaos harness: seeded, deterministic kill schedules for workers/nodes.

Parity: the reference's chaos fixtures (``_ray_start_chaos_cluster``,
``python/ray/tests/conftest.py:900``; killer actors
``python/ray/_private/test_utils.py:1500``), with one deliberate upgrade —
**determinism**. Every delay and every victim choice comes from one
``random.Random(seed)`` stream over *sorted* candidate lists, so a chaos
failure replays exactly under the same ``CHAOS_SEED`` instead of being a
once-in-CI ghost.

The monkey runs driver-side (a plain thread, not an actor): an injector
that lived in the cluster it is attacking could kill itself or be starved
by the very faults it injects.
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

_DEFAULT_SEED = 1729


def chaos_seed(default: int = _DEFAULT_SEED) -> int:
    """The run's chaos seed: ``CHAOS_SEED`` env var, else ``default``.
    Print it in failure output; re-exporting it reproduces the run."""
    try:
        return int(os.environ.get("CHAOS_SEED", "") or default)
    except ValueError:
        return default


class KillSchedule:
    """A deterministic sequence of inter-kill delays drawn from
    ``interval_s = (lo, hi)``. Two schedules with the same seed are
    identical forever."""

    def __init__(self, seed: int, interval_s: Tuple[float, float] = (0.4, 1.2)):
        self._rng = random.Random(seed)
        self.interval_s = interval_s

    def next_delay(self) -> float:
        lo, hi = self.interval_s
        return self._rng.uniform(lo, hi)

    def choose(self, candidates: Sequence):
        """Deterministic victim pick — candidates must be pre-sorted by
        the caller so the choice depends only on the seed and the set."""
        if not candidates:
            return None
        return self._rng.choice(list(candidates))


def actor_pids(class_name: str) -> List[int]:
    """PIDs of ALIVE actors of one class (``state.list_actors`` rows carry
    class provenance), sorted for deterministic victim choice. Excludes
    this process."""
    from ray_tpu.util import state as state_api

    me = os.getpid()
    pids = set()
    try:
        for row in state_api.list_actors():
            if (
                row.get("state") == "ALIVE"
                and row.get("class_name") == class_name
                and row.get("pid")
                and row["pid"] != me
            ):
                pids.add(row["pid"])
    except Exception:
        pass
    return sorted(pids)


def train_worker_pids() -> List[int]:
    """PIDs of live train workers (the ``_TrainWorker`` actor group)."""
    return actor_pids("_TrainWorker")


def serve_replica_pids() -> List[int]:
    """PIDs of live serve replica actors (serve chaos victims)."""
    return actor_pids("Replica")


def serve_controller_pids() -> List[int]:
    """PID (singleton list) of the live serve controller actor."""
    return actor_pids("ServeController")


def elastic_sgd_loop(total_steps: int, step_sleep: float = 0.0):
    """Deterministic full-batch linear-regression SGD, world-size
    invariant: every rank computes the identical replicated update, saves
    only ITS row partition of the weights (a genuinely sharded elastic
    checkpoint), and restores the full weights from whatever shard layout
    was committed. Same step count => bitwise-same weights, at any world
    size and through any number of resumes. Shared by the chaos
    convergence tests and bench_core's goodput row so both measure the
    same workload."""

    def loop(config=None):
        import time as _time

        import numpy as np

        from ray_tpu import train

        rng = np.random.default_rng(7)
        X = rng.normal(size=(48, 6))
        w_true = np.array([1.0, -2.0, 3.0, 0.5, -1.5, 2.5])
        y = X @ w_true
        state = train.load_elastic(full=True)
        if state is not None:
            arrays, extra = state
            w, start = arrays["w"], int(extra["step"])
        else:
            w, start = np.zeros(6), 0
        for step in range(start, total_steps):
            grad = 2.0 * X.T @ (X @ w - y) / len(y)
            w = w - 0.05 * grad
            loss = float(np.mean((X @ w - y) ** 2))
            if step_sleep:
                _time.sleep(step_sleep)
            train.report_elastic(
                {"loss": loss, "resumed_at": float(start)},
                {"w": w},
                extra={"step": step + 1},
            )

    return loop


class ChaosMonkey:
    """Driver-side thread that SIGKILLs one victim per schedule tick.

    ``victims`` returns the current candidate pid list (sorted); the
    default targets live train workers. ``node_pids`` adds node-daemon
    pids to the pool with probability ``node_kill_prob`` per tick — a
    node kill models whole-host preemption. Stop with :meth:`stop`;
    ``monkey.kills`` is the ordered (timestamp, pid, kind) log."""

    def __init__(
        self,
        *,
        seed: Optional[int] = None,
        interval_s: Tuple[float, float] = (0.4, 1.2),
        victims: Callable[[], List[int]] = train_worker_pids,
        node_pids: Callable[[], List[int]] = lambda: [],
        node_kill_prob: float = 0.0,
        max_kills: Optional[int] = None,
        duration_s: Optional[float] = None,
        arm_when: Optional[Callable[[], bool]] = None,
    ):
        self.seed = chaos_seed() if seed is None else seed
        self.schedule = KillSchedule(self.seed, interval_s)
        self._victims = victims
        self._node_pids = node_pids
        self._node_kill_prob = node_kill_prob
        self._max_kills = max_kills
        self._duration_s = duration_s
        # optional arming predicate: hold fire until it turns true (e.g.
        # "a committed checkpoint exists") — anchors the schedule to
        # workload PROGRESS instead of wall time, which keeps a seeded
        # run meaningful across hosts of different speeds
        self._arm_when = arm_when
        self.kills: List[Tuple[float, int, str]] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="chaos-monkey", daemon=True
        )

    def start(self) -> "ChaosMonkey":
        self._thread.start()
        return self

    def _run(self) -> None:
        if self._arm_when is not None:
            while not self._stop.is_set():
                try:
                    if self._arm_when():
                        break
                except Exception:
                    pass
                if self._stop.wait(0.1):
                    return
        t0 = time.monotonic()
        while not self._stop.is_set():
            if self._max_kills is not None and len(self.kills) >= self._max_kills:
                return
            if (
                self._duration_s is not None
                and time.monotonic() - t0 > self._duration_s
            ):
                return
            if self._stop.wait(self.schedule.next_delay()):
                return
            kind = "worker"
            pool = self._victims()
            if self._node_kill_prob > 0:
                # the node-vs-worker coin comes from the same seeded
                # stream, so the whole attack sequence is reproducible
                if self.schedule._rng.random() < self._node_kill_prob:
                    nodes = sorted(self._node_pids())
                    if nodes:
                        pool, kind = nodes, "node"
            victim = self.schedule.choose(sorted(pool))
            if victim is None:
                continue
            try:
                os.kill(victim, signal.SIGKILL)
                self.kills.append((time.monotonic() - t0, victim, kind))
            except (ProcessLookupError, PermissionError):
                continue

    def stop(self) -> int:
        """Stop injecting; returns the number of successful kills."""
        self._stop.set()
        self._thread.join(timeout=10)
        return len(self.kills)

    def __enter__(self) -> "ChaosMonkey":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.stop()
