"""Reusable chaos-injection harness for fault-tolerance tests.

Seeded and deterministic: every schedule and victim choice derives from
``CHAOS_SEED`` (env knob, see :func:`chaos_seed`), so a failing chaos run
reproduces with ``CHAOS_SEED=<n> make chaos``.
"""

from .harness import (  # noqa: F401
    ChaosMonkey,
    KillSchedule,
    chaos_seed,
    elastic_sgd_loop,
    serve_controller_pids,
    serve_replica_pids,
    train_worker_pids,
)
