"""Checkpoint plane: commit protocol, chaos (crashes mid-save/upload),
auto-resume, retention, and the state/CLI surfaces.

The invariant under test everywhere: a crash injected at ANY point of
save/upload never lets ``latest()`` / ``Checkpoint.from_uri`` observe an
uncommitted or digest-mismatched checkpoint.
"""

import glob
import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private import external_storage as storage
from ray_tpu.train import checkpointing
from ray_tpu.train._checkpoint import Checkpoint


def _make_src(tmp_path, name="src", files=(("a.bin", b"A" * 256), ("sub/b.txt", b"hello"))):
    src = tmp_path / name
    for rel, data in files:
        p = src / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_bytes(data)
    return str(src)


class _FaultyBackend(storage.MemoryBackend):
    """Raises after ``fail_after`` writes — the crash-injection hook: the
    uploader dies at an arbitrary point mid-upload."""

    def __init__(self):
        super().__init__()
        self.writes = 0
        self.fail_after = None

    def write_bytes(self, path, data):
        self.writes += 1
        if self.fail_after is not None and self.writes > self.fail_after:
            raise OSError("injected storage failure")
        super().write_bytes(path, data)


class _SlowBackend(storage.MemoryBackend):
    def __init__(self):
        super().__init__()
        self.delay_s = 0.0

    def write_bytes(self, path, data):
        time.sleep(self.delay_s)
        super().write_bytes(path, data)


@pytest.fixture
def faulty_scheme():
    backend = _FaultyBackend()
    storage.register_backend("faulty", lambda: backend)
    yield backend
    storage._FACTORIES.pop("faulty", None)
    storage._BACKENDS.pop("faulty", None)


@pytest.fixture
def slow_scheme():
    backend = _SlowBackend()
    storage.register_backend("slowst", lambda: backend)
    yield backend
    storage._FACTORIES.pop("slowst", None)
    storage._BACKENDS.pop("slowst", None)


# --------------------------------------------------------------------------
# commit protocol
# --------------------------------------------------------------------------


def test_crash_at_every_point_of_upload_never_observable(tmp_path, faulty_scheme):
    """Sweep the crash point across the ENTIRE upload (every write index,
    payload through markers): readers must either see nothing or the fully
    committed checkpoint — no middle state."""
    src = _make_src(tmp_path)
    total_writes = len(storage.build_manifest(src)["files"]) + 2  # + manifest + COMMIT
    for crash_at in range(total_writes):
        base = f"faulty://sweep{crash_at}"
        uri = storage.join(base, checkpointing.step_dir_name(1))
        faulty_scheme.writes, faulty_scheme.fail_after = 0, crash_at
        with pytest.raises(OSError):
            storage.commit_dir_to_uri(src, uri)
        faulty_scheme.fail_after = None
        assert not storage.is_committed(uri)
        assert checkpointing.latest_step(base) is None
        with pytest.raises(FileNotFoundError):
            Checkpoint.from_uri(uri)
    # the un-crashed run commits and restores
    faulty_scheme.fail_after = None
    uri = storage.join("faulty://sweepok", checkpointing.step_dir_name(1))
    storage.commit_dir_to_uri(src, uri)
    assert checkpointing.latest_step("faulty://sweepok") == 1
    restored = Checkpoint.from_uri(uri)
    assert (
        open(os.path.join(restored.path, "sub", "b.txt"), "rb").read() == b"hello"
    )


def test_uploader_killed_mid_upload_latest_stays_on_committed(tmp_path, faulty_scheme):
    """Manager-level chaos: the background uploader dies mid-upload of step
    2; latest() keeps answering step 1 and the failure is recorded (and
    surfaces as CHECKPOINT_FAILED, not silence)."""
    base = str(tmp_path / "run")
    os.makedirs(base)
    mgr = checkpointing.CheckpointManager(
        base, storage_uri="faulty://chaos", world_size=1, run_name="chaos"
    )
    sd1 = os.path.join(base, checkpointing.step_dir_name(1))
    os.makedirs(sd1)
    (lambda p: open(p, "wb").write(b"one"))(os.path.join(sd1, "w.bin"))
    assert mgr.note_shard(0, 1, sd1)
    assert mgr.wait(timeout=30)
    assert checkpointing.latest_step("faulty://chaos") == 1

    faulty_scheme.fail_after = faulty_scheme.writes + 1  # die mid-step-2 upload
    sd2 = os.path.join(base, checkpointing.step_dir_name(2))
    os.makedirs(sd2)
    open(os.path.join(sd2, "w.bin"), "wb").write(b"two")
    assert mgr.note_shard(0, 2, sd2)
    assert mgr.wait(timeout=30)
    faulty_scheme.fail_after = None
    assert checkpointing.latest_step("faulty://chaos") == 1  # never the partial
    assert 2 in mgr.failures()
    mgr.shutdown()


def test_digest_mismatch_refused(tmp_path):
    src = _make_src(tmp_path, files=(("a.bin", b"A" * 256), ("u.txt", b"digests")))
    uri = "memory://digest/checkpoint_000001"
    storage.commit_dir_to_uri(src, uri)
    # corrupt one payload byte post-commit (bit-rot / torn overwrite); drop
    # the restore cache so the read actually hits the corrupted storage (a
    # cache hit would legitimately serve the digest-valid earlier copy)
    storage.write_bytes(storage.join(uri, "a.bin"), b"B" * 256)
    checkpointing.clear_restore_cache()
    with pytest.raises(storage.IntegrityError):
        Checkpoint.from_uri(uri)
    # verify_checkpoint agrees
    with pytest.raises(storage.IntegrityError):
        checkpointing.verify_checkpoint(uri)


def test_from_uri_cache_reuse_no_temp_leak(tmp_path):
    """The seed leaked one ckpt_dl_* dir per from_uri call; committed
    restores now share a digest-keyed cache slot."""
    src = _make_src(tmp_path)
    uri = "memory://cache/checkpoint_000001"
    storage.commit_dir_to_uri(src, uri)
    a = Checkpoint.from_uri(uri)
    b = Checkpoint.from_uri(uri)
    assert a.path == b.path
    # legacy (uncommitted) prefixes rotate generations in a per-URI slot:
    # re-download semantics, bounded disk (current + previous kept)
    legacy = "memory://cache/legacy"
    storage.write_bytes(storage.join(legacy, "x.bin"), b"x")
    paths = [
        Checkpoint.from_uri(legacy, allow_uncommitted=True).path for _ in range(4)
    ]
    slot = os.path.dirname(paths[-1])
    assert all(os.path.dirname(p) == slot for p in paths)
    assert len(os.listdir(slot)) <= 2, os.listdir(slot)


def test_async_save_returns_in_local_copy_time(tmp_path, slow_scheme):
    """note_shard (what train.report blocks on past the local copy) must
    not wait for the upload: with a 0.2s-per-write backend, the report
    path returns immediately and the commit lands in the background."""
    base = str(tmp_path / "run")
    os.makedirs(base)
    slow_scheme.delay_s = 0.2
    mgr = checkpointing.CheckpointManager(
        base, storage_uri="slowst://bg", world_size=1, run_name="bg"
    )
    sd = os.path.join(base, checkpointing.step_dir_name(1))
    os.makedirs(sd)
    open(os.path.join(sd, "w.bin"), "wb").write(b"payload")
    t0 = time.monotonic()
    assert mgr.note_shard(0, 1, sd)
    enqueue_s = time.monotonic() - t0
    assert enqueue_s < 0.15, f"note_shard blocked on the upload ({enqueue_s:.3f}s)"
    assert checkpointing.latest_step("slowst://bg") is None or enqueue_s < 0.15
    assert mgr.wait(timeout=30)
    assert checkpointing.latest_step("slowst://bg") == 1
    mgr.shutdown()


def test_retention_gc_keep_and_uncommitted_garbage(tmp_path):
    base = str(tmp_path / "run")
    os.makedirs(base)
    for step in (1, 2, 3):
        sd = os.path.join(base, checkpointing.step_dir_name(step))
        os.makedirs(sd)
        open(os.path.join(sd, "w.bin"), "wb").write(bytes([step]) * 32)
        if step != 2:  # step 2 simulates a crashed, never-committed save
            storage.write_commit_markers(
                sd, storage.build_manifest(sd, step=step, created=time.time())
            )
    deleted = checkpointing.gc_checkpoints(base, keep=1)
    # keep=1 -> committed step 1 doomed; uncommitted step 2 (older than the
    # newest committed step 3) is crashed garbage, also reclaimed
    assert sorted(deleted) == [1, 2]
    rows = checkpointing.list_checkpoints(base)
    assert [(r["step"], r["committed"]) for r in rows] == [(3, True)]


def test_preemption_hook_can_report_from_drain_thread(tmp_path):
    """The documented hook pattern — train.report(checkpoint=) one last
    time — runs on the SIGTERM drain SIDE thread, where the thread-local
    session is unset; the process-wide fallback must serve it."""
    from ray_tpu.train._session import TrainContext, _Session, _set_session

    trial = str(tmp_path / "trial")
    os.makedirs(trial)
    session = _Session(
        TrainContext(world_rank=0, world_size=1, trial_dir=trial), None, None
    )
    _set_session(session)
    errors = []

    def hook():
        try:
            from ray_tpu import train

            d = str(tmp_path / "src")
            os.makedirs(d, exist_ok=True)
            open(os.path.join(d, "final.txt"), "w").write("snap")
            train.report({"final": 1.0}, checkpoint=train.Checkpoint.from_directory(d))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    checkpointing.register_preemption_hook(hook)
    try:
        t = threading.Thread(  # the drain runs hooks off the task thread
            target=checkpointing.run_preemption_hooks, kwargs={"timeout_s": 10.0}
        )
        t.start()
        t.join(timeout=30)
    finally:
        checkpointing.unregister_preemption_hook(hook)
        _set_session(None)
    assert not errors, errors
    assert os.path.isfile(
        os.path.join(trial, checkpointing.step_dir_name(1), "final.txt")
    )


def test_preemption_hook_commits_pending(tmp_path):
    """The SIGTERM drain path: user hooks run (may report a final
    snapshot), then live managers drain so barriered saves reach COMMIT."""
    base = str(tmp_path / "run")
    os.makedirs(base)
    mgr = checkpointing.CheckpointManager(base, world_size=1, run_name="pre")
    calls = []

    def hook():
        calls.append(True)
        sd = os.path.join(base, checkpointing.step_dir_name(7))
        os.makedirs(sd, exist_ok=True)
        open(os.path.join(sd, "final.bin"), "wb").write(b"last gasp")
        mgr.note_shard(0, 7, sd)

    checkpointing.register_preemption_hook(hook)
    try:
        checkpointing.run_preemption_hooks(timeout_s=10.0)
    finally:
        checkpointing.unregister_preemption_hook(hook)
        mgr.shutdown()
    assert calls
    assert checkpointing.latest_step(base) == 7


# --------------------------------------------------------------------------
# trainer integration (cluster)
# --------------------------------------------------------------------------


def _counting_loop(marker_kill=None, steps=4):
    """A train loop that checkpoints every step and optionally SIGKILLs
    itself (non-graceful worker death) once at step 2."""

    def loop(config=None):
        import os as _os
        import signal as _signal
        import tempfile

        from ray_tpu import train

        ckpt = train.get_checkpoint()
        start = 0
        if ckpt is not None:
            with open(_os.path.join(ckpt.path, "it.txt")) as fh:
                start = int(fh.read()) + 1
        for i in range(start, steps):
            d = tempfile.mkdtemp()
            with open(_os.path.join(d, "it.txt"), "w") as fh:
                fh.write(str(i))
            train.report(
                {"it": float(i), "resumed_from": float(start)},
                checkpoint=train.Checkpoint.from_directory(d),
            )
            if marker_kill and i == 1 and not _os.path.exists(marker_kill):
                open(marker_kill, "w").close()
                _os.kill(_os.getpid(), _signal.SIGKILL)

    return loop


def test_worker_killed_mid_run_resumes_from_committed(ray_start_regular, tmp_path):
    """Chaos acceptance: SIGKILL a train worker mid-run; fit() must resume
    from the last COMMITTED step and retention must hold (no more than
    keep checkpoints on disk afterwards)."""
    from ray_tpu.train import (
        CheckpointConfig,
        FailureConfig,
        JaxTrainer,
        RunConfig,
        ScalingConfig,
    )

    marker = str(tmp_path / "killed_once")
    keep = 2
    trainer = JaxTrainer(
        _counting_loop(marker_kill=marker),
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            storage_path=str(tmp_path),
            name="chaos_resume",
            failure_config=FailureConfig(max_failures=2),
            checkpoint_config=CheckpointConfig(num_to_keep=keep),
        ),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["it"] == 3.0
    # the retried attempt resumed from committed step 2 (it=1), not scratch
    assert result.metrics["resumed_from"] == 2.0
    trial_dir = str(tmp_path / "chaos_resume")
    ckpt_dirs = [d for d in os.listdir(trial_dir) if d.startswith("checkpoint_")]
    assert len(ckpt_dirs) <= keep, ckpt_dirs
    # everything still on disk is committed
    for d in ckpt_dirs:
        assert storage.is_committed(os.path.join(trial_dir, d))
    # the result checkpoint is the digest-valid newest one
    assert result.checkpoint is not None
    with open(os.path.join(result.checkpoint.path, "it.txt")) as fh:
        assert fh.read() == "3"


def test_multiworker_shard_barrier_and_manifest(ray_start_regular, tmp_path):
    """2 ranks: each reports its own shard; the head barriers them into ONE
    committed checkpoint whose manifest covers both shards; on resume each
    rank sees its own shard."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def loop(config=None):
        import os as _os
        import tempfile

        from ray_tpu import train

        ctx = train.get_context()
        ckpt = train.get_checkpoint()
        resumed_rank = -1.0
        if ckpt is not None:
            with open(_os.path.join(ckpt.path, "rank.txt")) as fh:
                resumed_rank = float(fh.read())
        d = tempfile.mkdtemp()
        with open(_os.path.join(d, "rank.txt"), "w") as fh:
            fh.write(str(ctx.get_world_rank()))
        train.report(
            {"rank": ctx.get_world_rank(), "resumed_rank": resumed_rank},
            checkpoint=train.Checkpoint.from_directory(d),
        )

    run_cfg = RunConfig(storage_path=str(tmp_path), name="sharded")
    result = JaxTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2), run_config=run_cfg
    ).fit()
    assert result.error is None, result.error
    step_dir = os.path.join(str(tmp_path / "sharded"), checkpointing.step_dir_name(1))
    manifest = storage.read_committed_manifest(step_dir)
    assert manifest is not None and manifest["world_size"] == 2
    shards = {rel.split(os.sep)[0] for rel in manifest["files"]}
    assert shards == {"shard-00000-of-00002", "shard-00001-of-00002"}
    # resume: a second fit from that checkpoint gives each rank ITS shard
    result2 = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path), name="sharded2"),
        resume_from_checkpoint=result.checkpoint,
    ).fit()
    assert result2.error is None, result2.error
    assert result2.metrics["resumed_rank"] == 0.0  # rank 0 read shard 0


def test_rank0_only_checkpoint_still_commits(ray_start_regular, tmp_path):
    """The reference's default gather pattern — only rank 0 reports a
    checkpoint — must commit a single-shard checkpoint once every rank has
    reported the step (not stall the barrier forever)."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def loop(config=None):
        import os as _os
        import tempfile

        from ray_tpu import train

        ctx = train.get_context()
        if ctx.get_world_rank() == 0:
            d = tempfile.mkdtemp()
            open(_os.path.join(d, "gathered.txt"), "w").write("all ranks state")
            train.report({"rank": 0}, checkpoint=train.Checkpoint.from_directory(d))
        else:
            train.report({"rank": ctx.get_world_rank()})

    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path), name="rank0only"),
    ).fit()
    assert result.error is None, result.error
    assert result.checkpoint is not None
    step_dir = os.path.join(str(tmp_path / "rank0only"), checkpointing.step_dir_name(1))
    manifest = storage.read_committed_manifest(step_dir)
    assert manifest is not None
    shards = {rel.split(os.sep)[0] for rel in manifest["files"]}
    assert shards == {"shard-00000-of-00002"}, shards


def test_trainer_commits_to_uri_and_registry(ray_start_regular, tmp_path):
    """URI storage: checkpoints are committed (not bare-mirrored) to the
    backend, CHECKPOINT_COMMITTED events land in the cluster event log, and
    state.list_checkpoints sees the run via the KV registry."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig
    from ray_tpu.util import state

    result = JaxTrainer(
        _counting_loop(steps=2),
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="uri_commit", storage_path="memory://ckpt_plane"),
    ).fit()
    assert result.error is None, result.error
    base = "memory://ckpt_plane/uri_commit"
    assert checkpointing.latest_step(base) == 2
    restored = Checkpoint.from_uri(
        storage.join(base, checkpointing.step_dir_name(2))
    )
    with open(os.path.join(restored.path, "it.txt")) as fh:
        assert fh.read() == "1"
    rows = state.list_checkpoints(filters=[("run", "=", "uri_commit")])
    assert rows and all(r["committed"] for r in rows if r["step"] == 2)
    events = state.list_cluster_events(filters=[("type", "=", "CHECKPOINT_COMMITTED")])
    assert any(e.get("run") == "uri_commit" for e in events), events
    # save/commit spans ride the telemetry plane into the timeline
    names = {e.get("name") for e in ray_tpu.timeline()}
    assert any(n and "checkpoint_commit" in n for n in names), sorted(
        n for n in names if n
    )[:40]


def test_tuner_resume_from_uri_after_node_loss(tmp_path):
    """Satellite: a tune experiment on external storage survives losing
    BOTH the driver and the node-local staging dirs — Tuner.restore(uri)
    resumes trials from committed checkpoint URIs."""
    import signal
    import subprocess
    import sys
    import textwrap

    import ray_tpu as rt

    store = tmp_path / "store"
    script = textwrap.dedent(f"""
        import ray_tpu, time
        from ray_tpu import tune
        from ray_tpu._private import external_storage as storage
        from ray_tpu.train import Checkpoint, RunConfig, report
        storage.register_backend("mock", storage.FileBackend)
        ray_tpu.init(num_cpus=2)

        def slow_trial(config):
            import os, tempfile
            for i in range(40):
                d = tempfile.mkdtemp()
                open(os.path.join(d, "it.txt"), "w").write(str(i))
                report({{"step": i, "tag": config["tag"]}},
                       checkpoint=Checkpoint.from_directory(d))
                time.sleep(0.4)

        tune.Tuner(
            slow_trial,
            param_space={{"tag": tune.grid_search([1, 2])}},
            tune_config=tune.TuneConfig(num_samples=1, max_concurrent_trials=2),
            run_config=RunConfig(storage_path="mock://{store}", name="uexp"),
        ).fit()
    """)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(rt.__file__)))
    proc = subprocess.Popen([sys.executable, "-c", script], env=env)
    # wait until both trials have a committed checkpoint in the mirror AND
    # the snapshot is mirrored, then kill the driver mid-sweep
    storage.register_backend("mock", storage.FileBackend)
    exp_uri = f"mock://{store}/uexp"
    deadline = time.monotonic() + 90
    try:
        import cloudpickle

        while time.monotonic() < deadline:
            # the MIRRORED snapshot must already reference a committed URI
            # for both trials (the 2s mirror cadence lags the commits)
            snap_blob = storage.read_bytes(
                storage.join(exp_uri, "experiment_state.pkl")
            )
            uris_ok = False
            if snap_blob:
                try:
                    snap = cloudpickle.loads(snap_blob)
                    uris = [
                        t.get("checkpoint_uri")
                        for t in snap["trials"].values()
                    ]
                    uris_ok = len(uris) >= 2 and all(
                        u and storage.is_committed(u) for u in uris
                    )
                except Exception:
                    uris_ok = False
            if uris_ok:
                break
            time.sleep(0.3)
        else:
            raise TimeoutError("mirrored snapshot/checkpoints never appeared")
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=15)
    # simulate restoring on a different node: the old driver's local
    # staging dirs are gone
    import shutil
    import tempfile

    for d in glob.glob(os.path.join(tempfile.gettempdir(), "ray_tpu_tune_uexp_*")):
        shutil.rmtree(d, ignore_errors=True)

    from ray_tpu import tune

    def fast_trial(config):
        import os as _os

        from ray_tpu import train

        ckpt = train.get_checkpoint()
        assert ckpt is not None, "trial did not resume from the URI checkpoint"
        with open(_os.path.join(ckpt.path, "it.txt")) as fh:
            start = int(fh.read())
        train.report({"tag": config["tag"], "resumed_at": start})

    rt.init(num_cpus=2, ignore_reinit_error=True)
    try:
        results = tune.Tuner.restore(exp_uri, trainable=fast_trial).fit()
        tags = sorted(r.metrics["tag"] for r in results)
        assert tags == [1, 2]
        assert all(r.error is None for r in results)
        assert all(r.metrics["resumed_at"] >= 0 for r in results)
    finally:
        rt.shutdown()


def test_ckpt_cli_list_latest_verify_gc(tmp_path, capsys):
    """``ray_tpu ckpt`` against a bare --storage base (no cluster)."""
    from ray_tpu.scripts.cli import main as cli_main

    base = str(tmp_path / "clirun")
    os.makedirs(base)
    for step in (1, 2):
        sd = os.path.join(base, checkpointing.step_dir_name(step))
        os.makedirs(sd)
        open(os.path.join(sd, "w.bin"), "wb").write(bytes([step]) * 64)
        storage.write_commit_markers(
            sd,
            storage.build_manifest(sd, step=step, created=time.time(), run="clirun"),
        )
    cli_main(["ckpt", "list", "--storage", base])
    out = capsys.readouterr().out
    assert out.count("COMMITTED") == 2
    cli_main(["ckpt", "latest", "--storage", base])
    assert checkpointing.step_dir_name(2) in capsys.readouterr().out
    cli_main(["ckpt", "verify", os.path.join(base, checkpointing.step_dir_name(1))])
    assert capsys.readouterr().out.startswith("OK:")
    cli_main(["ckpt", "gc", "--storage", base, "--keep", "1"])
    assert "deleted 1" in capsys.readouterr().out
    rows = checkpointing.list_checkpoints(base)
    assert [r["step"] for r in rows] == [2]


def test_bounded_queue_backpressure(tmp_path, slow_scheme):
    """max_inflight bounds the upload queue: a burst of saves can only run
    so far ahead of the uploader (memory safety), and every one commits."""
    base = str(tmp_path / "run")
    os.makedirs(base)
    slow_scheme.delay_s = 0.05
    mgr = checkpointing.CheckpointManager(
        base,
        storage_uri="slowst://burst",
        world_size=1,
        max_inflight=2,
        run_name="burst",
    )
    done = []

    def producer():
        for step in range(1, 7):
            sd = os.path.join(base, checkpointing.step_dir_name(step))
            os.makedirs(sd, exist_ok=True)
            open(os.path.join(sd, "w.bin"), "wb").write(bytes([step]) * 16)
            mgr.note_shard(0, step, sd)
            done.append(step)

    t = threading.Thread(target=producer)
    t.start()
    t.join(timeout=60)
    assert not t.is_alive()
    assert mgr.wait(timeout=60)
    assert checkpointing.latest_step("slowst://burst") == 6
    assert not mgr.failures()
    mgr.shutdown()
