"""Continuous-batching engine correctness (no cluster: engine-in-process).

The load-bearing claim: in-flight batching is *schedule-invariant* — a
sequence's greedy tokens are identical whether it decodes alone or joins
a running batch mid-flight with mixed lengths (the fixed decode shape +
per-sequence positions/PRNG make batch composition invisible). Plus:
KV blocks free the moment a sequence finishes, and KV exhaustion sheds
with the serve plane's typed overload error instead of hanging.
"""

import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.models import generation as G  # noqa: E402
from ray_tpu.models.transformer import TransformerConfig, init_params  # noqa: E402
from ray_tpu.serve.exceptions import DeploymentOverloadedError  # noqa: E402
from ray_tpu.serve.llm.engine import EngineConfig, InferenceEngine  # noqa: E402

CFG = TransformerConfig(
    vocab_size=97,
    d_model=32,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,  # GQA path exercised
    d_ff=64,
    max_seq_len=128,
    dtype=jnp.float32,
)
ECFG = EngineConfig(
    block_size=4,
    num_blocks=64,
    max_batch=3,
    max_blocks_per_seq=16,
    max_waiting=16,
    stream_timeout_s=60.0,
)


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture
def engine(params):
    eng = InferenceEngine(params, CFG, ECFG, deployment="test-llm")
    yield eng
    eng.shutdown()


def _prompts(n, lo=3, hi=13, seed=2):
    rs = np.random.RandomState(seed)
    return [list(rs.randint(1, CFG.vocab_size, size=rs.randint(lo, hi))) for _ in range(n)]


def test_continuous_matches_isolated_greedy(params, engine):
    """Staggered arrivals + mixed lengths through the shared engine emit
    tokenwise-identical greedy outputs to each prompt decoded in
    isolation (dense static path AND solo engine run)."""
    prompts = _prompts(7)
    dense = [
        np.asarray(G.generate(params, p, CFG, max_new_tokens=9))[0].tolist()
        for p in prompts
    ]
    streams = []
    for i, p in enumerate(prompts):
        streams.append(engine.submit(p, max_new_tokens=9))
        time.sleep(0.01 * (i % 3))  # stagger so cohorts genuinely mix
    outs = [s.tokens() for s in streams]
    assert outs == dense
    # and a solo engine pass (paged, batch of one) agrees too
    solo = InferenceEngine(params, CFG, ECFG, deployment="test-llm-solo")
    try:
        assert solo.submit(prompts[0], max_new_tokens=9).tokens() == dense[0]
    finally:
        solo.shutdown()


def test_sampling_seeded_and_batch_invariant(params, engine):
    """temperature/top-k sampling is keyed by (seed, step) per sequence:
    the same request samples the same tokens alone or mid-batch."""
    prompt = _prompts(1, seed=5)[0]
    kw = dict(max_new_tokens=8, temperature=0.9, top_k=5, seed=123)
    alone = engine.submit(prompt, **kw).tokens()
    # resubmit surrounded by greedy neighbours occupying the other slots
    neighbours = [
        engine.submit(p, max_new_tokens=12) for p in _prompts(2, seed=6)
    ]
    again = engine.submit(prompt, **kw).tokens()
    for s in neighbours:
        s.tokens()
    assert again == alone
    # a different seed moves the sample (sanity: not argmax in disguise)
    other = engine.submit(prompt, **dict(kw, seed=124)).tokens()
    assert other != alone or len(alone) <= 2


def test_greedy_default_unchanged_by_sampling_params(params, engine):
    """temperature=0 stays bitwise-stable regardless of top_k/seed."""
    prompt = _prompts(1, seed=9)[0]
    a = engine.submit(prompt, max_new_tokens=6).tokens()
    b = engine.submit(prompt, max_new_tokens=6, top_k=3, seed=77).tokens()
    assert a == b


def test_blocks_free_immediately_on_finish(params, engine):
    """A short sequence finishing mid-batch returns its blocks while a
    long neighbour is still decoding — reclamation is per-sequence, not
    per-cohort."""
    long_s = engine.submit(_prompts(1, seed=11)[0], max_new_tokens=40)
    short_s = engine.submit(_prompts(1, seed=12)[0], max_new_tokens=2)
    short_s.tokens()  # drained: finished
    deadline = time.time() + 10
    saw_reclaim = False
    while time.time() < deadline:
        st = engine.kv_stats()
        if st["running"] == 1 and st["blocks_committed"] > 0:
            saw_reclaim = True
            break
        time.sleep(0.02)
    long_s.tokens()
    assert saw_reclaim, "short sequence's finish did not free its slot early"
    st = engine.kv_stats()
    assert st["blocks_free"] == st["blocks_total"]
    assert st["blocks_committed"] == 0


def test_kv_exhaustion_sheds_typed_never_hangs(params):
    """Admission over a tiny pool: excess submits fail FAST with the typed
    overload error (retry_after set), admitted work still completes, and
    nothing hangs."""
    eng = InferenceEngine(
        params,
        CFG,
        EngineConfig(
            block_size=4,
            num_blocks=9,  # 8 usable blocks
            max_batch=2,
            max_blocks_per_seq=8,
            max_waiting=1,
            stream_timeout_s=30.0,
        ),
        deployment="test-llm-tiny",
    )
    try:
        prompt = _prompts(1, seed=3)[0][:6]
        admitted, shed = [], []
        t0 = time.perf_counter()
        for _ in range(10):
            try:
                admitted.append(eng.submit(prompt, max_new_tokens=8))
            except DeploymentOverloadedError as e:
                shed.append(e)
        elapsed = time.perf_counter() - t0
        assert shed, "tiny pool never shed"
        assert admitted, "everything shed"
        assert elapsed < 5.0, f"shedding took {elapsed:.1f}s — queued, not shed"
        for e in shed:
            assert e.retry_after_s > 0
            assert e.capacity == 8
        for s in admitted:
            assert len(s.tokens()) == 8  # admitted work unaffected
        st = eng.kv_stats()
        assert st["blocks_free"] == st["blocks_total"]
    finally:
        eng.shutdown()


def test_submit_rejects_oversized_context(params, engine):
    with pytest.raises(ValueError):
        engine.submit([1] * 100, max_new_tokens=1000)


def test_eos_token_stops_early(params, engine):
    """Whatever greedy emits first, declaring it the eos stops the
    stream at one token with reason 'stop'."""
    prompt = _prompts(1, seed=4)[0]
    first = engine.submit(prompt, max_new_tokens=5).tokens()[0]
    s = engine.submit(prompt, max_new_tokens=5, eos_token=first)
    assert s.tokens() == [first]
    assert s.finish_reason == "stop"


def test_shutdown_fails_streams_typed(params):
    eng = InferenceEngine(params, CFG, ECFG, deployment="test-llm-down")
    streams = [eng.submit(p, max_new_tokens=50) for p in _prompts(3, seed=8)]
    eng.shutdown()
    outcomes = []
    for s in streams:
        try:
            s.tokens()
            outcomes.append("done")
        except RuntimeError:
            outcomes.append("typed")
        except TimeoutError:
            outcomes.append("hang")
    assert "hang" not in outcomes


def test_generate_top_k_sampling(params):
    """Satellite: generate() grows top-k; greedy default is untouched."""
    prompt = _prompts(1, seed=10)[0]
    g1 = np.asarray(G.generate(params, prompt, CFG, max_new_tokens=6))
    g2 = np.asarray(G.generate(params, prompt, CFG, max_new_tokens=6, top_k=4))
    assert (g1 == g2).all(), "top_k must not perturb greedy decode"
    key = jax.random.PRNGKey(1)
    s1 = np.asarray(
        G.generate(
            params, prompt, CFG, max_new_tokens=6, temperature=0.8, top_k=3, key=key
        )
    )
    s2 = np.asarray(
        G.generate(
            params, prompt, CFG, max_new_tokens=6, temperature=0.8, top_k=3, key=key
        )
    )
    assert (s1 == s2).all(), "same key must reproduce the same sample"


def test_sample_token_top_k_masks_tail():
    """top_k=1 sampling degenerates to argmax for any key."""
    logits = jnp.asarray(np.random.RandomState(0).randn(4, 33), jnp.float32)
    for i in range(3):
        tok = G.sample_token(
            logits, temperature=1.0, top_k=1, key=jax.random.PRNGKey(i)
        )
        assert (np.asarray(tok) == np.asarray(logits).argmax(-1)).all()
