"""Priority preemption vs the training plane (slow chaos test).

A low-priority ``JaxTrainer`` run saturating the cluster is preempted by a
high-priority tenant's starved task. The scheduler kills one trainer rank
(SIGTERM → checkpoint drain hooks), the urgent task runs, and the elastic
executor replaces the rank — which must resume from the latest COMMITTED
step with ``steps_redone == 0`` (the async local commit keeps the redo
window empty) and land on the exact loss of a calm run.

Slow-marked (tier-1 budget); run via ``make chaos`` or ``-m slow``.
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu.train import checkpointing

# pytest's prepend import mode puts tests/ on sys.path (no tests/__init__)
from chaos import elastic_sgd_loop

pytestmark = pytest.mark.slow


def _fit(tmp_path, name, total_steps, *, step_sleep=0.0):
    from ray_tpu.train import FailureConfig, JaxTrainer, RunConfig, ScalingConfig

    return JaxTrainer(
        elastic_sgd_loop(total_steps, step_sleep),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            storage_path=str(tmp_path),
            name=name,
            failure_config=FailureConfig(
                max_failures=8,
                retry_backoff_s=0.2,
                retry_backoff_jitter=0.0,
                replacement_timeout_s=60.0,
                abort_drain_timeout_s=60.0,
            ),
        ),
    ).fit()


def test_preempted_trainer_resumes_from_committed_zero_redone(tmp_path):
    rt = ray_tpu.init(
        num_cpus=2, _system_config={"preemption_wait_s": 0.8}
    )
    try:
        total = 26
        calm = _fit(tmp_path, "calm", total)
        assert calm.error is None, calm.error

        trial = str(tmp_path / "victim")
        aggressor_out = {}

        def aggressor():
            # arm only once a committed step exists: the preemption then
            # provably forces a resume-from-committed, never a
            # restart-from-scratch
            deadline = time.monotonic() + 180
            while (checkpointing.latest_step(trial) or 0) < 2:
                if time.monotonic() > deadline:
                    aggressor_out["error"] = "no committed step appeared"
                    return
                time.sleep(0.2)

            @ray_tpu.remote
            def urgent():
                return "served"

            with ray_tpu.job_scope(name="urgent", priority=10):
                ref = urgent.remote()
            # both CPUs are held by priority-0 trainer ranks: this get only
            # returns because the scheduler preempts one of them
            aggressor_out["result"] = ray_tpu.get(ref, timeout=120)

        t = threading.Thread(target=aggressor, daemon=True)
        t.start()
        with ray_tpu.job_scope(name="train-lo", priority=0):
            churned = _fit(tmp_path, "victim", total, step_sleep=0.15)
        t.join(timeout=120)

        assert aggressor_out.get("result") == "served", aggressor_out
        assert churned.error is None, churned.error
        assert churned.metrics["training_iteration"] == total
        # resumed from a committed step, bitwise-identically
        assert churned.metrics["resumed_at"] > 0
        assert churned.metrics["loss"] == calm.metrics["loss"]
        # the acceptance bar: zero redone steps — every step the preempted
        # run reported after recovery continued from the committed frontier
        assert churned.goodput is not None
        assert churned.goodput["steps_redone"] == 0, churned.goodput

        from ray_tpu.util import state

        preempts = state.list_cluster_events(
            filters=[("type", "=", "PREEMPTED")]
        )
        assert preempts, "scheduler never preempted a trainer rank"
        rows = {r["name"]: r for r in state.list_jobs()}
        assert rows["train-lo"]["preemptions"] >= 1
        assert rows["urgent"]["priority"] == 10
        # the preemption rode the worker-death plane the elastic executor
        # watches: the rank was replaced, not the whole run restarted
        types = {e["type"] for e in state.list_cluster_events()}
        assert "TRAIN_WORKER_DIED" in types, sorted(types)
        # the final step is committed and digest-valid
        assert checkpointing.latest_step(trial) == total
        checkpointing.verify_checkpoint(
            checkpointing.discover_steps(trial)[total]
        )
    finally:
        ray_tpu.shutdown()
