"""Transfer-plane observability tests (fast tier-1).

Covers: per-transfer stage decomposition (dial → request →
first_byte_wait → wire → seal sums against wall time), the config-driven
``wait_covered`` / ``wait_serves_drained`` deadlines with the typed
``ObjectTransferStalledError``, pipelined-relay fail propagation when the
source dies mid-serve, leaked-buffer accounting, same-host shm vs socket
content parity, the scheduler's link ledger + relay-hop tagging + trace
join on a real socket-plane broadcast, the slow-link and stalled-transfer
watchdogs (seeded positive + calm-silence), SLOW_LINK /
OBJECT_TRANSFER_STALLED queryability through the state API and the
``ray_tpu events --type`` CLI, and the ``ray_tpu net`` CLI surfaces.
"""

import json
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.ids import ObjectID, NodeID
from ray_tpu._private.object_store import ObjectStoreClient
from ray_tpu._private.object_transfer import (
    ObjectServer,
    _InflightRead,
    fetch_from_same_host,
    fetch_into_local_store,
    fetch_object_bytes,
)
from ray_tpu.exceptions import ObjectTransferStalledError
from ray_tpu.util import state

KEY = b"test-key"


def _sch():
    from ray_tpu._private.worker import get_runtime

    return get_runtime().node.scheduler


@pytest.fixture
def two_cpu():
    rt = ray_tpu.init(num_cpus=2)
    yield rt
    ray_tpu.shutdown()


@pytest.fixture
def served_store(tmp_path):
    shm_dir = str(tmp_path / "shm")
    store = ObjectStoreClient(shm_dir, str(tmp_path / "fb"), 1 << 28)
    store.shm_dir = shm_dir  # the peer-read root (tests only)
    server = ObjectServer(store, "127.0.0.1", KEY)
    yield store, server
    server.close()
    store.close()


# ---------------------------------------------------------------------------
# stage decomposition
# ---------------------------------------------------------------------------


def test_fetch_stage_decomposition(served_store, tmp_path):
    """A socket fetch decomposes into dial/request/first_byte_wait/wire/
    seal; bytes and chunks are recorded and the stage sum approximates the
    wall (acceptance: within 10%, measured here against the driver wall)."""
    store, server = served_store
    dest = ObjectStoreClient(str(tmp_path / "shm2"), str(tmp_path / "fb2"), 1 << 28)
    oid = ObjectID.from_random()
    payload = bytes(range(256)) * (64 * 1024)  # 16 MiB: several chunks
    store.put_bytes(oid, payload)
    stats = {}
    t0 = time.perf_counter()
    ok = fetch_into_local_store(
        dest, server.address, oid, KEY, stats=stats
    )
    wall_ms = (time.perf_counter() - t0) * 1e3
    assert ok and bytes(dest.get(oid, timeout=5)) == payload
    assert stats["path"] == "socket"
    assert stats["bytes"] == len(payload)
    assert stats["chunks"] >= 2
    for k in ("dial_ms", "request_ms", "first_byte_wait_ms", "wire_ms",
              "seal_ms"):
        assert k in stats, f"missing stage {k}: {stats}"
    ssum = sum(stats[k] for k in ("dial_ms", "request_ms",
                                  "first_byte_wait_ms", "wire_ms", "seal_ms"))
    assert ssum <= wall_ms * 1.10
    assert ssum >= wall_ms * 0.5  # the stages cover the bulk of the wall
    dest.close()


def test_shm_peer_vs_socket_parity(served_store, tmp_path):
    """Same-host short-circuit and the socket plane must deliver identical
    bytes; the shm copy records a shm_peer stage record."""
    store, server = served_store
    oid = ObjectID.from_random()
    payload = np.arange(512 * 1024, dtype=np.int64).tobytes()  # 4 MiB
    store.put_bytes(oid, payload)

    via_socket = bytes(fetch_object_bytes(server.address, oid, KEY))

    dest = ObjectStoreClient(str(tmp_path / "shm3"), str(tmp_path / "fb3"), 1 << 28)
    stats = {}
    assert fetch_from_same_host(
        dest, store.shm_dir, oid, stats=stats
    ), "same-host short-circuit missed a sealed .obj copy"
    via_shm = bytes(dest.get(oid, timeout=5))
    assert via_shm == via_socket == payload
    assert stats["path"] == "shm_peer"
    assert stats["bytes"] == len(payload)
    assert "wire_ms" in stats and "seal_ms" in stats
    dest.close()


# ---------------------------------------------------------------------------
# typed stall error + drain/leak accounting (satellites 1 + 2)
# ---------------------------------------------------------------------------


def test_wait_covered_timeout_raises_typed_error():
    """A coverage TIMEOUT raises ObjectTransferStalledError with progress
    provenance instead of the old bare False; an upstream FAILURE still
    returns False (the downstream re-sources)."""
    buf = bytearray(100)
    tracker = _InflightRead(memoryview(buf), 100)
    tracker.mark(0, 40)
    with pytest.raises(ObjectTransferStalledError) as ei:
        tracker.wait_covered(40, 80, timeout=0.1)
    err = ei.value
    assert err.covered_bytes == 40
    assert err.total_bytes == 100
    assert err.waited_s >= 0.1
    # failure semantics unchanged: returns False, never raises
    tracker.fail()
    assert tracker.wait_covered(40, 80, timeout=0.1) is False


def test_wait_serves_drained_deadline_is_config_driven():
    buf = bytearray(10)
    tracker = _InflightRead(memoryview(buf), 10)
    tracker.serve_begin()
    t0 = time.monotonic()
    assert tracker.wait_serves_drained(timeout=0.2) is False
    assert time.monotonic() - t0 < 5.0
    tracker.serve_end()
    assert tracker.wait_serves_drained(timeout=0.2) is True


def test_relay_fail_propagation_mid_serve(served_store):
    """Pipelined relay: a downstream peer streaming off an IN-FLIGHT
    receive must fail promptly — not hang — when the upstream source dies
    mid-transfer (tracker.fail cascades through wait_covered)."""
    store, server = served_store
    oid = ObjectID.from_random()
    buf = bytearray(32 * 1024 * 1024)
    tracker = server.register_inflight(oid, memoryview(buf), len(buf))
    tracker.mark(0, 9 * 1024 * 1024)  # one served chunk lands...

    results = []

    def downstream():
        try:
            results.append(fetch_object_bytes(server.address, oid, KEY))
        except Exception as e:  # noqa: BLE001
            results.append(e)

    t = threading.Thread(target=downstream, daemon=True)
    t.start()
    time.sleep(0.3)  # downstream is now blocked on chunk 2's coverage
    tracker.fail()  # ...then the upstream dies mid-transfer
    server.unregister_inflight(oid)
    t.join(timeout=15)
    assert not t.is_alive(), "downstream fetch hung on a dead upstream"
    assert len(results) == 1 and isinstance(results[0], Exception), results


def test_leaked_buffer_accounting(two_cpu):
    """A drain-timeout leak (stats rode the fetch completion message)
    lands on the leaked-buffer counters and emits a WARNING cluster
    event — recycled-arena leakage is visible, not silent."""
    sch = _sch()
    head = sch._node.head_node_id
    oid = ObjectID.from_random()
    sch._fetching[(oid, head)] = (head, True)
    sch._xfer_complete(
        oid, head, False,
        stats={"path": "socket", "bytes": 1 << 20, "wire_ms": 5.0,
               "leaked_bytes": 1 << 20, "error": "relay serves did not drain"},
    )
    assert sch._xfer_leaked[0] == 1
    assert sch._xfer_leaked[1] == 1 << 20
    evs = state.list_cluster_events(
        filters=[("type", "=", "TRANSFER_BUFFER_LEAKED")]
    )
    assert evs and evs[-1]["leaked_bytes"] == 1 << 20
    summary = state.summarize_transfers(group_by="path")
    assert summary["leaked_buffers"] == 1
    assert summary["leaked_bytes"] == 1 << 20


# ---------------------------------------------------------------------------
# ledger + relay hops + trace join on a real socket-plane broadcast
# ---------------------------------------------------------------------------


def test_socket_broadcast_ledger_and_trace_join():
    """The flagship end-to-end check: a socket-plane broadcast (shm
    short-circuit off) fills the link ledger with socket + relay rows
    (hop-tagged), per-transfer stage sums stay within 10% of the recorded
    wall, per-source fanout admission is honored (peak load <= cap), and
    the consuming task's trace shows a wire child span with link + GiB/s."""
    import ray_tpu.cluster_utils as cu

    cluster = cu.Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        for _ in range(3):
            cluster.add_node(num_cpus=1, resources={"reader": 1.0}, wait=False)
        cluster.wait_for_nodes(timeout=300)
        sch = _sch()
        sch.config.same_host_shm_transfer = False

        @ray_tpu.remote(num_cpus=0, resources={"reader": 1.0})
        def read(x):
            from ray_tpu.util import tracing

            ctx = tracing.get_current_context()
            return int(x[0]) + x.nbytes, ctx.trace_id if ctx else None

        blob = ray_tpu.put(np.full(2 * 1024 * 1024, 7, dtype=np.int64))
        out = ray_tpu.get(
            [read.remote(blob) for _ in range(3)], timeout=600
        )
        assert [o[0] for o in out] == [7 + 16 * 1024 * 1024] * 3

        deadline = time.time() + 30
        while time.time() < deadline:
            links = state.list_links()
            if sum(r["transfers"] for r in links) >= 3 and not sch._fetching:
                break
            time.sleep(0.2)
        paths = {r["path"] for r in links}
        assert "socket" in paths, links
        assert "relay" in paths, links  # fanout=2, 3 dests => >= 1 relay hop
        assert all(r["bytes"] >= 16 * 1024 * 1024 for r in links)
        assert max(r["max_hop"] for r in links) >= 1
        # fanout admission: no source ever served more than the cap
        assert sch._xfer_load_peak <= sch.config.object_transfer_fanout

        xfers = state.list_transfers()
        assert len(xfers) >= 3
        for r in xfers:
            assert r["ok"], r
            assert r["stages_ms"], r
            if r.get("total_ms"):
                ssum = sum(r["stages_ms"].values())
                assert ssum <= r["total_ms"] * 1.10, r
        # per-path + per-job groupings see the broadcast
        by_path = state.summarize_transfers(group_by="path")
        assert {r["group"] for r in by_path["rows"]} >= {"socket", "relay"}
        by_task = state.summarize_transfers(group_by="task")
        assert by_task["rows"] and by_task["rows"][0]["group"] == "<put>"

        # trace join: the task's trace carries a wire child span naming the
        # link it crossed with a measured rate
        trace_id = out[0][1]
        assert trace_id
        deadline = time.time() + 15
        wire = []
        while time.time() < deadline and not wire:
            t = ray_tpu.trace(trace_id)
            wire = [
                s for s in t.spans.values()
                if s.name.startswith("wire:") and s.extra.get("link")
            ]
            if not wire:
                time.sleep(0.3)
        assert wire, "no link-labeled wire span joined the trace"
        assert wire[0].extra.get("gib_per_s") is not None
        assert "->" in wire[0].extra["link"]
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# watchdogs: slow link + stalled transfer (seeded positive, calm silence)
# ---------------------------------------------------------------------------


def _feed_link(sch, src, dst, gibps, n=4, nbytes=8 * 1024 * 1024):
    """Synthesize n completed socket transfers at a given rate."""
    wire_ms = nbytes / 2**30 / gibps * 1e3
    for _ in range(n):
        oid = ObjectID.from_random()
        sch._fetching[(oid, dst)] = (src, True)
        sch._xfer_complete(
            oid, dst, True,
            stats={"path": "socket", "bytes": nbytes, "wire_ms": wire_ms,
                   "total_ms": wire_ms, "t0": time.time()},
        )


def test_slow_link_watchdog_flags_only_throttled_link(two_cpu):
    sch = _sch()
    head = sch._node.head_node_id
    nodes = [NodeID.from_random() for _ in range(4)]
    # three healthy links and one ~20x slower (the seeded throttled pair)
    for dst in nodes[:3]:
        _feed_link(sch, head, dst, gibps=2.0)
    _feed_link(sch, nodes[0], nodes[3], gibps=0.1)
    sch._net_watchdog_scan()
    evs = state.list_cluster_events(filters=[("type", "=", "SLOW_LINK")])
    assert len(evs) == 1, evs
    slow_label = sch._node_label(nodes[3])
    assert evs[0]["link"].endswith(slow_label)
    assert evs[0]["exemplar_object_ids"]
    assert sch._slow_link_events == 1
    slow_rows = [r for r in state.list_links() if r.get("slow")]
    assert len(slow_rows) == 1 and slow_rows[0]["dst"] == slow_label
    # re-scan within the dedup window: no event flood
    sch._net_watchdog_scan()
    assert sch._slow_link_events == 1


def test_slow_link_watchdog_silent_on_uniform_links(two_cpu):
    sch = _sch()
    head = sch._node.head_node_id
    for dst in (NodeID.from_random() for _ in range(4)):
        _feed_link(sch, head, dst, gibps=1.0)
    sch._net_watchdog_scan()
    assert sch._slow_link_events == 0
    assert not state.list_cluster_events(filters=[("type", "=", "SLOW_LINK")])


def test_stalled_transfer_watchdog(two_cpu):
    """An in-flight fetch whose received-byte watermark stops moving past
    transfer_stall_warn_s gets an OBJECT_TRANSFER_STALLED event with
    progress provenance; progress resets the clock."""
    from ray_tpu._private import netplane

    sch = _sch()
    head = sch._node.head_node_id
    src = NodeID.from_random()
    oid = ObjectID.from_random()
    key = (oid, head)
    sch._fetching[key] = (src, True)
    sch._fetch_meta[key] = {
        "t0": time.time(), "t0_mono": time.monotonic(), "hop": 0,
        "trace": ("t" * 32, "s" * 16), "seen_bytes": -1,
        "seen_t": time.monotonic(),
    }
    netplane.begin_inflight(oid.hex(), 1 << 26)
    netplane.note_progress(oid.hex(), 1 << 20)
    try:
        sch._net_watchdog_scan()  # observes the watermark: arms, no event
        assert sch._xfer_stalled_total == 0
        # no progress since, and the watermark is old enough now
        sch._fetch_meta[key]["seen_t"] = time.monotonic() - 100.0
        sch._net_watchdog_scan()
        assert sch._xfer_stalled_total == 1
        evs = state.list_cluster_events(
            filters=[("type", "=", "OBJECT_TRANSFER_STALLED")]
        )
        assert evs, "stall event missing"
        ev = evs[-1]
        assert ev["object_id"] == oid.hex()
        assert ev["bytes_received"] == 1 << 20
        assert ev["total_bytes"] == 1 << 26
        assert ev["trace_id"] == "t" * 32
        # progress resumes -> the clock re-arms (no second event)
        netplane.note_progress(oid.hex(), 2 << 20)
        sch._net_watchdog_scan()
        assert sch._xfer_stalled_total == 1
    finally:
        netplane.end_inflight(oid.hex())
        sch._fetching.pop(key, None)
        sch._fetch_meta.pop(key, None)


# ---------------------------------------------------------------------------
# event queryability + CLI surfaces (satellites 4 + 6)
# ---------------------------------------------------------------------------


def test_new_event_types_queryable_like_pr4_set(two_cpu, capsys):
    """SLOW_LINK / OBJECT_TRANSFER_STALLED are queryable through
    state.list_cluster_events filters and `ray_tpu events --type`, exactly
    like the PR-4 event set."""
    sch = _sch()
    sch.record_cluster_event(
        "SLOW_LINK", "link a->b EWMA under fleet median",
        severity="WARNING", link="a->b",
    )
    sch.record_cluster_event(
        "OBJECT_TRANSFER_STALLED", "transfer of deadbeef stalled",
        severity="WARNING", link="a->b", object_id="deadbeef",
    )
    for etype in ("SLOW_LINK", "OBJECT_TRANSFER_STALLED"):
        rows = state.list_cluster_events(filters=[("type", "=", etype)])
        assert rows and all(r["type"] == etype for r in rows)

    from ray_tpu.scripts.cli import main

    main(["events", "--type", "SLOW_LINK", "--json"])
    rows = json.loads(capsys.readouterr().out)
    assert rows and all(r["type"] == "SLOW_LINK" for r in rows)


def test_net_cli_surfaces(two_cpu, capsys):
    sch = _sch()
    _feed_link(sch, sch._node.head_node_id, NodeID.from_random(), gibps=1.0)
    from ray_tpu.scripts.cli import main

    main(["net", "links", "--json"])
    links = json.loads(capsys.readouterr().out)
    assert links and links[0]["path"] == "socket"
    main(["net", "transfers", "--json"])
    xfers = json.loads(capsys.readouterr().out)
    assert xfers and xfers[0]["stages_ms"]["wire_ms"] > 0
    main(["net", "top", "--group-by", "path", "--json"])
    top = json.loads(capsys.readouterr().out)
    assert top["rows"][0]["group"] == "socket"
    # human-readable renderings don't crash either
    main(["net", "links"])
    assert "SRC" in capsys.readouterr().out
    main(["net", "top"])
    assert "transfers:" in capsys.readouterr().out


def test_dashboard_net_endpoint(two_cpu):
    import urllib.request

    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    sch = _sch()
    _feed_link(sch, sch._node.head_node_id, NodeID.from_random(), gibps=1.0)
    port = start_dashboard(port=0)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/net", timeout=10
        ) as resp:
            body = json.loads(resp.read())
        assert body["links"], body
        assert body["transfers"], body
        assert any(r["group"] == "socket" for r in body["summary"]["rows"])
    finally:
        stop_dashboard()


def test_metrics_expose_transfer_series(two_cpu):
    sch = _sch()
    _feed_link(sch, sch._node.head_node_id, NodeID.from_random(), gibps=1.0)
    series = {s["name"]: s for s in sch._runtime_metric_series()}
    for name in (
        "ray_tpu_transfer_path_gib_per_s",
        "ray_tpu_transfers_inflight",
        "ray_tpu_transfer_stage_seconds_total",
        "ray_tpu_link_bytes_total",
        "ray_tpu_link_throughput_gib_per_s",
        "ray_tpu_transfer_relay_hops_total",
        "ray_tpu_transfer_leaked_buffers_total",
        "ray_tpu_transfer_leaked_bytes_total",
        "ray_tpu_transfer_stalled_total",
        "ray_tpu_transfer_retries_total",
        "ray_tpu_slow_link_events_total",
    ):
        assert name in series, name
    link_bytes = series["ray_tpu_link_bytes_total"]["data"]
    assert sum(link_bytes.values()) >= 4 * 8 * 1024 * 1024
