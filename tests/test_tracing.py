"""Request-tracing & continuous-profiling plane (see DESIGN_MAP "Request
tracing & profiling").

Covers the PR's acceptance bar: trace-context propagation across nested
tasks, direct actor calls, and serve streaming (TTFT span present); retried
attempts linked to the same trace; stage decomposition summing to the
measured wall time; profiler attribution for threaded actors; sub-ms
histogram buckets; per-deployment latency aggregation with exemplars.
"""

import json
import time
import urllib.request

import pytest

import ray_tpu


def _get_trace(trace_id, min_spans=1, tries=12):
    """Trace reads ride telemetry batches: retry briefly until the span
    tree is complete (request_telemetry_flush is read-your-writes for
    workers, but serve controller/proxy threads flush on their own cadence)."""
    for _ in range(tries):
        t = ray_tpu.trace(trace_id)
        if t.span_count() >= min_spans:
            return t
        time.sleep(0.3)
    return ray_tpu.trace(trace_id)


def test_nested_task_span_tree_and_stage_sum(ray_start_regular):
    """A nested task graph yields one complete cross-process span tree, and
    the root's stage decomposition sums to its wall time within 10%."""

    @ray_tpu.remote
    def leaf(x):
        time.sleep(0.05)
        return x * 2

    @ray_tpu.remote
    def mid(x):
        return ray_tpu.get(leaf.remote(x)) + 1

    @ray_tpu.remote
    def root(x):
        time.sleep(0.02)
        return ray_tpu.get(mid.remote(x)) + 100

    assert ray_tpu.get(root.remote(3)) == 107
    traces = ray_tpu.recent_traces(limit=20)
    tid = next(t["trace_id"] for t in traces if t["root"] == "root")
    tr = _get_trace(tid, min_spans=3)
    assert tr.span_count() == 3
    # one chain: root -> mid -> leaf, all in the SAME trace, across
    # (potentially) three worker processes
    assert len(tr.roots) == 1
    r = tr.roots[0]
    assert r.name == "root"
    assert len(r.children) == 1 and r.children[0].name == "mid"
    assert len(r.children[0].children) == 1
    assert r.children[0].children[0].name == "leaf"
    # every span has worker-side execution stages
    for s in tr.spans.values():
        assert s.states.get("RUNNING") is not None
        assert s.end is not None and s.start is not None
    # acceptance: stages cover the root's wall time within 10%
    bd = r.stage_breakdown()
    assert bd, "no stage decomposition on the root span"
    covered = sum(bd.values())
    wall = r.duration_ms
    assert wall > 0
    assert abs(covered - wall) / wall < 0.10, (bd, wall)
    # critical path reaches the leaf
    names = [row["name"] for row in tr.critical_path()]
    assert names == ["root", "mid", "leaf"]


def test_direct_actor_call_trace_and_arg_fetch(ray_start_regular):
    """Direct actor calls (which never touch the head) still produce spans
    — caller-side SUBMITTED + worker-side RUNNING/FINISHED — and large ref
    args are attributed with bytes + transfer path."""
    import numpy as np

    @ray_tpu.remote
    class Worker:
        def consume(self, arr):
            return int(arr.nbytes)

    a = Worker.remote()
    big = ray_tpu.put(np.zeros(1 << 20, dtype=np.uint8))  # 1 MiB, stored
    assert ray_tpu.get(a.consume.remote(big)) == 1 << 20
    tid = next(
        t["trace_id"]
        for t in ray_tpu.recent_traces(limit=20)
        if t["root"] == "consume"
    )
    tr = _get_trace(tid)
    span = next(s for s in tr.spans.values() if s.name == "consume")
    # caller-side submission anchor + worker execution on one span
    assert "SUBMITTED" in span.states
    assert "RUNNING" in span.states
    assert span.end is not None
    # arg_fetch stage carries bytes and the transfer path
    assert span.stages.get("arg_bytes", 0) >= 1 << 20
    assert span.stages.get("arg_paths"), span.stages
    assert span.stages.get("arg_fetch_ms") is not None


def test_retry_lands_in_same_trace(ray_start_regular):
    """A task that fails once and retries records BOTH attempts under the
    same trace/span (attempt count >= 2)."""
    import os

    @ray_tpu.remote(max_retries=2)
    def flaky(marker_dir):
        marker = os.path.join(marker_dir, "attempted")
        if not os.path.exists(marker):
            open(marker, "w").close()
            os._exit(1)  # hard death: provokes a retry
        return "ok"

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        assert ray_tpu.get(flaky.remote(d), timeout=120) == "ok"
    tid = next(
        t["trace_id"]
        for t in ray_tpu.recent_traces(limit=20)
        if t["root"] == "flaky"
    )
    tr = _get_trace(tid)
    span = next(s for s in tr.spans.values() if s.name == "flaky")
    # the retried attempt lands in the SAME trace/span: either both worker
    # attempts reported (attempts >= 2), or — when the killed worker died
    # before flushing its batch — the head's RETRY event links them
    assert span.attempts >= 2 or "RETRY" in span.states, span.to_dict()
    assert "FINISHED" in span.states


def test_serve_streaming_ttft_span(ray_start_regular):
    """A streaming serve request yields a trace whose replica span carries a
    TTFT extra, and the task span records first_yield/stream stages."""
    from ray_tpu import serve

    @serve.deployment(num_replicas=1)
    class Streamer:
        def gen(self, n):
            for i in range(int(n)):
                time.sleep(0.02)
                yield i

    h = serve.run(Streamer.bind(), name="ttft_app")
    try:
        sh = h.options(stream=True)
        assert list(sh.gen.remote(3)) == [0, 1, 2]
        tr = None
        for digest in ray_tpu.recent_traces(limit=30):
            cand = _get_trace(digest["trace_id"], min_spans=2)
            if any(
                (s.name or "").startswith("serve:replica:Streamer")
                for s in cand.spans.values()
            ):
                tr = cand
                break
        assert tr is not None, "no serve streaming trace found"
        replica_span = next(
            s
            for s in tr.spans.values()
            if (s.name or "").startswith("serve:replica:Streamer")
        )
        # TTFT present on the replica section (first item yielded)
        assert replica_span.extra.get("ttft_ms") is not None
        assert replica_span.extra.get("stream_items") == 3
        # the task span measured the stream stages too
        task_span = next(
            (s for s in tr.spans.values() if s.stages.get("stream_items")),
            None,
        )
        assert task_span is not None
        assert task_span.stages.get("first_yield_ms") is not None
    finally:
        serve.shutdown()


def test_serve_failover_retry_same_trace(ray_start_regular):
    """A request that fails over to another replica (unstarted failure)
    records a serve:retry event in the SAME trace as the final success."""
    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    class Svc:
        def __call__(self, x):
            return x + 1

    h = serve.run(Svc.bind(), name="retry_app")
    try:
        # drain one replica so its next dispatch is rejected (provably
        # unstarted -> transparent retry on the other replica)
        info = ray_tpu.get(
            ray_tpu.get_actor("SERVE_CONTROLLER").get_handle_info.remote(
                "retry_app", "Svc"
            ),
            timeout=30,
        )
        victim = info["replicas"][0]
        ray_tpu.get(victim.prepare_drain.remote(), timeout=30)
        results = set()
        for i in range(8):
            results.add(h.remote(i).result(timeout_s=30))
        assert results == {i + 1 for i in range(8)}
        assert h._retry_count + h._shed_count >= 0  # sanity
        # find a trace containing a serve:retry event alongside a finished
        # replica execution
        found = False
        for digest in ray_tpu.recent_traces(limit=60):
            tr = ray_tpu.trace(digest["trace_id"])
            names = [(s.name or "") for s in tr.spans.values()]
            if any(n == "serve:retry" for n in names):
                assert any(
                    n.startswith("serve:replica:") or n == "__call__"
                    for n in names
                ), names
                found = True
                break
        assert found or h._retry_count == 0
    finally:
        serve.shutdown()


def test_profiler_threaded_actor_attribution(ray_start_regular):
    """Stack samples taken inside a threaded actor attribute to the right
    task ids (per pool thread), not to <untasked>."""

    @ray_tpu.remote(max_concurrency=2)
    class Busy:
        def spin(self, s):
            t0 = time.time()
            x = 0
            while time.time() - t0 < s:
                x += 1
            return x

    a = Busy.remote()
    ray_tpu.get(a.spin.remote(0.05))  # ensure the worker is up
    ray_tpu.request_profile(hz=200, duration_s=3.0)
    refs = [a.spin.remote(1.0), a.spin.remote(1.0)]
    ray_tpu.get(refs, timeout=60)
    time.sleep(1.2)  # one flush interval: samples ride telemetry batches
    rt = ray_tpu._worker.get_runtime()
    rows = None
    for _ in range(10):
        from ray_tpu._private import telemetry as _tele

        _tele.flush()
        rt.scheduler.request_telemetry_flush()
        rows = rt.scheduler_rpc("profile_samples", (None, None))
        tasks = {r[0] for r in rows if r[0]}
        if len(tasks) >= 2:
            break
        time.sleep(0.5)
    tasks = {r[0] for r in rows if r[0]}
    # both concurrent spin() calls sampled under their own task ids
    assert len(tasks) >= 2, tasks
    attributed = sum(n for t, _tr, _s, n in rows if t)
    assert attributed > 0
    # spans carry trace attribution too
    traced = {r[1] for r in rows if r[1]}
    assert traced, "no trace ids on profiler samples"


def test_profile_dump_formats(ray_start_regular, tmp_path):
    """Collapsed-stack and speedscope exports are well-formed."""

    @ray_tpu.remote
    def spin(s):
        t0 = time.time()
        while time.time() - t0 < s:
            pass
        return 1

    ray_tpu.get(spin.remote(0.05))
    ray_tpu.request_profile(hz=150, duration_s=2.0)
    ray_tpu.get([spin.remote(0.8) for _ in range(2)], timeout=60)
    time.sleep(1.2)
    collapsed = tmp_path / "prof.txt"
    n_lines = ray_tpu.profile_dump(str(collapsed), format="collapsed")
    assert n_lines > 0
    for line in collapsed.read_text().splitlines():
        stack, _, count = line.rpartition(" ")
        assert stack and int(count) > 0
    speedscope = tmp_path / "prof.json"
    n_prof = ray_tpu.profile_dump(str(speedscope), format="speedscope")
    assert n_prof > 0
    doc = json.loads(speedscope.read_text())
    assert doc["$schema"].startswith("https://www.speedscope.app")
    assert doc["shared"]["frames"]
    for prof in doc["profiles"]:
        assert prof["type"] == "sampled"
        assert len(prof["samples"]) == len(prof["weights"])
        nframes = len(doc["shared"]["frames"])
        for sample in prof["samples"]:
            assert all(0 <= i < nframes for i in sample)


def test_histogram_submillisecond_buckets_and_overrides(ray_start_regular):
    """Default histogram grid resolves sub-ms observations; bounds are
    configurable per metric (API + env var)."""
    import os

    from ray_tpu.util import metrics as m

    # default grid includes sub-ms buckets
    assert any(b < 1 for b in m.DEFAULT_HISTOGRAM_BOUNDARIES)
    h = m.Histogram("tr_default_grid_ms")
    h.observe(0.02)
    h.observe(0.3)
    h.observe(40)
    text = m.prometheus_text()
    assert 'tr_default_grid_ms_bucket{le="0.05"} 1' in text
    assert 'tr_default_grid_ms_bucket{le="0.5"} 2' in text
    # per-metric override API
    m.configure_histogram_boundaries("tr_custom_ms", [5, 50])
    h2 = m.Histogram("tr_custom_ms")
    assert h2._boundaries == [5, 50]
    # env override wins over everything
    os.environ["RAY_TPU_HIST_BUCKETS_TR_ENV_MS"] = "2,20,200"
    try:
        h3 = m.Histogram("tr_env_ms", boundaries=[1, 10])
        assert h3._boundaries == [2.0, 20.0, 200.0]
    finally:
        del os.environ["RAY_TPU_HIST_BUCKETS_TR_ENV_MS"]
    # serve's latency histogram rides the fine default grid now
    from ray_tpu.serve import _replica

    lat = _replica._replica_metrics()["latency"]
    assert any(b < 1 for b in lat._boundaries)


def test_job_latency_window_and_exemplars(ray_start_regular):
    """Per-job sliding-window quantiles exist with exemplar trace ids that
    resolve to real traces."""

    @ray_tpu.remote
    def work(ms):
        time.sleep(ms / 1e3)
        return ms

    ray_tpu.get([work.remote(5), work.remote(30), work.remote(60)])
    rt = ray_tpu._worker.get_runtime()
    lat = rt.scheduler_rpc("job_latency", ())
    assert lat, "no per-job latency windows"
    snap = next(iter(lat.values()))
    assert snap["count"] >= 3
    assert snap["p50"] is not None and snap["p99"] >= snap["p50"]
    assert snap["exemplars"], snap
    ex = snap["exemplars"][0]
    tr = _get_trace(ex["trace_id"])
    assert tr.span_count() >= 1
    # the exemplar series also reaches the Prometheus exposition
    from ray_tpu.util.metrics import prometheus_text

    text = prometheus_text()
    assert "ray_tpu_job_latency_ms" in text


def test_serve_per_deployment_latency_in_status(ray_start_regular):
    """Controller aggregates replica latency windows per deployment and
    surfaces them in serve.status() (satellite: was per-replica only)."""
    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    class Api:
        def __call__(self, x):
            time.sleep(0.01)
            return x

    h = serve.run(Api.bind(), name="lat_app")
    try:
        for i in range(6):
            h.remote(i).result(timeout_s=30)
        deadline = time.time() + 30
        lat = None
        while time.time() < deadline:
            st = serve.status()
            lat = st.get("lat_app", {}).get("Api", {}).get("latency")
            if lat and lat.get("count"):
                break
            time.sleep(0.5)
        assert lat and lat["count"] >= 1, lat
        assert lat["p50"] is not None
        assert "exemplars" in lat
    finally:
        serve.shutdown()


def test_tracing_disabled_is_silent(ray_start_regular):
    """tracing disabled: tasks run untraced (no trace index growth), and
    the plane's read APIs still answer."""
    from ray_tpu.util import tracing

    tracing.disable_tracing()
    try:
        before = len(ray_tpu.recent_traces(limit=1000))

        @ray_tpu.remote
        def f(x):
            return x

        assert ray_tpu.get(f.remote(1)) == 1
        after = len(ray_tpu.recent_traces(limit=1000))
        assert after == before
    finally:
        tracing.reset_tracing()


def test_timeline_regression_with_tracing(ray_start_regular):
    """PR-2 chrome timeline keeps working with the tracing plane on: events
    parse, lifecycle phases present, PROFILE spans carry trace args."""

    @ray_tpu.remote
    def t(x):
        from ray_tpu._private.profiling import profile

        with profile("user_section"):
            time.sleep(0.01)
        return x

    ray_tpu.get(t.remote(1))
    events = ray_tpu.timeline()
    assert any(e.get("cat") == "TASK_PHASE" for e in events)
    user = [e for e in events if e.get("name") == "user_section"]
    assert user and user[0]["args"].get("trace_id")
