"""Mesh/sharding/SPMD tests on the virtual 8-device CPU mesh (SURVEY.md §4e)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.parallel.mesh import MeshConfig, create_mesh
from ray_tpu.parallel.sharding import (
    DEFAULT_LM_RULES,
    batch_sharding,
    infer_param_sharding,
    logical_to_mesh_spec,
)


def test_mesh_resolution(cpu_mesh_devices):
    mesh = create_mesh(MeshConfig(data=-1, tensor=2))
    assert mesh.shape["tensor"] == 2
    assert mesh.shape["data"] == 4


def test_mesh_axis_product_mismatch(cpu_mesh_devices):
    with pytest.raises(ValueError):
        create_mesh(MeshConfig(data=3, tensor=2))


def test_mesh_two_wildcards_rejected(cpu_mesh_devices):
    with pytest.raises(ValueError):
        create_mesh(MeshConfig(data=-1, tensor=-1))


def test_logical_to_mesh_spec(cpu_mesh_devices):
    mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    spec = logical_to_mesh_spec(("embed", "heads", "head_dim"), DEFAULT_LM_RULES, mesh)
    assert spec == P("fsdp", "tensor")
    # batch spreads over data+fsdp
    spec = logical_to_mesh_spec(("batch", "sequence"), DEFAULT_LM_RULES, mesh)
    assert spec == P(("data", "fsdp"))


def test_logical_spec_skips_trivial_axes(cpu_mesh_devices):
    mesh = create_mesh(MeshConfig(data=8))  # tensor axis size 1
    spec = logical_to_mesh_spec(("embed", "mlp"), DEFAULT_LM_RULES, mesh)
    assert spec == P()  # fsdp and tensor both trivial -> replicated


def test_batch_sharding_placement(cpu_mesh_devices):
    mesh = create_mesh(MeshConfig(data=4, tensor=2))
    sh = batch_sharding(mesh)
    x = jax.device_put(np.zeros((8, 16)), sh)
    assert len(x.sharding.device_set) == 8 or len(x.sharding.device_set) == 4


def test_ring_attention_matches_dense(cpu_mesh_devices):
    from ray_tpu.ops.attention import _einsum_attention, make_context_parallel_attention

    mesh = create_mesh(MeshConfig(context=8))
    b, s, h, d = 2, 64, 4, 16
    key = jax.random.PRNGKey(0)
    q, k, v = (
        jax.random.normal(kk, (b, s, h, d), jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    spec = NamedSharding(mesh, P(None, "context", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    for causal in (True, False):
        ref = _einsum_attention(q, k, v, causal=causal)
        out = jax.jit(make_context_parallel_attention(mesh, causal=causal))(qs, ks, vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_gqa(cpu_mesh_devices):
    from ray_tpu.ops.attention import _einsum_attention, make_context_parallel_attention

    mesh = create_mesh(MeshConfig(context=8))
    b, s, h, d = 1, 32, 4, 8
    key = jax.random.PRNGKey(1)
    q = jax.random.normal(key, (b, s, h, d))
    k = jax.random.normal(key, (b, s, 2, d))
    v = jax.random.normal(jax.random.fold_in(key, 1), (b, s, 2, d))
    ref = _einsum_attention(q, jnp.repeat(k, 2, 2), jnp.repeat(v, 2, 2), causal=True)
    spec = NamedSharding(mesh, P(None, "context", None, None))
    out = jax.jit(make_context_parallel_attention(mesh))(
        jax.device_put(q, spec), jax.device_put(k, spec), jax.device_put(v, spec)
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_lm_train_step_loss_decreases(cpu_mesh_devices):
    from ray_tpu.models.transformer import TINY
    from ray_tpu.parallel.spmd import build_lm_train_step

    mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    bundle = build_lm_train_step(TINY, mesh, learning_rate=1e-3)
    state = bundle.init_fn(jax.random.PRNGKey(0))
    # params actually sharded
    assert state["params"]["w_up"].sharding.spec == P(None, "fsdp", "tensor")
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 255, (8, 128), dtype=np.int32)
    targets = np.roll(tokens, -1, axis=1)
    tok, tgt = bundle.shard_batch(tokens, targets)
    first = last = None
    for _ in range(5):
        state, metrics = bundle.step_fn(state, tok, tgt)
        last = float(metrics["loss"])
        first = first if first is not None else last
    assert last < first


def test_forward_parallel_vs_sequential_block(cpu_mesh_devices):
    from ray_tpu.models.transformer import TINY, forward, init_params
    import dataclasses

    cfg_p = dataclasses.replace(TINY, parallel_block=True, use_swiglu=False)
    params = init_params(jax.random.PRNGKey(0), cfg_p)
    tokens = np.zeros((1, 16), dtype=np.int32)
    out = forward(params, tokens, cfg_p)
    assert out.shape == (1, 16, TINY.vocab_size)
    assert np.all(np.isfinite(np.asarray(out, dtype=np.float32)))


def test_graft_entry_single(cpu_mesh_devices):
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == args[1].shape[0]
