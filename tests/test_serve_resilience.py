"""Serve-plane resilience: graceful drain, replica failover, load shedding,
controller fault tolerance (DESIGN_MAP "Serve resilience").

Fast tier-1 slice — the heavy churn variants live in tests/test_serve_chaos.py
(slow-marked, `make chaos-serve`).
"""

import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request
import warnings

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_cluster():
    rt = ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield rt
    serve.shutdown()
    ray_tpu.shutdown()


def test_redeploy_under_load_drops_zero_requests(serve_cluster):
    """A graceful redeploy (full replica restart) under sustained load
    completes with ZERO failed requests: old replicas drain in-flight work,
    new dispatches fail over to the new replica set transparently."""

    @serve.deployment(num_replicas=2, health_check_period_s=0.5)
    class Versioned:
        def __init__(self, version):
            self.version = version

        def __call__(self, x):
            time.sleep(0.02)
            return (self.version, x)

    serve.run(Versioned.bind(1), name="redeploy_app")
    errors = []
    results = []
    stop = threading.Event()

    def client(i):
        h = serve.get_app_handle("redeploy_app")
        n = 0
        while not stop.is_set():
            try:
                results.append(h.remote((i, n)).result(timeout_s=60))
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            n += 1

    threads = [threading.Thread(target=client, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    time.sleep(1.0)
    # full redeploy: init arg changed -> every replica restarts
    serve.run(Versioned.bind(2), name="redeploy_app")
    time.sleep(1.5)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    assert not errors, f"redeploy dropped {len(errors)} requests: {errors[:3]}"
    versions = {v for v, _ in results}
    assert 1 in versions and 2 in versions, versions
    serve.delete("redeploy_app")


def test_dead_replica_failover_retries_unstarted_once(serve_cluster):
    """A call routed to a dead replica whose work provably never started
    (scheduler started-marker False) is retried on a live replica exactly
    once — transparent to the caller."""

    @serve.deployment(num_replicas=2, health_check_period_s=30.0)
    class Echo:
        def __call__(self, x):
            return (os.getpid(), x)

    serve.run(Echo.bind(), name="failover_app")
    handle = serve.get_app_handle("failover_app")
    pids = {handle.remote(i).result(timeout_s=60)[0] for i in range(16)}
    assert len(pids) == 2

    victim = handle._replicas[0]
    ray_tpu.kill(victim)
    time.sleep(0.3)
    # force the next dispatch onto the corpse: it fails with
    # ActorDiedError(task_started=False) and must fail over exactly once
    handle._excluded.clear()
    with handle._lock:
        handle._model_affinity["corpse"] = 0
    handle._model_id = "corpse"
    before = handle._retry_count
    out = handle.remote(99).result(timeout_s=60)
    assert out[1] == 99
    assert handle._retry_count - before == 1, "expected exactly one retry"
    # the corpse is now excluded: subsequent calls don't touch it
    before = handle._retry_count
    handle._model_id = ""
    for i in range(6):
        handle.remote(i).result(timeout_s=60)
    assert handle._retry_count == before
    serve.delete("failover_app")


def test_torn_unary_work_raises_typed_replica_died(serve_cluster):
    """A replica killed while a request is EXECUTING must not silently
    retry: the caller gets a typed ReplicaDiedError with started=True."""

    @serve.deployment(num_replicas=1, health_check_period_s=30.0)
    class Hang:
        def __call__(self):
            time.sleep(30)
            return "done"

    serve.run(Hang.bind(), name="torn_app")
    handle = serve.get_app_handle("torn_app")
    resp = handle.remote()
    time.sleep(0.5)  # let it reach the replica and start
    ray_tpu.kill(handle._replicas[0])
    with pytest.raises(serve.ReplicaDiedError) as ei:
        resp.result(timeout_s=30)
    assert ei.value.started is True
    assert ei.value.deployment == "Hang"
    serve.delete("torn_app")


def test_saturated_deployment_sheds_503_with_retry_after(serve_cluster):
    """Admission control: beyond replicas x max_ongoing x shed_queue_factor
    the handle raises DeploymentOverloadedError and the HTTP proxy returns a
    FAST 503 + Retry-After instead of queueing into a timeout."""

    @serve.deployment(
        num_replicas=1,
        max_ongoing_requests=1,
        shed_queue_factor=2.0,
        shed_retry_after_s=3.0,
        health_check_period_s=30.0,
    )
    class Slow:
        def __call__(self, p=None):
            time.sleep(1.0)
            return "ok"

    serve.run(Slow.bind(), name="shed_app", route_prefix="/shed")
    handle = serve.get_app_handle("shed_app")
    # capacity = 1 * 1 * 2 = 2: the 3rd concurrent call sheds
    ok, shed = [], []
    for _ in range(6):
        try:
            ok.append(handle.remote())
        except serve.DeploymentOverloadedError as e:
            shed.append(e)
    assert len(ok) == 2 and len(shed) == 4, (len(ok), len(shed))
    assert shed[0].retry_after_s == 3.0
    assert handle._shed_count >= 4
    for r in ok:
        assert r.result(timeout_s=60) == "ok"

    # HTTP path: saturate through the proxy, expect fast 503 + Retry-After
    statuses = []
    lock = threading.Lock()

    def post():
        t0 = time.monotonic()
        try:
            resp = urllib.request.urlopen(
                urllib.request.Request(
                    "http://127.0.0.1:8700/shed",
                    data=json.dumps(None).encode(),
                    headers={"Content-Type": "application/json"},
                ),
                timeout=60,
            )
            with lock:
                statuses.append((resp.status, None, time.monotonic() - t0))
        except urllib.error.HTTPError as e:
            with lock:
                statuses.append(
                    (e.code, e.headers.get("Retry-After"), time.monotonic() - t0)
                )

    threads = [threading.Thread(target=post) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    codes = [s for s, _, _ in statuses]
    assert codes.count(200) >= 1
    sheds = [(s, ra, dt) for s, ra, dt in statuses if s == 503]
    assert sheds, f"no 503s under saturation: {statuses}"
    for s, ra, dt in sheds:
        assert ra == "3"  # Retry-After from shed_retry_after_s
        assert dt < 5.0  # fast-fail, not a queued hang
    serve.delete("shed_app")


def test_graceful_drain_finishes_inflight_stream(serve_cluster):
    """Redeploy mid-stream: the old replica enters DRAINING, the open
    stream runs to completion before the replica is killed, and a
    REPLICA_DRAINED event lands in the cluster event log."""

    @serve.deployment(
        num_replicas=1,
        graceful_shutdown_timeout_s=30.0,
        health_check_period_s=0.5,
    )
    class Streamer:
        def __call__(self, n):
            for i in range(n):
                time.sleep(0.15)
                yield i

    serve.run(Streamer.bind(), name="drain_app")
    handle = serve.get_app_handle("drain_app")
    it = iter(handle.options(stream=True).remote(12))
    first = next(it)
    # full redeploy while the stream is open
    serve.run(
        Streamer.options(max_ongoing_requests=4).bind(), name="drain_app"
    )
    rest = list(it)
    assert [first] + rest == list(range(12)), "drain tore an open stream"
    # new replica serves fresh work
    assert list(handle.options(stream=True).remote(3)) == [0, 1, 2]
    # the drained replica shows up in forensics
    from ray_tpu.util import state as state_api

    deadline = time.monotonic() + 30
    drained = []
    while time.monotonic() < deadline and not drained:
        drained = [
            e
            for e in state_api.list_cluster_events()
            if e.get("type") == "REPLICA_DRAINED"
            and e.get("deployment") == "Streamer"
        ]
        time.sleep(0.5)
    assert drained, "REPLICA_DRAINED event never recorded"
    serve.delete("drain_app")


def test_drain_timeout_kills_hung_replica(serve_cluster):
    """A replica that cannot finish in-flight work within
    graceful_shutdown_timeout_s is killed anyway (bounded drain)."""

    @serve.deployment(
        num_replicas=1,
        graceful_shutdown_timeout_s=1.0,
        health_check_period_s=0.5,
    )
    class Stuck:
        def __call__(self):
            time.sleep(60)
            return "never"

    serve.run(Stuck.bind(), name="stuck_app")
    handle = serve.get_app_handle("stuck_app")
    resp = handle.remote()
    time.sleep(0.5)  # request is executing
    old_replica = handle._replicas[0]
    serve.run(Stuck.options(max_ongoing_requests=4).bind(), name="stuck_app")
    # the hung request dies with the timed-out drain, typed as torn work
    with pytest.raises(serve.ReplicaDiedError):
        resp.result(timeout_s=30)
    # and the old replica is actually gone
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        try:
            ray_tpu.get(old_replica.check_health.remote(), timeout=2)
            time.sleep(0.5)
        except Exception:
            break
    else:
        pytest.fail("drain-timeout never killed the hung replica")
    serve.delete("stuck_app")


def test_controller_death_preserves_routes_and_replicas(serve_cluster):
    """SIGKILL the controller: the detached actor auto-restarts, restores
    apps/routes from the GCS KV, and RE-ADOPTS the still-alive replicas
    (same pids — no fleet cold start). Handles and HTTP keep working."""
    from chaos import serve_controller_pids

    @serve.deployment(num_replicas=2, health_check_period_s=0.5)
    class Echo:
        def __call__(self, x=None):
            return os.getpid()

    serve.run(Echo.bind(), name="ft_app", route_prefix="/ft")
    handle = serve.get_app_handle("ft_app")
    pids_before = {handle.remote().result(timeout_s=60) for _ in range(16)}
    assert len(pids_before) == 2

    cpids = serve_controller_pids()
    assert len(cpids) == 1, cpids
    os.kill(cpids[0], signal.SIGKILL)

    # the controller auto-restarts and restores state from the KV
    deadline = time.monotonic() + 40
    st = {}
    while time.monotonic() < deadline:
        try:
            st = serve.status()
            if "ft_app" in st:
                break
        except Exception:
            pass
        time.sleep(0.5)
    assert "ft_app" in st, f"controller never recovered: {st}"
    # routes survived
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    routes = ray_tpu.get(controller.get_routes.remote(), timeout=30)
    assert routes.get("/ft") == "ft_app"
    # replicas were re-adopted, not restarted: same pids serve traffic
    fresh = serve.get_app_handle("ft_app")
    pids_after = {fresh.remote().result(timeout_s=60) for _ in range(16)}
    assert pids_after == pids_before, (pids_before, pids_after)
    # the new controller pid differs (it really did die)
    new_cpids = serve_controller_pids()
    assert new_cpids and new_cpids != cpids
    serve.delete("ft_app")


def test_handle_options_warns_once_and_typed_stream_timeout(serve_cluster):
    """options() warns once per unknown kwarg instead of silently dropping
    it; the streaming per-item timeout is configurable and typed."""

    @serve.deployment(health_check_period_s=30.0, graceful_shutdown_timeout_s=1.0)
    class SlowYield:
        def __call__(self):
            yield 1
            time.sleep(20)
            yield 2

    serve.run(SlowYield.bind(), name="sy_app")
    handle = serve.get_app_handle("sy_app")

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        handle.options(definitely_not_an_option=1)
        handle.options(definitely_not_an_option=2)
    msgs = [str(w.message) for w in caught]
    assert sum("definitely_not_an_option" in m for m in msgs) == 1, msgs

    it = iter(handle.options(stream=True, stream_item_timeout_s=0.5).remote())
    assert next(it) == 1
    t0 = time.monotonic()
    with pytest.raises(serve.RequestTimeoutError) as ei:
        next(it)
    assert time.monotonic() - t0 < 10.0
    assert ei.value.timeout_s == 0.5
    serve.delete("sy_app")


def test_health_check_period_honored_and_status_health(serve_cluster):
    """The reconcile loop probes each deployment at ITS
    health_check_period_s (not a hardcoded 1s), and serve.status() surfaces
    health + resilience knobs."""
    import tempfile

    fast_log = tempfile.NamedTemporaryFile(delete=False, suffix=".fast")
    slow_log = tempfile.NamedTemporaryFile(delete=False, suffix=".slow")
    fast_log.close()
    slow_log.close()

    @serve.deployment
    class Probed:
        def __init__(self, p):
            self.p = p

        def check_health(self):
            with open(self.p, "a") as f:
                f.write("x")

        def __call__(self):
            return 1

    serve.run(
        Probed.options(health_check_period_s=0.4, name="FastP").bind(
            fast_log.name
        ),
        name="probe_fast",
    )
    serve.run(
        Probed.options(health_check_period_s=10.0, name="SlowP").bind(
            slow_log.name
        ),
        name="probe_slow",
    )
    base_fast = os.path.getsize(fast_log.name)
    base_slow = os.path.getsize(slow_log.name)
    time.sleep(3.0)
    fast_probes = os.path.getsize(fast_log.name) - base_fast
    slow_probes = os.path.getsize(slow_log.name) - base_slow
    assert fast_probes >= 3, f"0.4s period produced {fast_probes} probes in 3s"
    assert slow_probes <= 1, f"10s period produced {slow_probes} probes in 3s"

    st = serve.status()
    row = st["probe_fast"]["FastP"]
    assert row["health"] == "HEALTHY"
    assert row["config"]["request_retries"] == 3
    assert row["config"]["graceful_shutdown_timeout_s"] == 20.0
    assert "draining" in row
    os.unlink(fast_log.name)
    os.unlink(slow_log.name)
    serve.delete("probe_fast")
    serve.delete("probe_slow")


def test_replica_death_emits_events_and_metrics(serve_cluster):
    """Replica death reaches forensics: REPLICA_DIED + DEPLOYMENT_UNHEALTHY
    cluster events and the serve resilience counters."""
    from ray_tpu.util import state as state_api

    @serve.deployment(num_replicas=1, health_check_period_s=0.4)
    class Mortal:
        def __call__(self):
            return "alive"

    serve.run(Mortal.bind(), name="mortal_app")
    handle = serve.get_app_handle("mortal_app")
    assert handle.remote().result(timeout_s=60) == "alive"
    ray_tpu.kill(handle._replicas[0])

    deadline = time.monotonic() + 30
    died, unhealthy = [], []
    while time.monotonic() < deadline and not (died and unhealthy):
        evs = state_api.list_cluster_events()
        died = [
            e for e in evs
            if e.get("type") == "REPLICA_DIED"
            and e.get("deployment") == "Mortal"
        ]
        unhealthy = [
            e for e in evs if e.get("type") == "DEPLOYMENT_UNHEALTHY"
            and e.get("deployment") == "Mortal"
        ]
        time.sleep(0.5)
    assert died, "REPLICA_DIED never recorded"
    assert unhealthy, "DEPLOYMENT_UNHEALTHY never recorded"
    # reconcile heals it back
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            if serve.get_app_handle("mortal_app").remote().result(
                timeout_s=30
            ) == "alive":
                break
        except Exception:
            time.sleep(0.5)
    else:
        pytest.fail("deployment never healed")
    serve.delete("mortal_app")
