"""Metrics lint (tier-1, fast): every ``ray_tpu_*`` Prometheus series must
be snake_case, registered in exactly one place, and documented in the
DESIGN_MAP metrics table — and the table must not list dead series.

Registration sites are the two real pipelines:

* ``metrics.Counter/Gauge/Histogram("ray_tpu_...")`` constructors
  (application metrics riding the telemetry KV aggregation), and
* ``add("ray_tpu_...", kind, ...)`` rows in the scheduler's
  ``_runtime_metric_series`` (runtime-internal series).

Docstrings/comments mentioning a series name do not count. An
undocumented, duplicated, or badly-named series fails here, at commit
time, instead of surfacing as a silently-unscrapable dashboard panel.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "ray_tpu")
DESIGN_MAP = os.path.join(REPO, "DESIGN_MAP.md")

# a registration is a Counter/Gauge/Histogram constructor or a runtime
# `add(` series row whose FIRST argument is the literal series name
# (possibly on the next line — black wraps long calls)
_REG_RE = re.compile(
    r"(?:\b(?:Counter|Gauge|Histogram)|(?<![\w.])add)\(\s*\n?\s*"
    r"[rbf]?[\"'](ray_tpu_[A-Za-z0-9_]+)[\"']",
    re.MULTILINE,
)
_SNAKE_RE = re.compile(r"^ray_tpu_[a-z0-9]+(_[a-z0-9]+)*$")
# DESIGN_MAP metrics-table rows: `| ray_tpu_foo | kind | ... |`
_TABLE_RE = re.compile(r"^\|\s*`?(ray_tpu_[A-Za-z0-9_]+)`?\s*\|", re.MULTILINE)


def find_registrations() -> Dict[str, List[Tuple[str, int]]]:
    """series name -> [(relpath, lineno), ...] across the package."""
    sites: Dict[str, List[Tuple[str, int]]] = {}
    for dirpath, dirnames, filenames in os.walk(PKG):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in filenames:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            with open(path, encoding="utf-8", errors="replace") as fh:
                text = fh.read()
            for m in _REG_RE.finditer(text):
                line = text.count("\n", 0, m.start()) + 1
                sites.setdefault(m.group(1), []).append(
                    (os.path.relpath(path, REPO), line)
                )
    return sites


def documented_series() -> List[str]:
    with open(DESIGN_MAP, encoding="utf-8") as fh:
        return _TABLE_RE.findall(fh.read())


def test_metric_names_are_snake_case():
    bad = [n for n in find_registrations() if not _SNAKE_RE.match(n)]
    assert not bad, f"non-snake_case metric series: {bad}"


def test_single_registration_site_per_series():
    dupes = {
        name: sites
        for name, sites in find_registrations().items()
        if len(sites) > 1
    }
    assert not dupes, (
        "metric series registered in more than one place (merge them or "
        f"rename): {dupes}"
    )


def test_every_series_documented_in_design_map():
    registered = set(find_registrations())
    documented = documented_series()
    missing = sorted(registered - set(documented))
    assert not missing, (
        "series registered in code but missing from the DESIGN_MAP "
        f"metrics table: {missing}"
    )


def test_no_stale_series_in_design_map():
    registered = set(find_registrations())
    documented = documented_series()
    stale = sorted(set(documented) - registered)
    assert not stale, (
        "DESIGN_MAP metrics table documents series with no registration "
        f"site (dead docs): {stale}"
    )
    dupes = sorted(n for n in set(documented) if documented.count(n) > 1)
    assert not dupes, f"series listed twice in the DESIGN_MAP table: {dupes}"


def _described_text(node) -> bool:
    """True when an AST node statically yields non-empty help text:
    a string literal (implicit concatenation folds to one Constant),
    an f-string, or a ``+``/parenthesized composition of those."""
    import ast

    if isinstance(node, ast.Constant):
        return isinstance(node.value, str) and bool(node.value.strip())
    if isinstance(node, ast.JoinedStr):
        return True  # f-strings always carry at least the template
    if isinstance(node, ast.BinOp):
        return _described_text(node.left) or _described_text(node.right)
    return False


def find_undescribed() -> List[Tuple[str, str, int]]:
    """(series, relpath, lineno) for every ray_tpu_* registration whose
    HELP description is missing or empty. Descriptions feed straight into
    the ``# HELP`` lines of ``prometheus_text()`` — an empty one ships an
    undocumented scrape series."""
    import ast

    bad: List[Tuple[str, str, int]] = []
    for dirpath, dirnames, filenames in os.walk(PKG):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in filenames:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            with open(path, encoding="utf-8", errors="replace") as fh:
                text = fh.read()
            try:
                tree = ast.parse(text)
            except SyntaxError:
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                fname_call = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else None)
                if fname_call not in ("Counter", "Gauge", "Histogram", "add"):
                    continue
                if not (node.args and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)
                        and node.args[0].value.startswith("ray_tpu_")):
                    continue
                series = node.args[0].value
                # description position: arg 1 for metric constructors,
                # arg 2 for the runtime add(name, kind, description, data)
                desc_idx = 2 if fname_call == "add" else 1
                desc = None
                for kw in node.keywords:
                    if kw.arg == "description":
                        desc = kw.value
                if desc is None and len(node.args) > desc_idx:
                    desc = node.args[desc_idx]
                if desc is None or not _described_text(desc):
                    rel = os.path.relpath(path, REPO)
                    bad.append((series, rel, node.lineno))
    return bad


def test_every_series_has_description():
    """Every ray_tpu_* registration must carry non-empty HELP text —
    ``prometheus_text()`` emits it verbatim as the series' ``# HELP``
    line, so an empty description is an undocumented scrape surface."""
    bad = find_undescribed()
    assert not bad, (
        "metric series registered without a HELP description "
        f"(add one — it becomes the # HELP line): {bad}"
    )


def test_scanner_finds_known_series():
    """Guard the scanner itself: if the regex rots, the other tests pass
    vacuously. These three series span both registration pipelines."""
    found = find_registrations()
    for name in (
        "ray_tpu_object_store_bytes_used",  # scheduler add(...)
        "ray_tpu_spill_bytes_total",  # memplane Counter(...)
        "ray_tpu_serve_request_latency_ms",  # serve Histogram(...)
    ):
        assert name in found, f"metrics-lint scanner lost {name}"
