"""Multi-host RL learner group: IMPALA learners as actor processes on daemon
nodes joining one ``jax.distributed`` mesh, with gang restart on failure.

Parity: ``rllib/core/learner/learner_group.py:154-174`` (multi-learner
updates) + the learner-group restart path. TPU-first: the update is one
jitted SPMD program over a mesh spanning the learner processes (gloo on the
virtual-CPU path, ICI on real slices).
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

LEARNER_ENV = {
    "env_vars": {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
    }
}


@pytest.fixture
def two_node_cluster():
    # head has no CPUs: learner + env-runner actors land on the daemon nodes
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 0})
    cluster.add_node(num_cpus=3)
    cluster.add_node(num_cpus=3)
    cluster.wait_for_nodes()
    yield cluster
    cluster.shutdown()


def _impala_config():
    from ray_tpu.rl import IMPALAConfig

    return (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(
            num_env_runners=2,
            num_envs_per_env_runner=16,
            rollout_fragment_length=64,
        )
        .training(lr=1e-3, entropy_coeff=0.005)
        .learners(num_learner_workers=2, learner_runtime_env=LEARNER_ENV)
        .debugging(seed=0)
    )


def test_impala_learner_group_spans_daemon_nodes(two_node_cluster):
    """2 learner processes x 2 virtual devices = one 4-device data mesh;
    CartPole learns to >= 150 through the multi-host learner group."""
    algo = _impala_config().build()
    try:
        assert algo._group is not None
        assert algo._group.total_devices == 4  # 2 procs x 2 devices
        # learners must be on daemon nodes (the head has no CPUs)
        best = 0.0
        for i in range(400):
            result = algo.training_step()
            best = max(best, result["episode_return_mean"])
            if best >= 150.0:
                break
        assert best >= 150.0, f"multi-host IMPALA did not learn (best {best})"
    finally:
        algo.stop()


def test_impala_learner_death_restarts_group(two_node_cluster):
    """Kill one learner actor mid-train: the group must re-rendezvous under
    a fresh coordinator, restore params, and keep training (parity: the
    learner-group / backend-executor restart path)."""
    algo = _impala_config().build()
    try:
        returns = []
        for i in range(8):
            result = algo.training_step()
            returns.append(result["episode_return_mean"])
            if i == 3:
                # hard-kill learner rank 1 (actor process dies mid-gang)
                ray_tpu.kill(algo._group.workers[1])
        # the kill forced at least one restart (fresh rendezvous attempt)
        assert algo._group._attempt >= 1, "group never restarted"
        assert all(np.isfinite(r) for r in returns)
        # training still works after the restart
        result = algo.training_step()
        assert np.isfinite(result["pg_loss"])
    finally:
        algo.stop()
