"""Tests: state API, runtime context, metrics, queue, collective, DAG.

Parity: ``python/ray/tests/test_state_api*.py``, ``test_metrics*.py``,
``test_queue.py``, ``util/collective`` tests, ``test_dag*.py`` (SURVEY.md §4).
"""

import numpy as np
import pytest

import ray_tpu


def test_state_api_lists(ray_start_regular):
    from ray_tpu.util import state

    @ray_tpu.remote
    def f():
        return 1

    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    a = A.remote()
    ray_tpu.get([f.remote(), a.ping.remote()])

    tasks = state.list_tasks()
    assert any(t["name"] == "f" and t["state"] == "FINISHED" for t in tasks)
    actors = state.list_actors()
    assert any(x["state"] == "ALIVE" for x in actors)
    nodes = state.list_nodes()
    assert len(nodes) == 1 and nodes[0]["alive"]
    workers = state.list_workers()
    assert any(w["state"] in ("idle", "busy") for w in workers)
    summary = state.summarize_tasks()
    assert summary["f"]["FINISHED"] >= 1


def test_state_api_filters(ray_start_regular):
    from ray_tpu.util import state

    @ray_tpu.remote
    def g():
        return 1

    ray_tpu.get(g.remote())
    done = state.list_tasks(filters=[("state", "=", "FINISHED")])
    assert all(t["state"] == "FINISHED" for t in done)


def test_runtime_context(ray_start_regular):
    ctx = ray_tpu.get_runtime_context()
    assert ctx.get_job_id() is not None

    @ray_tpu.remote
    def whoami():
        c = ray_tpu.get_runtime_context()
        return (c.get_task_id(), c.get_worker_id())

    task_id, worker_id = ray_tpu.get(whoami.remote())
    assert task_id is not None and worker_id is not None

    @ray_tpu.remote
    class Who:
        def me(self):
            return ray_tpu.get_runtime_context().get_actor_id()

    w = Who.remote()
    assert ray_tpu.get(w.me.remote()) is not None


def test_metrics_and_prometheus(ray_start_regular):
    from ray_tpu.util.metrics import Counter, Gauge, Histogram, prometheus_text

    c = Counter("requests_total", description="total requests", tag_keys=("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2.0, tags={"route": "/a"})
    g = Gauge("temperature")
    g.set(42.5)
    h = Histogram("latency_ms", boundaries=[1, 10, 100])
    h.observe(5.0)
    h.observe(50.0)

    text = prometheus_text()
    assert 'requests_total{route="/a"} 3.0' in text
    assert "temperature 42.5" in text
    assert "latency_ms_count 2" in text


def test_metrics_from_worker(ray_start_regular):
    from ray_tpu.util.metrics import prometheus_text

    @ray_tpu.remote
    def record():
        from ray_tpu.util.metrics import Counter

        Counter("worker_side_total").inc(7.0)
        return True

    ray_tpu.get(record.remote())
    assert "worker_side_total 7.0" in prometheus_text()


def test_queue(ray_start_regular):
    from ray_tpu.util.queue import Empty, Queue

    q = Queue(maxsize=10)
    q.put("a")
    q.put("b")
    assert q.qsize() == 2
    assert q.get() == "a"

    @ray_tpu.remote
    def consume(q):
        return q.get(timeout=30)

    ref = consume.remote(q)
    assert ray_tpu.get(ref, timeout=60) == "b"
    with pytest.raises(Empty):
        q.get_nowait()
    q.shutdown()


def test_collective_allreduce(ray_start_regular):
    from ray_tpu.util.collective import init_collective_group

    @ray_tpu.remote
    def member(rank, world):
        g = init_collective_group(world, rank, group_name="t1")
        out = g.allreduce(np.full(4, rank + 1.0))
        gathered = g.allgather(np.array([float(rank)]))
        g.barrier()
        return out.tolist(), [x.tolist() for x in gathered]

    results = ray_tpu.get([member.remote(r, 2) for r in range(2)], timeout=120)
    for out, gathered in results:
        assert out == [3.0, 3.0, 3.0, 3.0]  # 1+2
        assert gathered == [[0.0], [1.0]]


def test_collective_broadcast(ray_start_regular):
    from ray_tpu.util.collective import init_collective_group

    @ray_tpu.remote
    def member(rank, world):
        g = init_collective_group(world, rank, group_name="t2")
        return g.broadcast(np.arange(3.0) if rank == 0 else None, src_rank=0).tolist()

    results = ray_tpu.get([member.remote(r, 2) for r in range(2)], timeout=120)
    assert results == [[0.0, 1.0, 2.0]] * 2


def test_dag_functions(ray_start_regular):
    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    def plus(a, b):
        return a + b

    @ray_tpu.remote
    def times(a, k):
        return a * k

    with InputNode() as inp:
        dag = times.bind(plus.bind(inp, 10), 3)
    assert ray_tpu.get(dag.execute(5), timeout=60) == 45


def test_dag_with_actors(ray_start_regular):
    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    class Acc:
        def __init__(self):
            self.total = 0

        def add(self, x):
            self.total += x
            return self.total

    node = Acc.bind()
    with InputNode() as inp:
        dag = node.add.bind(inp)
    compiled = dag.experimental_compile()
    assert ray_tpu.get(compiled.execute(5), timeout=60) == 5
    assert ray_tpu.get(compiled.execute(7), timeout=60) == 12  # same actor reused
    compiled.teardown()


def test_compile_jax_pipeline():
    import jax.numpy as jnp

    from ray_tpu.dag import compile_jax_pipeline

    fused = compile_jax_pipeline([lambda x: x + 1, lambda x: x * 2, jnp.sum])
    assert float(fused(jnp.ones(4))) == 16.0


def test_state_logs_api(ray_start_regular, tmp_path):
    import os

    from ray_tpu.util import state
    from ray_tpu._private.worker import get_driver

    logs_dir = os.path.join(get_driver().node.session_dir, "logs")
    with open(os.path.join(logs_dir, "test.log"), "w") as fh:
        fh.write("line1\nline2\n")
    rows = state.list_logs()
    assert any(r["filename"] == "test.log" for r in rows)
    assert state.get_log("test.log", tail=1) == "line2\n"


def test_joblib_backend_sklearn(ray_start_regular):
    """joblib backend parity (ray.util.joblib): Parallel batches run as
    tasks; sklearn GridSearchCV works through it."""
    from joblib import Parallel, delayed, parallel_backend

    from ray_tpu.util.joblib import register_ray_tpu

    register_ray_tpu()
    with parallel_backend("ray_tpu"):
        out = Parallel(n_jobs=2)(delayed(lambda x: x * x)(i) for i in range(8))
    assert out == [i * i for i in range(8)]

    from sklearn.datasets import make_classification
    from sklearn.linear_model import LogisticRegression
    from sklearn.model_selection import GridSearchCV

    X, y = make_classification(n_samples=120, random_state=0)
    with parallel_backend("ray_tpu"):
        gs = GridSearchCV(LogisticRegression(max_iter=200), {"C": [0.1, 1.0]}, cv=2)
        gs.fit(X, y)
    assert gs.best_score_ > 0.7


def test_collective_send_recv(ray_start_regular):
    """p2p send/recv parity (ray.util.collective send/recv)."""
    import numpy as np

    from ray_tpu.util.collective import init_collective_group

    @ray_tpu.remote
    def rank0():
        g = init_collective_group(2, 0, "p2p_test")
        g.send(np.arange(4.0), dst_rank=1, tag=1)
        got = g.recv(src_rank=1, tag=2, timeout=60)
        return float(got.sum())

    @ray_tpu.remote
    def rank1():
        g = init_collective_group(2, 1, "p2p_test")
        got = g.recv(src_rank=0, tag=1, timeout=60)
        g.send(got * 10, dst_rank=0, tag=2)
        return float(got.sum())

    a, b = ray_tpu.get([rank0.remote(), rank1.remote()], timeout=120)
    assert b == 6.0      # received 0+1+2+3
    assert a == 60.0     # received the echo *10


def test_collective_send_recv_queues_per_key(ray_start_regular):
    """Two sends on the same (src, dst, tag) before the matching recv must
    both arrive, in order — the first payload is never dropped."""
    import numpy as np

    from ray_tpu.util.collective import init_collective_group

    @ray_tpu.remote
    def sender():
        g = init_collective_group(2, 0, "p2p_queue_test")
        g.send(np.array([1.0]), dst_rank=1, tag=7)
        g.send(np.array([2.0]), dst_rank=1, tag=7)
        # wait for the receiver's ack so the group actor stays alive
        return float(g.recv(src_rank=1, tag=8, timeout=60)[0])

    @ray_tpu.remote
    def receiver():
        import time

        g = init_collective_group(2, 1, "p2p_queue_test")
        time.sleep(1.0)  # let both sends land before the first recv
        first = float(g.recv(src_rank=0, tag=7, timeout=60)[0])
        second = float(g.recv(src_rank=0, tag=7, timeout=60)[0])
        g.send(np.array([9.0]), dst_rank=0, tag=8)
        return (first, second)

    ack, (first, second) = ray_tpu.get(
        [sender.remote(), receiver.remote()], timeout=120
    )
    assert (first, second) == (1.0, 2.0)
    assert ack == 9.0


def test_workflow_list_all(tmp_path, ray_start_regular):
    import ray_tpu as _rt
    from ray_tpu import workflow

    @_rt.remote
    def one():
        return 1

    storage = str(tmp_path / "wf")
    workflow.run(one.bind(), workflow_id="wf_a", storage=storage)
    workflow.run(one.bind(), workflow_id="wf_b", storage=storage)
    rows = workflow.list_all(storage=storage)
    assert rows == [("wf_a", "SUCCESSFUL"), ("wf_b", "SUCCESSFUL")]
    assert workflow.list_all("FAILED", storage=storage) == []
