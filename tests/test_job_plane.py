"""Multi-tenant job plane tests (fast tier-1).

Covers: deficit-weighted round-robin fairness between jobs, per-job quota
enforcement at dispatch, admission-control queueing/rejection/ordering,
priority preemption (victim killed, retry budget spared, PREEMPTED event),
the checkpoint-commit protect window, job-aware OOM attribution, the
``job_id=`` cluster-event filter, and the ``state.list_jobs`` surface.
Heavy isolation numbers live in ``bench_isolation.py`` (slow); the
preemption→committed-checkpoint-resume chaos test is in
``tests/test_preempt_chaos.py`` (slow).
"""

import time

import pytest

import ray_tpu
from ray_tpu.exceptions import JobAdmissionError


def _sch():
    from ray_tpu._private.worker import get_runtime

    return get_runtime().node.scheduler


@pytest.fixture
def one_cpu():
    rt = ray_tpu.init(num_cpus=1)
    yield rt
    ray_tpu.shutdown()


def _job_rows():
    from ray_tpu.util import state

    return {r["name"]: r for r in state.list_jobs()}


def test_dwrr_weight_proportional_dispatch(one_cpu):
    """Two contending jobs on one CPU: the weight-3 job must get ~3x the
    dispatch slots of the weight-1 job while both queues are deep — and
    the weight-1 job must not starve."""

    @ray_tpu.remote
    def gate():
        time.sleep(1.0)
        return 1

    # both jobs' tasks dep-park on the gate so neither gets a head start:
    # they become schedulable in one batch when the gate commits
    g = gate.remote()

    @ray_tpu.remote
    def tick(tag, i, _gate):
        return tag

    with ray_tpu.job_scope(name="heavy", weight=3.0):
        heavy = [tick.remote("heavy", i, g) for i in range(40)]
    with ray_tpu.job_scope(name="light", weight=1.0):
        light = [tick.remote("light", i, g) for i in range(40)]

    # completion order == dispatch order on a single serial CPU
    order = []
    pending = {r: t for refs, t in ((heavy, "heavy"), (light, "light")) for r in refs}
    deadline = time.monotonic() + 120
    while pending and time.monotonic() < deadline:
        ready, _ = ray_tpu.wait(list(pending), num_returns=1, timeout=30)
        for r in ready:
            order.append(pending.pop(r))
    assert not pending, "tasks did not drain"
    head = order[:32]
    n_heavy = head.count("heavy")
    n_light = head.count("light")
    # quantum is fair_share_quantum x weight (8 x 3 vs 8 x 1): expect
    # roughly 24/8 in every 32; generous bounds absorb lease batching
    assert n_light >= 4, f"light job starved: {n_heavy=} {n_light=}"
    assert n_heavy >= 1.7 * n_light, f"weights not honored: {n_heavy=} {n_light=}"
    rows = _job_rows()
    assert rows["heavy"]["dispatched_total"] == 40
    assert rows["light"]["dispatched_total"] == 40


def test_quota_caps_live_concurrency():
    """A job with ``CPU: 1`` quota on a 4-CPU node never runs two tasks
    at once: enforcement at dispatch degrades it to queueing."""
    ray_tpu.init(num_cpus=4)
    try:

        @ray_tpu.remote
        def span(i):
            t0 = time.time()
            time.sleep(0.25)
            return (t0, time.time())

        with ray_tpu.job_scope(name="capped", quota={"CPU": 1.0}):
            refs = [span.remote(i) for i in range(4)]
        spans = ray_tpu.get(refs, timeout=120)
        spans.sort()
        for (_, end_a), (start_b, _) in zip(spans, spans[1:]):
            assert start_b >= end_a - 0.05, f"quota overlap: {spans}"

        # an unquota'd job on the same cluster DOES overlap (the cap came
        # from the quota, not the fleet)
        with ray_tpu.job_scope(name="free"):
            refs = [span.remote(i) for i in range(4)]
        spans = sorted(ray_tpu.get(refs, timeout=120))
        overlaps = sum(
            1 for (_, e), (s, _) in zip(spans, spans[1:]) if s < e - 0.05
        )
        assert overlaps >= 1, f"expected parallelism without quota: {spans}"
    finally:
        ray_tpu.shutdown()


def test_admission_queue_reject_and_priority_order(one_cpu):
    """Submissions past the backlog bound are QUEUED (priority-ordered)
    or REJECTED (queue full); queued jobs admit priority-first once the
    backlog drains, with JOB_QUEUED/JOB_ADMITTED/JOB_REJECTED events."""
    from ray_tpu.util import state

    sch = _sch()
    sch.config.job_admission_backlog_max = 2
    sch.config.job_admission_max_queued = 3
    try:

        @ray_tpu.remote
        def busy(i):
            time.sleep(0.4)
            return i

        blockers = [busy.remote(i) for i in range(8)]  # backlog >> 2
        time.sleep(0.3)  # let the queue form

        rt = ray_tpu.get_runtime()

        def submit(name, priority):
            return rt.scheduler_rpc(
                "submit_job", (name, priority, 1.0, None, None)
            )

        lo = submit("adm-lo", 1)
        hi = submit("adm-hi", 5)
        mid = submit("adm-mid", 3)
        assert {lo["admission"], hi["admission"], mid["admission"]} == {"QUEUED"}
        # queue positions follow priority desc, FIFO within a priority
        rows = _job_rows()
        assert rows["adm-hi"]["queue_position"] == 1
        assert rows["adm-mid"]["queue_position"] == 2
        assert rows["adm-lo"]["queue_position"] == 3
        # the queue is full (3): the next submission bounces
        rejected = submit("adm-reject", 9)
        assert rejected["admission"] == "REJECTED"
        with pytest.raises(JobAdmissionError):
            with ray_tpu.job_scope(name="adm-scope-reject"):
                pass

        ray_tpu.get(blockers, timeout=120)  # drain the backlog
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            rows = _job_rows()
            if all(
                rows[n]["admission"] == "ADMITTED"
                for n in ("adm-lo", "adm-hi", "adm-mid")
            ):
                break
            time.sleep(0.1)
        else:
            raise AssertionError(f"admission queue never drained: {rows}")
        admitted = [
            ev["name"]
            for ev in state.list_cluster_events(
                filters=[("type", "=", "JOB_ADMITTED")]
            )
            if ev.get("name", "").startswith("adm-")
        ]
        assert admitted == ["adm-hi", "adm-mid", "adm-lo"]
        types = {ev["type"] for ev in state.list_cluster_events()}
        assert {"JOB_QUEUED", "JOB_ADMITTED", "JOB_REJECTED"} <= types
    finally:
        sch.config.job_admission_backlog_max = 0


def test_queued_job_presubmitted_work_parks_then_admits(one_cpu):
    """A QUEUED tenant's driver may keep submitting (job_scope only raises
    on REJECTED): its work must park without dispatching, the scheduler
    must survive passes where ONLY parked jobs have ready work (the
    empty-arbitration-set corner), and the parked backlog must not count
    against the admission bound — else a queued job that pre-submitted
    more entries than the bound could never be admitted (live-lock)."""
    sch = _sch()
    sch.config.job_admission_backlog_max = 2
    try:

        @ray_tpu.remote
        def busy(i):
            time.sleep(0.3)
            return i

        blockers = [busy.remote(i) for i in range(6)]  # backlog > 2
        time.sleep(0.2)
        with ray_tpu.job_scope(name="parked") as info:
            assert info["admission"] == "QUEUED"
            # deeper than the admission bound on purpose
            parked = [busy.remote(i) for i in range(4)]
        done, _ = ray_tpu.wait(list(parked), num_returns=1, timeout=0.5)
        assert not done, "parked job dispatched before admission"
        ray_tpu.get(blockers, timeout=120)
        # only the parked job has ready work now; the loop must keep
        # ticking and admit it despite its own 4-deep sub-queue
        assert ray_tpu.get(parked, timeout=60) == [0, 1, 2, 3]
        assert _job_rows()["parked"]["admission"] == "ADMITTED"
    finally:
        sch.config.job_admission_backlog_max = 0


def test_priority_preemption_spares_retry_budget():
    """A high-priority job starved past the wait bound preempts a
    lower-priority victim: the victim's task re-queues WITHOUT spending
    its retry budget, a PREEMPTED event lands, and the high-priority task
    runs."""
    ray_tpu.init(num_cpus=2, _system_config={"preemption_wait_s": 0.6})
    try:
        from ray_tpu.util import state

        @ray_tpu.remote(max_retries=3)
        def hog(i):
            time.sleep(120)
            return i

        with ray_tpu.job_scope(name="noisy", priority=0):
            hogs = [hog.remote(i) for i in range(2)]  # saturate both CPUs
        time.sleep(1.0)  # hogs running

        @ray_tpu.remote
        def urgent():
            return "done"

        with ray_tpu.job_scope(name="urgent", priority=10):
            ref = urgent.remote()
        assert ray_tpu.get(ref, timeout=60) == "done"

        events = state.list_cluster_events(
            filters=[("type", "=", "PREEMPTED")]
        )
        assert events, "no PREEMPTED event recorded"
        ev = events[-1]
        assert ev["victim_priority"] == 0
        assert ev["for_priority"] == 10
        rows = _job_rows()
        assert rows["noisy"]["preemptions"] >= 1
        # the preempted attempt kept its full retry budget
        retried = [
            t
            for t in state.list_tasks(filters=[("name", "=", "hog")])
            if t["attempt"] >= 1
        ]
        assert retried and all(t["retries_left"] == 3 for t in retried)
        # the event filter satellite: PREEMPTED is attributed to the noisy
        # job and the job_id= filter finds it
        noisy_hex = rows["noisy"]["job"]
        filtered = state.list_cluster_events(job_id=noisy_hex)
        assert any(e["type"] == "PREEMPTED" for e in filtered)
        assert all(
            e.get("job_id") == noisy_hex
            or (e.get("task_id") or "").endswith(noisy_hex)
            or (e.get("actor_id") or "").endswith(noisy_hex)
            for e in filtered
        )
        del hogs
    finally:
        ray_tpu.shutdown()


def test_protect_window_shields_from_victim_selection(one_cpu):
    """A worker inside a protect window (mid-commit checkpoint save) is
    skipped by victim selection: the OOM policy finds nothing to kill."""
    from ray_tpu._private.memory_monitor import make_scheduler_kill_policy

    @ray_tpu.remote(max_retries=2)
    def shielded():
        from ray_tpu._private.worker import get_runtime

        rt = get_runtime()
        rt.protect_from_preemption(1)
        time.sleep(3.0)
        rt.protect_from_preemption(-1)
        return "ok"

    ref = shielded.remote()
    sch = _sch()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if any(w.protect_count > 0 for w in sch.workers.values()):
            break
        time.sleep(0.05)
    else:
        raise AssertionError("protect window never registered")
    kill = make_scheduler_kill_policy(sch)
    assert not kill(), "OOM policy killed a protected worker"
    assert sch.pick_oom_victim() is None
    assert ray_tpu.get(ref, timeout=60) == "ok"
    # after release the worker is fair game again
    assert all(w.protect_count == 0 for w in sch.workers.values())


def test_oom_kill_attributes_job_and_counts(one_cpu):
    """The memory-monitor kill path lands the victim's job and priority
    in the OOM event and bumps the per-job counter + metric series."""
    from ray_tpu._private.memory_monitor import make_scheduler_kill_policy
    from ray_tpu.util import state

    @ray_tpu.remote(max_retries=1)
    def hog():
        time.sleep(60)
        return 1

    with ray_tpu.job_scope(name="oom-job", priority=2):
        ref = hog.remote()
    sch = _sch()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if any(
            t["state"] == "RUNNING"
            for t in state.list_tasks(filters=[("name", "=", "hog")])
        ):
            break
        time.sleep(0.05)
    kill = make_scheduler_kill_policy(sch)
    assert kill()
    events = state.list_cluster_events(filters=[("type", "=", "OOM")])
    assert events
    rows = _job_rows()
    assert events[-1]["job_id"] == rows["oom-job"]["job"]
    assert events[-1]["priority"] == 2
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if _job_rows()["oom-job"]["oom_kills"] >= 1:
            break
        time.sleep(0.05)
    assert _job_rows()["oom-job"]["oom_kills"] >= 1
    rt = ray_tpu.get_runtime()
    series = {s["name"] for s in rt.rpc("runtime_metrics")}
    assert {"ray_tpu_oom_kills_total", "ray_tpu_preemptions_total"} <= series
    ready, not_ready = ray_tpu.wait([ref], num_returns=1, timeout=0.5)
    assert not_ready  # retrying, not lost


def test_list_jobs_columns_and_object_store_quota(one_cpu):
    """``state.list_jobs`` exposes quota/usage/object_store_bytes; a job
    past its object-store-bytes cap parks its ready queue until frees."""
    import numpy as np

    from ray_tpu.util import state

    with ray_tpu.job_scope(
        name="putter", quota={"object_store_bytes": 1}
    ) as info:
        blob = ray_tpu.put(np.zeros(1 << 18, dtype=np.uint8))  # 256 KiB

        @ray_tpu.remote
        def parked():
            return "ran"

        ref = parked.remote()
    row = _job_rows()["putter"]
    assert row["quota"] == {"object_store_bytes": 1.0}
    assert info["admission"] == "ADMITTED"
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        row = _job_rows()["putter"]
        if row["object_store_bytes"] > 1:
            break
        time.sleep(0.05)
    assert row["object_store_bytes"] > 1, row
    # over the byte cap: the task stays parked in the job's sub-queue
    ready, not_ready = ray_tpu.wait([ref], num_returns=1, timeout=1.5)
    assert not_ready and _job_rows()["putter"]["ready"] == 1
    # freeing the blob releases the charge and un-parks the queue
    del blob
    assert ray_tpu.get(ref, timeout=60) == "ran"
    cols = set(_job_rows()["putter"])
    assert {
        "priority",
        "weight",
        "quota",
        "usage",
        "queue_position",
        "admission",
        "preemptions",
        "oom_kills",
    } <= cols
