"""Training step-plane tests: per-step stage attribution ("where did the
step go"), recompile detection, ingest-stall attribution, the goodput
downtime ledger, live mid-run publication, and regression guards for the
PR-2 timeline / PR-11 trace / PR-13 memory planes riding the same ring."""

import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (
    Checkpoint,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.util import state

_STAGES = (
    "data_wait_ms",
    "host_to_device_ms",
    "compile_ms",
    "compute_ms",
    "collective_wait_ms",
    "checkpoint_stall_ms",
    "other_ms",
)


def _fit(loop, name, tmp_path, workers=1, config=None, **kw):
    trainer = JaxTrainer(
        loop,
        train_loop_config=config or {},
        scaling_config=ScalingConfig(num_workers=workers),
        run_config=RunConfig(storage_path=str(tmp_path), name=name, **kw),
        datasets=kw.pop("datasets", None) if "datasets" in kw else None,
    )
    return trainer.fit()


def test_step_stage_sum_within_10pct_2rank(ray_start_regular, tmp_path):
    """The acceptance bar: per-rank stage decomposition sums to within 10%
    of the measured step wall on a 2-rank run, with head-side
    collective_wait + straggler attribution."""

    def loop(config):
        ctx = train.get_context()
        for i in range(4):
            # rank 1 computes longer: rank 0 must show collective_wait
            time.sleep(0.03 + 0.04 * ctx.get_world_rank())
            train.report({"loss": float(i)})

    res = _fit(loop, "obs_sum", tmp_path, workers=2)
    assert res.error is None
    d = state.train_run("obs_sum")
    assert d is not None and d["world"] == 2
    assert d["steps_seen"] == 4
    checked = 0
    for srec in d["steps"]:
        assert set(srec["ranks"]) == {"0", "1"}
        for rec in srec["ranks"].values():
            wall = rec["wall_ms"]
            total = sum(rec["stages"].get(k, 0.0) for k in _STAGES)
            assert wall > 0
            assert abs(total - wall) <= 0.10 * wall, (rec["stages"], wall)
            checked += 1
    assert checked == 8
    # rank 1 is the straggler (its pre-report timestamp is latest); rank 0
    # waited for it in the step's collectives
    last = d["steps"][-1]["ranks"]
    skew = d["skew"][d["steps"][-1]["step"]]
    assert skew["straggler_rank"] == 1
    assert last["0"]["stages"]["collective_wait_ms"] > 10.0
    assert last["1"]["stages"]["collective_wait_ms"] == 0.0
    # run digest row surfaces the same run
    runs = state.list_train_runs()
    assert any(r["run"] == "obs_sum" and r["steps"] == 4 for r in runs)
    # timeline renders a per-rank waterfall with the straggler marked
    text = ray_tpu.train_timeline("obs_sum").summary()
    assert "step waterfall" in text and "straggler" in text


def test_ingest_stall_attribution_throttled_dataset(ray_start_regular, tmp_path):
    """A throttled dataset's batch waits land in data_wait, attributed to
    the bottleneck streaming-executor operator; device_put time lands in
    host_to_device."""

    def loop(config):
        it = train.get_dataset_shard("train")
        assert it is not None
        n = 0
        for batch in it.iter_jax_batches(batch_size=8, drop_last=False):
            train.report({"rows": int(next(iter(batch.values())).shape[0])})
            n += 1
        assert n > 0

    def slow(block):
        time.sleep(0.04)
        return block

    ds = ray_tpu.data.range(32).map_batches(slow)
    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path), name="obs_ingest"),
        datasets={"train": ds},
    )
    res = trainer.fit()
    assert res.error is None
    d = state.train_run("obs_ingest")
    assert d is not None and d["steps_seen"] >= 3
    totals = d["totals"]
    assert totals["data_wait_ms"] > 30.0, totals
    # per-operator stall attribution from the backpressure stats
    assert d["ops"], d
    assert sum(d["ops"].values()) > 10.0
    # the throttled map stage (or its source feed) is the named bottleneck
    assert any("map" in op or op == "source" for op in d["ops"])
    # host->device transfer was measured on the iter_jax_batches path
    assert totals["host_to_device_ms"] >= 0.0
    h2d_steps = [
        rec["stages"]["host_to_device_ms"]
        for s in d["steps"]
        for rec in s["ranks"].values()
    ]
    assert any(v > 0 for v in h2d_steps)
    from ray_tpu.util.metrics import prometheus_text

    text = prometheus_text()
    assert "ray_tpu_train_ingest_stall_seconds_total" in text
    assert "ray_tpu_train_data_wait_ratio" in text


def test_dataset_shard_is_per_rank_disjoint(ray_start_regular, tmp_path):
    """get_dataset_shard gives each rank a disjoint lazy shard of the
    trainer-attached dataset (round-robin over source blocks, stages
    preserved) — not the full dataset duplicated per rank."""

    def add_one(block):
        return {"id": [int(v) + 1000 for v in block["id"]]}

    def loop2(config):
        ctx = train.get_context()
        it = train.get_dataset_shard("train")
        seen = []
        for batch in it.iter_batches(batch_size=64):
            seen.extend(int(v) for v in batch["id"])
        with open(
            os.path.join(str(tmp_path), f"rank{ctx.get_world_rank()}.txt"), "w"
        ) as fh:
            fh.write(",".join(map(str, sorted(seen))))
        train.report({"n": len(seen)})

    trainer = JaxTrainer(
        loop2,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path), name="obs_shard"),
        datasets={
            "train": ray_tpu.data.range(64, num_blocks=8).map_batches(add_one)
        },
    )
    assert trainer.fit().error is None
    seen_by_rank = {}
    for r in (0, 1):
        with open(os.path.join(str(tmp_path), f"rank{r}.txt")) as fh:
            seen_by_rank[r] = set(
                int(x) for x in fh.read().split(",") if x
            )
    assert seen_by_rank[0] and seen_by_rank[1]
    assert not (seen_by_rank[0] & seen_by_rank[1]), "ranks saw shared rows"
    # stages applied on the sharded path (map ran: values offset by 1000)
    assert seen_by_rank[0] | seen_by_rank[1] == set(range(1000, 1064))


def _jit_loop(vary):
    def loop(config):
        import jax
        import numpy as np

        from ray_tpu._private import sampler, stepplane

        # the flusher's 1s probe may not have fired yet in this fresh
        # worker: install the jax.monitoring listener deterministically
        sampler.install_jax_hooks()
        f = jax.jit(lambda x: (x * 2.0).sum())
        for i in range(5):
            n = 8 + (i if vary else 0)
            x = np.ones((n,), dtype=np.float32)
            stepplane.note_batch_signature(f"x:float32[{n}]")
            float(f(x))
            train.report({"i": float(i)})

    return loop


def test_recompile_detector_flags_shape_change(ray_start_regular, tmp_path):
    res = _fit(_jit_loop(vary=True), "obs_recomp", tmp_path)
    assert res.error is None
    d = state.train_run("obs_recomp")
    warm = int(
        getattr(ray_tpu.init(ignore_reinit_error=True).config,
                "train_recompile_warmup_steps", 2)
    )
    flagged = [
        rec
        for s in d["steps"]
        for rec in s["ranks"].values()
        if rec["recompiled"]
    ]
    assert flagged, d["steps"]
    # every flag is post-warmup and carries the changed shape signature
    for rec in flagged:
        assert rec["step"] > warm
        assert rec["sig"] and "float32" in rec["sig"]
    assert d["recompiles"] == len(flagged)
    events = state.list_cluster_events(
        filters=[("type", "=", "TRAIN_RECOMPILE")]
    )
    assert events and events[-1].get("signature")
    # compile time was attributed to the flagged steps' compile stage
    assert any(rec["stages"]["compile_ms"] > 0 for rec in flagged)


def test_recompile_detector_silent_on_static_shapes(ray_start_regular, tmp_path):
    res = _fit(_jit_loop(vary=False), "obs_static", tmp_path)
    assert res.error is None
    d = state.train_run("obs_static")
    assert d["recompiles"] == 0
    assert not any(
        rec["recompiled"] for s in d["steps"] for rec in s["ranks"].values()
    )
    assert not state.list_cluster_events(
        filters=[("type", "=", "TRAIN_RECOMPILE")]
    )


def test_checkpoint_stall_stage(ray_start_regular, tmp_path):
    def loop(config):
        import tempfile

        for i in range(3):
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "w.bin"), "wb") as fh:
                fh.write(os.urandom(256 * 1024))
            train.report(
                {"i": float(i)}, checkpoint=Checkpoint.from_directory(d)
            )

    res = _fit(loop, "obs_ckpt", tmp_path)
    assert res.error is None
    d = state.train_run("obs_ckpt")
    stalls = [
        rec["stages"]["checkpoint_stall_ms"]
        for s in d["steps"]
        for rec in s["ranks"].values()
    ]
    assert any(v > 0 for v in stalls), stalls
    assert d["totals"]["checkpoint_stall_ms"] > 0


def test_downtime_ledger_under_seeded_kill(ray_start_regular, tmp_path):
    """One seeded kill: the in-run recovery window lands in the downtime
    ledger as cause=recovery and goodput reports the attributed gap."""
    marker = str(tmp_path / "killed_once")

    def loop(config):
        ctx = train.get_context()
        for i in range(6):
            time.sleep(0.05)
            train.report({"i": float(i)})
            if (
                i == 2
                and ctx.get_world_rank() == 1
                and not os.path.exists(marker)
            ):
                open(marker, "w").close()
                os._exit(1)  # seeded preemption of rank 1

    res = _fit(
        loop,
        "obs_chaos",
        tmp_path,
        workers=2,
        failure_config=FailureConfig(max_failures=2, retry_backoff_s=0.1),
    )
    assert res.error is None
    ledger = res.goodput["downtime_ledger"]
    causes = {e["cause"] for e in ledger}
    assert causes & {"recovery", "gang_restart"}, ledger
    attributed = sum(e["seconds"] for e in ledger)
    assert attributed > 0
    assert res.goodput["downtime_s"] == pytest.approx(
        sum(res.goodput["downtime_by_cause"].values()), rel=0.01
    )
    # the scheduler-side run record carries the same ledger + final status
    d = state.train_run("obs_chaos")
    meta = d["meta"]
    assert meta["status"] == "finished"
    assert meta["downtime_ledger"]
    from ray_tpu.util.metrics import prometheus_text

    assert "ray_tpu_train_downtime_seconds" in prometheus_text()


def test_goodput_published_live_mid_run(tmp_path):
    """Satellite: ray_tpu_train_goodput + run meta appear DURING the run on
    the publish cadence, not only at fit() teardown."""
    os.environ["RAY_TPU_TRAIN_GOODPUT_PUBLISH_INTERVAL_S"] = "0.2"
    try:
        ray_tpu.init(num_cpus=4, ignore_reinit_error=True)

        def loop(config):
            for i in range(30):
                time.sleep(0.1)
                train.report({"i": float(i)})

        done = []

        def run():
            done.append(_fit(loop, "obs_live", tmp_path))

        t = threading.Thread(target=run)
        t.start()
        try:
            seen_running = False
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and not seen_running:
                rows = [
                    r
                    for r in state.list_train_runs()
                    if r["run"] == "obs_live"
                ]
                if rows and rows[0]["status"] == "running" and rows[0][
                    "goodput"
                ] is not None:
                    seen_running = True
                time.sleep(0.2)
            assert seen_running, "run meta never published mid-run"
            from ray_tpu.util.metrics import prometheus_text

            assert "ray_tpu_train_goodput" in prometheus_text()
        finally:
            t.join(timeout=60)
        assert done and done[0].error is None
    finally:
        os.environ.pop("RAY_TPU_TRAIN_GOODPUT_PUBLISH_INTERVAL_S", None)
        ray_tpu.shutdown()


def test_jax_compile_spans_join_trace(ray_start_regular):
    """Satellite: jax:* duration spans carry the executing (task, trace)
    instead of landing as global orphans — ray_tpu.trace(id) shows them
    inside the request's span tree."""

    @ray_tpu.remote
    def jit_task():
        import jax
        import numpy as np

        from ray_tpu._private import sampler

        sampler.install_jax_hooks()
        f = jax.jit(lambda x: (x * 3.0).sum())
        out = float(f(np.ones((16,), dtype=np.float32)))
        from ray_tpu.util import tracing

        return out, tracing.current_trace_id()

    out, trace_id = ray_tpu.get(jit_task.remote(), timeout=120)
    assert out == 48.0
    assert trace_id
    t = ray_tpu.trace(trace_id)
    jax_spans = [
        s for s in t.spans.values() if (s.name or "").startswith("jax:")
    ]
    assert jax_spans, [s.name for s in t.spans.values()]
    # parented inside the tree, not floating as roots
    assert any(s.parent_id for s in jax_spans)


def test_prior_planes_regression_guard(ray_start_regular, tmp_path):
    """PR-2 timeline, PR-11 traces, PR-13 memory plane keep working with
    the step plane riding the same telemetry ring."""

    def loop(config):
        for i in range(2):
            time.sleep(0.01)
            train.report({"i": float(i)})

    res = _fit(loop, "obs_guard", tmp_path)
    assert res.error is None
    # PR-2: chrome trace renders with task phase spans
    events = ray_tpu.timeline()
    assert any(e.get("cat") == "TASK_PHASE" for e in events)
    # PR-11: traces recorded; step records carry a joinable trace id
    assert ray_tpu.recent_traces()
    d = state.train_run("obs_guard")
    tids = [
        rec.get("trace_id")
        for s in d["steps"]
        for rec in s["ranks"].values()
    ]
    assert any(tids)
    t = ray_tpu.trace([x for x in tids if x][0])
    assert t.span_count() >= 1
    # PR-13: memory plane summaries still served
    summary = state.summarize_objects(group_by="callsite")
    assert "total_objects" in summary
    # step-plane series all exported with the documented names
    from ray_tpu.util.metrics import prometheus_text

    text = prometheus_text()
    for series in (
        "ray_tpu_train_step_seconds",
        "ray_tpu_train_step_wall_seconds",
        "ray_tpu_train_steps_total",
    ):
        assert series in text, series


def test_cli_train_runs_and_steps(ray_start_regular, tmp_path, capsys):
    def loop(config):
        for i in range(3):
            time.sleep(0.01)
            train.report({"i": float(i)})

    assert _fit(loop, "obs_cli", tmp_path).error is None
    import argparse

    from ray_tpu.scripts.cli import cmd_train

    base = dict(num_cpus=None, num_tpus=None, json=False, rank=None, limit=20)
    cmd_train(argparse.Namespace(train_cmd="runs", run=None, **base))
    out = capsys.readouterr().out
    assert "obs_cli" in out
    cmd_train(argparse.Namespace(train_cmd="steps", run="obs_cli", **base))
    out = capsys.readouterr().out
    assert "step waterfall" in out and "rank 0" in out
    cmd_train(argparse.Namespace(train_cmd="stalls", run="obs_cli", **base))
    out = capsys.readouterr().out
    assert "where did the step go" in out
