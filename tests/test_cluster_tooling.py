"""Autoscaler, job submission, CLI, dashboard, workflow tests.

Parity: ``python/ray/tests/test_autoscaler*.py`` (MockProvider pattern),
dashboard/job module tests, workflow tests (SURVEY.md §4).
"""

import json
import time
import urllib.request

import pytest

import ray_tpu


# -- autoscaler -------------------------------------------------------------


def test_autoscaler_scales_up_for_demand(ray_start_regular):
    from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig, FakeNodeProvider, NodeType

    @ray_tpu.remote(resources={"elastic": 1})
    def needs_elastic():
        return "ran"

    refs = [needs_elastic.remote() for _ in range(3)]
    time.sleep(0.3)  # let tasks reach the pending queue

    provider = FakeNodeProvider()
    autoscaler = Autoscaler(
        AutoscalerConfig(
            node_types=[NodeType("elastic.node", {"CPU": 1, "elastic": 2}, max_workers=4)],
            idle_timeout_s=9999,
        ),
        provider,
    )
    report = autoscaler.update()
    assert report["launched"] >= 1
    # the pending tasks now run on the launched nodes
    assert ray_tpu.get(refs, timeout=120) == ["ran"] * 3


def test_autoscaler_respects_min_and_max(ray_start_regular):
    from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig, FakeNodeProvider, NodeType

    provider = FakeNodeProvider()
    autoscaler = Autoscaler(
        AutoscalerConfig(
            node_types=[NodeType("minny", {"CPU": 1}, min_workers=2, max_workers=3)],
            idle_timeout_s=9999,
        ),
        provider,
    )
    autoscaler.update()
    assert len(provider.non_terminated_nodes()) == 2  # min_workers honored
    autoscaler.update()
    assert len(provider.non_terminated_nodes()) == 2  # idempotent


def test_autoscaler_terminates_idle(ray_start_regular):
    from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig, FakeNodeProvider, NodeType

    provider = FakeNodeProvider()
    autoscaler = Autoscaler(
        AutoscalerConfig(
            node_types=[NodeType("tmp", {"CPU": 1}, min_workers=0, max_workers=2)],
            idle_timeout_s=0.0,
        ),
        provider,
    )
    provider.create_node("tmp", {"CPU": 1})
    autoscaler.update()  # records idle
    report = autoscaler.update()
    assert report["terminated"] >= 1 or len(provider.non_terminated_nodes()) == 0


# -- job submission ---------------------------------------------------------


def test_job_submit_and_logs(ray_start_regular):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint="echo hello-from-job && echo done")
    status = client.wait_until_finished(job_id, timeout=60)
    assert status == JobStatus.SUCCEEDED
    assert "hello-from-job" in client.get_job_logs(job_id)
    jobs = client.list_jobs()
    assert any(j["job_id"] == job_id for j in jobs)


def test_job_failure_status(ray_start_regular):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint="exit 3")
    assert client.wait_until_finished(job_id, timeout=60) == JobStatus.FAILED


def test_job_stop(ray_start_regular):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint="sleep 60")
    assert client.get_job_status(job_id) == JobStatus.RUNNING
    client.stop_job(job_id)
    status = client.wait_until_finished(job_id, timeout=60)
    assert status in (JobStatus.FAILED, JobStatus.STOPPED)


# -- dashboard --------------------------------------------------------------


def test_dashboard_endpoints(ray_start_regular):
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get(f.remote())
    port = start_dashboard(port=0)
    try:
        status = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/cluster_status", timeout=30
            ).read()
        )
        assert status["total"]["CPU"] == 4.0
        tasks = json.loads(
            urllib.request.urlopen(f"http://127.0.0.1:{port}/api/tasks", timeout=30).read()
        )
        assert any(t["name"] == "f" for t in tasks)
        html = urllib.request.urlopen(f"http://127.0.0.1:{port}/", timeout=30).read()
        assert b"ray_tpu" in html
        metrics = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30
        ).read()
        assert metrics is not None
    finally:
        stop_dashboard()


# -- CLI --------------------------------------------------------------------


def test_cli_status_and_summary(ray_start_regular, capsys):
    from ray_tpu.scripts.cli import main

    main(["status"])
    out = capsys.readouterr().out
    assert "cluster resources" in out
    main(["summary"])


# -- workflow ---------------------------------------------------------------


def test_workflow_run_and_idempotent_steps(ray_start_regular, tmp_path):
    from ray_tpu import workflow
    from ray_tpu.dag import InputNode

    calls_file = tmp_path / "calls.txt"

    @ray_tpu.remote
    def expensive(x):
        with open(calls_file, "a") as fh:
            fh.write("x")
        return x * 2

    @ray_tpu.remote
    def final(a, b):
        return a + b

    with InputNode() as inp:
        dag = final.bind(expensive.bind(inp), 100)

    out = workflow.run(dag, workflow_id="wf1", storage=str(tmp_path), args=(21,))
    assert out == 142
    assert workflow.get_status("wf1", storage=str(tmp_path)) == "SUCCESSFUL"
    assert workflow.get_output("wf1", storage=str(tmp_path)) == 142

    # resume: completed steps are NOT re-executed
    out2 = workflow.resume("wf1", storage=str(tmp_path))
    assert out2 == 142
    assert calls_file.read_text() == "x"  # expensive ran exactly once


def test_workflow_resume_after_failure(ray_start_regular, tmp_path):
    from ray_tpu import workflow
    from ray_tpu.dag import InputNode

    marker = tmp_path / "fail_once"

    @ray_tpu.remote
    def step_a(x):
        return x + 1

    @ray_tpu.remote
    def flaky(x):
        import os

        if not os.path.exists(marker):
            open(marker, "w").close()
            raise RuntimeError("transient")
        return x * 10

    with InputNode() as inp:
        dag = flaky.bind(step_a.bind(inp))

    with pytest.raises(RuntimeError):
        workflow.run(dag, workflow_id="wf2", storage=str(tmp_path), args=(4,))
    assert workflow.get_status("wf2", storage=str(tmp_path)) == "FAILED"
    assert workflow.resume("wf2", storage=str(tmp_path)) == 50


def test_autoscaler_launches_real_daemons(ray_start_regular):
    """Scale-up launches REAL node-daemon processes in response to pending
    demand; scale-down terminates idle ones (parity: the reference tests the
    autoscaler against fake_multi_node's real raylet processes)."""
    import time

    from ray_tpu.autoscaler import (
        Autoscaler,
        AutoscalerConfig,
        LocalDaemonNodeProvider,
        NodeType,
    )

    provider = LocalDaemonNodeProvider()
    config = AutoscalerConfig(
        node_types=[NodeType("cpu2", {"CPU": 2.0, "grow": 2.0}, min_workers=0, max_workers=2)],
        idle_timeout_s=2.0,
    )
    scaler = Autoscaler(config, provider)
    try:
        assert scaler.update()["launched"] == 0  # no demand yet

        # infeasible demand: tasks needing a custom resource nothing has
        @ray_tpu.remote(resources={"grow": 1.0})
        def job():
            return 1

        refs = [job.remote() for _ in range(2)]
        time.sleep(0.5)
        result = scaler.update()
        assert result["launched"] >= 1  # a real daemon was spawned
        assert ray_tpu.get(refs, timeout=60) == [1, 1]  # demand now satisfied
        alive = [n for n in ray_tpu.nodes() if n["alive"]]
        assert len(alive) >= 2

        # idle: terminated after the timeout
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if scaler.update()["terminated"] >= 1:
                break
            time.sleep(0.5)
        else:
            raise AssertionError("idle daemon never terminated")
    finally:
        for n in provider.non_terminated_nodes():
            provider.terminate_node(n["node_id"])
