"""Autoscaler, job submission, CLI, dashboard, workflow tests.

Parity: ``python/ray/tests/test_autoscaler*.py`` (MockProvider pattern),
dashboard/job module tests, workflow tests (SURVEY.md §4).
"""

import json
import time
import urllib.request

import pytest

import ray_tpu


# -- autoscaler -------------------------------------------------------------


def test_autoscaler_scales_up_for_demand(ray_start_regular):
    from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig, FakeNodeProvider, NodeType

    @ray_tpu.remote(resources={"elastic": 1})
    def needs_elastic():
        return "ran"

    refs = [needs_elastic.remote() for _ in range(3)]
    time.sleep(0.3)  # let tasks reach the pending queue

    provider = FakeNodeProvider()
    autoscaler = Autoscaler(
        AutoscalerConfig(
            node_types=[NodeType("elastic.node", {"CPU": 1, "elastic": 2}, max_workers=4)],
            idle_timeout_s=9999,
        ),
        provider,
    )
    report = autoscaler.update()
    assert report["launched"] >= 1
    # the pending tasks now run on the launched nodes
    assert ray_tpu.get(refs, timeout=120) == ["ran"] * 3


def test_autoscaler_respects_min_and_max(ray_start_regular):
    from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig, FakeNodeProvider, NodeType

    provider = FakeNodeProvider()
    autoscaler = Autoscaler(
        AutoscalerConfig(
            node_types=[NodeType("minny", {"CPU": 1}, min_workers=2, max_workers=3)],
            idle_timeout_s=9999,
        ),
        provider,
    )
    autoscaler.update()
    assert len(provider.non_terminated_nodes()) == 2  # min_workers honored
    autoscaler.update()
    assert len(provider.non_terminated_nodes()) == 2  # idempotent


def test_autoscaler_terminates_idle(ray_start_regular):
    from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig, FakeNodeProvider, NodeType

    provider = FakeNodeProvider()
    autoscaler = Autoscaler(
        AutoscalerConfig(
            node_types=[NodeType("tmp", {"CPU": 1}, min_workers=0, max_workers=2)],
            idle_timeout_s=0.0,
        ),
        provider,
    )
    provider.create_node("tmp", {"CPU": 1})
    autoscaler.update()  # records idle
    report = autoscaler.update()
    assert report["terminated"] >= 1 or len(provider.non_terminated_nodes()) == 0


# -- job submission ---------------------------------------------------------


def test_job_submit_and_logs(ray_start_regular):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint="echo hello-from-job && echo done")
    status = client.wait_until_finished(job_id, timeout=60)
    assert status == JobStatus.SUCCEEDED
    assert "hello-from-job" in client.get_job_logs(job_id)
    jobs = client.list_jobs()
    assert any(j["job_id"] == job_id for j in jobs)


def test_job_failure_status(ray_start_regular):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint="exit 3")
    assert client.wait_until_finished(job_id, timeout=60) == JobStatus.FAILED


def test_job_stop(ray_start_regular):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint="sleep 60")
    assert client.get_job_status(job_id) == JobStatus.RUNNING
    client.stop_job(job_id)
    status = client.wait_until_finished(job_id, timeout=60)
    assert status in (JobStatus.FAILED, JobStatus.STOPPED)


# -- dashboard --------------------------------------------------------------


def test_dashboard_endpoints(ray_start_regular):
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get(f.remote())
    port = start_dashboard(port=0)
    try:
        status = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/cluster_status", timeout=30
            ).read()
        )
        assert status["total"]["CPU"] == 4.0
        tasks = json.loads(
            urllib.request.urlopen(f"http://127.0.0.1:{port}/api/tasks", timeout=30).read()
        )
        assert any(t["name"] == "f" for t in tasks)
        html = urllib.request.urlopen(f"http://127.0.0.1:{port}/", timeout=30).read()
        assert b"ray_tpu" in html
        # the single-page UI with its tab renderers
        assert b"placement_groups" in html and b"RENDER" in html
        overview = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/overview", timeout=30
        ).read()
        assert b"Resources" in overview
        stacks = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/stacks", timeout=30
            ).read()
        )
        assert "driver" in stacks and "thread" in stacks["driver"]
        metrics = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30
        ).read()
        assert metrics is not None
    finally:
        stop_dashboard()


# -- CLI --------------------------------------------------------------------


def test_cli_status_and_summary(ray_start_regular, capsys):
    from ray_tpu.scripts.cli import main

    main(["status"])
    out = capsys.readouterr().out
    assert "cluster resources" in out
    main(["summary"])


# -- workflow ---------------------------------------------------------------


def test_workflow_run_and_idempotent_steps(ray_start_regular, tmp_path):
    from ray_tpu import workflow
    from ray_tpu.dag import InputNode

    calls_file = tmp_path / "calls.txt"

    @ray_tpu.remote
    def expensive(x):
        with open(calls_file, "a") as fh:
            fh.write("x")
        return x * 2

    @ray_tpu.remote
    def final(a, b):
        return a + b

    with InputNode() as inp:
        dag = final.bind(expensive.bind(inp), 100)

    out = workflow.run(dag, workflow_id="wf1", storage=str(tmp_path), args=(21,))
    assert out == 142
    assert workflow.get_status("wf1", storage=str(tmp_path)) == "SUCCESSFUL"
    assert workflow.get_output("wf1", storage=str(tmp_path)) == 142

    # resume: completed steps are NOT re-executed
    out2 = workflow.resume("wf1", storage=str(tmp_path))
    assert out2 == 142
    assert calls_file.read_text() == "x"  # expensive ran exactly once


def test_workflow_resume_after_failure(ray_start_regular, tmp_path):
    from ray_tpu import workflow
    from ray_tpu.dag import InputNode

    marker = tmp_path / "fail_once"

    @ray_tpu.remote
    def step_a(x):
        return x + 1

    @ray_tpu.remote
    def flaky(x):
        import os

        if not os.path.exists(marker):
            open(marker, "w").close()
            raise RuntimeError("transient")
        return x * 10

    with InputNode() as inp:
        dag = flaky.bind(step_a.bind(inp))

    with pytest.raises(RuntimeError):
        workflow.run(dag, workflow_id="wf2", storage=str(tmp_path), args=(4,))
    assert workflow.get_status("wf2", storage=str(tmp_path)) == "FAILED"
    assert workflow.resume("wf2", storage=str(tmp_path)) == 50


def test_autoscaler_launches_real_daemons(ray_start_regular):
    """Scale-up launches REAL node-daemon processes in response to pending
    demand; scale-down terminates idle ones (parity: the reference tests the
    autoscaler against fake_multi_node's real raylet processes)."""
    import time

    from ray_tpu.autoscaler import (
        Autoscaler,
        AutoscalerConfig,
        LocalDaemonNodeProvider,
        NodeType,
    )

    provider = LocalDaemonNodeProvider()
    config = AutoscalerConfig(
        node_types=[NodeType("cpu2", {"CPU": 2.0, "grow": 2.0}, min_workers=0, max_workers=2)],
        idle_timeout_s=2.0,
    )
    scaler = Autoscaler(config, provider)
    try:
        assert scaler.update()["launched"] == 0  # no demand yet

        # infeasible demand: tasks needing a custom resource nothing has
        @ray_tpu.remote(resources={"grow": 1.0})
        def job():
            return 1

        refs = [job.remote() for _ in range(2)]
        time.sleep(0.5)
        result = scaler.update()
        assert result["launched"] >= 1  # a real daemon was spawned
        assert ray_tpu.get(refs, timeout=60) == [1, 1]  # demand now satisfied
        alive = [n for n in ray_tpu.nodes() if n["alive"]]
        assert len(alive) >= 2

        # idle: terminated after the timeout
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if scaler.update()["terminated"] >= 1:
                break
            time.sleep(0.5)
        else:
            raise AssertionError("idle daemon never terminated")
    finally:
        for n in provider.non_terminated_nodes():
            provider.terminate_node(n["node_id"])


def test_usage_stats_recording(tmp_path, monkeypatch):
    """Parity: usage_lib tag recording + opt-out (SURVEY §2.2)."""
    from ray_tpu._private import usage

    usage.reset_for_test()
    usage.record_extra_usage_tag("test_tag", "42")
    usage.record_library_usage("data")
    report = usage.get_usage_report()
    assert report["extra_usage_tags"]["test_tag"] == "42"
    assert "data" in report["libraries_used"]
    path = usage.write_usage_report(str(tmp_path))
    import json

    assert json.load(open(path))["extra_usage_tags"]["test_tag"] == "42"

    monkeypatch.setenv("RAY_TPU_USAGE_STATS_ENABLED", "0")
    usage.reset_for_test()
    usage.record_extra_usage_tag("nope", "1")
    assert usage.get_usage_report()["extra_usage_tags"] == {}


def test_profile_spans_in_timeline(ray_start_regular):
    """Parity: ray._private.profiling.profile -> chrome trace 'X' events."""
    import time

    import ray_tpu
    from ray_tpu._private.profiling import profile

    @ray_tpu.remote
    def work():
        with profile("inner_phase", extra_data={"k": "v"}):
            time.sleep(0.02)
        return 1

    assert ray_tpu.get(work.remote(), timeout=60) == 1
    with profile("driver_phase"):
        time.sleep(0.01)
    time.sleep(0.5)  # let the pipe-carried span land in the scheduler
    events = ray_tpu.timeline()
    spans = [e for e in events if e.get("ph") == "X"]
    names = {e["name"] for e in spans}
    assert "inner_phase" in names and "driver_phase" in names
    inner = next(e for e in spans if e["name"] == "inner_phase")
    assert inner["dur"] >= 15_000  # >= 15 ms in chrome-trace microseconds
    assert inner["args"]["k"] == "v"


def test_trace_context_propagation(ray_start_regular):
    """Parity: tracing_helper inject/extract across nested tasks."""
    import ray_tpu
    from ray_tpu.util import tracing

    tracing.enable_tracing()
    try:
        @ray_tpu.remote
        def child():
            ctx = tracing.get_current_context()
            return ctx.to_dict()

        @ray_tpu.remote
        def parent():
            ctx = tracing.get_current_context()
            inner = ray_tpu.get(child.remote(), timeout=60)
            return ctx.to_dict(), inner

        root = tracing.start_span()
        outer, inner = ray_tpu.get(parent.remote(), timeout=60)
        # one trace across all three processes; parent links chain
        assert outer["trace_id"] == root.trace_id == inner["trace_id"]
        assert outer["parent_id"] == root.span_id
        assert inner["parent_id"] == outer["span_id"]
    finally:
        tracing.reset_tracing()  # back to config-driven (default-on) tracing
        tracing.deactivate()


def test_dashboard_jax_profiler(ray_start_regular, tmp_path):
    import glob
    import json
    import urllib.request

    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    port = start_dashboard(port=0)
    try:
        logdir = str(tmp_path / "trace")
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/profiler/start?logdir={logdir}"
        ) as r:
            assert json.load(r)["status"] == "tracing"
        import jax
        import jax.numpy as jnp

        jax.jit(lambda x: x * 2)(jnp.ones(8)).block_until_ready()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/profiler/stop"
        ) as r:
            assert json.load(r)["status"] == "stopped"
        assert glob.glob(logdir + "/**/*.xplane.pb", recursive=True)
    finally:
        stop_dashboard()


def test_workflow_wait_for_event(ray_start_regular, tmp_path):
    """Event steps: the workflow blocks on a published event, consumes it
    exactly once (resume does not re-wait), parity: wait_for_event +
    http_event_provider roles."""
    import threading
    import time as _time

    import ray_tpu
    from ray_tpu import workflow

    @ray_tpu.remote
    def process(payload):
        return f"approved:{payload}"

    dag = process.bind(
        workflow.wait_for_event(workflow.KVEventListener, "approval", 60.0)
    )

    def approve_later():
        _time.sleep(1.0)
        workflow.post_event("approval", {"by": "alice"})

    t = threading.Thread(target=approve_later)
    t.start()
    out = workflow.run(dag, workflow_id="wf_event", storage=str(tmp_path))
    t.join()
    assert out == "approved:{'by': 'alice'}"

    # resume replays from the checkpointed event payload — no new event needed
    out2 = workflow.resume("wf_event", storage=str(tmp_path))
    assert out2 == out

    # the KV mailbox drains on consume: a brand-new listener on the same key
    # must NOT see the already-consumed event from the earlier run
    import pytest as _pytest

    listener = workflow.KVEventListener()
    with _pytest.raises(TimeoutError):
        listener.poll_for_event("approval", timeout_s=1.0)


def test_workflow_timer_listener(ray_start_regular, tmp_path):
    import time as _time

    import ray_tpu
    from ray_tpu import workflow

    @ray_tpu.remote
    def after(ts):
        return "fired"

    fire_at = _time.time() + 0.5
    dag = after.bind(workflow.wait_for_event(workflow.TimerListener, fire_at))
    t0 = _time.time()
    assert workflow.run(dag, storage=str(tmp_path)) == "fired"
    assert _time.time() - t0 >= 0.4


def test_tpu_vm_provider_reconciles_with_cloud(ray_start_regular):
    """TPU-VM provider state discipline (parity: the reference's GCP
    provider reconciling against the cloud): ``non_terminated_nodes``
    consults ``gcloud list``; forgotten (billable!) slices are re-adopted
    by cluster label, cloud-deleted slices are dropped, and the table
    survives a provider rebuild via the cluster KV (which rides the GCS
    snapshot)."""
    import json

    from ray_tpu.autoscaler.node_provider import TPUVMNodeProvider

    cloud = {}  # name -> entry, the mocked fleet

    class MockedProvider(TPUVMNodeProvider):
        def _run_gcloud(self, *args):
            if args[0] == "create":
                name = args[1]
                accel = next(
                    a.split("=", 1)[1] for a in args if a.startswith("--accelerator-type=")
                )
                cloud[name] = {
                    "name": f"projects/p/locations/z/nodes/{name}",
                    "acceleratorType": accel,
                    "state": "READY",
                    "labels": {"ray-tpu-cluster": self.cluster_name},
                }
                return "{}"
            if args[0] == "delete":
                cloud.pop(args[1], None)
                return "{}"
            if args[0] == "list":
                return json.dumps(list(cloud.values()))
            raise AssertionError(f"unexpected gcloud verb {args}")

    p = MockedProvider("proj", "zone", cluster_name="c1", list_cache_s=0.0)
    n1 = p.create_node("v5litepod-16", {"TPU": 16.0})
    n2 = p.create_node("v5litepod-16", {"TPU": 16.0})
    assert {n["node_id"] for n in p.non_terminated_nodes()} == {n1, n2}

    # cloud-side deletion (preemption) is noticed
    cloud.pop(n2)
    assert {n["node_id"] for n in p.non_terminated_nodes()} == {n1}

    # a slice of ANOTHER cluster is never adopted
    cloud["foreign"] = {
        "name": "projects/p/locations/z/nodes/foreign",
        "acceleratorType": "v5litepod-8",
        "state": "READY",
        "labels": {"ray-tpu-cluster": "other"},
    }
    assert {n["node_id"] for n in p.non_terminated_nodes()} == {n1}

    # head restart: a FRESH provider with empty memory re-adopts n1 from the
    # KV mirror immediately, and from the cloud listing either way
    p2 = MockedProvider("proj", "zone", cluster_name="c1", list_cache_s=0.0)
    assert {n["node_id"] for n in p2.non_terminated_nodes()} == {n1}

    # ...even with the KV wiped (worst case), the cloud listing re-adopts
    from ray_tpu._private.worker import get_runtime

    get_runtime().rpc("kv_del", MockedProvider._KV_NS, MockedProvider._KV_KEY)
    p3 = MockedProvider("proj", "zone", cluster_name="c1", list_cache_s=0.0)
    nodes3 = p3.non_terminated_nodes()
    assert {n["node_id"] for n in nodes3} == {n1}
    assert any(n.get("adopted") for n in nodes3)
