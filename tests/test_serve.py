"""Serve tests. Parity: ``python/ray/serve/tests`` patterns (SURVEY.md §4)."""

import json
import os
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_cluster():
    rt = ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield rt
    serve.shutdown()
    ray_tpu.shutdown()


def test_function_deployment(serve_cluster):
    @serve.deployment
    def echo(payload=None):
        return {"echo": payload}

    handle = serve.run(echo.bind(), name="echo_app")
    assert handle.remote({"x": 1}).result(timeout_s=60) == {"echo": {"x": 1}}


def test_class_deployment_and_methods(serve_cluster):
    @serve.deployment
    class Counter:
        def __init__(self, start):
            self.v = start

        def __call__(self, k=1):
            self.v += k
            return self.v

        def value(self):
            return self.v

    handle = serve.run(Counter.bind(10), name="counter_app")
    assert handle.remote(5).result(timeout_s=60) == 15
    assert handle.value.remote().result(timeout_s=60) == 15


def test_multiple_replicas_spread_load(serve_cluster):
    import os

    @serve.deployment(num_replicas=2)
    class WhoAmI:
        def __call__(self):
            return os.getpid()

    handle = serve.run(WhoAmI.bind(), name="pids")
    pids = {handle.remote().result(timeout_s=60) for _ in range(20)}
    assert len(pids) == 2


def test_model_composition(serve_cluster):
    @serve.deployment
    class Preprocess:
        def __call__(self, x):
            return x * 2

    @serve.deployment
    class Model:
        def __init__(self, pre):
            self.pre = pre

        def __call__(self, x):
            y = self.pre.remote(x).result(timeout_s=60)
            return y + 1

    handle = serve.run(Model.bind(Preprocess.bind()), name="composed")
    assert handle.remote(10).result(timeout_s=60) == 21


def test_replica_death_reconciled(serve_cluster):
    @serve.deployment(num_replicas=1)
    class Fragile:
        def __call__(self):
            return "alive"

        def die(self):
            import os

            os._exit(1)

    handle = serve.run(Fragile.bind(), name="fragile")
    assert handle.remote().result(timeout_s=60) == "alive"
    try:
        handle.die.remote().result(timeout_s=30)
    except Exception:
        pass
    # reconciler restarts the replica within a few seconds
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            fresh = serve.get_app_handle("fragile")
            assert fresh.remote().result(timeout_s=30) == "alive"
            break
        except Exception:
            time.sleep(0.5)
    else:
        pytest.fail("replica was not restarted")


def test_http_proxy(serve_cluster):
    @serve.deployment
    def double(payload=None):
        return {"doubled": payload["x"] * 2}

    serve.run(double.bind(), name="http_app", route_prefix="/double")
    req = urllib.request.Request(
        "http://127.0.0.1:8700/double",
        data=json.dumps({"x": 21}).encode(),
        headers={"Content-Type": "application/json"},
    )
    body = json.loads(urllib.request.urlopen(req, timeout=60).read())
    assert body["result"]["doubled"] == 42
    # 404 on unknown route
    try:
        urllib.request.urlopen("http://127.0.0.1:8700/nope", timeout=30)
        pytest.fail("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_batching(serve_cluster):
    @serve.deployment(max_ongoing_requests=8)
    class Batched:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
        def __call__(self, items):
            self.batch_sizes.append(len(items))
            return [i * 10 for i in items]

        def sizes(self):
            return self.batch_sizes

    handle = serve.run(Batched.bind(), name="batched")
    responses = [handle.remote(i) for i in range(8)]
    out = sorted(r.result(timeout_s=60) for r in responses)
    assert out == [0, 10, 20, 30, 40, 50, 60, 70]
    sizes = handle.sizes.remote().result(timeout_s=60)
    assert max(sizes) > 1  # batching actually coalesced requests


def test_status_and_delete(serve_cluster):
    @serve.deployment(num_replicas=2)
    def f(p=None):
        return 1

    serve.run(f.bind(), name="stat_app")
    st = serve.status()
    assert st["stat_app"]["f"]["num_replicas"] == 2
    serve.delete("stat_app")
    with pytest.raises(ValueError):
        serve.get_app_handle("stat_app")


def test_streaming_response(serve_cluster):
    @serve.deployment
    class Streamer:
        def __call__(self, n):
            for i in range(n):
                yield i * 3

    handle = serve.run(Streamer.bind(), name="stream_app")
    out = list(handle.options(stream=True).remote(4))
    assert out == [0, 3, 6, 9]


def test_multiplexed_models(serve_cluster):
    @serve.deployment(num_replicas=2)
    class MultiModel:
        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id: str):
            return f"model:{model_id}"

        def __call__(self, x):
            mid = serve.get_multiplexed_model_id()
            model = self.get_model(mid)
            return f"{model}+{x}"

    handle = serve.run(MultiModel.bind(), name="mux_app")
    r1 = handle.options(multiplexed_model_id="a").remote(1).result(timeout_s=60)
    r2 = handle.options(multiplexed_model_id="b").remote(2).result(timeout_s=60)
    assert r1 == "model:a+1"
    assert r2 == "model:b+2"


def test_autoscaling_up_and_down(serve_cluster):
    @serve.deployment(
        autoscaling_config={
            "min_replicas": 1,
            "max_replicas": 3,
            "target_ongoing_requests": 1,
        },
        max_ongoing_requests=1,
    )
    class Slow:
        def __call__(self):
            time.sleep(1.2)
            return "ok"

    handle = serve.run(Slow.bind(), name="auto_app")

    def replica_count():
        st = serve.status()
        return st["auto_app"]["Slow"]["num_replicas"]

    assert replica_count() == 1
    # sustained burst: keep >= 6 requests in flight so the controller's
    # metric poll sees depth > target and scales out
    responses = [handle.remote() for _ in range(12)]
    deadline = time.monotonic() + 40
    grew = False
    while time.monotonic() < deadline:
        if replica_count() >= 2:
            grew = True
            break
        responses = [r for r in responses if True]  # keep refs alive
        time.sleep(0.5)
    for r in responses:
        r.result(timeout_s=120)
    assert grew, "deployment never scaled out"
    # idle: scales back down to min
    deadline = time.monotonic() + 60
    shrank = False
    while time.monotonic() < deadline:
        if replica_count() == 1:
            shrank = True
            break
        time.sleep(0.5)
    assert shrank, "deployment never scaled back in"


def test_lm_generation_deployment(serve_cluster):
    """KV-cache generation behind a Serve deployment (examples/serve_lm.py)."""
    import os
    import sys

    examples_dir = os.path.join(os.path.dirname(__file__), "..", "examples")
    sys.path.insert(0, examples_dir)
    try:
        from serve_lm import LMServer
    finally:
        sys.path.pop(0)

    handle = serve.run(LMServer.bind(), name="lm_gen")
    out = handle.generate.remote([1, 2, 3, 4], max_new_tokens=4).result(timeout_s=120)
    assert len(out["tokens"]) == 4
    assert all(isinstance(t, int) for t in out["tokens"])


def _repo_root_on_path():
    import os
    import sys

    root = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    if root not in sys.path:
        sys.path.insert(0, root)


def test_build_and_deploy_config(serve_cluster, tmp_path):
    """serve.build -> yaml -> deploy_config_file round trip with overrides."""
    _repo_root_on_path()
    from examples.serve_config_app import app

    config = serve.build(
        app, name="cfgapp", import_path="examples.serve_config_app:app"
    )
    names = [d["name"] for d in config["applications"][0]["deployments"]]
    assert set(names) == {"Doubler", "Ingress"}
    # override replica count through the config
    for d in config["applications"][0]["deployments"]:
        if d["name"] == "Doubler":
            d["num_replicas"] = 2
    path = str(tmp_path / "serve.yaml")
    serve.dump_config(config, path)

    handles = serve.deploy_config_file(path)
    assert serve.status()["cfgapp"]["Doubler"]["num_replicas"] == 2
    assert handles["cfgapp"].remote(20).result(timeout_s=60) == 41
    serve.delete("cfgapp")


def test_serve_cli_status_and_build(serve_cluster, tmp_path, capsys):
    _repo_root_on_path()
    from examples.serve_config_app import app as _app  # noqa: F401
    from ray_tpu.scripts.cli import main

    out = str(tmp_path / "out.yaml")
    main(["serve", "build", "examples.serve_config_app:app",
          "--name", "cliapp", "-o", out])
    import yaml

    config = yaml.safe_load(open(out))
    assert config["applications"][0]["import_path"] == "examples.serve_config_app:app"

    main(["serve", "run", out])
    main(["serve", "status"])
    captured = capsys.readouterr().out
    assert "cliapp" in captured
    from ray_tpu.serve import get_app_handle

    assert get_app_handle("cliapp").remote(1).result(timeout_s=60) == 3
    serve.delete("cliapp")


def test_grpc_ingress(serve_cluster):
    """Parity: the gRPC proxy ingress (proxy.py gRPCProxy)."""

    @serve.deployment
    class Echo:
        def __call__(self, payload):
            return {"echo": payload, "n": len(payload)}

    serve.run(Echo.bind(), name="grpcapp")
    port = serve.start_grpc_proxy()
    out = serve.grpc_predict(f"127.0.0.1:{port}", "hello", application="grpcapp")
    assert out == {"echo": "hello", "n": 5}

    # errors surface as exceptions, not hung calls
    @serve.deployment
    class Boom:
        def __call__(self, payload):
            raise ValueError("nope")

    serve.run(Boom.bind(), name="grpcboom")
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="nope"):
        serve.grpc_predict(f"127.0.0.1:{port}", "x", application="grpcboom")
    # unauthenticated raw pickle must be rejected before unpickling
    # (pickle.loads executes code; parity with the HMAC auth on every other
    # socket in the framework)
    import pickle

    import grpc

    from ray_tpu.serve._grpc_proxy import SERVICE_METHOD

    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    try:
        fn = channel.unary_unary(SERVICE_METHOD)
        with pytest.raises(grpc.RpcError) as excinfo:
            fn(pickle.dumps("unauthenticated"), timeout=30)
        assert excinfo.value.code() == grpc.StatusCode.UNAUTHENTICATED
    finally:
        channel.close()
    serve.delete("grpcapp")
    serve.delete("grpcboom")


def test_user_config_reconfigure(serve_cluster):
    """user_config: delivered at startup, and a redeploy changing ONLY
    user_config reconfigures live replicas without restarting them."""
    import os

    @serve.deployment(user_config={"threshold": 1})
    class Configurable:
        def __init__(self):
            self.threshold = None
            self.pid = os.getpid()

        def reconfigure(self, config):
            self.threshold = config["threshold"]

        def __call__(self):
            return {"threshold": self.threshold, "pid": self.pid}

    handle = serve.run(Configurable.bind(), name="ucfg")
    first = handle.remote().result(timeout_s=60)
    assert first["threshold"] == 1

    # redeploy with ONLY user_config changed -> same replica pid, new config
    handle2 = serve.run(
        Configurable.options(user_config={"threshold": 7}).bind(), name="ucfg"
    )
    second = handle2.remote().result(timeout_s=60)
    assert second["threshold"] == 7
    assert second["pid"] == first["pid"], "replica was restarted (heavyweight)"

    # changing num_replicas too -> full restart (new pid allowed)
    handle3 = serve.run(
        Configurable.options(user_config={"threshold": 9}, num_replicas=1,
                             max_ongoing_requests=4).bind(),
        name="ucfg",
    )
    third = handle3.remote().result(timeout_s=60)
    assert third["threshold"] == 9
    serve.delete("ucfg")


def test_rest_deploy_endpoint(serve_cluster, tmp_path):
    """PUT /api/serve/applications deploys a declarative config (parity: the
    reference's serve REST API)."""
    import urllib.request

    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    _repo_root_on_path()
    port = start_dashboard(port=0)
    try:
        config = {
            "applications": [
                {
                    "name": "restapp",
                    "import_path": "examples.serve_config_app:app",
                    "deployments": [{"name": "Doubler", "num_replicas": 1}],
                }
            ]
        }
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/serve/applications",
            data=json.dumps(config).encode(),
            method="PUT",
            headers={"Content-Type": "application/json"},
        )
        body = json.loads(urllib.request.urlopen(req, timeout=120).read())
        assert body["deployed"] == ["restapp"]
        from ray_tpu.serve import get_app_handle

        assert get_app_handle("restapp").remote(3).result(timeout_s=60) == 7
        # GET /api/serve reflects it
        st = json.loads(
            urllib.request.urlopen(f"http://127.0.0.1:{port}/api/serve", timeout=30).read()
        )
        assert "restapp" in st
        serve.delete("restapp")
    finally:
        stop_dashboard()


def test_per_node_proxies():
    """One HTTP ingress per alive node (parity: ProxyState's proxy-per-node),
    each serving the registered routes via its own handles."""
    import json as _json
    import urllib.request

    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        cluster.add_node(num_cpus=1)
        cluster.wait_for_nodes()

        @serve.deployment
        class Echo:
            def __call__(self, x):
                return {"echo": x}

        serve.run(Echo.bind(), name="pnp", route_prefix="/pnp")
        proxies = serve.start_node_proxies()
        assert len(proxies) >= 2  # head + daemon node
        for nid, (host, port) in proxies.items():
            req = urllib.request.Request(
                f"http://{host}:{port}/pnp",
                data=_json.dumps(5).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=60) as resp:
                body = _json.loads(resp.read())
            assert body["result"] == {"echo": 5}, (nid, body)
        serve.delete("pnp")
    finally:
        cluster.shutdown()


def test_probed_queue_depths_reach_handles(serve_cluster):
    """The controller's reconcile loop probes replica queue depths and
    handles fold them into pow-2 scoring (pow_2_scheduler.py:49 parity)."""
    import time as _time

    @serve.deployment(num_replicas=2)
    class Slowish:
        def __call__(self, x):
            return x

    serve.run(Slowish.bind(), name="probed")
    handle = serve.get_app_handle("probed")
    assert handle.remote(1).result(timeout_s=60) == 1
    # wait past a reconcile pass, then force a refresh and check depths came
    deadline = _time.monotonic() + 30
    while _time.monotonic() < deadline:
        handle._last_refresh = 0.0
        handle.remote(2).result(timeout_s=60)
        if handle._probed_depths:
            break
        _time.sleep(0.5)
    assert handle._probed_depths, "controller depths never reached the handle"
    serve.delete("probed")


# ---- ASGI-grade ingress (parity: serve.ingress + uvicorn data plane) ----


def _http_roundtrip(host, port, method, path, body=b"", headers=None, n=1):
    """Raw HTTP/1.1 client exercising keep-alive: n requests on ONE socket.
    Returns list of (status, headers_dict, body_bytes)."""
    import socket

    out = []
    s = socket.create_connection((host, port), timeout=30)
    try:
        for _ in range(n):
            hdrs = {"Host": host, "Content-Length": str(len(body))}
            hdrs.update(headers or {})
            req = f"{method} {path} HTTP/1.1\r\n" + "".join(
                f"{k}: {v}\r\n" for k, v in hdrs.items()
            ) + "\r\n"
            s.sendall(req.encode() + body)
            f = s.makefile("rb")
            status = int(f.readline().split()[1])
            resp_headers = {}
            while True:
                line = f.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode().partition(":")
                resp_headers[k.strip().lower()] = v.strip()
            if resp_headers.get("transfer-encoding") == "chunked":
                chunks = []
                while True:
                    size = int(f.readline().strip(), 16)
                    if size == 0:
                        f.readline()
                        break
                    chunks.append(f.read(size))
                    f.readline()
                payload = b"".join(chunks)
            else:
                payload = f.read(int(resp_headers.get("content-length", 0)))
            out.append((status, resp_headers, payload))
    finally:
        s.close()
    return out


def test_http_raw_bytes_body(serve_cluster):
    """Non-JSON request/response: raw bytes pass through untouched."""
    import ray_tpu.serve as serve
    from ray_tpu.serve._proxy import ensure_proxy
    from ray_tpu.serve.api import _get_or_create_controller

    @serve.deployment
    def echo_upper(data):
        assert isinstance(data, bytes)
        return data.upper()  # bytes in, bytes out

    serve.run(echo_upper.bind(), name="rawapp", route_prefix="/raw")
    proxy = ensure_proxy(_get_or_create_controller(), "rawapp", "/raw")
    host, port = ray_tpu.get(proxy.address.remote(), timeout=60)
    [(status, hdrs, body)] = _http_roundtrip(
        host, port, "POST", "/raw", b"\x00binary\xffdata",
        headers={"Content-Type": "application/octet-stream"},
    )
    assert status == 200
    assert hdrs["content-type"] == "application/octet-stream"
    assert body == b"\x00BINARY\xffDATA"
    serve.delete("rawapp")


def test_http_asgi_app_and_streaming(serve_cluster):
    """An ASGI app mounted with serve.ingress: routed responses, raw bodies,
    and a chunked streaming endpoint delivering incrementally."""
    import ray_tpu.serve as serve
    from ray_tpu.serve._proxy import ensure_proxy
    from ray_tpu.serve.api import _get_or_create_controller

    async def app(scope, receive, send):
        assert scope["type"] == "http"
        path = scope["path"]
        if path.endswith("/stream"):
            await send({"type": "http.response.start", "status": 200,
                        "headers": [(b"content-type", b"text/plain")]})
            for i in range(5):
                await send({"type": "http.response.body",
                            "body": f"chunk-{i};".encode(), "more_body": True})
            await send({"type": "http.response.body", "body": b"done",
                        "more_body": False})
            return
        msg = await receive()
        body = msg.get("body", b"")
        await send({"type": "http.response.start", "status": 201,
                    "headers": [(b"content-type", b"application/x-custom"),
                                (b"x-echo-len", str(len(body)).encode())]})
        await send({"type": "http.response.body",
                    "body": b"asgi:" + body[::-1], "more_body": False})

    @serve.deployment
    @serve.ingress(app)
    class AsgiD:
        pass

    serve.run(AsgiD.bind(), name="asgiapp", route_prefix="/asgi")
    proxy = ensure_proxy(_get_or_create_controller(), "asgiapp", "/asgi")
    host, port = ray_tpu.get(proxy.address.remote(), timeout=60)

    [(status, hdrs, body)] = _http_roundtrip(
        host, port, "POST", "/asgi/echo", b"hello",
        headers={"Content-Type": "application/octet-stream"},
    )
    assert status == 201
    assert hdrs["content-type"] == "application/x-custom"
    assert hdrs["x-echo-len"] == "5"
    assert body == b"asgi:olleh"

    [(status, hdrs, body)] = _http_roundtrip(host, port, "GET", "/asgi/stream")
    assert status == 200
    assert hdrs.get("transfer-encoding") == "chunked"
    assert body == b"chunk-0;chunk-1;chunk-2;chunk-3;chunk-4;done"
    serve.delete("asgiapp")


def test_http_keep_alive_reuse(serve_cluster):
    """Several requests on one client socket (persistent connections)."""
    import ray_tpu.serve as serve
    from ray_tpu.serve._proxy import ensure_proxy
    from ray_tpu.serve.api import _get_or_create_controller

    @serve.deployment
    def count(payload=None):
        return {"n": (payload or {}).get("n", 0) * 2}

    serve.run(count.bind(), name="kaapp", route_prefix="/ka")
    proxy = ensure_proxy(_get_or_create_controller(), "kaapp", "/ka")
    host, port = ray_tpu.get(proxy.address.remote(), timeout=60)
    results = []
    import json as _json

    for i in range(4):
        results.append(
            _http_roundtrip(
                host, port, "POST", "/ka",
                _json.dumps({"n": i}).encode(),
                headers={"Content-Type": "application/json"},
            )[0]
        )
    # all four rode persistent connections and returned doubled values
    assert [
        _json.loads(b)["result"]["n"] for (_, _, b) in results
    ] == [0, 2, 4, 6]
    # and 4 requests over a SINGLE socket work end-to-end
    multi = _http_roundtrip(
        host, port, "POST", "/ka", _json.dumps({"n": 5}).encode(),
        headers={"Content-Type": "application/json"}, n=4,
    )
    assert all(_json.loads(b)["result"]["n"] == 10 for (_, _, b) in multi)
    serve.delete("kaapp")


# ---- websockets (parity: ASGI websocket scopes through the proxy) ----


def test_websocket_echo_roundtrip(serve_cluster):
    """Full RFC 6455 session: upgrade, subprotocol negotiation, text and
    binary echo, ping/pong, app-initiated close with code+reason."""
    import ray_tpu.serve as serve
    from ray_tpu.serve._proxy import ensure_proxy
    from ray_tpu.serve._ws import WSClient
    from ray_tpu.serve.api import _get_or_create_controller

    async def app(scope, receive, send):
        assert scope["type"] == "websocket"
        msg = await receive()
        assert msg["type"] == "websocket.connect"
        sub = scope["subprotocols"][0] if scope["subprotocols"] else None
        await send({"type": "websocket.accept", "subprotocol": sub})
        while True:
            msg = await receive()
            if msg["type"] == "websocket.disconnect":
                return
            if msg.get("text") is not None:
                if msg["text"] == "quit":
                    await send({"type": "websocket.close", "code": 4001,
                                "reason": "bye"})
                    return
                await send({"type": "websocket.send",
                            "text": msg["text"].upper()})
            else:
                await send({"type": "websocket.send",
                            "bytes": msg["bytes"][::-1]})

    @serve.deployment
    @serve.ingress(app)
    class WsD:
        pass

    serve.run(WsD.bind(), name="wsapp", route_prefix="/ws")
    proxy = ensure_proxy(_get_or_create_controller(), "wsapp", "/ws")
    host, port = ray_tpu.get(proxy.address.remote(), timeout=60)

    c = WSClient(host, port, "/ws/chat", subprotocols=("chat", "alt"))
    try:
        assert c.subprotocol == "chat"
        c.send_text("hello")
        assert c.recv() == "HELLO"
        c.send_bytes(b"\x01\x02\x03")
        assert c.recv() == b"\x03\x02\x01"
        c.ping(b"p")
        assert c.recv() == ("pong", b"p")
        c.send_text("quit")
        assert c.recv() == ("close", 4001, "bye")
    finally:
        c.close()
    serve.delete("wsapp")


def test_websocket_reject_and_client_disconnect(serve_cluster):
    """App close before accept surfaces as HTTP 403; an accepted session
    whose client vanishes delivers websocket.disconnect to the app."""
    import ray_tpu.serve as serve
    from ray_tpu.serve._proxy import ensure_proxy
    from ray_tpu.serve._ws import WSClient
    from ray_tpu.serve.api import _get_or_create_controller

    async def app(scope, receive, send):
        await receive()  # websocket.connect
        if scope["path"].endswith("/reject"):
            await send({"type": "websocket.close", "code": 1008})
            return
        await send({"type": "websocket.accept"})
        while True:
            msg = await receive()
            if msg["type"] == "websocket.disconnect":
                # visible side channel: write a marker the test can poll
                with open(scope["extensions"]["marker_path"], "w") as f:
                    f.write(str(msg.get("code")))
                return
            await send({"type": "websocket.send", "text": "ok"})

    import tempfile

    marker = tempfile.NamedTemporaryFile(delete=False)
    marker.close()
    marker_path = marker.name

    async def wrapped(scope, receive, send):
        ext = dict(scope.get("extensions") or {})
        ext["marker_path"] = marker_path
        scope = dict(scope)
        scope["extensions"] = ext
        await app(scope, receive, send)

    @serve.deployment
    @serve.ingress(wrapped)
    class WsR:
        pass

    serve.run(WsR.bind(), name="wsrapp", route_prefix="/wsr")
    proxy = ensure_proxy(_get_or_create_controller(), "wsrapp", "/wsr")
    host, port = ray_tpu.get(proxy.address.remote(), timeout=60)

    try:
        WSClient(host, port, "/wsr/reject")
        assert False, "upgrade should have been refused"
    except ConnectionError as e:
        assert "403" in str(e)

    c = WSClient(host, port, "/wsr/chat")
    c.send_text("x")
    assert c.recv() == "ok"
    c._sock.close()  # vanish without a close frame
    deadline = time.time() + 30
    code = ""
    while time.time() < deadline:
        with open(marker_path) as f:
            code = f.read().strip()
        if code:
            break
        time.sleep(0.2)
    assert code == "1006", f"app never saw the disconnect (marker={code!r})"
    os.unlink(marker_path)
    serve.delete("wsrapp")


def test_websocket_fragmented_message_with_interleaved_ping(serve_cluster):
    """RFC 6455 §5.4: control frames may be injected inside a fragmented
    message; the relay must buffer the partial message across them."""
    import ray_tpu.serve as serve
    from ray_tpu.serve import _ws as ws
    from ray_tpu.serve._proxy import ensure_proxy
    from ray_tpu.serve.api import _get_or_create_controller

    async def app(scope, receive, send):
        await receive()
        await send({"type": "websocket.accept"})
        while True:
            m = await receive()
            if m["type"] == "websocket.disconnect":
                return
            await send({"type": "websocket.send", "text": m["text"].upper()})

    @serve.deployment
    @serve.ingress(app)
    class WsF:
        pass

    serve.run(WsF.bind(), name="wsfrag", route_prefix="/wsfrag")
    proxy = ensure_proxy(_get_or_create_controller(), "wsfrag", "/wsfrag")
    host, port = ray_tpu.get(proxy.address.remote(), timeout=60)
    c = ws.WSClient(host, port, "/wsfrag")
    try:
        c._sock.sendall(ws.encode_frame(ws.OP_TEXT, b"hel", fin=False, mask=True))
        c._sock.sendall(ws.encode_frame(ws.OP_PING, b"p", mask=True))
        c._sock.sendall(ws.encode_frame(ws.OP_CONT, b"lo", fin=True, mask=True))
        msgs = [c.recv(), c.recv()]
        assert ("pong", b"p") in msgs and "HELLO" in msgs, msgs
    finally:
        c.close()
    serve.delete("wsfrag")


def test_websocket_replica_death_closes_session(serve_cluster):
    """Killing the replica mid-session must surface as an abnormal close
    (1011 close frame, or a dropped connection) to the client, not a hang."""
    from ray_tpu.serve._proxy import ensure_proxy
    from ray_tpu.serve._ws import WSClient
    from ray_tpu.serve.api import _get_or_create_controller, get_app_handle

    async def app(scope, receive, send):
        await receive()
        await send({"type": "websocket.accept"})
        while True:
            m = await receive()
            if m["type"] == "websocket.disconnect":
                return
            await send({"type": "websocket.send", "text": "pong"})

    @serve.deployment
    @serve.ingress(app)
    class WsK:
        pass

    serve.run(WsK.bind(), name="wskill", route_prefix="/wskill")
    proxy = ensure_proxy(_get_or_create_controller(), "wskill", "/wskill")
    host, port = ray_tpu.get(proxy.address.remote(), timeout=60)
    c = WSClient(host, port, "/wskill")
    try:
        c.send_text("hi")
        assert c.recv() == "pong"
        # kill every replica out from under the session
        handle = get_app_handle("wskill")
        replicas = list(handle._replicas)
        assert replicas, "no replicas to kill"
        for r in replicas:
            ray_tpu.kill(r)
        try:
            got = c.recv()
        except ConnectionError:
            got = ("close", 1006, "connection dropped")  # also abnormal
        assert isinstance(got, tuple) and got[0] == "close", got
        assert got[1] in (1006, 1011), got
    finally:
        c.close()
        serve.delete("wskill")
