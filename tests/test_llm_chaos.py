"""LLM serving chaos: SIGKILL replicas under live decode streams
(`make chaos-serve`, seeded via CHAOS_SEED).

Acceptance (ISSUE 20): a replica killed mid-decode fails its streams
either before the first token or with a typed ``ReplicaDiedError`` —
never an untyped error, never a hang; after the fleet heals, greedy
decode still matches the pre-chaos reference (weights re-seed
deterministically). Graceful drain (redeploy under load) finishes every
in-flight decode with zero failures of any kind.
"""

import threading
import time

import pytest

pytest.importorskip("jax")

import ray_tpu  # noqa: E402
from ray_tpu import serve  # noqa: E402
from ray_tpu.serve.llm import llm_deployment, TINY_MODEL  # noqa: E402

# pytest's prepend import mode puts tests/ on sys.path (no tests/__init__),
# so the chaos harness package imports as a top-level name
from chaos import ChaosMonkey, chaos_seed, serve_replica_pids  # noqa: E402

pytestmark = pytest.mark.slow

ENGINE = dict(
    block_size=4,
    num_blocks=256,
    max_batch=4,
    max_blocks_per_seq=32,
    max_waiting=8,
)
PROMPT = [7, 3, 11, 23, 5, 42]
N_TOKENS = 32


def _deploy_llm(name, **opts):
    opts.setdefault("num_replicas", 2)
    opts.setdefault("health_check_period_s", 0.5)
    opts.setdefault("max_ongoing_requests", 12)
    app = llm_deployment(TINY_MODEL, ENGINE, deployment_name="llm", **opts)
    serve.run(app, name=name)
    return serve.get_app_handle(name).options(stream=True)


def test_llm_replica_kill_mid_decode_fails_typed_or_pre_token():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    try:
        h = _deploy_llm("llmchaos")
        # pre-chaos greedy reference: every healed replica re-seeds the
        # same weights, so this must still hold token-for-token after kills
        reference = list(h.generate.remote(PROMPT, max_new_tokens=N_TOKENS))
        assert len(reference) == N_TOKENS

        counts = {"ok": 0, "typed": 0, "shed": 0, "other": 0}
        post_token_untyped = []
        other_errors = []
        lock = threading.Lock()
        stop = threading.Event()

        def client(i):
            hc = serve.get_app_handle("llmchaos").options(stream=True)
            while not stop.is_set():
                got = []
                try:
                    for tok in hc.generate.remote(
                        PROMPT, max_new_tokens=N_TOKENS
                    ):
                        got.append(tok)
                    with lock:
                        counts["ok"] += 1
                    assert got == reference
                except serve.ReplicaDiedError:
                    # typed death is acceptable at ANY point in the stream
                    with lock:
                        counts["typed"] += 1
                except serve.DeploymentOverloadedError:
                    # sheds may only happen before the first token
                    with lock:
                        counts["shed"] += 1
                        if got:
                            post_token_untyped.append(
                                f"shed after {len(got)} tokens"
                            )
                except Exception as e:  # noqa: BLE001
                    with lock:
                        counts["other"] += 1
                        if len(other_errors) < 5:
                            other_errors.append(repr(e))
                        if got:
                            post_token_untyped.append(
                                f"{type(e).__name__} after {len(got)} tokens"
                            )

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()

        monkey = ChaosMonkey(
            seed=chaos_seed(),
            interval_s=(1.0, 2.0),
            victims=serve_replica_pids,
            max_kills=2,
            arm_when=lambda: counts["ok"] >= 3,
        )
        monkey.start()
        deadline = time.monotonic() + 25.0
        while time.monotonic() < deadline and len(monkey.kills) < 2:
            time.sleep(0.2)
        kills = monkey.stop()
        # let the fleet heal while load continues
        heal_deadline = time.monotonic() + 60.0
        while time.monotonic() < heal_deadline:
            try:
                row = serve.status().get("llmchaos", {}).get("llm", {})
                if row.get("num_replicas") == 2 and row.get("health") == "HEALTHY":
                    break
            except Exception:
                pass
            time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(timeout=60)

        assert kills >= 1, f"chaos monkey landed no kills (seed={chaos_seed()})"
        assert counts["other"] == 0, (
            f"untyped failures under decode chaos (seed={chaos_seed()}): "
            f"{counts} {other_errors}"
        )
        assert not post_token_untyped, (
            f"streams failed non-typed AFTER first token "
            f"(seed={chaos_seed()}): {post_token_untyped}"
        )
        assert counts["ok"] > 3, f"not enough successful decodes: {counts}"

        # the healed fleet still decodes the reference greedily
        healed = list(h.generate.remote(PROMPT, max_new_tokens=N_TOKENS))
        assert healed == reference
        print(
            f"llm chaos (seed={chaos_seed()}): kills={monkey.kills} "
            f"counts={counts}"
        )
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


def test_llm_drain_finishes_inflight_decodes():
    """Graceful redeploy while decode streams are open: the drain keeps
    old replicas alive until their in-flight decodes finish — every
    stream completes, token-for-token, zero failures."""
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    try:
        h = _deploy_llm(
            "llmdrain", num_replicas=2, graceful_shutdown_timeout_s=30.0
        )
        reference = list(h.generate.remote(PROMPT, max_new_tokens=48))
        results = []
        errors = []

        def consumer(i):
            hc = serve.get_app_handle("llmdrain").options(stream=True)
            try:
                results.append(
                    list(hc.generate.remote(PROMPT, max_new_tokens=48))
                )
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=consumer, args=(i,)) for i in range(3)
        ]
        for t in threads:
            t.start()
        time.sleep(0.3)  # let the decodes start
        # full replica restart mid-decode: drain must let them finish
        serve.run(
            llm_deployment(
                TINY_MODEL,
                ENGINE,
                deployment_name="llm",
                num_replicas=2,
                health_check_period_s=0.5,
                max_ongoing_requests=12,
                graceful_shutdown_timeout_s=30.0,
            ),
            name="llmdrain",
        )
        for t in threads:
            t.join(timeout=90)
        assert not errors, f"drain tore open decode streams: {errors[:3]}"
        assert len(results) == 3
        for out in results:
            assert out == reference
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
