"""Pipeline parallelism, MoE expert parallelism, MNIST models (CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.parallel.mesh import MeshConfig, create_mesh


def test_pipeline_matches_sequential(cpu_mesh_devices):
    from ray_tpu.parallel.pipeline import make_pipeline_fn

    mesh = create_mesh(MeshConfig(pipeline=4, data=2))
    P_stages, M, mb, d = 4, 8, 4, 16

    def stage_fn(params, x):
        return jnp.tanh(x @ params["w"] + params["b"])

    key = jax.random.PRNGKey(0)
    stacked = {
        "w": jax.random.normal(key, (P_stages, d, d)) * 0.5,
        "b": jnp.zeros((P_stages, d)),
    }
    microbatches = jax.random.normal(jax.random.fold_in(key, 1), (M, mb, d))
    ref = microbatches
    for s in range(P_stages):
        ref = jnp.tanh(ref @ stacked["w"][s] + stacked["b"][s])

    pipe = make_pipeline_fn(stage_fn, mesh)
    sharded = jax.device_put(stacked, NamedSharding(mesh, P("pipeline")))
    out = jax.jit(pipe)(sharded, microbatches)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_moe_expert_parallel_matches_single(cpu_mesh_devices):
    from ray_tpu.models.moe import (
        MoEConfig,
        init_moe_params,
        moe_mlp,
        moe_param_logical_axes,
    )
    from ray_tpu.parallel.sharding import DEFAULT_LM_RULES, infer_param_sharding

    cfg = MoEConfig(d_model=32, d_ff=64, num_experts=8, top_k=2, capacity_factor=2.0)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 32))
    y_ref, aux_ref = moe_mlp(params, x, cfg)

    mesh = create_mesh(MeshConfig(expert=8))
    shardings = infer_param_sharding(moe_param_logical_axes(), DEFAULT_LM_RULES, mesh)
    params_sh = jax.tree.map(lambda p, s: jax.device_put(p, s), params, shardings)
    y_ep, aux_ep = jax.jit(lambda p, xx: moe_mlp(p, xx, cfg))(params_sh, x)
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_ref), atol=1e-5)
    assert abs(float(aux_ep) - float(aux_ref)) < 1e-5


def test_moe_capacity_drops_overflow():
    from ray_tpu.models.moe import MoEConfig, init_moe_params, moe_mlp

    # capacity far below demand: outputs are partially zero but finite
    cfg = MoEConfig(d_model=16, d_ff=32, num_experts=2, top_k=1, capacity_factor=0.25)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 32, 16))
    y, aux = moe_mlp(params, x, cfg)
    assert np.all(np.isfinite(np.asarray(y)))


def test_mnist_mlp_learns_synthetic(cpu_mesh_devices):
    import optax

    from ray_tpu.models.mnist import accuracy, apply_mlp, cross_entropy_loss, init_mlp
    from ray_tpu.parallel.sharding import batch_sharding

    mesh = create_mesh(MeshConfig(data=8))
    rng = np.random.default_rng(0)
    # synthetic separable data: class = argmax of 10 fixed projections
    w_true = rng.normal(size=(784, 10))
    xs = rng.normal(size=(512, 784)).astype(np.float32)
    ys = np.argmax(xs @ w_true, axis=1).astype(np.int32)

    params = init_mlp(jax.random.PRNGKey(0), hidden=(64,))
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, x, y):
        def loss(p):
            return cross_entropy_loss(apply_mlp(p, x), y)

        lval, grads = jax.value_and_grad(loss)(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, lval

    sh = batch_sharding(mesh)
    xd = jax.device_put(xs, sh)
    yd = jax.device_put(ys, sh)
    first = None
    for i in range(30):
        params, opt_state, lval = step(params, opt_state, xd, yd)
        # sync every step: queuing many async 8-way collectives starves the
        # XLA-CPU rendezvous on a 1-core host and aborts the process
        lval = float(lval)
        first = first if first is not None else lval
    assert lval < first * 0.6
    acc = float(accuracy(apply_mlp(params, xd), yd))
    assert acc > 0.5


def test_mnist_cnn_shapes():
    from ray_tpu.models.mnist import apply_cnn, init_cnn

    params = init_cnn(jax.random.PRNGKey(0))
    x = jnp.ones((2, 28, 28, 1))
    logits = apply_cnn(params, x)
    assert logits.shape == (2, 10)


def test_kv_cache_generation_matches_full_forward(cpu_mesh_devices):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models.generation import generate
    from ray_tpu.models.transformer import TransformerConfig, forward, init_params

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=64, remat=False, dtype=jnp.float32,
    )
    params = init_params(jax.random.PRNGKey(1), cfg)
    prompt = np.array([[5, 9, 3, 7, 2], [1, 2, 3, 4, 6]], dtype=np.int32)
    toks = np.asarray(generate(params, prompt, cfg, max_new_tokens=5))
    cur = prompt
    for step in range(5):
        logits = forward(params, jnp.asarray(cur), cfg)
        nxt = np.argmax(np.asarray(logits[:, -1, :], dtype=np.float32), axis=-1)
        assert (toks[:, step] == nxt).all(), f"divergence at step {step}"
        cur = np.concatenate([cur, nxt[:, None].astype(np.int32)], axis=1)


def test_vit_forward_and_grads():
    """ViT family: forward shapes, fp32 logits, grads flow."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import vit

    cfg = vit.VIT_TINY_TEST
    params = vit.init_params(jax.random.PRNGKey(0), cfg)
    images = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    logits = jax.jit(lambda p, x: vit.forward(cfg, p, x))(params, images)
    assert logits.shape == (4, 10) and logits.dtype == jnp.float32

    labels = jnp.array([0, 1, 2, 3])
    (loss, acc), grads = jax.value_and_grad(
        lambda p: vit.loss_fn(cfg, p, images, labels), has_aux=True
    )(params)
    assert jnp.isfinite(loss)
    gnorm = jax.tree_util.tree_reduce(
        lambda a, g: a + jnp.sum(jnp.abs(g.astype(jnp.float32))), grads, 0.0
    )
    assert gnorm > 0


def test_vit_patchify_roundtrip():
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import vit

    cfg = vit.ViTConfig(image_size=4, patch_size=2, num_channels=1,
                        d_model=8, n_layers=1, n_heads=1, d_ff=8)
    img = jnp.arange(16, dtype=jnp.float32).reshape(1, 4, 4, 1)
    patches = vit.patchify(cfg, img)
    assert patches.shape == (1, 4, 4)
    # first patch = top-left 2x2 block in row-major order
    np.testing.assert_array_equal(np.asarray(patches[0, 0]), [0, 1, 4, 5])


def test_vit_sharded_train_step_on_mesh():
    """ViT under DP+TP GSPMD sharding on the virtual mesh."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.models import vit
    from ray_tpu.parallel.mesh import create_mesh

    from ray_tpu.parallel.sharding import (
        DEFAULT_LM_RULES,
        batch_sharding,
        shard_params,
    )

    if len(jax.devices()) < 4:
        import pytest

        pytest.skip("needs the virtual multi-device mesh")
    mesh = create_mesh(data=-1, tensor=2, drop_trivial_axes=True)
    cfg = vit.VIT_TINY_TEST
    params = vit.init_params(jax.random.PRNGKey(0), cfg)
    params = shard_params(
        params, vit.param_logical_axes(cfg), DEFAULT_LM_RULES, mesh
    )
    opt = optax.sgd(1e-2)
    opt_state = opt.init(params)

    batch_shard = batch_sharding(mesh)

    @jax.jit
    def step(params, opt_state, images, labels):
        (loss, acc), grads = jax.value_and_grad(
            lambda p: vit.loss_fn(cfg, p, images, labels), has_aux=True
        )(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    images = jax.device_put(
        np.random.RandomState(0).randn(8, 32, 32, 3).astype(np.float32),
        batch_shard,
    )
    labels = jax.device_put(np.arange(8) % 10, batch_shard)
    losses = []
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, images, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0]  # it optimizes
