"""Paged KV cache host-side bookkeeping: free-list allocator invariants
(reuse-after-free, all-or-nothing, typed exhaustion, zero external
fragmentation by construction) and per-sequence block tables."""

import random

import pytest

from ray_tpu.serve.llm.kv_cache import (
    NULL_BLOCK,
    BlockAllocator,
    BlockTable,
    KVCacheExhausted,
)


def test_allocator_basic_and_null_block_reserved():
    a = BlockAllocator(num_blocks=8, block_size=4)
    assert a.num_usable == 7
    assert a.num_free == 7
    got = a.allocate(7)
    assert len(set(got)) == 7
    assert NULL_BLOCK not in got, "null block must never be handed out"
    assert a.num_free == 0


def test_allocator_reuse_after_free():
    a = BlockAllocator(num_blocks=6, block_size=2)
    first = a.allocate(5)
    a.free(first)
    second = a.allocate(5)
    # same physical blocks cycle back (LIFO free list)
    assert set(second) == set(first)
    assert a.num_free == 0


def test_allocator_exhaustion_is_typed_and_atomic():
    a = BlockAllocator(num_blocks=5, block_size=4)
    a.allocate(2)
    free_before = a.num_free
    with pytest.raises(KVCacheExhausted) as ei:
        a.allocate(3)
    # all-or-nothing: the failed request must not leak partial blocks
    assert a.num_free == free_before
    assert ei.value.requested == 3
    assert ei.value.free == 2


def test_allocator_double_free_rejected():
    a = BlockAllocator(num_blocks=4, block_size=1)
    blocks = a.allocate(2)
    a.free(blocks)
    with pytest.raises(ValueError):
        a.free([blocks[0]])
    with pytest.raises(ValueError):
        a.free([NULL_BLOCK])


def test_allocator_no_external_fragmentation():
    """Fixed-size blocks: after ANY alloc/free history, a request for
    n <= num_free always succeeds — there is no fragmentation to hit."""
    rng = random.Random(7)
    a = BlockAllocator(num_blocks=33, block_size=8)
    held = []
    for _ in range(500):
        if held and rng.random() < 0.5:
            a.free(held.pop(rng.randrange(len(held))))
        else:
            want = rng.randint(1, 4)
            if want <= a.num_free:
                held.append(a.allocate(want))
        # the invariant under test, every step
        n = a.num_free
        if n:
            probe = a.allocate(n)
            assert len(probe) == n
            a.free(probe)
    # full reclamation
    for h in held:
        a.free(h)
    assert a.num_free == a.num_usable


def test_block_table_growth_and_release():
    a = BlockAllocator(num_blocks=16, block_size=4)
    t = BlockTable(a)
    t.reserve(6)  # 6 tokens -> 2 blocks
    t.length = 6
    assert len(t.blocks) == 2
    assert a.num_free == a.num_usable - 2
    # appending within the block: no new allocation until the boundary
    t.append_token()  # 7
    t.append_token()  # 8
    assert len(t.blocks) == 2
    t.append_token()  # 9 crosses into block 3
    assert len(t.blocks) == 3
    padded = t.as_list(5)
    assert padded[:3] == t.blocks and padded[3:] == [NULL_BLOCK, NULL_BLOCK]
    with pytest.raises(ValueError):
        t.as_list(2)
    t.release()
    assert a.num_free == a.num_usable
    t.release()  # idempotent


def test_blocks_for_tokens_math():
    a = BlockAllocator(num_blocks=4, block_size=8)
    assert a.blocks_for_tokens(0) == 0
    assert a.blocks_for_tokens(1) == 1
    assert a.blocks_for_tokens(8) == 1
    assert a.blocks_for_tokens(9) == 2
    assert a.blocks_for_tokens(17) == 3
