"""Per-node reporter agent: heartbeat-pushed stats, fanned-out stack dumps,
py-spy-style sampling.

Parity: ``python/ray/dashboard/modules/reporter/reporter_agent.py:314``.
"""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    c.add_node(num_cpus=1)
    c.wait_for_nodes()
    yield c
    c.shutdown()


def _scheduler():
    from ray_tpu._private.worker import get_runtime

    return get_runtime().node.scheduler


def test_heartbeat_carries_node_stats(cluster):
    sch = _scheduler()
    deadline = time.monotonic() + 30
    stats = {}
    while time.monotonic() < deadline:
        stats = sch.node_stats()
        daemon_rows = [v for v in stats.values() if v.get("node") != "head"]
        if daemon_rows and "cpu_percent" in daemon_rows[0]:
            break
        time.sleep(0.3)
    daemon_rows = [v for v in stats.values() if v.get("node") != "head"]
    assert daemon_rows, stats
    row = daemon_rows[0]
    assert row["mem_total"] > 0
    assert row["rss_bytes"] > 0
    assert "object_store_bytes" in row
    assert row["workers"] >= 0
    assert row["heartbeat_age_s"] is not None and row["heartbeat_age_s"] < 10
    # the head reports its own stats too
    head_rows = [v for v in stats.values() if v.get("node") == "head"]
    assert head_rows and head_rows[0]["mem_total"] > 0
    # and the worker-facing rpc serves the same table (the dashboard's
    # /api/node_stats depends on this op existing)
    from ray_tpu._private.worker import get_runtime

    via_rpc = get_runtime().rpc("node_stats")
    assert via_rpc and any(v.get("mem_total", 0) > 0 for v in via_rpc.values())


def test_stack_dump_includes_workers(cluster):
    @ray_tpu.remote
    def sleeper():
        time.sleep(20)
        return 1

    ref = sleeper.remote()
    time.sleep(2.0)  # let it start on the daemon node
    sch = _scheduler()
    stacks = sch.request_node_stacks(timeout=15)
    assert stacks, "no node stacks returned"
    text = "\n".join(stacks.values())
    assert "==== daemon ====" in text
    assert "worker-" in text, "worker stacks missing from the dump"
    assert "sleeper" in text or "sleep" in text
    ray_tpu.cancel(ref, force=True)


def test_stack_sampling_profile(cluster):
    sch = _scheduler()
    samples = sch.request_node_stack_samples(duration_s=0.6, interval_s=0.02)
    assert samples, "no sampling results"
    for node, counts in samples.items():
        assert counts, f"{node} returned no samples"
        # hottest-first dict of stack -> hit count
        values = list(counts.values())
        assert all(isinstance(v, int) and v >= 1 for v in values)
        assert values == sorted(values, reverse=True)
