"""Dataset tests. Parity: ``python/ray/data/tests`` patterns (SURVEY.md §4)."""

import csv
import time
import json
import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


def test_range_count_take(ray_start_regular):
    ds = rd.range(100)
    assert ds.count() == 100
    assert [r["id"] for r in ds.take(5)] == [0, 1, 2, 3, 4]


def test_map_batches(ray_start_regular):
    ds = rd.range(100).map_batches(lambda b: {"id": b["id"] * 2})
    assert [r["id"] for r in ds.take(3)] == [0, 2, 4]


def test_map_and_filter(ray_start_regular):
    ds = rd.range(20).map(lambda r: {"id": r["id"] + 1}).filter(lambda r: r["id"] % 2 == 0)
    assert ds.count() == 10


def test_flat_map(ray_start_regular):
    ds = rd.from_items([1, 2]).flat_map(lambda r: [{"v": r["item"]}, {"v": r["item"] * 10}])
    assert sorted(r["v"] for r in ds.take_all()) == [1, 2, 10, 20]


def test_iter_batches_exact_sizes(ray_start_regular):
    ds = rd.range(100, num_blocks=7)
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=32)]
    assert sizes == [32, 32, 32, 4]
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=32, drop_last=True)]
    assert sizes == [32, 32, 32]


def test_repartition_and_num_blocks(ray_start_regular):
    ds = rd.range(100).repartition(5)
    assert ds.num_blocks() == 5
    assert ds.count() == 100


def test_split_equal(ray_start_regular):
    shards = rd.range(100).split(4, equal=True)
    assert [s.count() for s in shards] == [25, 25, 25, 25]


def test_streaming_split_feeds_workers(ray_start_regular):
    its = rd.range(64).streaming_split(2, equal=True)

    @ray_tpu.remote
    def consume(it):
        return sum(int(b["id"].sum()) for b in it.iter_batches(batch_size=8))

    totals = ray_tpu.get([consume.remote(it) for it in its], timeout=120)
    assert sum(totals) == sum(range(64))


def test_union_zip_limit(ray_start_regular):
    a = rd.range(10)
    b = rd.range(10).map(lambda r: {"id": r["id"] + 100})
    u = a.union(b)
    assert u.count() == 20
    z = rd.range(5).zip(rd.range(5).map(lambda r: {"other": r["id"] * 2}))
    rows = z.take_all()
    assert rows[3]["id"] == 3 and rows[3]["other"] == 6
    assert rd.range(100).limit(7).count() == 7


def test_random_shuffle_preserves_rows(ray_start_regular):
    ds = rd.range(50).random_shuffle(seed=0)
    vals = sorted(r["id"] for r in ds.take_all())
    assert vals == list(range(50))


def test_from_numpy_and_schema(ray_start_regular):
    ds = rd.from_numpy(np.ones((10, 3), dtype=np.float32), column="x")
    assert ds.schema() == {"x": "float32"}
    assert ds.count() == 10


def test_read_csv_json(ray_start_regular, tmp_path):
    csv_path = tmp_path / "t.csv"
    with open(csv_path, "w", newline="") as fh:
        w = csv.DictWriter(fh, fieldnames=["a", "b"])
        w.writeheader()
        for i in range(5):
            w.writerow({"a": i, "b": i * 2})
    ds = rd.read_csv(str(csv_path))
    assert ds.count() == 5
    assert ds.take(1)[0]["b"] == 0

    json_path = tmp_path / "t.jsonl"
    with open(json_path, "w") as fh:
        for i in range(3):
            fh.write(json.dumps({"v": i}) + "\n")
    assert rd.read_json(str(json_path)).count() == 3


def test_read_parquet(ray_start_regular, tmp_path):
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq

    table = pa.table({"x": list(range(10)), "y": [float(i) for i in range(10)]})
    path = tmp_path / "t.parquet"
    pq.write_table(table, str(path))
    ds = rd.read_parquet(str(path))
    assert ds.count() == 10
    assert ds.map_batches(lambda b: {"x2": b["x"] * 2}).take(2)[1]["x2"] == 2


def test_dataset_feeds_jax_trainer(ray_start_regular, tmp_path):
    from ray_tpu import train
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    ds = rd.range(64)

    def loop(config):
        # PR-14 routes `datasets=` through the instrumented shard API and
        # pops __datasets__ from the user config
        it = train.get_dataset_shard("train")
        total = sum(int(b["id"].sum()) for b in it.iter_batches(batch_size=16))
        train.report({"total": total})

    result = JaxTrainer(
        loop,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path), name="d1"),
        datasets={"train": rd.DataIterator(ds)},
    ).fit()
    assert result.error is None
    assert result.metrics["total"] == sum(range(64))


def test_zip_misaligned_blocks(ray_start_regular):
    a = rd.from_items([{"a": i} for i in range(6)], num_blocks=2)
    b = rd.from_items([{"b": i} for i in range(6)], num_blocks=3)
    rows = a.zip(b).take_all()
    assert len(rows) == 6
    assert all(r["a"] == r["b"] for r in rows)


def test_zip_count_mismatch_raises(ray_start_regular):
    with pytest.raises(ValueError):
        rd.range(5).zip(rd.range(6)).take_all()


def test_range_zero(ray_start_regular):
    assert rd.range(0).count() == 0


def test_distributed_shuffle(ray_start_regular):
    ds = rd.range(100, num_blocks=5).random_shuffle(seed=1)
    vals = [r["id"] for r in ds.take_all()]
    assert sorted(vals) == list(range(100))
    assert vals[:10] != list(range(10))  # actually shuffled


def test_trainer_custom_resource_only_worker(ray_start_regular, tmp_path):
    # resources_per_worker without CPU must not deadlock (regression)
    from ray_tpu import train
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    ray_tpu.get_runtime()  # ensure init
    import ray_tpu._private.worker as w

    def loop():
        train.report({"ok": 1})

    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1, resources_per_worker={"CPU": 1.0}),
        run_config=RunConfig(storage_path=str(tmp_path), name="cpuonly"),
    ).fit()
    assert result.error is None


def test_sort(ray_start_regular):
    import numpy as np

    rng = np.random.default_rng(0)
    vals = rng.permutation(500).astype(np.int64)
    ds = ray_tpu.data.from_numpy({"x": vals}).repartition(5)
    out = ds.sort("x")
    col = out.to_block()["x"]
    assert len(col) == 500
    assert (col == np.arange(500)).all()
    desc = ds.sort("x", descending=True).to_block()["x"]
    assert (desc == np.arange(499, -1, -1)).all()


def test_groupby_aggregate(ray_start_regular):
    import numpy as np

    n = 300
    ds = ray_tpu.data.from_numpy(
        {"k": np.arange(n) % 3, "v": np.arange(n, dtype=np.float64)}
    ).repartition(4)
    out = ds.groupby("k").sum("v").to_block()
    got = dict(zip(out["k"].tolist(), out["sum(v)"].tolist()))
    want = {}
    for i in range(n):
        want[i % 3] = want.get(i % 3, 0.0) + float(i)
    assert got == want

    cnt = ds.groupby("k").count().to_block()
    assert dict(zip(cnt["k"].tolist(), cnt["count"].tolist())) == {0: 100, 1: 100, 2: 100}

    means = ds.groupby("k").mean("v").to_block()
    assert abs(dict(zip(means["k"].tolist(), means["mean(v)"].tolist()))[0] - np.mean(
        [float(i) for i in range(n) if i % 3 == 0]
    )) < 1e-9


def test_global_aggregates(ray_start_regular):
    import numpy as np

    ds = ray_tpu.data.from_numpy({"v": np.arange(100, dtype=np.float64)}).repartition(3)
    assert ds.sum("v") == float(np.sum(np.arange(100)))
    assert ds.min("v") == 0.0
    assert ds.max("v") == 99.0
    assert abs(ds.mean("v") - 49.5) < 1e-9
    assert abs(ds.std("v") - np.std(np.arange(100), ddof=1)) < 1e-9


def test_map_groups(ray_start_regular):
    import numpy as np

    ds = ray_tpu.data.from_numpy({"k": np.arange(60) % 2, "v": np.ones(60)})
    out = ds.groupby("k").map_groups(
        lambda g: {"k": g["k"][:1], "total": np.array([g["v"].sum()])}
    ).to_block()
    assert dict(zip(out["k"].tolist(), out["total"].tolist())) == {0: 30.0, 1: 30.0}


def test_map_batches_actor_pool(ray_start_regular):
    import numpy as np

    class AddBias:
        def __init__(self):
            self.bias = 5.0  # expensive setup, done once per pool actor

        def __call__(self, block):
            return {"x": block["x"] + self.bias}

    ds = ray_tpu.data.from_numpy({"x": np.arange(40, dtype=np.float64)}).repartition(4)
    out = ds.map_batches(AddBias, compute=ray_tpu.data.ActorPoolStrategy(size=2))
    col = np.sort(out.to_block()["x"])
    assert (col == np.arange(40) + 5.0).all()


def test_streaming_window_bounds_inflight(ray_start_regular):
    """A dataset larger than the in-flight window streams through a consumer
    one window at a time (the backpressure contract)."""
    import numpy as np

    from ray_tpu.data.context import DataContext

    DataContext.get_current().max_inflight_blocks = 2
    try:
        nblocks = 12
        ds = ray_tpu.data.from_numpy(
            {"x": np.arange(nblocks * 10, dtype=np.float64)}
        ).repartition(nblocks)
        ds2 = ds.map_batches(lambda b: {"x": b["x"] * 2})
        seen = 0
        from ray_tpu.util import state as state_api

        max_running = 0
        for batch in ds2.iter_batches(batch_size=10):
            seen += len(batch["x"])
            rows = [
                t
                for t in state_api.list_tasks()
                if t["name"] == "_exec_block" and t["state"] in ("RUNNING", "PENDING")
            ]
            max_running = max(max_running, len(rows))
        assert seen == nblocks * 10
        # never more than window + a small dispatch slop in flight
        assert max_running <= 4, max_running
    finally:
        DataContext.get_current().max_inflight_blocks = 4


def test_iter_torch_batches(ray_start_regular):
    import numpy as np
    import torch

    ds = ray_tpu.data.from_numpy({"x": np.arange(20, dtype=np.float32)})
    it = ds.streaming_split(1)[0]
    batches = list(it.iter_torch_batches(batch_size=8))
    assert all(isinstance(b["x"], torch.Tensor) for b in batches)
    total = torch.cat([b["x"] for b in batches])
    assert float(total.sum()) == float(np.arange(20).sum())


def test_event_stats_rpc(ray_start_regular):
    @ray_tpu.remote
    def noop():
        return 1

    ray_tpu.get([noop.remote() for _ in range(5)], timeout=60)
    from ray_tpu._private.worker import get_driver

    stats = get_driver().rpc("event_stats")
    assert stats.get("cmd.submit", {}).get("count", 0) >= 5
    assert any(k.startswith("worker.") for k in stats)


def test_column_ops(ray_start_regular):
    ds = ray_tpu.data.range(10)
    ds = ds.add_column("double", lambda b: b["id"] * 2)
    ds = ds.rename_columns({"id": "orig"})
    rows = ds.select_columns(["double"]).take_all()
    assert [r["double"] for r in rows] == [i * 2 for i in range(10)]
    assert "orig" in ds.schema() and "id" not in ds.schema()
    dropped = ds.drop_columns(["double"])
    assert list(dropped.schema()) == ["orig"]


def test_unique(ray_start_regular):
    ds = ray_tpu.data.from_items([{"v": i % 3} for i in range(12)])
    assert ds.unique("v") == [0, 1, 2]


def test_write_read_roundtrip(ray_start_regular, tmp_path):
    ds = ray_tpu.data.range(20, num_blocks=3)
    files = ds.write_csv(str(tmp_path / "csv"))
    assert len(files) == 3
    back = ray_tpu.data.read_csv(str(tmp_path / "csv"))
    assert sorted(r["id"] for r in back.take_all()) == list(range(20))

    jfiles = ds.write_json(str(tmp_path / "json"))
    backj = ray_tpu.data.read_json(str(tmp_path / "json"))
    assert sorted(r["id"] for r in backj.take_all()) == list(range(20))

    try:
        import pyarrow  # noqa: F401
    except ImportError:
        return
    ds.write_parquet(str(tmp_path / "pq"))
    backp = ray_tpu.data.read_parquet(str(tmp_path / "pq"))
    assert sorted(r["id"] for r in backp.take_all()) == list(range(20))


def test_read_text_binary(ray_start_regular, tmp_path):
    p = tmp_path / "a.txt"
    p.write_text("hello\nworld\n")
    ds = ray_tpu.data.read_text(str(p))
    assert [r["text"] for r in ds.take_all()] == ["hello", "world"]

    b = tmp_path / "blob.bin"
    b.write_bytes(b"\x00\x01\x02")
    bds = ray_tpu.data.read_binary_files(str(b))
    row = bds.take_all()[0]
    assert row["bytes"] == b"\x00\x01\x02" and row["path"].endswith("blob.bin")


def test_to_pandas(ray_start_regular):
    import pandas as pd

    df = ray_tpu.data.range(5).to_pandas()
    assert isinstance(df, pd.DataFrame) and list(df["id"]) == list(range(5))


def test_iter_tf_batches(ray_start_regular):
    import numpy as np

    ds = ray_tpu.data.from_numpy({"x": np.arange(20, dtype=np.float32)})
    it = ds.streaming_split(1)[0]
    batches = list(it.iter_tf_batches(batch_size=8))
    import tensorflow as tf

    assert all(isinstance(b["x"], tf.Tensor) for b in batches)
    total = float(sum(tf.reduce_sum(b["x"]) for b in batches))
    assert total == float(np.arange(20).sum())


def test_random_sample_and_take_batch(ray_start_regular):
    import numpy as np

    ds = ray_tpu.data.range(1000, num_blocks=4)
    sampled = ds.random_sample(0.2, seed=0)
    n = sampled.count()
    assert 100 < n < 320  # ~200 expected
    batch = ds.take_batch(10)
    assert list(np.asarray(batch["id"])) == list(range(10))
    import pytest as _pytest

    with _pytest.raises(ValueError):
        ray_tpu.data.from_items([]).take_batch(5)


def test_streaming_executor_stage_overlap(ray_start_regular, tmp_path):
    """Stage 2 (actor pool) processes block k while stage 1 is still working
    on block k+n — the pipeline runs concurrently, not stage-by-stage
    (parity: the reference's StreamingExecutor, streaming_executor.py:48)."""
    import os
    import time

    from ray_tpu.data.context import ActorPoolStrategy

    marks = str(tmp_path)
    n_blocks = 8
    ds = ray_tpu.data.range(n_blocks * 100, num_blocks=n_blocks)

    def slow_stage1(batch):
        import os as _os
        import time as _time

        _time.sleep(1.0)
        i = int(batch["id"][0]) // 100
        open(_os.path.join(marks, f"s1_{i}"), "w").close()
        return batch

    class Stage2:
        def __call__(self, batch):
            import os as _os

            i = int(batch["id"][0]) // 100
            open(_os.path.join(marks, f"s2_{i}"), "w").close()
            return batch

    out = ds.map_batches(slow_stage1).map_batches(
        Stage2, compute=ActorPoolStrategy(1)
    )
    it = out.iter_batches(batch_size=100)
    first = next(it)
    assert len(first["id"]) == 100
    # stage 2 has already produced block 0...
    assert os.path.exists(os.path.join(marks, "s2_0"))
    # ...while stage 1 has NOT yet finished the tail block (it is still
    # in a later submission wave: window 4 < 8 blocks, 1s per block)
    assert not os.path.exists(os.path.join(marks, f"s1_{n_blocks - 1}")), (
        "stage 1 finished everything before stage 2 produced block 0 — "
        "the pipeline barriered between stages"
    )
    # drain: everything flows through both stages exactly once
    rest = list(it)
    assert sum(len(b["id"]) for b in [first] + rest) == n_blocks * 100
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if all(
            os.path.exists(os.path.join(marks, f"s{s}_{i}"))
            for s in (1, 2)
            for i in range(n_blocks)
        ):
            break
        time.sleep(0.1)
    else:
        raise AssertionError("not all blocks flowed through both stages")


def test_actor_pool_and_rebatch_are_lazy(ray_start_regular):
    """Plan construction must not execute anything: actor-pool map and
    batch_size rebatching are pipeline stages, not plan-time barriers."""
    import time

    from ray_tpu.data.context import ActorPoolStrategy

    ds = ray_tpu.data.range(2000, num_blocks=8)

    def slow(batch):
        import time as _time

        _time.sleep(0.5)
        return batch

    t0 = time.monotonic()
    out = ds.map_batches(slow).map_batches(
        lambda b: b, compute=ActorPoolStrategy(2), batch_size=100
    )
    plan_time = time.monotonic() - t0
    assert plan_time < 0.5, (
        f"plan construction took {plan_time:.2f}s — a stage executed eagerly"
    )
    assert out.count() == 2000


def test_lazy_reads_bounded_submission(ray_start_regular, tmp_path):
    """read_* sources are lazy ReadTasks driven by the executor window; the
    full read->map->consume pipeline still yields every row exactly once."""
    import numpy as np

    df_dir = str(tmp_path / "csvs")
    import os

    os.makedirs(df_dir)
    for i in range(6):
        with open(os.path.join(df_dir, f"f{i}.csv"), "w") as fh:
            fh.write("x\n")
            for v in range(i * 10, (i + 1) * 10):
                fh.write(f"{v}\n")
    ds = ray_tpu.data.read_csv(df_dir)
    from ray_tpu.data.streaming_executor import ReadTask

    # plan holds unsubmitted read tasks
    assert all(isinstance(r, ReadTask) for r in ds._block_refs)
    got = sorted(
        int(v) for b in ds.map_batches(lambda b: b).iter_batches(batch_size=7)
        for v in np.asarray(b["x"])
    )
    assert got == list(range(60))


def test_backpressure_memory_cap_throttles_source(ray_start_regular):
    """OutputMemoryPolicy (parity: StreamingOutputBackpressurePolicy): with
    a byte cap on ready-but-unconsumed output, a slow sink holds the fast
    source to a bounded submission lead instead of letting it sprint ahead."""
    import numpy as np

    from ray_tpu.data import backpressure as bp
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    saved_bytes, saved_blocks = ctx.max_inflight_bytes, ctx.max_inflight_blocks
    ctx.max_inflight_bytes = 512 * 1024  # ~1 block of 64Ki float64 rows
    ctx.max_inflight_blocks = 64  # wide window: the MEMORY policy must bind
    bp.last_execution_stats.clear()
    try:
        ds = ray_tpu.data.range(20, num_blocks=20).map_batches(
            lambda b: {"x": np.ones((len(b["id"]), 64 * 1024))}  # ~512KiB/blk
        )
        seen = 0
        max_lead = 0
        for _ in ds.iter_batches(batch_size=1):
            seen += 1
            time.sleep(0.05)  # the slow sink
            for st in bp.last_execution_stats:
                if st.name.startswith("map"):
                    max_lead = max(max_lead, st.submitted - st.consumed)
        assert seen == 20
        # without the memory policy the 64-block window would let the map
        # stage sprint ~20 blocks ahead; the cap holds the lead to a handful
        # (liveness is proven by seen == 20; the lead may sample as 0 when
        # the cap serializes to one block at a time)
        assert max_lead <= 6, f"map lead {max_lead} not memory-bounded"
    finally:
        ctx.max_inflight_bytes = saved_bytes
        ctx.max_inflight_blocks = saved_blocks


def test_actor_pool_grows_under_backlog(ray_start_regular):
    """ActorPoolStrategy(size, max_size): the pool adds workers when every
    member is backlogged (parity: execution/autoscaler op autoscaling)."""
    import time as _time

    from ray_tpu.data.context import ActorPoolStrategy

    class Slow:
        def __call__(self, batch):
            _time.sleep(0.15)
            return batch

    ds = ray_tpu.data.range(12, num_blocks=12).map_batches(
        Slow, compute=ActorPoolStrategy(size=1, max_size=3)
    )
    assert ds.count() == 12
    from ray_tpu.data.streaming_executor import ActorMapStage

    stages = [s for s in ds._stages if isinstance(s, ActorMapStage)]
    assert stages and stages[0].pool_size() > 1, "pool never grew"


# ---- logical-plan optimizer (parity: _internal/logical/rules/) ----


def test_optimizer_projection_algebra():
    from ray_tpu.data.optimizer import optimize_ops

    # select/select dedups same-set pairs, drop/drop unions
    assert optimize_ops([("select", ["a", "b"]), ("select", ["b", "a"])]) == [
        ("select", ["b", "a"])
    ]
    assert optimize_ops([("drop", ["a"]), ("drop", ["b"])]) == [
        ("drop", ["a", "b"])
    ]
    # a drop disjoint from the selection is a no-op and is eliminated
    assert optimize_ops([("select", ["a", "b"]), ("drop", ["c"])]) == [
        ("select", ["a", "b"])
    ]
    # select of a column the earlier select pruned must NOT merge (the
    # runtime KeyError is user-visible behavior)
    ops = [("select", ["a"]), ("select", ["b"])]
    assert optimize_ops(ops) == ops
    # narrowing select/select must NOT merge either: select(["a","b"])
    # validates "b" against the block even though a later select prunes it
    ops = [("select", ["a", "b"]), ("select", ["a"])]
    assert optimize_ops(ops) == ops
    # nor a drop of a selected column: the select's missing-column check
    # for the dropped column must still run
    ops = [("select", ["a", "b"]), ("drop", ["b"])]
    assert optimize_ops(ops) == ops
    # rename compose
    assert optimize_ops(
        [("rename", {"a": "b"}), ("rename", {"b": "c", "x": "y"})]
    ) == [("rename", {"a": "c", "x": "y"})]
    # select commutes left past rename (pushdown direction)
    out = optimize_ops([("rename", {"a": "b"}), ("select", ["b", "c"])])
    assert out == [("select", ["a", "c"]), ("rename", {"a": "b"})]


def test_optimizer_preserves_missing_column_errors():
    """Regression: select-select / select-drop merges used to swallow the
    missing-column KeyError of a column only the EARLIER select referenced
    (it validates every named column against the block at execution)."""
    from ray_tpu.data.dataset import _apply_ops
    from ray_tpu.data.optimizer import optimize_ops

    block = {"a": [1, 2, 3]}  # no column "b"
    for ops in (
        [("select", ["a", "b"]), ("select", ["a"])],
        [("select", ["a", "b"]), ("drop", ["b"])],
    ):
        with pytest.raises(KeyError):
            _apply_ops(dict(block), ops)
        with pytest.raises(KeyError):
            _apply_ops(dict(block), optimize_ops(ops))


def test_optimizer_pushdown_into_parquet_read(ray_start_regular, tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ray_tpu.data.optimizer import optimize_plan
    from ray_tpu.data.streaming_executor import TaskMapStage

    p = tmp_path / "t.parquet"
    pq.write_table(
        pa.table({"a": [1, 2, 3], "b": [4.0, 5.0, 6.0], "c": ["x", "y", "z"]}),
        p,
    )
    ds = rd.read_parquet(str(p)).select_columns(["a", "b"])
    # the plan rewrite moves the select into the read task
    src, stages = optimize_plan(ds._block_refs, ds._stages)
    assert src[0].columns == ["a", "b"]
    assert not any(
        op for s in stages if isinstance(s, TaskMapStage) for op in s.ops
    )
    # end-to-end result is identical to the unoptimized semantics
    rows = ds.take_all()
    assert rows == [{"a": 1, "b": 4.0}, {"a": 2, "b": 5.0}, {"a": 3, "b": 6.0}]
    # rename then select: commutes into the read too
    ds2 = (
        rd.read_parquet(str(p))
        .rename_columns({"a": "id"})
        .select_columns(["id"])
    )
    src2, _ = optimize_plan(ds2._block_refs, ds2._stages)
    assert src2[0].columns == ["a"]
    assert ds2.take_all() == [{"id": 1}, {"id": 2}, {"id": 3}]


def test_read_parquet_columns_arg(ray_start_regular, tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    p = tmp_path / "t.parquet"
    pq.write_table(pa.table({"a": [1, 2], "b": [3, 4]}), p)
    assert rd.read_parquet(str(p), columns=["b"]).take_all() == [
        {"b": 3},
        {"b": 4},
    ]


def test_arrow_roundtrip(ray_start_regular):
    import pyarrow as pa

    t = pa.table({"x": list(range(10)), "s": [f"r{i}" for i in range(10)]})
    ds = rd.from_arrow(t, num_blocks=3)
    assert ds.count() == 10
    back = ds.to_arrow()
    assert back.column("x").to_pylist() == list(range(10))
    assert back.column("s").to_pylist() == [f"r{i}" for i in range(10)]
    # per-block refs form
    tables = ray_tpu.get(ds.to_arrow_refs(), timeout=120)
    assert sum(tb.num_rows for tb in tables) == 10


def test_declarative_column_ops_execute(ray_start_regular):
    ds = rd.from_items([{"a": i, "b": i * 2, "c": i * 3} for i in range(6)])
    out = (
        ds.drop_columns(["c"])
        .rename_columns({"b": "bb"})
        .select_columns(["bb"])
        .take_all()
    )
    assert out == [{"bb": i * 2} for i in range(6)]
    with pytest.raises((KeyError, ray_tpu.exceptions.TaskError, Exception)):
        ds.select_columns(["nope"]).take_all()


def test_optimizer_preserves_error_semantics(ray_start_regular, tmp_path):
    """The rewrite must never mask a user-visible KeyError or widen a read
    (review r5 findings: renamed-away selects, pre-restricted reads)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ray_tpu.data.optimizer import optimize_ops, optimize_plan

    # select of a renamed-AWAY column must not merge (it must still raise)
    ops = [("rename", {"a": "b"}), ("select", ["a"])]
    assert optimize_ops(ops) == ops
    ds = rd.from_items([{"a": 1}]).rename_columns({"a": "b"}).select_columns(["a"])
    with pytest.raises(Exception):
        ds.take_all()
    # drop of a renamed-away column is a no-op, not a drop of the source
    ds2 = rd.from_items([{"a": 1}]).rename_columns({"a": "b"}).drop_columns(["a"])
    assert ds2.take_all() == [{"b": 1}]

    # pushdown must not widen a read_parquet(columns=...) restriction
    p = tmp_path / "t.parquet"
    pq.write_table(pa.table({"a": [1], "b": [2]}), p)
    ds3 = rd.read_parquet(str(p), columns=["a"]).select_columns(["b"])
    src, stages = optimize_plan(ds3._block_refs, ds3._stages)
    assert src[0].columns == ["a"]  # untouched
    with pytest.raises(Exception):
        ds3.take_all()
    # narrowing select DOES push into a restricted read
    ds4 = rd.read_parquet(str(p), columns=["a", "b"]).select_columns(["a"])
    src4, _ = optimize_plan(ds4._block_refs, ds4._stages)
    assert src4[0].columns == ["a"]
    assert ds4.take_all() == [{"a": 1}]


def test_stats_reports_stage_executions(ray_start_regular):
    ds = rd.range(2000, num_blocks=8).map_batches(lambda b: b)
    assert ds.count() == 2000
    s = ds.stats()
    assert "Last execution:" in s
    assert "map[1 ops]" in s and "blocks in" in s, s
    # an UNEXECUTED dataset must not show another pipeline's stages
    fresh = rd.range(10)
    assert "Last execution:" not in fresh.stats()
