"""Node-local lease dispatch tests.

Parity: the raylet's local task queue + dispatch
(``src/ray/raylet/local_task_manager.cc:74``) — the head leases blocks of
normal tasks to daemon dispatchers, which run them on daemon-owned worker
pools and report completions in batches; plus the work-stealing rebalance
when capacity frees elsewhere.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 0})
    yield c
    c.shutdown()


@ray_tpu.remote
def _node_of():
    # daemon-node workers share the daemon's shm dir: a per-node fingerprint
    from ray_tpu._private.worker import get_runtime

    return get_runtime().shm_dir


def test_lease_drain_on_daemon_nodes(cluster):
    """With a 0-CPU head, every task must run via daemon-local dispatch,
    and a deep queue drains across both nodes."""
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()

    @ray_tpu.remote
    def sq(i):
        return i * i

    out = ray_tpu.get([sq.remote(i) for i in range(200)], timeout=300)
    assert out == [i * i for i in range(200)]
    nodes = set(ray_tpu.get([_node_of.remote() for _ in range(20)], timeout=300))
    assert len(nodes) >= 1  # daemon-hosted (head has no CPUs)


def test_lease_task_states_reach_running_and_finish(cluster):
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()

    @ray_tpu.remote
    def slow():
        time.sleep(1.5)
        return "ok"

    ref = slow.remote()
    from ray_tpu.util import state as state_api

    deadline = time.monotonic() + 60
    saw_running = False
    while time.monotonic() < deadline and not saw_running:
        rows = [t for t in state_api.list_tasks() if t["name"] == "slow"]
        if rows and rows[0]["state"] == "RUNNING":
            saw_running = True
        time.sleep(0.05)
    assert saw_running, "leased task never reported RUNNING"
    assert ray_tpu.get(ref, timeout=120) == "ok"


def test_lease_worker_death_retries(cluster):
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()

    @ray_tpu.remote(max_retries=2)
    def flaky(path):
        # die the first time, succeed after the marker exists
        if not os.path.exists(path):
            open(path, "w").close()
            os._exit(1)
        return "survived"

    marker = f"/tmp/lease_flaky_{os.getpid()}"
    try:
        assert ray_tpu.get(flaky.remote(marker), timeout=300) == "survived"
    finally:
        if os.path.exists(marker):
            os.unlink(marker)


def test_lease_worker_death_no_retries_fails(cluster):
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()

    @ray_tpu.remote(max_retries=0)
    def die():
        os._exit(1)

    with pytest.raises(exc.WorkerCrashedError):
        ray_tpu.get(die.remote(), timeout=300)


def test_cancel_leased_task(cluster):
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()

    @ray_tpu.remote
    def blocker():
        time.sleep(60)

    @ray_tpu.remote
    def queued():
        return 1

    b = blocker.remote()
    time.sleep(1.0)  # let it start occupying the node's one slot
    q = queued.remote()  # backlogged behind the blocker at the daemon
    ray_tpu.cancel(q)
    with pytest.raises(exc.RayTpuError):
        ray_tpu.get(q, timeout=60)
    ray_tpu.cancel(b, force=True)


def test_work_stealing_rebalances_backlog(cluster):
    """Tasks parked behind a busy node migrate when capacity appears."""
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()

    @ray_tpu.remote
    def hold(sec):
        time.sleep(sec)
        from ray_tpu._private.worker import get_runtime

        return get_runtime().shm_dir

    @ray_tpu.remote
    def quick():
        from ray_tpu._private.worker import get_runtime

        return get_runtime().shm_dir

    # one long task occupies node A; quick tasks pile into its backlog
    long_ref = hold.remote(20)
    time.sleep(1.0)
    quick_refs = [quick.remote() for _ in range(3)]
    time.sleep(0.5)
    # capacity appears elsewhere: the parked tasks must be stolen to it and
    # complete long before the 20 s blocker releases node A
    cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    t0 = time.monotonic()
    homes = ray_tpu.get(quick_refs, timeout=60)
    assert time.monotonic() - t0 < 15, "backlogged tasks were not stolen"
    assert len(set(homes)) >= 1
    ray_tpu.cancel(long_ref, force=True)


def test_lease_respects_custom_resources(cluster):
    cluster.add_node(num_cpus=1, resources={"gadget": 2.0})
    cluster.wait_for_nodes()

    @ray_tpu.remote(num_cpus=0, resources={"gadget": 1.0})
    def use_gadget():
        return "used"

    assert ray_tpu.get([use_gadget.remote() for _ in range(4)], timeout=300) == [
        "used"
    ] * 4


def test_nested_tasks_from_lease_workers(cluster):
    """A leased task submitting and getting child tasks must not deadlock
    (blocked workers release their local slot)."""
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()

    @ray_tpu.remote
    def child(x):
        return x + 1

    @ray_tpu.remote
    def parent():
        return ray_tpu.get(child.remote(41), timeout=120)

    assert ray_tpu.get(parent.remote(), timeout=300) == 42


def test_no_resource_leak_under_steal_churn(cluster):
    """Regression: steal-vs-promote races must not leak node resources.
    After everything drains, every node's available == total."""
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()

    @ray_tpu.remote
    def quick(i):
        return i

    refs = [quick.remote(i) for i in range(60)]
    # capacity appears mid-flight: steals fire while promotes race them
    cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    refs += [quick.remote(i) for i in range(60, 120)]
    out = ray_tpu.get(refs, timeout=600)
    assert out == list(range(120))
    time.sleep(1.5)  # let trailing lease_done batches settle
    for n in ray_tpu.nodes():
        if not n["alive"]:
            continue
        for k, total in n["total"].items():
            assert abs(n["available"][k] - total) < 1e-6, (
                f"leaked {k} on node {n['node_id'][:8]}: "
                f"{n['available'][k]} != {total}"
            )


def test_lost_lease_batch_reconciles(cluster, monkeypatch):
    """A lease_tasks batch that vanishes between head and daemon (conn
    churn) must be detected by the heartbeat reconciler and requeued —
    without burning the task's retry budget. A 50-node drain wedged
    permanently on exactly this failure mode."""
    from ray_tpu._private.worker import get_runtime

    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()
    sch = get_runtime().node.scheduler
    monkeypatch.setattr(type(sch), "RECONCILE_GRACE_S", 3.0)

    real_send = sch._daemon_send
    dropped = {"n": 0}

    def lossy_send(node, msg):
        if msg[0] == "lease_tasks" and dropped["n"] == 0:
            dropped["n"] += 1
            return True  # swallowed: the daemon never sees the batch
        return real_send(node, msg)

    monkeypatch.setattr(sch, "_daemon_send", lossy_send)

    @ray_tpu.remote(max_retries=0)  # reconcile must NOT consume retries
    def task():
        return "healed"

    ref = task.remote()
    assert ray_tpu.get(ref, timeout=120) == "healed"
    assert dropped["n"] == 1, "the loss was never injected"


def test_wide_head_does_not_idle_narrow_capacity(cluster):
    """A 4-CPU lease parked at the queue head must not idle cores that
    queued 1-CPU leases could use (bounded lookahead past an infeasible
    head; parity: local_task_manager.cc:122 iterating schedulable classes).
    Same-shape tasks still never overtake each other."""
    cluster.add_node(num_cpus=4)
    cluster.wait_for_nodes()

    @ray_tpu.remote(num_cpus=1)
    def hold(sec):
        time.sleep(sec)
        return "held"

    @ray_tpu.remote(num_cpus=4)
    def wide():
        return "wide"

    @ray_tpu.remote(num_cpus=1)
    def narrow(i):
        return i

    # warm the daemon's worker pool so spawn latency doesn't blur the
    # dispatch-order measurement
    ray_tpu.get([narrow.remote(i) for i in range(8)], timeout=60)

    # occupy 1 CPU so the 4-CPU task cannot start, then queue it ahead of
    # a batch of 1-CPU tasks
    blocker = hold.remote(8.0)
    time.sleep(1.0)  # blocker is running; 3 CPUs free
    w = wide.remote()
    narrows = [narrow.remote(i) for i in range(12)]

    # the narrow tasks must complete on the 3 spare cores while the wide
    # task waits for the blocker — i.e. well before the blocker finishes
    t0 = time.monotonic()
    out = ray_tpu.get(narrows, timeout=60)
    narrow_done = time.monotonic() - t0
    assert out == list(range(12))
    assert narrow_done < 4.0, f"narrow tasks waited on the wide head ({narrow_done:.1f}s)"

    # the wide task still runs once the blocker frees its core
    assert ray_tpu.get(w, timeout=60) == "wide"
    assert ray_tpu.get(blocker, timeout=60) == "held"
