"""Inter-node object transfer tests: striped fetch, zero-copy receive,
broadcast tree bookkeeping.

Parity: ``src/ray/object_manager`` tests (push/pull manager, buffer pool).
"""

import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import ObjectStoreClient
from ray_tpu._private.object_transfer import (
    ObjectServer,
    fetch_object_bytes,
    fetch_object_into,
)

KEY = b"test-key"


@pytest.fixture
def served_store(tmp_path):
    store = ObjectStoreClient(str(tmp_path / "shm"), str(tmp_path / "fb"), 1 << 28)
    server = ObjectServer(store, "127.0.0.1", KEY)
    yield store, server.address
    server.close()
    store.close()


def test_fetch_small_object(served_store):
    store, addr = served_store
    oid = ObjectID.from_random()
    store.put_bytes(oid, b"hello transfer")
    out = fetch_object_bytes(addr, oid, KEY)
    assert bytes(out) == b"hello transfer"


def test_fetch_missing_object(served_store):
    _, addr = served_store
    assert fetch_object_bytes(addr, ObjectID.from_random(), KEY) is None


def test_striped_fetch_large_object(served_store):
    """Objects above the stripe threshold arrive over several concurrent
    range connections; content must be byte-identical."""
    store, addr = served_store
    oid = ObjectID.from_random()
    arr = np.arange(40 * 1024 * 1024 // 8, dtype=np.float64)  # 40 MiB > 32 MiB
    store.put_bytes(oid, arr.tobytes())
    out = fetch_object_bytes(addr, oid, KEY)
    got = np.frombuffer(out, dtype=np.float64)
    np.testing.assert_array_equal(arr, got)


def test_fetch_into_destination_store(served_store, tmp_path):
    """fetch_object_into writes straight into a create()d buffer."""
    store, addr = served_store
    dest = ObjectStoreClient(str(tmp_path / "shm2"), str(tmp_path / "fb2"), 1 << 28)
    oid = ObjectID.from_random()
    payload = bytes(range(256)) * 4096  # 1 MiB
    store.put_bytes(oid, payload)

    def make_dest(size):
        return dest.create(oid, size)

    n = fetch_object_into(addr, oid, KEY, make_dest)
    assert n == len(payload)
    dest.seal(oid)
    assert bytes(dest.get(oid, timeout=5)) == payload
    dest.close()


def test_concurrent_fetches_same_object(served_store):
    store, addr = served_store
    oid = ObjectID.from_random()
    payload = b"x" * (4 * 1024 * 1024)
    store.put_bytes(oid, payload)
    results = []

    def f():
        results.append(bytes(fetch_object_bytes(addr, oid, KEY)))

    threads = [threading.Thread(target=f) for _ in range(4)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert all(r == payload for r in results)


def test_broadcast_zero_copy_and_tree_bookkeeping():
    """Both broadcast planes on one cluster:

    1. default (same-host shm): readers get content with NO transfers — the
       origin stays the only replica (zero-copy pinned views);
    2. socket plane (short-circuit disabled): per-source admission relays
       the object as a tree; every node lands a replica and the per-source
       load ledger drains to zero."""
    import ray_tpu.cluster_utils as cu

    cluster = cu.Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        for _ in range(4):
            cluster.add_node(num_cpus=1, resources={"reader": 1.0}, wait=False)
        cluster.wait_for_nodes(timeout=300)

        @ray_tpu.remote(num_cpus=0, resources={"reader": 1.0})
        def read(x):
            return int(x[0]) + x.nbytes

        from ray_tpu._private.worker import get_runtime

        sch = get_runtime().node.scheduler

        blob = ray_tpu.put(np.full(1024 * 1024, 7, dtype=np.int64))
        out = ray_tpu.get([read.remote(blob) for _ in range(4)], timeout=600)
        assert out == [7 + 8 * 1024 * 1024] * 4
        # zero-copy delivery: the origin remains the only replica
        locs = sch._object_locations.get(blob.id(), set())
        assert len(locs) == 1, locs

        # socket plane: disable the shm short-circuit and broadcast afresh
        sch.config.same_host_shm_transfer = False
        try:
            blob2 = ray_tpu.put(np.full(1024 * 1024, 9, dtype=np.int64))
            out = ray_tpu.get(
                [read.remote(blob2) for _ in range(4)], timeout=600
            )
            assert out == [9 + 8 * 1024 * 1024] * 4
            deadline = time.time() + 30
            while time.time() < deadline:
                if (
                    len(sch._object_locations.get(blob2.id(), set())) >= 5
                    and not sch._fetching
                ):
                    break
                time.sleep(0.1)
            # every reader node + origin holds a replica; ledger drained
            assert len(sch._object_locations.get(blob2.id(), set())) >= 5
            assert all(v == 0 for v in sch._xfer_load.values()), dict(
                sch._xfer_load
            )
            assert not sch._fetching
        finally:
            sch.config.same_host_shm_transfer = True
    finally:
        cluster.shutdown()
