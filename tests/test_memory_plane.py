"""Memory-observability plane tests (fast tier-1).

Covers: allocation-provenance round-trip (driver puts + task returns land
in the scheduler's index with a resolvable creation callsite), server-side
``list_objects`` filter pushdown with the hard row cap + truncation flag,
``summarize_objects`` groupings, the leak watchdog (flags a deliberately
leaked ref within one window; stays silent on a churning-but-bounded
workload), sealed-vs-unsealed store accounting, per-job spill byte
attribution, the OOM-kill memory snapshot, the ``ray_tpu memory`` CLI
output, and a PR-2/PR-11 telemetry regression guard with the plane on.
"""

import gc
import json
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import state


def _sch():
    from ray_tpu._private.worker import get_runtime

    return get_runtime().node.scheduler


@pytest.fixture
def two_cpu():
    rt = ray_tpu.init(num_cpus=2)
    yield rt
    ray_tpu.shutdown()


@pytest.fixture
def leak_tuned():
    """Cluster with the watchdog tuned tight enough to converge within a
    test budget: 0.1s scans, 5-scan window, small growth thresholds, fast
    telemetry flushes so provenance reaches the index promptly."""
    rt = ray_tpu.init(
        num_cpus=1,
        _system_config={
            "leak_watchdog_interval_s": 0.1,
            "leak_watchdog_window": 5,
            "leak_watchdog_min_growth_bytes": 50_000,
            "leak_watchdog_min_count_growth": 3,
            "metrics_report_interval_ms": 50,
        },
    )
    yield rt
    ray_tpu.shutdown()


def _flush():
    from ray_tpu._private import telemetry

    telemetry.flush()
    _sch().request_telemetry_flush()


def test_callsite_provenance_roundtrip(two_cpu):
    """Driver puts and task returns land in the provenance index with a
    resolvable creation callsite, owner job/task decoded from the oid,
    size, and kind."""

    @ray_tpu.remote
    def make_block():
        return np.zeros(200_000, dtype=np.uint8)

    put_ref = ray_tpu.put(np.ones(150_000, dtype=np.uint8))  # PROBE-LINE
    ret_ref = make_block.remote()
    ray_tpu.get(ret_ref, timeout=60)
    _flush()
    rows = {r["object_id"]: r for r in state.list_objects()}
    put_row = rows[put_ref.hex()]
    assert put_row["callsite"].startswith("test_memory_plane.py:")
    assert put_row["kind"] == "put"
    assert put_row["size_bytes"] > 150_000
    assert put_row["job"] == put_ref.binary()[20:24].hex()
    assert put_row["task"] == put_ref.binary()[:24].hex()
    assert put_row["class"] in (
        "IN_USE",
        "CAPTURED_IN_ACTOR",
        "LEAK_SUSPECT",
        "PINNED_BY_DEAD_OWNER",
    )
    ret_row = rows[ret_ref.hex()]
    assert ret_row["callsite"] == "task:make_block"
    assert ret_row["kind"] == "return"
    assert ret_row["size_bytes"] > 200_000
    # server-side grouping: the put's callsite shows up with its bytes
    summary = state.summarize_objects(group_by="callsite")
    by_group = {g["group"]: g for g in summary["rows"]}
    assert any(
        cs.startswith("test_memory_plane.py:") for cs in by_group
    ), summary["rows"]
    assert "task:make_block" in by_group
    assert by_group["task:make_block"]["bytes"] >= 200_000
    assert summary["total_bytes"] >= 350_000
    # job grouping sums both objects under the interactive job
    jobs = state.summarize_objects(group_by="job")
    jrow = {g["group"]: g for g in jobs["rows"]}[put_row["job"]]
    assert jrow["count"] >= 2
    # exemplars resolve back to real object ids
    assert all(len(e) == 56 for g in summary["rows"] for e in g["exemplars"])


def test_list_objects_server_side_filter_and_cap(two_cpu):
    refs = [ray_tpu.put(np.zeros(60_000, dtype=np.uint8)) for _ in range(8)]
    big = ray_tpu.put(np.zeros(500_000, dtype=np.uint8))
    _flush()
    # ordering filter pushed server-side: only the big object matches
    page = state.list_objects_page(
        filters=[("size_bytes", ">", 400_000)], limit=100
    )
    assert [r["object_id"] for r in page["rows"]] == [big.hex()]
    assert page["total"] == 1 and not page["truncated"]
    # hard cap + truncation flag: more matches than the limit
    page = state.list_objects_page(limit=3)
    assert len(page["rows"]) == 3
    assert page["truncated"] is True
    assert page["total"] >= 9
    # equality filter on provenance fields works server-side too
    page = state.list_objects_page(filters=[("kind", "=", "put")], limit=100)
    assert page["total"] >= 9
    del refs, big


def test_leak_watchdog_flags_seeded_leak(leak_tuned):
    """A deliberately leaked ref stream (grow-only holder list) is flagged
    within one window: OBJECT_LEAK_SUSPECT with a resolvable callsite and
    exemplar object ids."""
    from ray_tpu._private import telemetry

    hoard = []
    deadline = time.monotonic() + 20
    flagged = []
    while time.monotonic() < deadline:
        hoard.append(ray_tpu.put(np.zeros(30_000, dtype=np.uint8)))  # LEAK-SITE
        telemetry.flush()
        flagged = state.list_cluster_events(
            filters=[("type", "=", "OBJECT_LEAK_SUSPECT")]
        )
        if flagged:
            break
        time.sleep(0.1)
    assert flagged, "leak watchdog never flagged the seeded leak"
    ev = flagged[-1]
    # the callsite resolves to the leaking line in THIS file
    assert ev["callsite"].startswith("test_memory_plane.py:")
    assert ev["live_count"] >= 3
    assert ev["live_bytes"] >= 50_000
    exemplars = ev["exemplar_object_ids"]
    assert exemplars and all(len(e) == 56 for e in exemplars)
    live_ids = {r.hex() for r in hoard}
    assert set(exemplars) <= live_ids
    # the suspect surfaces in summarize_objects + the class counts
    summary = state.summarize_objects(group_by="callsite")
    assert ev["callsite"] in summary["leak_suspects"]
    flagged_groups = [g for g in summary["rows"] if g["leak_suspect"]]
    assert any(g["group"] == ev["callsite"] for g in flagged_groups)


def test_leak_watchdog_silent_on_bounded_churn(leak_tuned):
    """A churning-but-bounded put/get/del workload (the calm bench_core
    shape) must produce ZERO leak suspects."""
    from ray_tpu._private import telemetry

    keep = None
    t0 = time.monotonic()
    while time.monotonic() - t0 < 2.5:
        keep = ray_tpu.put(np.zeros(40_000, dtype=np.uint8))
        ray_tpu.get(keep, timeout=30)
        keep = None
        gc.collect()
        telemetry.flush()
        time.sleep(0.02)
    events = state.list_cluster_events(
        filters=[("type", "=", "OBJECT_LEAK_SUSPECT")]
    )
    assert events == [], f"false-positive leak flags on bounded churn: {events}"
    assert _sch()._leak_suspects == {}


def test_usage_stats_sealed_unsealed_split(tmp_path):
    """usage_stats snapshots under the store lock and reports in-flight
    (created, unsealed) bytes separately from sealed ones."""
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.object_store import ObjectStoreClient

    store = ObjectStoreClient(
        str(tmp_path / "shm"), str(tmp_path / "fb"), capacity=1 << 24
    )
    sealed_id, open_id = ObjectID.from_random(), ObjectID.from_random()
    buf = store.create(sealed_id, 1000)
    buf[:4] = b"xxxx"
    store.seal(sealed_id)
    store.create(open_id, 2000)  # deliberately never sealed
    st = store.usage_stats()
    assert st["sealed_objects"] == 1 and st["unsealed_objects"] == 1
    assert 1000 <= st["sealed_bytes"] <= 1100
    assert 2000 <= st["unsealed_bytes"] <= 2100
    # usage_bytes = one consistent snapshot's total
    assert store.usage_bytes() == st["sealed_bytes"] + st["unsealed_bytes"]
    store.abort(open_id)
    st = store.usage_stats()
    assert st["unsealed_objects"] == 0 and st["unsealed_bytes"] == 0
    store.close()


def test_spill_bytes_attributed_per_job(tmp_path):
    """LRU spill out of a small arena lands on the owning job's
    ray_tpu_spill_bytes_total series."""
    rt = ray_tpu.init(num_cpus=1, object_store_memory=8 * 1024 * 1024)
    try:
        from ray_tpu._private.native_store import NativeStoreClient

        if not isinstance(rt.node.store_client, NativeStoreClient):
            pytest.skip("native arena store not available (no LRU spill path)")
        refs = [
            ray_tpu.put(np.random.bytes(3 * 1024 * 1024)) for _ in range(4)
        ]
        from ray_tpu.util.metrics import prometheus_text

        text = prometheus_text()
        job_hex = rt.job_id.binary().hex()
        needle = f'ray_tpu_spill_bytes_total{{job="{job_hex}"}}'
        assert needle in text, text[:2000]
        value = float(
            next(
                line.split()[-1]
                for line in text.splitlines()
                if line.startswith(needle)
            )
        )
        assert value >= 3 * 1024 * 1024
        del refs
    finally:
        ray_tpu.shutdown()


def test_oom_event_carries_memory_snapshot(two_cpu):
    """The memory-monitor kill event names what FILLED the store (usage +
    top callsites) and the victim-ranking provenance, not just the
    victim."""
    from ray_tpu._private.memory_monitor import make_scheduler_kill_policy

    hold = ray_tpu.put(np.zeros(300_000, dtype=np.uint8))  # OOM-FILLER

    @ray_tpu.remote(max_retries=1)
    def hog():
        time.sleep(60)

    ref = hog.remote()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if any(
            t["state"] == "RUNNING"
            for t in state.list_tasks(filters=[("name", "=", "hog")])
        ):
            break
        time.sleep(0.05)
    _flush()
    kill = make_scheduler_kill_policy(_sch())
    assert kill()
    events = state.list_cluster_events(filters=[("type", "=", "OOM")])
    assert events
    ev = events[-1]
    assert ev["store_capacity_bytes"] > 0
    assert "store_used_bytes" in ev
    tops = ev["top_callsites"]
    assert tops and any(
        t["callsite"].startswith("test_memory_plane.py:") for t in tops
    )
    assert "job_top_callsites" in ev
    # pick_oom_victim provenance in the event body
    victim = ev["victim"]
    assert victim["task_name"] == "hog"
    assert victim["retriable"] is True
    assert victim["task_id"]
    ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=0.5)
    del hold


def test_memory_cli_output(two_cpu, capsys):
    """`ray_tpu memory` golden-ish output: store header, grouped callsite
    rows with bytes/count/class columns, --json parses, units honored."""
    from ray_tpu.scripts import cli

    keep = ray_tpu.put(np.zeros(250_000, dtype=np.uint8))  # CLI-SITE
    _flush()
    cli.main(["memory", "--units", "KB"])
    out = capsys.readouterr().out
    assert "== object store:" in out
    assert "BYTES(KB)" in out and "CALLSITE" in out
    assert "test_memory_plane.py:" in out
    cli.main(["memory", "--group-by", "object", "--units", "B", "--limit", "10"])
    out = capsys.readouterr().out
    assert "OBJECT" in out and "test_memory_plane.py:" in out
    cli.main(["memory", "--json"])
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["group_by"] == "callsite"
    assert parsed["total_bytes"] >= 250_000
    cli.main(["memory", "--leaks-only"])
    out = capsys.readouterr().out
    assert "== object store:" in out  # calm cluster: header, no leak rows
    del keep


def test_telemetry_and_tracing_regression_guard(two_cpu):
    """PR-2/PR-11 surfaces stay intact with the memory plane on: timeline
    events flow, prometheus text exposes both old and new series, traces
    still resolve."""

    @ray_tpu.remote
    def work(x):
        return x + 1

    assert ray_tpu.get(work.remote(1), timeout=60) == 2
    keep = ray_tpu.put(np.zeros(120_000, dtype=np.uint8))
    events = ray_tpu.timeline()
    assert any(e.get("cat") == "TASK_PHASE" for e in events)
    from ray_tpu.util.metrics import prometheus_text

    text = prometheus_text()
    for series in (
        "ray_tpu_telemetry_batches_total",  # PR-2
        "ray_tpu_scheduler_queue_depth",  # PR-2
        "ray_tpu_object_store_bytes_used",  # PR-2 (now sealed-only)
        "ray_tpu_object_store_unsealed_bytes",  # memory plane
        "ray_tpu_object_provenance_entries",  # memory plane
        "ray_tpu_objects_by_class",  # memory plane
    ):
        assert series in text, f"{series} missing from /metrics"
    traces = ray_tpu.recent_traces(limit=5)
    assert traces, "tracing plane lost its recent-trace index"
    t = ray_tpu.trace(traces[0]["trace_id"])
    assert t.span_count() >= 1
    del keep


def test_device_memory_gauges(two_cpu):
    """Once jax is imported, the device-memory sweep records live-array
    gauges (the PR-11 probe-don't-import seam)."""
    import jax
    import jax.numpy as jnp

    keep = jnp.zeros((1024,), dtype=jnp.float32)
    keep.block_until_ready()
    from ray_tpu._private import memplane

    assert memplane.collect_device_metrics()
    from ray_tpu.util.metrics import prometheus_text

    text = prometheus_text()
    assert "ray_tpu_device_live_buffers" in text
    assert "ray_tpu_device_live_bytes" in text
    value = max(
        float(line.split()[-1])
        for line in text.splitlines()
        if line.startswith("ray_tpu_device_live_bytes{")
    )
    assert value >= keep.nbytes
    del keep, jax


def test_provenance_index_bounded(two_cpu):
    """Overflow beyond object_provenance_max is counted, never silent."""
    sch = _sch()
    sch.config.object_provenance_max = 5
    try:
        refs = [
            ray_tpu.put(np.zeros(40_000, dtype=np.uint8)) for _ in range(9)
        ]
        _flush()
        assert len(sch._obj_prov) <= 5
        series = {
            s["name"]: s for s in ray_tpu.get_runtime().rpc("runtime_metrics")
        }
        dropped = sum(
            series["ray_tpu_object_provenance_dropped_total"]["data"].values()
        )
        assert dropped >= 4
        del refs
    finally:
        sch.config.object_provenance_max = 50_000


def test_freed_objects_leave_the_index(two_cpu):
    ref = ray_tpu.put(np.zeros(90_000, dtype=np.uint8))
    oid_hex = ref.hex()
    _flush()
    assert any(r["object_id"] == oid_hex for r in state.list_objects())
    del ref
    gc.collect()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if all(r["object_id"] != oid_hex for r in state.list_objects()):
            break
        time.sleep(0.2)
    assert all(r["object_id"] != oid_hex for r in state.list_objects())
    assert oid_hex not in _sch()._obj_prov
