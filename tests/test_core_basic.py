"""Core API tests: tasks, objects, errors.

Test strategy parity: ``python/ray/tests/test_basic.py`` family (SURVEY.md §4).
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import exceptions as exc


def test_put_get(ray_start_regular):
    ref = ray_tpu.put(42)
    assert ray_tpu.get(ref) == 42


def test_put_get_large_numpy(ray_start_regular):
    arr = np.arange(500_000, dtype=np.float64)
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(arr, out)


def test_put_objectref_rejected(ray_start_regular):
    ref = ray_tpu.put(1)
    with pytest.raises(TypeError):
        ray_tpu.put(ref)


def test_simple_task(ray_start_regular):
    @ray_tpu.remote
    def f(a, b):
        return a + b

    assert ray_tpu.get(f.remote(1, 2)) == 3


def test_task_kwargs_and_defaults(ray_start_regular):
    @ray_tpu.remote
    def f(a, b=10, *, c=100):
        return a + b + c

    assert ray_tpu.get(f.remote(1)) == 111
    assert ray_tpu.get(f.remote(1, b=2, c=3)) == 6


def test_task_with_ref_args(ray_start_regular):
    @ray_tpu.remote
    def double(x):
        return x * 2

    a = double.remote(5)
    b = double.remote(a)
    c = double.remote(b)
    assert ray_tpu.get(c) == 40


def test_task_large_arg_roundtrip(ray_start_regular):
    arr = np.ones((1000, 200), dtype=np.float32)

    @ray_tpu.remote
    def total(x):
        return float(x.sum())

    assert ray_tpu.get(total.remote(arr)) == 200_000.0


def test_multiple_returns(ray_start_regular):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    r1, r2, r3 = three.remote()
    assert ray_tpu.get([r1, r2, r3]) == [1, 2, 3]


def test_error_propagation(ray_start_regular):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    ref = boom.remote()
    with pytest.raises(ValueError, match="kaboom"):
        ray_tpu.get(ref)
    # also a TaskError
    with pytest.raises(exc.TaskError):
        ray_tpu.get(ref)


def test_error_through_dependency(ray_start_regular):
    @ray_tpu.remote
    def boom():
        raise RuntimeError("first failure")

    @ray_tpu.remote
    def consume(x):
        return x

    with pytest.raises(RuntimeError):
        ray_tpu.get(consume.remote(boom.remote()))


def test_nested_tasks(ray_start_regular):
    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) * 10

    assert ray_tpu.get(outer.remote(1), timeout=60) == 20


def test_deeply_nested(ray_start_regular):
    @ray_tpu.remote
    def fib(n):
        if n < 2:
            return n
        return sum(ray_tpu.get([fib.remote(n - 1), fib.remote(n - 2)]))

    assert ray_tpu.get(fib.remote(6), timeout=120) == 8


def test_wait(ray_start_regular):
    @ray_tpu.remote
    def slow(t):
        time.sleep(t)
        return t

    fast = slow.remote(0.01)
    never = slow.remote(30)
    ready, not_ready = ray_tpu.wait([fast, never], num_returns=1, timeout=10)
    assert ready == [fast]
    assert not_ready == [never]


def test_wait_timeout(ray_start_regular):
    @ray_tpu.remote
    def slow():
        time.sleep(30)

    ready, not_ready = ray_tpu.wait([slow.remote()], num_returns=1, timeout=0.2)
    assert ready == []
    assert len(not_ready) == 1


def test_get_timeout(ray_start_regular):
    @ray_tpu.remote
    def slow():
        time.sleep(30)

    with pytest.raises(exc.GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=0.2)


def test_options_name_and_retries(ray_start_regular):
    @ray_tpu.remote
    def f():
        return 1

    assert ray_tpu.get(f.options(name="custom", max_retries=0).remote()) == 1


def test_streaming_generator(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * i

    out = [ray_tpu.get(r) for r in gen.remote(5)]
    assert out == [0, 1, 4, 9, 16]


def test_remote_lambda_closure(ray_start_regular):
    factor = 7
    f = ray_tpu.remote(lambda x: x * factor)
    assert ray_tpu.get(f.remote(6)) == 42


def test_cluster_and_available_resources(ray_start_regular):
    total = ray_tpu.cluster_resources()
    assert total["CPU"] == 4.0
    avail = ray_tpu.available_resources()
    assert avail["CPU"] <= total["CPU"]


def test_timeline_events(ray_start_regular):
    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get(f.remote())
    events = ray_tpu.timeline()
    assert any(e["args"]["state"] == "FINISHED" for e in events)


def test_direct_call_rejected(ray_start_regular):
    @ray_tpu.remote
    def f():
        return 1

    with pytest.raises(TypeError):
        f()
