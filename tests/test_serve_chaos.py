"""Serve-plane chaos: seeded replica + controller kills under sustained
mixed unary/streaming load (`make chaos-serve`, seeded via CHAOS_SEED).

Acceptance (ISSUE 9): across replica churn >= 99% of requests succeed and
every failure is a typed ReplicaDiedError on work that had already started;
after the fleet heals, a graceful redeploy under load completes with ZERO
failed requests.
"""

import os
import signal
import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve

# pytest's prepend import mode puts tests/ on sys.path (no tests/__init__),
# so the chaos harness package imports as a top-level name
from chaos import ChaosMonkey, chaos_seed, serve_controller_pids, serve_replica_pids

pytestmark = pytest.mark.slow


def test_serve_churn_mixed_load_and_graceful_redeploy():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    try:
        @serve.deployment(
            num_replicas=3,
            health_check_period_s=0.5,
            graceful_shutdown_timeout_s=5.0,
        )
        class Mixed:
            def __init__(self, version=1):
                self.version = version

            def __call__(self, x):
                time.sleep(0.01)
                return x

            def stream(self, n):
                for i in range(n):
                    time.sleep(0.01)
                    yield i

        serve.run(Mixed.bind(1), name="churn")

        counts = {"ok": 0, "typed": 0, "other": 0}
        lock = threading.Lock()
        stop = threading.Event()
        other_errors = []

        def note(kind, err=None):
            with lock:
                counts[kind] += 1
                if kind == "other" and len(other_errors) < 5:
                    other_errors.append(repr(err))

        def unary_client(i):
            h = serve.get_app_handle("churn")
            n = 0
            while not stop.is_set():
                try:
                    assert h.remote(n).result(timeout_s=60) == n
                    note("ok")
                except serve.ReplicaDiedError:
                    note("typed")
                except Exception as e:  # noqa: BLE001
                    note("other", e)
                n += 1

        def stream_client(i):
            h = serve.get_app_handle("churn").options(stream=True)
            while not stop.is_set():
                try:
                    out = list(h.stream.remote(5))
                    if out == list(range(5)):
                        note("ok")
                    else:
                        note("other", RuntimeError(f"partial stream {out}"))
                except serve.ReplicaDiedError:
                    note("typed")  # already-started stream torn by a kill
                except Exception as e:  # noqa: BLE001
                    note("other", e)

        threads = [
            threading.Thread(target=unary_client, args=(i,)) for i in range(6)
        ] + [threading.Thread(target=stream_client, args=(i,)) for i in range(2)]
        for t in threads:
            t.start()

        # ---- phase 1: replica churn + one controller kill ----------------
        monkey = ChaosMonkey(
            seed=chaos_seed(),
            interval_s=(1.0, 2.0),
            victims=serve_replica_pids,
            max_kills=3,
            arm_when=lambda: counts["ok"] > 50,
        )
        monkey.start()
        deadline = time.monotonic() + 15.0
        controller_killed = False
        while time.monotonic() < deadline:
            if not controller_killed and len(monkey.kills) >= 1:
                cpids = serve_controller_pids()
                if cpids:
                    os.kill(cpids[0], signal.SIGKILL)
                    controller_killed = True
            time.sleep(0.2)
        kills = monkey.stop()
        assert kills >= 2, f"chaos monkey landed only {kills} kills"
        assert controller_killed, "controller was never killed"
        # keep load running while the fleet heals
        heal_deadline = time.monotonic() + 30.0
        while time.monotonic() < heal_deadline:
            try:
                st = serve.status()
                row = st.get("churn", {}).get("Mixed", {})
                if row.get("num_replicas") == 3 and row.get("health") == "HEALTHY":
                    break
            except Exception:
                pass
            time.sleep(0.5)

        with lock:
            churn_counts = dict(counts)
        total = sum(churn_counts.values())
        assert total > 200, f"not enough load generated: {churn_counts}"
        assert churn_counts["other"] == 0, (
            f"untyped failures under churn (seed={chaos_seed()}): "
            f"{churn_counts} {other_errors}"
        )
        success = churn_counts["ok"] / total
        assert success >= 0.99, (
            f"success rate {success:.4f} < 0.99 under churn "
            f"(seed={chaos_seed()}, counts={churn_counts}, kills={monkey.kills})"
        )

        # ---- phase 2: graceful redeploy under load = zero drops ----------
        with lock:
            for k in counts:
                counts[k] = 0
            other_errors.clear()
        serve.run(Mixed.bind(2), name="churn")  # full replica restart
        time.sleep(2.0)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        with lock:
            redeploy_counts = dict(counts)
        assert redeploy_counts["typed"] == 0 and redeploy_counts["other"] == 0, (
            f"graceful redeploy dropped requests (seed={chaos_seed()}): "
            f"{redeploy_counts} {other_errors}"
        )
        assert redeploy_counts["ok"] > 50

        print(
            f"serve chaos (seed={chaos_seed()}): churn={churn_counts} "
            f"success={success:.4f} kills={monkey.kills} "
            f"redeploy={redeploy_counts}"
        )
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


def test_serve_drain_under_chaos_preserves_streams():
    """Heavier drain variant: long streams crossing several redeploys all
    complete (drain keeps old replicas alive until their streams finish)."""
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    try:
        @serve.deployment(
            num_replicas=2,
            health_check_period_s=0.5,
            graceful_shutdown_timeout_s=30.0,
        )
        class Long:
            def __init__(self, version=1):
                self.version = version

            def stream(self, n):
                for i in range(n):
                    time.sleep(0.05)
                    yield i

        serve.run(Long.bind(1), name="drainchaos")
        results = []
        errors = []

        def consumer(i):
            h = serve.get_app_handle("drainchaos").options(stream=True)
            try:
                results.append(list(h.stream.remote(40)))  # ~2s per stream
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=consumer, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.5)
        # two back-to-back full redeploys while every stream is open
        serve.run(Long.bind(2), name="drainchaos")
        serve.run(Long.bind(3), name="drainchaos")
        for t in threads:
            t.join(timeout=60)
        assert not errors, f"drain tore open streams: {errors[:3]}"
        assert len(results) == 4
        for out in results:
            assert out == list(range(40))
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
