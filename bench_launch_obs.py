"""Control-plane (actor-launch) observability bench.

Two measurements, recorded as BENCH_SCALE.jsonl rows with --append:

1. launch_obs_overhead_ratio — actor launch rate with the launch plane
   ON vs OFF (``launch_obs_enabled``), interleaved toggles inside ONE
   cluster (bench_memplane methodology), median of per-pair ratios
   (round-7 host caveats: absolute rates are unresolvable on these noisy
   boxes, and fresh-cluster launch-rate pairs are dominated by spawn-path
   drift; the flag is read live by the head every pass, so same-cluster
   toggles cancel both). Budget: <= 1.05.

2. launch_stage_decomposition_1000 — the "where did the ACTOR go"
   acceptance row: 1000 creations (launched in bounded waves so the
   process count stays sane on one box), per-stage mean/p95 from
   ``state.launch_profile()``, plus launch_stage_coverage — the median
   per-creation (submit+placement+worker_spawn+execute)/total, which must
   stay within 10% of the wall (same bar test_launch_obs.py asserts).

Run: python bench_launch_obs.py [--quick] [--append]
"""

from __future__ import annotations

import argparse
import json
import statistics
import time

import ray_tpu


def emit(row: dict) -> str:
    line = json.dumps(row)
    print(line, flush=True)
    return line


def _launch_wave_rate(n_actors: int, wave: int) -> float:
    """Launch n_actors in waves of `wave` (create, prove ready with one
    round-trip, kill) — measures the creation control path, bounding the
    number of live dedicated workers."""

    @ray_tpu.remote(num_cpus=0)
    class Member:
        def ping(self):
            return 1

    t0 = time.perf_counter()
    launched = 0
    while launched < n_actors:
        k = min(wave, n_actors - launched)
        actors = [Member.remote() for _ in range(k)]
        assert ray_tpu.get(
            [a.ping.remote() for a in actors], timeout=600
        ) == [1] * k
        for a in actors:
            ray_tpu.kill(a)
        launched += k
    return n_actors / (time.perf_counter() - t0)


def overhead_ratio(pairs: int, seg_actors: int, wave: int):
    """ON/OFF launch-rate ratio via one-cluster interleaved toggles
    (bench_memplane methodology): `launch_obs_enabled` is read live by the
    head on every pass, so alternating ON/OFF segments inside ONE cluster
    cancel the worker-pool / page-cache / host drift that dominates
    cluster-to-cluster launch-rate comparisons."""
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    from ray_tpu._private.worker import get_runtime

    cfg = get_runtime().node.scheduler.config
    _launch_wave_rate(20, wave)  # settle the initial pool out of the bench
    ratios = []
    try:
        for _ in range(pairs):
            cfg.launch_obs_enabled = True
            on = _launch_wave_rate(seg_actors, wave)
            cfg.launch_obs_enabled = False
            off = _launch_wave_rate(seg_actors, wave)
            ratios.append(off / on)  # >1: the plane slowed launches down
    finally:
        cfg.launch_obs_enabled = True
    return statistics.median(ratios), ratios


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--actors", type=int, default=1000)
    ap.add_argument("--wave", type=int, default=100)
    ap.add_argument("--pairs", type=int, default=5)
    ap.add_argument("--pair-actors", type=int, default=100)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument(
        "--append",
        action="store_true",
        help="append result rows to BENCH_SCALE.jsonl",
    )
    args = ap.parse_args()
    if args.quick:
        args.actors, args.pairs, args.pair_actors = 150, 3, 60

    rows = []

    # --- 1. overhead ratio (one-cluster interleaved toggles) --------------
    ratio, ratios = overhead_ratio(args.pairs, args.pair_actors, args.wave)
    ratio = round(ratio, 4)
    rows.append(
        emit(
            {
                "metric": "launch_obs_overhead_ratio",
                "value": ratio,
                "unit": "x",
                "pairs": [round(r, 4) for r in ratios],
                "note": "actor launch rate, plane-on/plane-off interleaved "
                "toggles inside one cluster (median of per-pair ratios; "
                "per round-7 caveats fresh-cluster pairs are dominated by "
                "spawn-path drift); budget <= 1.05",
            }
        )
    )

    # --- 2. per-stage decomposition at scale ------------------------------
    ray_tpu.shutdown()
    ray_tpu.init(
        num_cpus=4,
        ignore_reinit_error=True,
        _system_config={"launch_obs_enabled": True, "launch_recent_max": 1024},
    )
    rate = _launch_wave_rate(args.actors, args.wave)
    from ray_tpu.util import state

    prof = state.launch_profile(limit=1024)
    stages = {
        k: {"mean_ms": v["mean_ms"], "p95_ms": v["p95_ms"]}
        for k, v in prof["stages"].items()
    }
    coverage = []
    head = ("submit_ms", "placement_ms", "worker_spawn_ms", "execute_ms")
    for entry in prof["recent"]:
        total = entry["stages"].get("total_ms")
        if total:
            coverage.append(
                sum(entry["stages"].get(k, 0.0) for k in head) / total
            )
    cov = round(statistics.median(coverage), 4) if coverage else None
    rows.append(
        emit(
            {
                "metric": f"launch_stage_decomposition_{args.actors}",
                "value": stages,
                "unit": "ms",
                "launch_rate": round(rate, 2),
                "launched_total": prof["launched_total"],
                "total_mean_ms": prof["total"]["mean_ms"],
                "total_p95_ms": prof["total"]["p95_ms"],
                "note": "per-stage launch decomposition over the profile "
                "window (submit/placement/worker_spawn/execute head stages "
                "partition the wall; runtime_env/actor_class_load are "
                "worker-measured refinements of execute's lead-in)",
            }
        )
    )
    rows.append(
        emit(
            {
                "metric": "launch_stage_coverage",
                "value": cov,
                "unit": "stage_sum/wall",
                "creations": len(coverage),
                "note": "median per-creation "
                "(submit+placement+worker_spawn+execute)/total — "
                "acceptance: within 10% of wall",
            }
        )
    )
    ray_tpu.shutdown()

    assert ratio <= 1.05, f"launch plane overhead {ratio} > 1.05 budget"
    assert cov is not None and abs(cov - 1.0) <= 0.10, (
        f"stage coverage {cov} outside 10% of wall"
    )

    if args.append:
        with open("BENCH_SCALE.jsonl", "a") as fh:
            for line in rows:
                fh.write(line + "\n")


if __name__ == "__main__":
    main()
