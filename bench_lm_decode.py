"""LM decode bench: static batching vs continuous batching + KV saturation.

BASELINE.json's serving target is Llama-2-7B batched replicas on v5e; on
CPU hosts a scaled-down geometry keeps every mode runnable in CI.

Modes (``--mode``, default ``all``):

* ``static``      — the dense KV-cache decode path (``make_decode_fns``)
  run the way static batching actually serves: fixed batches admitted
  together, every batch decodes until its LONGEST member finishes
  (padding waste included). Useful tokens / wall-clock.
* ``continuous``  — the SAME workload through the paged continuous-
  batching engine (``serve.llm.InferenceEngine``): finished sequences
  free their slot + KV blocks immediately and waiting work joins at step
  boundaries. Also emits the ``lm_decode_continuous_vs_static_floor_ratio``
  row (floor 1.0: continuous must not lose to static on its home turf).
* ``serve``       — deploy the engine behind the serve plane, drive
  streams, and quote the deployment TTFT p50/p99 from the tracing-plane
  stream spans as folded by the controller (``serve.status()['..']['ttft']``
  — the same window the ``deployment_ttft_p99`` SLO burns against, which
  this mode registers).
* ``saturate``    — >= 100 concurrent streams against one replica with a
  deliberately small KV pool: counts ok / typed sheds / untyped failures
  (must be 0) and checks sheds stay fast.

Every row appends to ``BENCH_LM_DECODE.jsonl`` (append-only ledger; the
newest row per metric is the current claim, gated by
``tools/bench_check.py`` / ``make bench-gate``).
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import threading
import time

LEDGER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "BENCH_LM_DECODE.jsonl")


def _fingerprint() -> dict:
    import jax

    return {
        "host": platform.node(),
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]).split(":")[0],
        "cpus": os.cpu_count(),
    }


def _append(row: dict) -> None:
    with open(LEDGER, "a") as fh:
        fh.write(json.dumps(row) + "\n")
    print(json.dumps(row))


def _geometry():
    """(model cfg, workload) sized to the attached backend."""
    import jax

    from ray_tpu.models.transformer import TransformerConfig

    if jax.default_backend() == "tpu":
        # Llama-2-7B geometry; weights bf16 (~13.5 GB) + cache fit 16G HBM
        cfg = TransformerConfig(
            vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
            d_ff=11008, max_seq_len=1024, remat=False,
        )
        prompt_len, lengths = 128, [64, 384, 128, 256, 64, 384, 192, 320]
    else:
        # big enough that a decode step costs real time (utilization, not
        # python overhead, decides the comparison), small enough for CI
        cfg = TransformerConfig(
            vocab_size=512, d_model=256, n_layers=4, n_heads=8,
            d_ff=512, max_seq_len=256, remat=False,
        )
        prompt_len, lengths = 8, [8, 56, 16, 48, 8, 64, 24, 56, 16, 40, 8, 48]
    return cfg, prompt_len, lengths


def _params(cfg):
    import jax

    from ray_tpu.models.transformer import init_params

    # jit the init: XLA frees the fp32 sampling intermediates instead of
    # holding a transient fp32 copy of every bf16 tensor (OOM at 7B)
    return jax.jit(lambda k: init_params(k, cfg))(jax.random.PRNGKey(0))


def _prompts(cfg, prompt_len, n, seed=0):
    import numpy as np

    rng = np.random.default_rng(seed)
    return [
        rng.integers(1, cfg.vocab_size - 1, prompt_len).tolist()
        for _ in range(n)
    ]


# -- static batching -------------------------------------------------------


def run_static(cfg, params, prompt_len, lengths, batch=4):
    """Fixed batch-of-4 admission: each batch decodes to its longest
    member (the static-batching padding tax), batches run back-to-back."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models.generation import init_kv_cache, make_decode_fns

    max_len = prompt_len + max(lengths) + 1
    prefill, decode_step = make_decode_fns(cfg, max_len)
    prompts = _prompts(cfg, prompt_len, len(lengths))

    # compile warmup (one batch shape, reused by every batch)
    cache = init_kv_cache(cfg, batch, max_len)
    warm = jnp.asarray(np.asarray(prompts[:batch], dtype=np.int32))
    logits, cache = prefill(params, warm, cache)
    tok = jnp.argmax(logits, axis=-1)
    logits, cache = decode_step(params, tok[:, None], cache)
    float(jax.device_get(logits[0, 0]))

    useful = 0
    t0 = time.perf_counter()
    for start in range(0, len(lengths), batch):
        group = list(range(start, min(start + batch, len(lengths))))
        pad = group + [group[-1]] * (batch - len(group))
        cache = init_kv_cache(cfg, batch, max_len)
        pb = jnp.asarray(np.asarray([prompts[i] for i in pad], dtype=np.int32))
        logits, cache = prefill(params, pb, cache)
        tok = jnp.argmax(logits, axis=-1)
        steps = max(lengths[i] for i in group)  # longest member gates
        for _ in range(steps - 1):
            logits, cache = decode_step(params, tok[:, None], cache)
            tok = jnp.argmax(logits, axis=-1)
        float(jax.device_get(logits[0, 0]))  # force completion (tunnel)
        useful += sum(lengths[i] for i in group)
    dt = time.perf_counter() - t0
    return {
        "tokens_per_sec": round(useful / dt, 1),
        "useful_tokens": useful,
        "wall_s": round(dt, 3),
        "batch": batch,
        "padding_tax": round(
            1.0
            - useful
            / sum(
                batch * max(lengths[i] for i in g)
                for g in [
                    list(range(s, min(s + batch, len(lengths))))
                    for s in range(0, len(lengths), batch)
                ]
            ),
            3,
        ),
    }


# -- continuous batching ---------------------------------------------------


def run_continuous(cfg, params, prompt_len, lengths, max_batch=4):
    """Same workload through the paged engine: slots refill the moment a
    sequence finishes, so mixed lengths stop taxing the batch."""
    from ray_tpu.serve.llm import EngineConfig, InferenceEngine

    block_size = 16
    blocks_per_seq = -(-(prompt_len + max(lengths) + 1) // block_size) + 1
    eng = InferenceEngine(
        params,
        cfg,
        EngineConfig(
            block_size=block_size,
            num_blocks=blocks_per_seq * (max_batch + len(lengths)) + 1,
            max_batch=max_batch,
            max_blocks_per_seq=blocks_per_seq,
            max_waiting=len(lengths) + 1,
            stream_timeout_s=600.0,
        ),
        deployment="bench",
    )
    try:
        prompts = _prompts(cfg, prompt_len, len(lengths))
        # compile warmup (prefill bucket + decode step)
        eng.submit(prompts[0], max_new_tokens=2).tokens()
        t0 = time.perf_counter()
        streams = [
            eng.submit(p, max_new_tokens=n)
            for p, n in zip(prompts, lengths)
        ]
        ttfts = []
        useful = 0
        for s in streams:
            useful += len(s.tokens())
            ttfts.append(s.ttft_s)
        dt = time.perf_counter() - t0
    finally:
        eng.shutdown()
    ttfts = sorted(1000.0 * t for t in ttfts if t is not None)
    return {
        "tokens_per_sec": round(useful / dt, 1),
        "useful_tokens": useful,
        "wall_s": round(dt, 3),
        "max_batch": max_batch,
        "ttft_p50_ms": round(ttfts[len(ttfts) // 2], 1) if ttfts else None,
        "ttft_p99_ms": round(ttfts[-1], 1) if ttfts else None,
    }


# -- serve-deployed TTFT (tracing-plane spans via the controller fold) -----


def run_serve_ttft(streams_n=24):
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import TINY_MODEL, llm_deployment
    from ray_tpu.util import state

    ray_tpu.init(
        num_cpus=4,
        ignore_reinit_error=True,
        _system_config={"incident_plane_enabled": True},
    )
    try:
        # the SLO this row feeds: burns against the same ray_tpu_serve_ttft_ms
        # window the controller folds into serve.status()
        state.register_slo(
            "llm-ttft", "deployment_ttft_p99", 5_000.0, severity="WARNING"
        )
        app = llm_deployment(
            TINY_MODEL,
            dict(block_size=16, num_blocks=128, max_batch=4,
                 max_blocks_per_seq=8, max_waiting=64),
            deployment_name="llm",
            health_check_period_s=0.5,
            max_ongoing_requests=64,
        )
        serve.run(app, name="bench-llm")
        h = serve.get_app_handle("bench-llm").options(stream=True)
        prompt = [7, 3, 11, 23, 5, 42, 9, 2]
        list(h.generate.remote(prompt, max_new_tokens=4))  # compile warmup

        def one():
            list(h.generate.remote(prompt, max_new_tokens=16))

        threads = [threading.Thread(target=one) for _ in range(streams_n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        # the controller folds replica stream-TTFT spans on its probe tick
        snap = None
        deadline = time.time() + 20
        while time.time() < deadline:
            snap = serve.status().get("bench-llm", {}).get("llm", {}).get("ttft")
            if snap and snap.get("count", 0) >= streams_n:
                break
            time.sleep(0.25)
        slo_rows = [s for s in state.list_slos() if s.get("name") == "llm-ttft"]
        serve.delete("bench-llm")
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
    if not snap or not snap.get("count"):
        return None
    return {
        "ttft_p50_ms": snap.get("p50"),
        "ttft_p99_ms": snap.get("p99"),
        "folded_streams": snap.get("count"),
        "source": "serve.status() controller fold of replica stream-TTFT spans",
        "slo_registered": bool(slo_rows),
    }


# -- KV saturation ---------------------------------------------------------


def run_saturate(streams_n=100):
    """>= 100 concurrent streams against ONE replica with a small KV pool:
    KV-aware admission must shed typed (DeploymentOverloadedError with
    retry_after) fast, admitted streams complete, nothing fails untyped."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import TINY_MODEL, llm_deployment

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    try:
        app = llm_deployment(
            TINY_MODEL,
            dict(block_size=4, num_blocks=33, max_batch=4,
                 max_blocks_per_seq=8, max_waiting=4, retry_after_s=1.0),
            deployment_name="llm",
            health_check_period_s=0.5,
            # the ENGINE's KV admission is the layer under test
            max_ongoing_requests=4 * streams_n,
        )
        serve.run(app, name="sat-llm")
        h = serve.get_app_handle("sat-llm").options(stream=True)
        prompt = [5, 3, 1, 2, 4, 6]
        list(h.generate.remote(prompt, max_new_tokens=4))  # compile warmup

        counts = {"ok": 0, "shed": 0, "untyped": 0}
        ttfts = []
        lock = threading.Lock()

        def client():
            t0 = time.perf_counter()
            try:
                first_at = None
                n = 0
                for _ in h.generate.remote(prompt, max_new_tokens=8):
                    if first_at is None:
                        first_at = time.perf_counter() - t0
                    n += 1
                with lock:
                    counts["ok" if n == 8 else "untyped"] += 1
                    if first_at is not None:
                        ttfts.append(1000.0 * first_at)
            except serve.DeploymentOverloadedError as e:
                with lock:
                    counts["shed" if getattr(e, "retry_after_s", 0) > 0
                           else "untyped"] += 1
            except Exception:  # noqa: BLE001
                with lock:
                    counts["untyped"] += 1

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client) for _ in range(streams_n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        wall = time.perf_counter() - t0
        serve.delete("sat-llm")
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
    ttfts.sort()
    return {
        "streams": streams_n,
        "ok": counts["ok"],
        "shed_typed": counts["shed"],
        "untyped": counts["untyped"],
        "wall_s": round(wall, 2),
        "admitted_ttft_p99_ms": round(ttfts[-1], 1) if ttfts else None,
    }


# -- driver ----------------------------------------------------------------


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--mode",
        default="all",
        choices=["all", "static", "continuous", "serve", "saturate"],
    )
    ap.add_argument("--saturate-streams", type=int, default=100)
    args = ap.parse_args()

    fp = _fingerprint()
    cfg, prompt_len, lengths = _geometry()
    static = continuous = None

    if args.mode in ("all", "static", "continuous"):
        params = _params(cfg)
    if args.mode in ("all", "static"):
        static = run_static(cfg, params, prompt_len, lengths)
        _append({
            "metric": "lm_decode_static_tokens_per_sec",
            "value": static["tokens_per_sec"],
            "unit": "tokens/s", "mode": "static",
            "fingerprint": fp, "detail": static,
        })
    if args.mode in ("all", "continuous"):
        continuous = run_continuous(cfg, params, prompt_len, lengths)
        _append({
            "metric": "lm_decode_continuous_tokens_per_sec",
            "value": continuous["tokens_per_sec"],
            "unit": "tokens/s", "mode": "continuous",
            "fingerprint": fp, "detail": continuous,
        })
    if static and continuous:
        _append({
            "metric": "lm_decode_continuous_vs_static_floor_ratio",
            "value": round(
                continuous["tokens_per_sec"] / static["tokens_per_sec"], 3
            ),
            "unit": "continuous/static tokens/s (same workload, same host)",
            "floor": 1.0, "mode": "continuous",
            "fingerprint": fp,
        })
    if args.mode in ("all", "serve"):
        ttft = run_serve_ttft()
        if ttft:
            _append({
                "metric": "llm_deployment_ttft_p99_ms",
                "value": ttft["ttft_p99_ms"],
                "unit": "ms", "mode": "continuous",
                "budget": 5000.0,
                "fingerprint": fp, "detail": ttft,
            })
    if args.mode in ("all", "saturate"):
        sat = run_saturate(args.saturate_streams)
        _append({
            "metric": "lm_decode_saturation_untyped_failures",
            "value": sat["untyped"],
            "unit": "failures (must be 0)", "mode": "continuous",
            "budget": 0,
            "fingerprint": fp, "detail": sat,
        })


if __name__ == "__main__":
    main()
