"""LM decode throughput on the attached chip: the Serve north star's shape.

BASELINE.json's serving target is Llama-2-7B batched replicas on v5e.
This measures the in-tree KV-cache decode path (``models/generation.py``)
at the Llama-2-7B geometry (d_model 4096, 32 layers, 32 heads, d_ff 11008,
bf16) with a batch of concurrent sequences per replica.

Prints one JSON line: decode tokens/sec (batch-aggregate) + per-sequence.
"""

from __future__ import annotations

import json
import time


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models.generation import init_kv_cache, make_decode_fns
    from ray_tpu.models.transformer import TransformerConfig, init_params

    backend = jax.default_backend()
    if backend == "tpu":
        # Llama-2-7B geometry; weights bf16 (~13.5 GB) + cache fit 16G HBM
        cfg = TransformerConfig(
            vocab_size=32000,
            d_model=4096,
            n_layers=32,
            n_heads=32,
            d_ff=11008,
            max_seq_len=1024,
            remat=False,
        )
        batch, prompt_len, max_len, steps = 4, 128, 512, 64
    else:
        cfg = TransformerConfig(
            vocab_size=512, d_model=128, n_layers=2, n_heads=4,
            d_ff=256, max_seq_len=128, remat=False,
        )
        batch, prompt_len, max_len, steps = 2, 8, 64, 8

    # jit the init: XLA frees the fp32 sampling intermediates instead of
    # holding a transient fp32 copy of every bf16 tensor (OOM at 7B)
    params = jax.jit(lambda k: init_params(k, cfg))(jax.random.PRNGKey(0))
    prefill, decode_step = make_decode_fns(cfg, max_len)
    cache = init_kv_cache(cfg, batch, max_len)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size - 1, (batch, prompt_len), dtype=np.int32)

    t0 = time.perf_counter()
    logits, cache = prefill(params, jnp.asarray(prompt), cache)
    tok = jnp.argmax(logits, axis=-1)
    float(jax.device_get(logits[0, 0]))  # sync
    prefill_s = time.perf_counter() - t0

    # warm decode compile
    logits, cache = decode_step(params, tok[:, None], cache)
    tok = jnp.argmax(logits, axis=-1)
    float(jax.device_get(logits[0, 0]))

    t0 = time.perf_counter()
    for _ in range(steps):
        logits, cache = decode_step(params, tok[:, None], cache)
        tok = jnp.argmax(logits, axis=-1)
    float(jax.device_get(logits[0, 0]))  # force real completion (tunnel)
    dt = time.perf_counter() - t0

    tok_s = batch * steps / dt
    print(
        json.dumps(
            {
                "metric": "llama2_7b_shape_decode_tokens_per_sec",
                "value": round(tok_s, 1),
                "unit": "tokens/s",
                "detail": {
                    "backend": backend,
                    "batch": batch,
                    "per_seq_tokens_per_sec": round(steps / dt, 2),
                    "decode_step_ms": round(1000 * dt / steps, 2),
                    "prefill_s_128tok": round(prefill_s, 2),
                    "n_params": cfg.num_params(),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
