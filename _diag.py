def main():
    import time
    import numpy as np
    import ray_tpu
    from ray_tpu.util import state as st

    ray_tpu.init(num_cpus=8)

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get([f.remote() for _ in range(20)], timeout=60)
    arr = np.zeros(200 * 1024 // 8)

    def phase_puts(dur):
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < dur:
            ray_tpu.put(arr)

    def phase_getcalls(dur):
        ref = ray_tpu.put(arr)
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < dur:
            ray_tpu.get(ref, timeout=60)

    big = np.zeros(1024 * 1024 * 128 // 8)

    def phase_bigputs(dur):
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < dur:
            r = ray_tpu.put(big)
            del r

    def sync_probe(tag):
        workers = st.list_workers()
        states = {}
        for w in workers:
            states[w["state"]] = states.get(w["state"], 0) + 1
        t0 = time.perf_counter()
        n = 0
        while time.perf_counter() - t0 < 1.0:
            ray_tpu.get(f.remote(), timeout=60)
            n += 1
        print(f"{tag}: {n}/s  workers={states}", flush=True)

    sync_probe("baseline")
    phase_puts(2.0); sync_probe("after put_calls(2s)")
    phase_getcalls(2.0); sync_probe("after get_calls(2s)")
    phase_bigputs(2.0); sync_probe("after big_puts(2s)")
    ray_tpu.shutdown()

if __name__ == "__main__":
    main()
