"""User-facing exception types.

Design parity: ``python/ray/exceptions.py`` — RayError hierarchy (RayTaskError
wrapping the remote traceback, RayActorError, ObjectLostError, OOM, timeouts).
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A remote task raised an exception; carries the remote traceback plus
    its origin: task id, attempt number, node, and executing pid.

    Mirrors ``RayTaskError`` (python/ray/exceptions.py): re-raised at
    ``get()`` with cause chained to the user's original exception, and the
    provenance fields survive pickling (parity: RayTaskError carrying
    proctitle/pid/ip through the object store).
    """

    def __init__(
        self,
        function_name: str,
        traceback_str: str,
        cause: Exception | None = None,
        task_id: str | None = None,
        attempt: int | None = None,
        node_id: str | None = None,
        pid: int | None = None,
    ):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        self.task_id = task_id
        self.attempt = attempt
        self.node_id = node_id
        self.pid = pid
        parts = [
            f"{k}={v}"
            for k, v in (("pid", pid), ("node", node_id), ("attempt", attempt))
            if v is not None
        ]
        where = f" ({', '.join(parts)})" if parts else ""
        super().__init__(f"task {function_name} failed{where}:\n{traceback_str}")

    def _provenance(self) -> tuple:
        return (self.task_id, self.attempt, self.node_id, self.pid)

    def __reduce__(self):
        return (
            TaskError,
            (self.function_name, self.traceback_str, self.cause)
            + self._provenance(),
        )

    def as_instanceof_cause(self):
        """Return an exception that is both a TaskError and the cause's type."""
        if self.cause is None:
            return self
        cause_cls = type(self.cause)
        if cause_cls in (TaskError, ActorDiedError):
            return self
        try:
            class _Wrapped(TaskError, cause_cls):  # noqa: N801
                def __init__(self, inner):
                    self._inner = inner
                    TaskError.__init__(
                        self,
                        inner.function_name,
                        inner.traceback_str,
                        inner.cause,
                        *inner._provenance(),
                    )

                def __str__(self):
                    return TaskError.__str__(self._inner)

                def __reduce__(self):
                    return (
                        _rebuild_task_error,
                        (self.function_name, self.traceback_str, self.cause)
                        + self._provenance(),
                    )

            _Wrapped.__name__ = cause_cls.__name__
            _Wrapped.__qualname__ = cause_cls.__qualname__
            return _Wrapped(self)
        except TypeError:
            return self


def _rebuild_task_error(
    function_name,
    traceback_str,
    cause,
    task_id=None,
    attempt=None,
    node_id=None,
    pid=None,
):
    return TaskError(
        function_name, traceback_str, cause, task_id, attempt, node_id, pid
    ).as_instanceof_cause()


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ActorDiedError(RayTpuError):
    """The actor is dead; pending and future calls fail with this.

    ``task_started`` is the scheduler's started-marker for the failed call:
    ``False`` means the call provably never reached a worker (still queued
    in the actor mailbox, or submitted after death) and is safe to retry;
    ``True`` means it had been dispatched for execution; ``None`` means the
    scheduler could not tell. Serve's replica failover keys off this.
    """

    def __init__(
        self,
        actor_id=None,
        reason: str = "actor died",
        task_started: bool | None = None,
    ):
        self.actor_id = actor_id
        self.reason = reason
        self.task_started = task_started
        super().__init__(reason)

    def __reduce__(self):
        # default Exception pickling would rebuild from args=(reason,),
        # shifting reason into actor_id and dropping the started-marker
        return (ActorDiedError, (self.actor_id, self.reason, self.task_started))


class ActorUnavailableError(RayTpuError):
    """The actor is temporarily unreachable (restarting)."""


class ObjectLostError(RayTpuError):
    """Object was evicted/lost and could not be reconstructed."""


class ObjectTransferStalledError(RayTpuError):
    """An in-flight inter-node transfer made no chunk progress for the
    configured window (``transfer_coverage_timeout_s``). Carries the link
    and coverage provenance so a relay stall names its transfer instead of
    surfacing as a generic fetch failure (transfer-plane observability)."""

    def __init__(
        self,
        message: str = "",
        *,
        object_id: str | None = None,
        link: str | None = None,
        covered_bytes: int | None = None,
        total_bytes: int | None = None,
        waited_s: float | None = None,
    ):
        self.object_id = object_id
        self.link = link
        self.covered_bytes = covered_bytes
        self.total_bytes = total_bytes
        self.waited_s = waited_s
        parts = [
            f"{k}={v}"
            for k, v in (
                ("object", object_id),
                ("link", link),
                ("covered", covered_bytes),
                ("total", total_bytes),
                ("waited_s", None if waited_s is None else round(waited_s, 3)),
            )
            if v is not None
        ]
        where = f" ({', '.join(parts)})" if parts else ""
        super().__init__((message or "object transfer stalled") + where)

    def __reduce__(self):
        return (
            _rebuild_transfer_stalled,
            (
                self.args[0] if self.args else "",
                self.object_id,
                self.link,
                self.covered_bytes,
                self.total_bytes,
                self.waited_s,
            ),
        )


def _rebuild_transfer_stalled(msg, object_id, link, covered, total, waited):
    err = ObjectTransferStalledError.__new__(ObjectTransferStalledError)
    RayTpuError.__init__(err, msg)
    err.object_id = object_id
    err.link = link
    err.covered_bytes = covered
    err.total_bytes = total
    err.waited_s = waited
    return err


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get()`` exceeded its timeout."""


class OutOfMemoryError(RayTpuError):
    """Task/actor was killed by the memory monitor."""


class ObjectStoreFullError(RayTpuError):
    """The object store is full and nothing could be evicted/spilled."""


class RuntimeEnvSetupError(RayTpuError):
    """Creating the runtime environment for a task/actor failed."""


class PendingCallsLimitExceeded(RayTpuError):
    """Back-pressure limit on an actor's pending call queue was reached."""


class CrossSliceTransferError(RayTpuError):
    """A device-to-device transfer across TPU slices failed (DCN path)."""


class JobAdmissionError(RayTpuError):
    """Admission control rejected the job submission (quota exceeded or
    admission queue full). The cluster never saw the job's tasks."""


class PreemptedError(RayTpuError):
    """The task's worker was killed by priority preemption; the attempt
    re-queued without spending the retry budget."""
