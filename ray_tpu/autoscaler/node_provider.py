"""Node providers.

Parity: ``python/ray/autoscaler/node_provider.py`` (NodeProvider plugin
surface: ``create_node`` / ``terminate_node`` / ``non_terminated_nodes``) with
the fake multi-node provider for tests
(``autoscaler/_private/fake_multi_node``) and a TPU-VM provider skeleton
covering the reference's GCP TPU support (``gcp/tpu.yaml``,
``tpu_command_runner.py``).
"""

from __future__ import annotations

import time
import uuid
from typing import Dict, List, Optional


class NodeProvider:
    def create_node(self, node_type: str, resources: Dict[str, float]) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[dict]:
        """[{node_id, node_type, resources, launched_at}]"""
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Creates virtual nodes in the live cluster (workers are real processes)."""

    def __init__(self):
        self._nodes: Dict[str, dict] = {}

    def create_node(self, node_type: str, resources: Dict[str, float]) -> str:
        from ray_tpu._private.worker import get_driver

        driver = get_driver()
        res = dict(resources)
        num_cpus = res.pop("CPU", 1.0)
        num_tpus = res.pop("TPU", 0.0)
        nid = driver.node.add_virtual_node(
            num_cpus=num_cpus, num_tpus=num_tpus, resources=res
        )
        node_id = nid.hex()
        self._nodes[node_id] = {
            "node_id": node_id,
            "node_type": node_type,
            "resources": dict(resources),
            "launched_at": time.time(),
            "_internal_id": nid,
        }
        return node_id

    def terminate_node(self, node_id: str) -> None:
        info = self._nodes.pop(node_id, None)
        if info is None:
            return
        from ray_tpu._private.worker import get_driver

        get_driver().node.remove_virtual_node(info["_internal_id"])

    def non_terminated_nodes(self) -> List[dict]:
        return [
            {k: v for k, v in n.items() if k != "_internal_id"}
            for n in self._nodes.values()
        ]


class LocalDaemonNodeProvider(NodeProvider):
    """Launches REAL node-daemon processes on this machine.

    Parity: the reference tests its autoscaler against
    ``fake_multi_node/node_provider.py`` — which starts *real raylet
    processes*; this is the same idea on this framework's raylet
    (``_private/raylet.py``): scale-up spawns a daemon that registers with
    the head over the socket plane, scale-down SIGTERMs it (the head sees
    the socket drop and removes the node)."""

    def __init__(self):
        self._procs: Dict[str, object] = {}
        self._nodes: Dict[str, dict] = {}

    def create_node(self, node_type: str, resources: Dict[str, float]) -> str:
        from ray_tpu._private.worker import get_driver
        from ray_tpu.cluster_utils import spawn_daemon_process

        res = dict(resources)
        num_cpus = res.pop("CPU", 1.0)
        num_tpus = res.pop("TPU", 0.0)
        proc, node_id = spawn_daemon_process(
            get_driver(),
            num_cpus=num_cpus,
            num_tpus=num_tpus,
            resources=res,
            labels={"autoscaler-node-type": node_type},
        )
        self._procs[node_id] = proc
        self._nodes[node_id] = {
            "node_id": node_id,
            "node_type": node_type,
            "resources": dict(resources),
            "launched_at": time.time(),
        }
        return node_id

    def terminate_node(self, node_id: str) -> None:
        proc = self._procs.pop(node_id, None)
        self._nodes.pop(node_id, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except Exception:
                proc.kill()

    def non_terminated_nodes(self) -> List[dict]:
        return [
            n
            for nid, n in self._nodes.items()
            if self._procs.get(nid) is not None and self._procs[nid].poll() is None
        ]


class TPUVMNodeProvider(NodeProvider):
    """TPU-VM (GCE) provider skeleton.

    Issues ``gcloud compute tpus tpu-vm`` commands (create/delete/list) —
    slice-granular: one "node" here is one pod slice (indivisible across
    jobs, SURVEY.md §7 step 4). Requires gcloud credentials on the head;
    raises a clear error when unavailable instead of silently no-oping.
    """

    def __init__(self, project: str, zone: str, version: str = "tpu-ubuntu2204-base"):
        self.project = project
        self.zone = zone
        self.version = version
        self._nodes: Dict[str, dict] = {}

    def _gcloud(self, *args: str) -> str:
        import subprocess

        cmd = ["gcloud", "compute", "tpus", "tpu-vm", *args,
               f"--project={self.project}", f"--zone={self.zone}", "--format=json"]
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(f"gcloud failed: {proc.stderr[-2000:]}")
        return proc.stdout

    def create_node(self, node_type: str, resources: Dict[str, float]) -> str:
        # node_type is the accelerator type, e.g. "v5litepod-16"
        name = f"ray-tpu-{node_type}-{uuid.uuid4().hex[:6]}"
        self._gcloud(
            "create", name,
            f"--accelerator-type={node_type}",
            f"--version={self.version}",
        )
        self._nodes[name] = {
            "node_id": name,
            "node_type": node_type,
            "resources": dict(resources),
            "launched_at": time.time(),
        }
        return name

    def terminate_node(self, node_id: str) -> None:
        self._gcloud("delete", node_id, "--quiet")
        self._nodes.pop(node_id, None)

    def non_terminated_nodes(self) -> List[dict]:
        return list(self._nodes.values())
