"""Node providers.

Parity: ``python/ray/autoscaler/node_provider.py`` (NodeProvider plugin
surface: ``create_node`` / ``terminate_node`` / ``non_terminated_nodes``) with
the fake multi-node provider for tests
(``autoscaler/_private/fake_multi_node``) and a TPU-VM provider skeleton
covering the reference's GCP TPU support (``gcp/tpu.yaml``,
``tpu_command_runner.py``).
"""

from __future__ import annotations

import time
import uuid
from typing import Dict, List, Optional


class NodeProvider:
    def create_node(self, node_type: str, resources: Dict[str, float]) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[dict]:
        """[{node_id, node_type, resources, launched_at}]"""
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Creates virtual nodes in the live cluster (workers are real processes)."""

    def __init__(self):
        self._nodes: Dict[str, dict] = {}

    def create_node(self, node_type: str, resources: Dict[str, float]) -> str:
        from ray_tpu._private.worker import get_driver

        driver = get_driver()
        res = dict(resources)
        num_cpus = res.pop("CPU", 1.0)
        num_tpus = res.pop("TPU", 0.0)
        nid = driver.node.add_virtual_node(
            num_cpus=num_cpus, num_tpus=num_tpus, resources=res
        )
        node_id = nid.hex()
        self._nodes[node_id] = {
            "node_id": node_id,
            "node_type": node_type,
            "resources": dict(resources),
            "launched_at": time.time(),
            "_internal_id": nid,
        }
        return node_id

    def terminate_node(self, node_id: str) -> None:
        info = self._nodes.pop(node_id, None)
        if info is None:
            return
        from ray_tpu._private.worker import get_driver

        get_driver().node.remove_virtual_node(info["_internal_id"])

    def non_terminated_nodes(self) -> List[dict]:
        return [
            {k: v for k, v in n.items() if k != "_internal_id"}
            for n in self._nodes.values()
        ]


class LocalDaemonNodeProvider(NodeProvider):
    """Launches REAL node-daemon processes on this machine.

    Parity: the reference tests its autoscaler against
    ``fake_multi_node/node_provider.py`` — which starts *real raylet
    processes*; this is the same idea on this framework's raylet
    (``_private/raylet.py``): scale-up spawns a daemon that registers with
    the head over the socket plane, scale-down SIGTERMs it (the head sees
    the socket drop and removes the node)."""

    def __init__(self):
        self._procs: Dict[str, object] = {}
        self._nodes: Dict[str, dict] = {}

    def create_node(self, node_type: str, resources: Dict[str, float]) -> str:
        from ray_tpu._private.worker import get_driver
        from ray_tpu.cluster_utils import spawn_daemon_process

        res = dict(resources)
        num_cpus = res.pop("CPU", 1.0)
        num_tpus = res.pop("TPU", 0.0)
        proc, node_id = spawn_daemon_process(
            get_driver(),
            num_cpus=num_cpus,
            num_tpus=num_tpus,
            resources=res,
            labels={"autoscaler-node-type": node_type},
        )
        self._procs[node_id] = proc
        self._nodes[node_id] = {
            "node_id": node_id,
            "node_type": node_type,
            "resources": dict(resources),
            "launched_at": time.time(),
        }
        return node_id

    def terminate_node(self, node_id: str) -> None:
        proc = self._procs.pop(node_id, None)
        self._nodes.pop(node_id, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except Exception:
                proc.kill()

    def non_terminated_nodes(self) -> List[dict]:
        return [
            n
            for nid, n in self._nodes.items()
            if self._procs.get(nid) is not None and self._procs[nid].poll() is None
        ]


class TPUVMNodeProvider(NodeProvider):
    """TPU-VM (GCE) provider.

    Issues ``gcloud compute tpus tpu-vm`` commands (create/delete/list) —
    slice-granular: one "node" here is one pod slice (indivisible across
    jobs, SURVEY.md §7 step 4). Requires gcloud credentials on the head;
    raises a clear error when unavailable instead of silently no-oping.

    State discipline (parity: the reference's GCP provider reconciling
    against the cloud, ``autoscaler/_private/gcp/node_provider.py``):

    * ``non_terminated_nodes`` RECONCILES against ``gcloud ... list`` —
      slices this provider forgot (head crash) are re-adopted by their
      cluster label, and slices the cloud no longer reports are dropped.
      The list is cached for ``list_cache_s`` to spare the API.
    * the slice table is mirrored into the cluster KV (which rides the GCS
      snapshot), so a restarted head sees its billable slices even before
      the first reconcile completes.
    """

    _KV_NS = "autoscaler"
    _KV_KEY = b"tpu_vm_nodes"

    def __init__(
        self,
        project: str,
        zone: str,
        version: str = "tpu-ubuntu2204-base",
        cluster_name: str = "default",
        list_cache_s: float = 10.0,
    ):
        self.project = project
        self.zone = zone
        self.version = version
        self.cluster_name = cluster_name
        self.list_cache_s = list_cache_s
        self._nodes: Dict[str, dict] = self._load_kv()
        self._last_list = 0.0

    # -- seams (tests monkeypatch _run_gcloud) -----------------------------

    def _run_gcloud(self, *args: str) -> str:
        import subprocess

        cmd = ["gcloud", "compute", "tpus", "tpu-vm", *args,
               f"--project={self.project}", f"--zone={self.zone}", "--format=json"]
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(f"gcloud failed: {proc.stderr[-2000:]}")
        return proc.stdout

    def _kv_rpc(self, op: str, *args):
        try:
            from ray_tpu._private.worker import get_runtime

            return get_runtime().rpc(op, *args)
        except Exception:
            return None  # no cluster attached (unit use): KV mirror off

    def _save_kv(self) -> None:
        import pickle

        self._kv_rpc(
            "kv_put", self._KV_NS, self._KV_KEY, pickle.dumps(self._nodes), True
        )

    def _load_kv(self) -> Dict[str, dict]:
        import pickle

        blob = self._kv_rpc("kv_get", self._KV_NS, self._KV_KEY)
        if blob:
            try:
                return dict(pickle.loads(blob))
            except Exception:
                return {}
        return {}

    # -- provider API ------------------------------------------------------

    def create_node(self, node_type: str, resources: Dict[str, float]) -> str:
        # node_type is the accelerator type, e.g. "v5litepod-16"
        name = f"ray-tpu-{node_type}-{uuid.uuid4().hex[:6]}"
        self._run_gcloud(
            "create", name,
            f"--accelerator-type={node_type}",
            f"--version={self.version}",
            f"--labels=ray-tpu-cluster={self.cluster_name}",
        )
        self._nodes[name] = {
            "node_id": name,
            "node_type": node_type,
            "resources": dict(resources),
            "launched_at": time.time(),
        }
        self._save_kv()
        return name

    def terminate_node(self, node_id: str) -> None:
        self._run_gcloud("delete", node_id, "--quiet")
        self._nodes.pop(node_id, None)
        self._save_kv()

    def _reconcile(self) -> None:
        import json

        try:
            raw = self._run_gcloud("list")
        except Exception:
            return  # transient API failure: keep the last known table
        try:
            listed = json.loads(raw) if raw.strip() else []
        except ValueError:
            return
        live: Dict[str, dict] = {}
        for entry in listed:
            name = str(entry.get("name", "")).rsplit("/", 1)[-1]
            labels = entry.get("labels") or {}
            if labels.get("ray-tpu-cluster") != self.cluster_name:
                continue
            state = str(entry.get("state", "")).upper()
            if state in ("DELETING", "TERMINATED", "PREEMPTED"):
                continue
            known = self._nodes.get(name)
            accel = str(entry.get("acceleratorType", "")).rsplit("/", 1)[-1]
            live[name] = known or {
                # a slice this provider forgot (head crash before the KV
                # mirror landed): re-adopt it — it is billable either way
                "node_id": name,
                "node_type": accel,
                "resources": {},
                "launched_at": time.time(),
                "adopted": True,
            }
        if live != self._nodes:
            self._nodes = live
            self._save_kv()

    def non_terminated_nodes(self) -> List[dict]:
        now = time.time()
        if now - self._last_list >= self.list_cache_s:
            self._last_list = now
            self._reconcile()
        return list(self._nodes.values())
