"""The reconciler: demand in, launch/terminate decisions out.

Parity: ``StandardAutoscaler.update`` (``autoscaler.py:172,374``) +
``resource_demand_scheduler.py`` bin-packing, restructured as the v2
reconciler: each ``update()`` computes a target node set from (per-shape
scheduler backlog, current nodes, min/max bounds, idle timeout) and drives
the provider toward it.

Inputs come from the scheduler's sharded ready queue via the
``backlog_summary`` rpc (shape -> queued/leased/node_backlog counts) — the
head never has to enumerate a million-deep queue to answer "what can't I
place". ``ClusterStateSource`` is the seam: unit tests substitute a fake
that feeds synthetic backlog ramps without a live cluster.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider


@dataclass
class NodeType:
    name: str
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10


@dataclass
class AutoscalerConfig:
    node_types: List[NodeType] = field(default_factory=list)
    idle_timeout_s: float = 60.0
    upscaling_speed: float = 1.0  # max new nodes per update = max(1, speed * current)
    # a shape's backlog (queued + node-queued) must reach this depth before
    # it contributes scale-up demand; 1 = any queued task scales
    scale_up_backlog_threshold: int = 1
    # scale-down candidates must be at/below this utilization fraction
    scale_down_util_floor: float = 0.0
    # no-flap hysteresis: after any launch, terminations are suppressed for
    # this long so a sawtooth backlog can't thrash nodes up and down
    scale_down_cooldown_s: float = 30.0
    # bound on demand entries expanded per shape for the bin-pack pass (a
    # million-task backlog saturates every max_workers bound long before it)
    max_demand_per_shape: int = 1024


class ClusterStateSource:
    """Live-cluster inputs for the reconciler. Tests fake this seam."""

    def backlog(self) -> dict:
        """The scheduler's per-shape backlog summary:
        ``{"shapes": [{"shape", "queued", "leased", "node_backlog"}],
        "pg_pending": [bundle, ...]}``."""
        from ray_tpu._private.worker import get_driver

        return get_driver().scheduler_rpc("backlog_summary", ())

    def utilization(self) -> Dict[str, float]:
        """node_id -> max resource utilization fraction."""
        import ray_tpu

        out = {}
        for n in ray_tpu.nodes():
            if not n["alive"]:
                continue
            fracs = [
                1.0 - n["available"].get(k, 0.0) / t
                for k, t in n["total"].items()
                if t > 0
            ]
            out[n["node_id"]] = max(fracs) if fracs else 0.0
        return out

    def record_decision(self, dec: dict) -> None:
        """Ship one reconcile decision to the head's flight recorder
        (``state.list_decisions`` / ``ray_tpu decisions``). Best-effort:
        an unreachable head must never break the reconcile loop."""
        try:
            from ray_tpu._private.worker import get_driver

            get_driver().scheduler_rpc("record_decision", (dec,))
        except Exception:
            pass


def _shape_fits(shape: Dict[str, float], resources: Dict[str, float]) -> bool:
    return all(resources.get(k, 0.0) >= v for k, v in shape.items())


class Autoscaler:
    def __init__(
        self,
        config: AutoscalerConfig,
        provider: NodeProvider,
        state: Optional[ClusterStateSource] = None,
    ):
        self.config = config
        self.provider = provider
        self.state = state if state is not None else ClusterStateSource()
        self._idle_since: Dict[str, float] = {}
        self._last_scale_up = float("-inf")

    # -- inputs ------------------------------------------------------------

    def _demand_entries(self, backlog: dict) -> List[Dict[str, float]]:
        """Expand the per-shape backlog into bin-packable demand entries,
        thresholded and bounded."""
        threshold = max(1, int(self.config.scale_up_backlog_threshold))
        cap = max(1, int(self.config.max_demand_per_shape))
        demand: List[Dict[str, float]] = []
        for row in backlog.get("shapes", ()):
            shape = row.get("shape") or {}
            if not shape:
                continue
            pressure = int(row.get("queued", 0)) + int(row.get("node_backlog", 0))
            if pressure < threshold:
                continue
            demand.extend(dict(shape) for _ in range(min(pressure, cap)))
        demand.extend(dict(b) for b in backlog.get("pg_pending", ()) if b)
        return demand

    @staticmethod
    def _backlogged_shapes(backlog: dict) -> List[Dict[str, float]]:
        out = [
            row["shape"]
            for row in backlog.get("shapes", ())
            if row.get("shape")
            and int(row.get("queued", 0)) + int(row.get("node_backlog", 0)) > 0
        ]
        out.extend(b for b in backlog.get("pg_pending", ()) if b)
        return out

    # -- reconcile ---------------------------------------------------------

    def update(self) -> Dict[str, int]:
        """One reconcile pass; returns {launched: n, terminated: m}."""
        try:
            backlog = self.state.backlog() or {}
        except Exception:
            backlog = {}
        demand = self._demand_entries(backlog)
        nodes = self.provider.non_terminated_nodes()
        by_type: Dict[str, List[dict]] = {}
        for n in nodes:
            by_type.setdefault(n["node_type"], []).append(n)

        launched = 0
        terminated = 0
        now = time.monotonic()
        # decision flight recorder: why this pass did (or didn't) scale
        reasons: List[str] = []

        # 1. satisfy min_workers
        for nt in self.config.node_types:
            have = len(by_type.get(nt.name, []))
            while have < nt.min_workers:
                self.provider.create_node(nt.name, nt.resources)
                have += 1
                launched += 1
        if launched:
            reasons.append("min_workers")

        # 2. bin-pack backlog demand onto hypothetical new nodes
        to_launch: Dict[str, int] = {}
        remaining = [dict(d) for d in demand if d]
        for nt in self.config.node_types:
            base = len(by_type.get(nt.name, []))
            while remaining and base + to_launch.get(nt.name, 0) < nt.max_workers:
                # greedily fill one hypothetical node of this type
                free = dict(nt.resources)
                packed = []
                for d in remaining:
                    if all(free.get(k, 0.0) >= v for k, v in d.items()):
                        for k, v in d.items():
                            free[k] -= v
                        packed.append(d)
                if not packed:
                    break
                for d in packed:
                    remaining.remove(d)
                to_launch[nt.name] = to_launch.get(nt.name, 0) + 1
        cap = max(1, int(self.config.upscaling_speed * max(1, len(nodes))))
        for name, count in to_launch.items():
            nt = next(t for t in self.config.node_types if t.name == name)
            for _ in range(min(count, cap)):
                self.provider.create_node(nt.name, nt.resources)
                launched += 1
            if count > cap:
                reasons.append("upscaling_speed_cap")
        if to_launch:
            reasons.append("backlog_demand")
        if launched:
            self._last_scale_up = now

        # 3. idle-drain scale-down beyond min_workers. Hysteresis: fresh
        # launches suppress terminations for scale_down_cooldown_s, and a
        # node whose resources could serve any still-backlogged shape is
        # never a candidate — queue pressure keeps the fleet up.
        try:
            util = self.state.utilization()
        except Exception:
            util = {}
        backlogged = self._backlogged_shapes(backlog)
        floor = self.config.scale_down_util_floor
        cooldown_active = now - self._last_scale_up < self.config.scale_down_cooldown_s
        for nt in self.config.node_types:
            current = self.provider.non_terminated_nodes()
            mine = [n for n in current if n["node_type"] == nt.name]
            serves_backlog = any(
                _shape_fits(shape, nt.resources) for shape in backlogged
            )
            for n in mine:
                nid = n["node_id"]
                if util.get(nid, 0.0) <= floor and not serves_backlog:
                    self._idle_since.setdefault(nid, now)
                else:
                    self._idle_since.pop(nid, None)
            if cooldown_active or serves_backlog:
                # drain suppressed: attribute the no-op so flapping (or the
                # absence of an expected drain) is explainable after the fact
                if any(n["node_id"] in self._idle_since for n in mine):
                    reasons.append(
                        "cooldown_active" if cooldown_active else "serves_backlog"
                    )
                continue
            idle_long = [
                n
                for n in mine
                if n["node_id"] in self._idle_since
                and now - self._idle_since[n["node_id"]]
                >= self.config.idle_timeout_s
            ]
            removable = len(mine) - nt.min_workers
            for n in idle_long[: max(0, removable)]:
                self.provider.terminate_node(n["node_id"])
                self._idle_since.pop(n["node_id"], None)
                terminated += 1
        if terminated:
            reasons.append("idle_timeout")

        # record a decision whenever there was something to explain — an
        # action taken, demand seen, or a drain explicitly suppressed. Pure
        # no-op passes stay out of the (bounded) ring.
        if launched or terminated or demand or reasons:
            rec = getattr(self.state, "record_decision", None)
            if rec is not None:
                try:
                    rec(
                        {
                            "kind": "autoscaler",
                            "demand": len(demand),
                            "backlog_shapes": len(
                                self._backlogged_shapes(backlog)
                            ),
                            "to_launch": dict(to_launch),
                            "launched": launched,
                            "terminated": terminated,
                            "reasons": sorted(set(reasons)),
                        }
                    )
                except Exception:
                    pass

        return {"launched": launched, "terminated": terminated}
