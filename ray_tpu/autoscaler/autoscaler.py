"""The reconciler: demand in, launch/terminate decisions out.

Parity: ``StandardAutoscaler.update`` (``autoscaler.py:172,374``) +
``resource_demand_scheduler.py`` bin-packing, restructured as the v2
reconciler: each ``update()`` computes a target node set from (pending
demand, current nodes, min/max bounds, idle timeout) and drives the provider
toward it.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider


@dataclass
class NodeType:
    name: str
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10


@dataclass
class AutoscalerConfig:
    node_types: List[NodeType] = field(default_factory=list)
    idle_timeout_s: float = 60.0
    upscaling_speed: float = 1.0  # max new nodes per update = max(1, speed * current)


class Autoscaler:
    def __init__(self, config: AutoscalerConfig, provider: NodeProvider):
        self.config = config
        self.provider = provider
        self._idle_since: Dict[str, float] = {}

    # -- inputs ------------------------------------------------------------

    def _pending_demand(self) -> List[Dict[str, float]]:
        from ray_tpu._private.worker import get_driver

        return get_driver().scheduler_rpc("pending_demand", ())

    def _node_utilization(self) -> Dict[str, float]:
        """node_id -> max resource utilization fraction."""
        import ray_tpu

        out = {}
        for n in ray_tpu.nodes():
            if not n["alive"]:
                continue
            fracs = [
                1.0 - n["available"].get(k, 0.0) / t
                for k, t in n["total"].items()
                if t > 0
            ]
            out[n["node_id"]] = max(fracs) if fracs else 0.0
        return out

    # -- reconcile ---------------------------------------------------------

    def update(self) -> Dict[str, int]:
        """One reconcile pass; returns {launched: n, terminated: m}."""
        demand = self._pending_demand()
        nodes = self.provider.non_terminated_nodes()
        by_type: Dict[str, List[dict]] = {}
        for n in nodes:
            by_type.setdefault(n["node_type"], []).append(n)

        launched = 0
        terminated = 0

        # 1. satisfy min_workers
        for nt in self.config.node_types:
            have = len(by_type.get(nt.name, []))
            while have < nt.min_workers:
                self.provider.create_node(nt.name, nt.resources)
                have += 1
                launched += 1

        # 2. bin-pack unplaced demand onto hypothetical new nodes
        to_launch: Dict[str, int] = {}
        remaining = [dict(d) for d in demand if d]
        for nt in self.config.node_types:
            base = len(by_type.get(nt.name, []))
            while remaining and base + to_launch.get(nt.name, 0) < nt.max_workers:
                # greedily fill one hypothetical node of this type
                free = dict(nt.resources)
                packed = []
                for d in remaining:
                    if all(free.get(k, 0.0) >= v for k, v in d.items()):
                        for k, v in d.items():
                            free[k] -= v
                        packed.append(d)
                if not packed:
                    break
                for d in packed:
                    remaining.remove(d)
                to_launch[nt.name] = to_launch.get(nt.name, 0) + 1
        cap = max(1, int(self.config.upscaling_speed * max(1, len(nodes))))
        for name, count in to_launch.items():
            nt = next(t for t in self.config.node_types if t.name == name)
            for _ in range(min(count, cap)):
                self.provider.create_node(nt.name, nt.resources)
                launched += 1

        # 3. terminate idle nodes beyond min_workers
        util = self._node_utilization()
        now = time.monotonic()
        for nt in self.config.node_types:
            current = self.provider.non_terminated_nodes()
            mine = [n for n in current if n["node_type"] == nt.name]
            for n in mine:
                nid = n["node_id"]
                if util.get(nid, 0.0) <= 0.0:
                    self._idle_since.setdefault(nid, now)
                else:
                    self._idle_since.pop(nid, None)
            idle_long = [
                n
                for n in mine
                if now - self._idle_since.get(n["node_id"], now)
                >= self.config.idle_timeout_s
            ]
            removable = len(mine) - nt.min_workers
            for n in idle_long[: max(0, removable)]:
                self.provider.terminate_node(n["node_id"])
                self._idle_since.pop(n["node_id"], None)
                terminated += 1

        return {"launched": launched, "terminated": terminated}
