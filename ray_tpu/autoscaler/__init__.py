"""Autoscaler (v2-reconciler style).

Parity: ``python/ray/autoscaler/v2`` — ``Autoscaler`` (``v2/autoscaler.py:42``)
reading cluster state + pending demand from the control plane, a ``Scheduler``
bin-packing demand onto node types, an instance manager driving a
``NodeProvider`` plugin. Providers: a fake in-process provider (parity:
``fake_multi_node``, used by the tests) and a TPU-VM provider skeleton (the
GCE surface of ``autoscaler/gcp/tpu_command_runner.py``); slice-atomicity:
TPU node types scale in whole slices.
"""

from ray_tpu.autoscaler.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    ClusterStateSource,
    NodeType,
)
from ray_tpu.autoscaler.node_provider import (
    FakeNodeProvider,
    LocalDaemonNodeProvider,
    NodeProvider,
)

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "ClusterStateSource",
    "NodeType",
    "NodeProvider",
    "FakeNodeProvider",
    "LocalDaemonNodeProvider",
]
