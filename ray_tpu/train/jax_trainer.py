"""JaxTrainer: the DataParallelTrainer equivalent for TPU.

Parity: ``DataParallelTrainer`` (``python/ray/train/data_parallel_trainer.py:25``)
+ ``TorchTrainer`` fit path (SURVEY.md §3.4). Differences by design:

* within one host no process-group rendezvous is needed — the train loop
  builds a mesh and jits; collectives are in-program over ICI. For a mesh
  *spanning* hosts, ``ScalingConfig(use_jax_distributed=True)`` makes each
  worker join a ``jax.distributed`` coordination service (rendezvous over
  the cluster KV) before the user loop runs — the TPU-native replacement
  for ``_setup_torch_process_group`` (``torch/config.py:65``);
* ``ScalingConfig(topology=...)`` turns into a slice-aware placement group;
* checkpoints are orbax pytrees behind the same dir-of-files ``Checkpoint``.
"""

from __future__ import annotations

import os
import time
import uuid
from typing import Any, Callable, Dict, Optional

from ray_tpu.train._backend_executor import BackendExecutor
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._config import RunConfig, ScalingConfig
from ray_tpu.train._result import Result


def _retry_backoff(attempt: int, fail_cfg) -> float:
    """Delay before gang-restart ``attempt`` (1-based): exponential from
    ``retry_backoff_s`` capped at ``retry_backoff_max_s``, with +/-
    ``retry_backoff_jitter`` fraction of randomization so crash-looping
    gangs desynchronize instead of hammering the scheduler in lockstep."""
    import random

    base = max(0.0, fail_cfg.retry_backoff_s)
    delay = base * (2 ** max(0, attempt - 1))
    jitter = min(1.0, max(0.0, fail_cfg.retry_backoff_jitter))
    if jitter:
        delay *= 1.0 + random.uniform(-jitter, jitter)
    # the cap is applied LAST: retry_backoff_max_s is a hard bound an
    # operator can rely on, jitter included
    return max(0.0, min(fail_cfg.retry_backoff_max_s, delay))


def _setup_jax_distributed(rendezvous_key: str) -> bool:
    """Join the jax.distributed coordination service (backend ``on_start``).

    Rank 0 publishes ``ip:port`` through the cluster KV; every worker calls
    ``jax.distributed.initialize`` against it. Afterwards ``jax.devices()``
    is the global device set across the worker group.
    """
    from ray_tpu._private.worker import get_runtime
    from ray_tpu.parallel import distributed as dist
    from ray_tpu.train._session import get_context
    from ray_tpu.train.jax_utils import ensure_platform
    from ray_tpu.train.torch_trainer import _node_ip

    ctx = get_context()
    rank, world = ctx.get_world_rank(), ctx.get_world_size()
    if world <= 1:
        return False
    ensure_platform()
    rt = get_runtime()
    coord = dist.rendezvous_via_kv(
        rt, rendezvous_key, rank, world, node_ip=_node_ip()
    )
    dist.initialize(coord, num_processes=world, process_id=rank)
    return True


def _teardown_jax_distributed(rendezvous_key: str) -> None:
    from ray_tpu._private.worker import get_runtime
    from ray_tpu.parallel import distributed as dist
    from ray_tpu.train._session import get_context

    try:
        # best-effort: rank 0 finishing first tears down the coordination
        # service, so a slower rank's shutdown may raise — that must never
        # overwrite a successful training result
        dist.shutdown()
    except Exception:
        pass
    try:
        if get_context().get_world_rank() == 0:
            dist.release_rendezvous(get_runtime(), rendezvous_key)
    except Exception:
        pass


class JaxTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.train_loop = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint
        if self.scaling_config.use_jax_distributed:
            self.train_loop = self._wrap_distributed(train_loop_per_worker)

    @staticmethod
    def _wrap_distributed(user_fn: Callable) -> Callable:
        base_key = f"jaxdist_{uuid.uuid4().hex[:12]}"

        def wrapped(config=None):
            import inspect

            # fit() injects a per-attempt suffix so a retry never rendezvous
            # against the dead coordinator a failed attempt left in the KV
            if isinstance(config, dict):
                key = f"{base_key}_{config.pop('__jaxdist_attempt__', 0)}"
            else:
                key = base_key
            joined = _setup_jax_distributed(key)
            try:
                if config is not None and len(inspect.signature(user_fn).parameters):
                    return user_fn(config)
                return user_fn()
            finally:
                if joined:
                    _teardown_jax_distributed(key)

        return wrapped

    def fit(self) -> Result:
        from ray_tpu.train import checkpointing

        name = self.run_config.name or f"JaxTrainer_{time.strftime('%Y%m%d_%H%M%S')}"
        # external storage: train into a local staging dir, mirror each
        # checkpoint out through the commit protocol (parity: the
        # reference's storage_path sync to FS/S3)
        trial_dir, storage_uri = checkpointing.resolve_staging(
            self.run_config.resolved_storage_path(), name, kind="trial"
        )
        os.makedirs(trial_dir, exist_ok=True)

        ckpt_cfg = self.run_config.checkpoint_config
        manager = checkpointing.CheckpointManager(
            trial_dir,
            storage_uri=storage_uri,
            world_size=self.scaling_config.num_workers,
            keep=ckpt_cfg.num_to_keep,
            run_name=name,
            score_attribute=ckpt_cfg.checkpoint_score_attribute,
            score_order=ckpt_cfg.checkpoint_score_order,
        )
        executor = BackendExecutor(self.scaling_config, self.run_config, trial_dir)
        last: Dict[str, Any] = {}

        def on_report(rank, iteration, metrics, ckpt_path):
            if rank == 0:
                last.clear()
                last.update(metrics)
                last["training_iteration"] = iteration
            # shard barrier: once all world ranks have landed a shard for
            # this step — or every rank has reported it and at least one
            # brought a shard (rank-0-only checkpointing) — the manager
            # commits (manifest + COMMIT) in its background uploader;
            # train.report never waits on it
            manager.note_report(
                rank,
                iteration,
                ckpt_path or None,
                metrics=metrics if rank == 0 else None,
            )

        fail_cfg = self.run_config.failure_config
        max_failures = fail_cfg.max_failures
        attempt = 0
        error: Optional[Exception] = None
        train_fn = self.train_loop
        config = self.train_loop_config
        if self.datasets:
            config = dict(config or {})
            config["__datasets__"] = self.datasets

        def resume_fn():
            # every (re-)dispatch resumes from the latest COMMITTED step —
            # never from a partial, uncommitted upload
            return manager.latest_checkpoint() or self.resume_from_checkpoint

        def prepare_resume():
            # MUST fully drain before ranks rewrite the same step dirs a
            # still-running commit may be hashing, and a dead attempt's
            # half-complete barrier must not bleed into the resumed one.
            # The wait is bounded: a wedged mirror must surface as a
            # CheckpointDrainError (failing the attempt/run), not hang
            # recovery forever — proceeding without the drain could tear a
            # committed-looking dir, so failing is the only safe exit.
            drain_timeout = self.run_config.checkpoint_config.drain_timeout_s
            if not manager.wait(timeout=drain_timeout):
                raise checkpointing.CheckpointDrainError(
                    manager.pending_steps(), drain_timeout
                )
            manager.reset_barrier()

        try:
            while True:
                try:
                    executor.start()
                    # auto-resume via resume_fn; the FIRST attempt honors an
                    # explicit resume_from_checkpoint even when the (reused)
                    # trial dir holds older commits.
                    if attempt == 0 and self.resume_from_checkpoint is not None:
                        latest = self.resume_from_checkpoint
                    else:
                        latest = resume_fn()
                    run_config = config
                    if self.scaling_config.use_jax_distributed:
                        # per-attempt rendezvous key suffix (see _wrap_distributed)
                        run_config = dict(config or {})
                        run_config["__jaxdist_attempt__"] = attempt
                    executor.run(
                        train_fn,
                        run_config,
                        latest_ckpt=latest,
                        report_callback=on_report,
                        resume_fn=resume_fn,
                        prepare_resume=prepare_resume,
                        on_resize=manager.resize,
                        attempt_tag=attempt,
                        run_name=name,
                    )
                    error = None
                    break
                except Exception as e:  # noqa: BLE001
                    error = e
                    attempt += 1
                    # downtime ledger: the whole teardown -> backoff ->
                    # restart window is attributed (closed by the restarted
                    # attempt's first dispatch)
                    executor.open_downtime(
                        "gang_restart",
                        detail=f"attempt {attempt}: {type(e).__name__}",
                    )
                    executor.shutdown()
                    try:
                        prepare_resume()
                    except checkpointing.CheckpointDrainError as de:
                        # the plane is wedged: retrying would hit the same
                        # wall — surface the drain failure and stop, with
                        # the attempt's real error preserved as the cause
                        de.__cause__ = error
                        error = de
                        break
                    # an elastic shrink may have left the barrier at M <
                    # num_workers; the fresh gang is full-size again, and a
                    # short barrier would commit torn (M-of-N-shard) steps
                    manager.resize(self.scaling_config.num_workers)
                    if max_failures != -1 and attempt > max_failures:
                        break
                    try:
                        from ray_tpu.train._backend_executor import _get_metrics

                        _get_metrics()["restarts"].inc(tags={"kind": "gang"})
                    except Exception:
                        pass
                    time.sleep(_retry_backoff(attempt, fail_cfg))
                finally:
                    executor.shutdown()
        finally:
            # drain the upload queue before returning: fit()'s contract is
            # that every fully-reported checkpoint is committed (or failed
            # loudly) by the time the Result exists — and a drain that
            # TIMES OUT must never return looking fully committed
            drain_timeout = self.run_config.checkpoint_config.drain_timeout_s
            drain_t0 = time.monotonic()
            drained = manager.wait(timeout=drain_timeout)
            drain_s = time.monotonic() - drain_t0
            if drain_s > 0.05:
                # blocking on uncommitted uploads at teardown is downtime
                # the goodput ledger must attribute (PR-5 commit spans show
                # the same window from the storage side)
                executor.add_downtime(
                    "checkpoint_drain", drain_s, detail="fit() teardown drain"
                )
            if not drained:
                from ray_tpu.train._backend_executor import _record_event

                undrained = manager.pending_steps()
                _record_event(
                    "CHECKPOINT_FAILED",
                    f"run {name}: checkpoint drain timed out after "
                    f"{drain_timeout:.0f}s with steps {undrained} still "
                    f"uncommitted",
                    severity="ERROR",
                    run=name,
                    undrained_steps=undrained,
                )
                drain_err = checkpointing.CheckpointDrainError(
                    undrained, drain_timeout
                )
                if error is None:
                    error = drain_err
                else:
                    # the run already failed; ride along as context
                    error.checkpoint_drain_error = drain_err
            manager.shutdown(wait=False)

        best = manager.latest_checkpoint()
        # a terminally-failed attempt can leave its gang_restart/recovery
        # window open (the break skips the dispatch that would close it):
        # close it now so downtime_s == sum(ledger) in the final stats
        executor._close_downtime()
        goodput = executor.goodput_stats()
        goodput["downtime_ledger"] = executor.downtime_ledger()
        # final publication: the run's terminal status + complete ledger
        # land in the scheduler's StepIndex (state.train_run / dashboard)
        executor._push_run_meta(
            name, status="failed" if error is not None else "finished"
        )
        executor._publish_goodput(name)
        return Result(
            metrics=dict(last),
            checkpoint=best,
            path=trial_dir,
            error=error,
            goodput=goodput,
        )
