"""JaxTrainer: the DataParallelTrainer equivalent for TPU.

Parity: ``DataParallelTrainer`` (``python/ray/train/data_parallel_trainer.py:25``)
+ ``TorchTrainer`` fit path (SURVEY.md §3.4). Differences by design:

* no process-group rendezvous — the train loop builds a mesh and jits
  (the reference's ``_setup_torch_process_group``, ``torch/config.py:65``,
  has no TPU analogue: collectives are in-program over ICI);
* ``ScalingConfig(topology=...)`` turns into a slice-aware placement group;
* checkpoints are orbax pytrees behind the same dir-of-files ``Checkpoint``.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu.train._backend_executor import BackendExecutor
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._config import RunConfig, ScalingConfig
from ray_tpu.train._result import Result


class JaxTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.train_loop = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        name = self.run_config.name or f"JaxTrainer_{time.strftime('%Y%m%d_%H%M%S')}"
        trial_dir = os.path.join(self.run_config.resolved_storage_path(), name)
        os.makedirs(trial_dir, exist_ok=True)

        executor = BackendExecutor(self.scaling_config, self.run_config, trial_dir)
        last: Dict[str, Any] = {}
        checkpoints: list = []

        def on_report(rank, iteration, metrics, ckpt_path):
            if rank == 0:
                last.clear()
                last.update(metrics)
                last["training_iteration"] = iteration
                if ckpt_path:
                    checkpoints.append(
                        (
                            {**metrics, "training_iteration": iteration},
                            Checkpoint(ckpt_path),
                        )
                    )
                    self._prune_checkpoints(checkpoints)

        max_failures = self.run_config.failure_config.max_failures
        attempt = 0
        error: Optional[Exception] = None
        train_fn = self.train_loop
        config = self.train_loop_config
        if self.datasets:
            config = dict(config or {})
            config["__datasets__"] = self.datasets
        while True:
            try:
                executor.start()
                latest = checkpoints[-1][1] if checkpoints else self.resume_from_checkpoint
                executor.run(train_fn, config, latest_ckpt=latest, report_callback=on_report)
                error = None
                break
            except Exception as e:  # noqa: BLE001
                error = e
                attempt += 1
                executor.shutdown()
                if max_failures != -1 and attempt > max_failures:
                    break
                time.sleep(1.0)
            finally:
                executor.shutdown()

        best = checkpoints[-1][1] if checkpoints else None
        return Result(metrics=dict(last), checkpoint=best, path=trial_dir, error=error)

    def _prune_checkpoints(self, checkpoints: list) -> None:
        cfg = self.run_config.checkpoint_config
        if cfg.num_to_keep is None or len(checkpoints) <= cfg.num_to_keep:
            return
        if cfg.checkpoint_score_attribute:
            reverse = cfg.checkpoint_score_order == "max"
            checkpoints.sort(
                key=lambda mc: mc[0].get(cfg.checkpoint_score_attribute, 0.0),
                reverse=reverse,
            )
            doomed = checkpoints[cfg.num_to_keep :]
            del checkpoints[cfg.num_to_keep :]
            checkpoints.sort(key=lambda mc: mc[0].get("training_iteration", 0))
        else:
            doomed = checkpoints[: -cfg.num_to_keep]
            del checkpoints[: -cfg.num_to_keep]
        import shutil

        for _, ckpt in doomed:
            shutil.rmtree(ckpt.path, ignore_errors=True)
