"""Worker-group executor for training.

Parity: ``BackendExecutor`` (``python/ray/train/_internal/backend_executor.py:67``,
PG creation ``:213``) + ``WorkerGroup`` (``_internal/worker_group.py``): N
worker actors gang-scheduled in a placement group, a per-framework backend
hook, reports streamed back to the driver. The JAX backend's ``on_start``
needs no NCCL rendezvous — single-host meshes come from ``jax.devices()`` and
multi-host alignment is by construction (same program, same mesh).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import cloudpickle

import ray_tpu
from ray_tpu.train._config import RunConfig, ScalingConfig
from ray_tpu.train._session import TrainContext, _Session, _set_session
from ray_tpu.util.placement_group import placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


@ray_tpu.remote(num_cpus=0)
class _ReportCollector:
    """Buffers (rank, iteration, metrics, checkpoint_path) reports."""

    def __init__(self):
        self.reports: List[Tuple[int, int, dict, Optional[str]]] = []

    def report(self, rank, iteration, metrics, ckpt_path):
        self.reports.append((rank, iteration, metrics, ckpt_path))
        return True

    def drain(self, start: int):
        return self.reports[start:]


@ray_tpu.remote
class _TrainWorker:
    """One member of the worker group; runs the user train loop."""

    def __init__(self, rank: int, world_size: int, trial_dir: str):
        self.context = TrainContext(
            world_rank=rank,
            world_size=world_size,
            local_rank=rank,
            trial_dir=trial_dir,
        )

    def run(self, fn_blob: bytes, config: Optional[dict], collector, latest_ckpt):
        fn = cloudpickle.loads(fn_blob)
        session = _Session(self.context, collector, latest_ckpt)
        _set_session(session)
        try:
            if config is not None:
                result = fn(config)
            else:
                result = fn()
            return result
        finally:
            _set_session(None)
            # the executor kills this worker right after the result lands;
            # push buffered telemetry (checkpoint_save spans, save-seconds
            # histogram) ahead of it — pipe FIFO makes the batch arrive
            # before the task result, so nothing is lost to the kill
            from ray_tpu._private import telemetry

            telemetry.flush()


class BackendExecutor:
    def __init__(self, scaling: ScalingConfig, run_config: RunConfig, trial_dir: str):
        self.scaling = scaling
        self.run_config = run_config
        self.trial_dir = trial_dir
        self.pg = None
        self.workers: List = []
        self.collector = None

    def start(self):
        res = self.scaling.worker_resources()
        bundles = [dict(res) for _ in range(self.scaling.num_workers)]
        if self.scaling.topology:
            # slice-aware gang scheduling: bundle 0 claims the slice-head
            # resource the accelerator manager plants on the slice's worker 0
            # (parity: TPU-{pod}-head, reference tpu.py:334) so the whole
            # group lands on one ICI-connected slice
            bundles[0][f"TPU-{self.scaling.topology}-head"] = 1.0
        self.pg = placement_group(bundles, strategy=self.scaling.placement_strategy)
        if not self.pg.wait(60):
            remove_placement_group(self.pg)
            raise RuntimeError(
                f"could not gang-schedule {self.scaling.num_workers} workers "
                f"with {res} each (cluster too small?)"
            )
        self.collector = _ReportCollector.remote()
        self.workers = []
        for rank in range(self.scaling.num_workers):
            w = _TrainWorker.options(
                # the actor's demand must equal the bundle's contents — a CPU
                # default here would never fit a CPU-less bundle
                num_cpus=res.get("CPU", 0.0),
                num_tpus=res.get("TPU", 0.0),
                resources={
                    k: v for k, v in res.items() if k not in ("CPU", "TPU")
                },
                runtime_env=self.scaling.worker_runtime_env,
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=self.pg, placement_group_bundle_index=rank
                ),
            ).remote(rank, self.scaling.num_workers, self.trial_dir)
            self.workers.append(w)

    def run(
        self,
        train_fn: Callable,
        config: Optional[dict],
        latest_ckpt=None,
        report_callback: Optional[Callable] = None,
        timeout: Optional[float] = None,
    ) -> List[Any]:
        fn_blob = cloudpickle.dumps(train_fn)
        refs = [
            w.run.remote(fn_blob, config, self.collector, latest_ckpt)
            for w in self.workers
        ]
        seen = 0
        deadline = None if timeout is None else time.monotonic() + timeout
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1, timeout=0.5)
            new = ray_tpu.get(self.collector.drain.remote(seen), timeout=60)
            seen += len(new)
            if report_callback:
                for r in new:
                    report_callback(*r)
            for r in ready:
                ray_tpu.get(r)  # surface worker errors immediately
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("training run timed out")
        new = ray_tpu.get(self.collector.drain.remote(seen), timeout=60)
        if report_callback:
            for r in new:
                report_callback(*r)
        return ray_tpu.get(refs)

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
        if self.pg is not None:
            remove_placement_group(self.pg)
            self.pg = None
