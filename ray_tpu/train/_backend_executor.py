"""Worker-group executor for training.

Parity: ``BackendExecutor`` (``python/ray/train/_internal/backend_executor.py:67``,
PG creation ``:213``) + ``WorkerGroup`` (``_internal/worker_group.py``): N
worker actors gang-scheduled in a placement group, a per-framework backend
hook, reports streamed back to the driver. The JAX backend's ``on_start``
needs no NCCL rendezvous — single-host meshes come from ``jax.devices()`` and
multi-host alignment is by construction (same program, same mesh).

Beyond the reference (whose failure policy is "tear the group down and
restart it at the same world size"), this executor is **elastic**: a worker
or node death keeps the surviving ``_TrainWorker`` actors alive, aborts the
attempt through the report control plane (survivors unwind at their next
``train.report``), provisions replacements for the dead ranks — or shrinks
to whatever the cluster can give within the
``ScalingConfig.min_workers..num_workers`` band — and re-dispatches every
rank from the last committed checkpoint with a fresh rendezvous key. The
whole-gang restart in ``JaxTrainer.fit()`` is the fallback, not the policy.
It also subscribes to the scheduler's cluster-event log so a preempted
(WORKER_DIED / NODE_DEAD) or straggling (STRAGGLER, opt-in) rank triggers
recovery *before* a collective or report timeout would surface it.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import cloudpickle

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.train._config import RunConfig, ScalingConfig
from ray_tpu.train._session import AttemptAborted, TrainContext, _Session, _set_session
from ray_tpu.util.placement_group import placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy

# sentinel returned by a worker whose attempt was aborted mid-run (the
# actor process is fine and will be re-dispatched)
_ABORTED = "__ray_tpu_attempt_aborted__"

_DEATH_ERRORS = (
    exc.ActorDiedError,
    exc.ActorUnavailableError,
    exc.WorkerCrashedError,
)


class WorkerGroupError(RuntimeError):
    """In-run elastic recovery failed (could not keep >= min_workers ranks
    alive). fit() treats this like any attempt failure: whole-gang
    restart with backoff."""


_metrics_lock = threading.Lock()
_metrics: Optional[Dict[str, Any]] = None


def _get_metrics() -> Dict[str, Any]:
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge

            _metrics = {
                "restarts": Counter(
                    "ray_tpu_train_restarts_total",
                    "training restarts (kind=in_run: elastic re-dispatch "
                    "keeping survivors alive; kind=gang: full worker-group "
                    "teardown + restart)",
                    tag_keys=("kind",),
                ),
                "resizes": Counter(
                    "ray_tpu_train_resizes_total",
                    "elastic world-size changes (shrink or regrow) of a "
                    "live training run",
                ),
                "lost_workers": Counter(
                    "ray_tpu_train_lost_workers_total",
                    "train workers lost to preemption/crash during a run",
                ),
                "goodput": Gauge(
                    "ray_tpu_train_goodput",
                    "useful-step-time / wall-time of the training run "
                    "(1.0 = no time lost to churn, redone steps, or "
                    "recovery); published live on "
                    "train_goodput_publish_interval_s, not just at fit() "
                    "teardown",
                    tag_keys=("run",),
                ),
                "downtime": Counter(
                    "ray_tpu_train_downtime_seconds",
                    "training wall time lost to attributed downtime "
                    "windows (cause=recovery|gang_restart|preemption|"
                    "checkpoint_drain|admission_wait) — the goodput gap's "
                    "ledger",
                    tag_keys=("run", "cause"),
                ),
            }
    return _metrics


@ray_tpu.remote(num_cpus=0)
class _ReportCollector:
    """Buffers (rank, iteration, metrics, checkpoint_path) reports, and
    doubles as the executor→worker control plane: ``report`` responses
    carry the abort generation when the executor is re-forming the group,
    so survivors unwind at their next report instead of timing out in a
    collective against a dead peer."""

    def __init__(self):
        self.reports: List[Tuple[int, int, dict, Optional[str], Any]] = []
        self._offset = 0  # entries already drained and dropped
        self._abort_gen: Optional[int] = None

    def report(self, rank, iteration, metrics, ckpt_path, step_rec=None):
        # step_rec is the rank's PREVIOUS step-plane record riding this
        # report (compact tuple; see _private/stepplane.py) — drained to
        # the executor, which batch-pushes records into the scheduler's
        # StepIndex on the publish cadence
        self.reports.append((rank, iteration, metrics, ckpt_path, step_rec))
        return True if self._abort_gen is None else self._abort_gen

    def drain(self, start: int):
        # drained entries are never re-read: drop them and keep a running
        # offset — a long run's full metrics history would otherwise
        # accumulate in this actor forever
        idx = max(0, start - self._offset)
        out = self.reports[idx:]
        self._offset += len(self.reports)
        self.reports = []
        return out

    def buffered(self) -> int:
        """Entries currently held (regression hook for the trim)."""
        return len(self.reports)

    def signal_abort(self, generation: int):
        self._abort_gen = generation
        return True

    def clear_abort(self):
        self._abort_gen = None
        return True


@ray_tpu.remote
class _TrainWorker:
    """One member of the worker group; runs the user train loop. The
    actor outlives a single attempt: an aborted or resumed attempt is a
    new ``run`` dispatch (possibly with a new rank/world after an elastic
    resize), not a new process."""

    def __init__(self, rank: int, world_size: int, trial_dir: str):
        self.context = TrainContext(
            world_rank=rank,
            world_size=world_size,
            local_rank=rank,
            trial_dir=trial_dir,
        )

    def ping(self):
        import os

        return os.getpid()

    def run(
        self,
        fn_blob: bytes,
        config: Optional[dict],
        collector,
        latest_ckpt,
        rank: Optional[int] = None,
        world_size: Optional[int] = None,
        run_name: str = "train",
    ):
        if rank is not None:
            self.context.world_rank = rank
            self.context.local_rank = rank
        if world_size is not None:
            self.context.world_size = world_size
        fn = cloudpickle.loads(fn_blob)
        datasets = None
        if isinstance(config, dict) and "__datasets__" in config:
            # internal plumbing, not a hyperparameter: the user fn gets a
            # config it can json.dumps/log without tripping over Datasets
            config = dict(config)
            datasets = config.pop("__datasets__")
        session = _Session(
            self.context,
            collector,
            latest_ckpt,
            run_name=run_name,
            datasets=datasets,
        )
        _set_session(session)
        try:
            if config is not None:
                result = fn(config)
            else:
                result = fn()
            return result
        except AttemptAborted:
            # unwound by the executor's abort signal: NOT an error — the
            # group is re-forming and this actor will be re-dispatched
            return _ABORTED
        finally:
            _set_session(None)
            # the executor kills this worker right after the result lands;
            # push buffered telemetry (checkpoint_save spans, save-seconds
            # histogram) ahead of it — pipe FIFO makes the batch arrive
            # before the task result, so nothing is lost to the kill
            from ray_tpu._private import telemetry

            telemetry.flush()


def _record_event(type: str, message: str, severity: str = "INFO", **extra):
    try:
        from ray_tpu._private import telemetry

        telemetry.record_cluster_event(
            type, message, severity=severity, source="TRAIN", **extra
        )
    except Exception:
        pass


class BackendExecutor:
    def __init__(self, scaling: ScalingConfig, run_config: RunConfig, trial_dir: str):
        self.scaling = scaling
        self.run_config = run_config
        self.failure = run_config.failure_config
        self.trial_dir = trial_dir
        self.pg = None
        self.workers: List = []
        self._bundles: List[Optional[int]] = []
        self.collector = None
        self._seen = 0  # reports drained from the current collector
        self._last_event_id = 0
        self._last_event_poll = 0.0
        # goodput accounting (persists across gang restarts: one fit call,
        # one wall clock)
        self._gp = {
            "wall_start": None,
            "useful_s": 0.0,
            "max_step": 0,
            "last_ts": None,
            "steps_useful": 0,
            "steps_redone": 0,
        }
        # downtime ledger: goodput's gap attributed by cause. Each entry is
        # {cause, start (wall clock), end, seconds, detail}; _open_dt is the
        # window currently accruing (closed by the next dispatch). Windows
        # open at the LAST PROGRESS timestamp, not at detection: the work
        # since the last report is discarded by the abort/restart, so it is
        # part of the loss this ledger must sum to.
        self._downtime: List[Dict[str, Any]] = []
        self._open_dt: Optional[Dict[str, Any]] = None
        self._last_progress: Optional[float] = None  # wall clock
        self._preempt_seen_at: float = 0.0
        self._last_publish: float = 0.0
        self._run_name: str = "train"
        self._admission_noted = False  # start() runs once per gang attempt
        # step-plane records drained off reports, batch-pushed into the
        # scheduler's StepIndex on the publish cadence
        self._step_recs: List[Any] = []

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        res = self.scaling.worker_resources()
        bundles = [dict(res) for _ in range(self.scaling.num_workers)]
        if self.scaling.topology:
            # slice-aware gang scheduling: bundle 0 claims the slice-head
            # resource the accelerator manager plants on the slice's worker 0
            # (parity: TPU-{pod}-head, reference tpu.py:334) so the whole
            # group lands on one ICI-connected slice
            bundles[0][f"TPU-{self.scaling.topology}-head"] = 1.0
        self.pg = placement_group(bundles, strategy=self.scaling.placement_strategy)
        if not self.pg.wait(60):
            remove_placement_group(self.pg)
            self.pg = None
            raise RuntimeError(
                f"could not gang-schedule {self.scaling.num_workers} workers "
                f"with {res} each (cluster too small?)"
            )
        self.collector = _ReportCollector.remote()
        self._seen = 0
        self.workers = [
            self._spawn(rank, self.scaling.num_workers, bundle_index=rank)
            for rank in range(self.scaling.num_workers)
        ]
        # which pg bundle each live worker occupies (None = unconstrained
        # replacement) — dead ranks free their bundle for reuse
        self._bundles: List[Optional[int]] = list(range(self.scaling.num_workers))
        # ignore cluster events from before this group existed
        self._last_event_id = self._event_horizon()
        self._note_admission_wait()

    def _note_admission_wait(self) -> None:
        """If this driver's job sat in the admission queue (multi-tenant
        plane: JOB_QUEUED -> JOB_ADMITTED), that wait is training downtime
        too — attribute it in the ledger instead of letting it read as a
        slow first step."""
        if self._admission_noted:
            return
        self._admission_noted = True
        try:
            from ray_tpu._private.worker import get_runtime

            job_hex = getattr(get_runtime(), "job_id", None)
            job_hex = job_hex.hex() if job_hex is not None else None
            if not job_hex:
                return
            queued = admitted = None
            for ev in self._list_events(limit=512):
                if ev.get("job_id") != job_hex:
                    continue
                if ev.get("type") == "JOB_QUEUED":
                    queued = ev.get("time")
                elif ev.get("type") == "JOB_ADMITTED" and queued is not None:
                    admitted = ev.get("time")
            if queued is not None and admitted is not None and admitted > queued:
                self.add_downtime(
                    "admission_wait",
                    admitted - queued,
                    detail=f"job {job_hex} queued for admission",
                )
        except Exception:
            pass

    def _spawn(self, rank: int, world: int, bundle_index: Optional[int] = None):
        res = self.scaling.worker_resources()
        opts = dict(
            # the actor's demand must equal the bundle's contents — a CPU
            # default here would never fit a CPU-less bundle
            num_cpus=res.get("CPU", 0.0),
            num_tpus=res.get("TPU", 0.0),
            resources={k: v for k, v in res.items() if k not in ("CPU", "TPU")},
            runtime_env=self.scaling.worker_runtime_env,
        )
        if bundle_index is not None and self.pg is not None:
            opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                placement_group=self.pg, placement_group_bundle_index=bundle_index
            )
        return _TrainWorker.options(**opts).remote(rank, world, self.trial_dir)

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
        self._bundles = []
        if self.pg is not None:
            remove_placement_group(self.pg)
            self.pg = None

    # -- reports / goodput --------------------------------------------------

    def _drain_reports(self, report_callback: Optional[Callable]) -> None:
        new = ray_tpu.get(self.collector.drain.remote(self._seen), timeout=60)
        self._seen += len(new)
        if new:
            self._last_progress = time.time()
            if self._open_dt is not None and self._open_dt.pop(
                "until_report", False
            ):
                # recovery's downtime window ends at the first report the
                # RESUMED generation produces (re-dispatch alone is not
                # recovery — session re-setup and the survivors' discarded
                # partial steps are part of the loss), minus one nominal
                # step: the step that produced this report was useful work
                gp = self._gp
                avg = (
                    gp["useful_s"] / gp["steps_useful"]
                    if gp["steps_useful"]
                    else 0.0
                )
                self._close_downtime(discount_s=avg)
        for r in new:
            self._note_goodput(r)
            if len(r) > 4 and r[4] is not None:
                self._step_recs.append(r[4])
            if report_callback:
                report_callback(*r[:4])

    def _note_goodput(self, report) -> None:
        rank, iteration = report[0], report[1]
        if rank != 0:
            return
        now = time.monotonic()
        gp = self._gp
        if gp["last_ts"] is not None:
            dt = now - gp["last_ts"]
            if iteration > gp["max_step"]:
                gp["useful_s"] += dt
                gp["steps_useful"] += 1
            else:
                gp["steps_redone"] += 1
        gp["max_step"] = max(gp["max_step"], iteration)
        gp["last_ts"] = now

    def goodput_stats(self) -> Dict[str, Any]:
        gp = self._gp
        wall = (
            time.monotonic() - gp["wall_start"] if gp["wall_start"] else 0.0
        )
        by_cause: Dict[str, float] = {}
        for e in self._downtime:
            by_cause[e["cause"]] = by_cause.get(e["cause"], 0.0) + e["seconds"]
        return {
            "wall_s": wall,
            "useful_step_s": gp["useful_s"],
            "steps_useful": gp["steps_useful"],
            "steps_redone": gp["steps_redone"],
            "goodput": (gp["useful_s"] / wall) if wall > 0 else 0.0,
            "downtime_s": round(sum(by_cause.values()), 3),
            "downtime_by_cause": {k: round(v, 3) for k, v in by_cause.items()},
        }

    # -- downtime ledger ----------------------------------------------------

    def downtime_ledger(self) -> List[Dict[str, Any]]:
        """Closed downtime windows so far, in order. The open window (if
        any) is included with its running duration — a live dashboard must
        see the outage it is currently in."""
        out = [dict(e) for e in self._downtime]
        if self._open_dt is not None:
            cur = dict(self._open_dt)
            cur["seconds"] = round(max(0.0, time.time() - cur["start"]), 3)
            cur["open"] = True
            out.append(cur)
        return out

    def open_downtime(self, cause: str, detail: str = "", start: Optional[float] = None) -> None:
        """Begin a downtime window; the next dispatch() closes it. Starts
        at the last progress timestamp unless given explicitly — work done
        since the last report is unwound by the recovery, so it counts."""
        if self._open_dt is not None:
            return  # already in an outage; first cause wins
        t0 = start if start is not None else (self._last_progress or time.time())
        self._open_dt = {"cause": cause, "start": t0, "detail": detail}

    def _close_downtime(self, discount_s: float = 0.0) -> None:
        dt = self._open_dt
        if dt is None:
            return
        self._open_dt = None
        dt.pop("until_report", None)
        dt["end"] = time.time()
        dt["seconds"] = round(
            max(0.0, dt["end"] - dt["start"] - max(0.0, discount_s)), 3
        )
        self._downtime.append(dt)
        try:
            _get_metrics()["downtime"].inc(
                dt["seconds"], tags={"run": self._run_name, "cause": dt["cause"]}
            )
        except Exception:
            pass

    def add_downtime(self, cause: str, seconds: float, detail: str = "") -> None:
        """Record an already-measured downtime window (checkpoint drains,
        admission waits — stalls with explicit bounds)."""
        if seconds <= 0:
            return
        end = time.time()
        self._downtime.append(
            {
                "cause": cause,
                "start": end - seconds,
                "end": end,
                "seconds": round(seconds, 3),
                "detail": detail,
            }
        )
        try:
            _get_metrics()["downtime"].inc(
                round(seconds, 3), tags={"run": self._run_name, "cause": cause}
            )
        except Exception:
            pass

    def _dead_cause(self) -> str:
        """Classify the recovery we are about to pay for: a PREEMPTED
        cluster event naming this group within the last poll window means
        the arbitration plane took the worker, not a crash."""
        if time.monotonic() - self._preempt_seen_at < 10.0:
            return "preemption"
        return "recovery"

    def _sched_rpc(self, op: str, args: tuple):
        from ray_tpu._private.worker import get_runtime

        rt = get_runtime()
        if hasattr(rt, "scheduler_rpc"):
            return rt.scheduler_rpc(op, args)
        return rt.rpc(op, *args)

    def _push_step_records(self) -> None:
        """Batch-push drained step records into the scheduler's StepIndex
        (on the publish cadence — per-record pushes would tax the step
        hot path the records were moved OFF of)."""
        if not self._step_recs:
            return
        recs, self._step_recs = self._step_recs, []
        try:
            self._sched_rpc("train_steps_batch", (recs,))
        except Exception:
            self._step_recs = recs + self._step_recs  # retry next push

    def _push_run_meta(self, run_name: str, status: str = "running") -> None:
        """Publish this run's goodput + downtime ledger into the
        scheduler's StepIndex (state.train_run / dashboard read side)."""
        self._push_step_records()
        try:
            self._sched_rpc(
                "train_run_meta",
                (
                    run_name,
                    {
                        "goodput": self.goodput_stats(),
                        "downtime_ledger": self.downtime_ledger(),
                        "world_size": self.scaling.num_workers,
                        "live_world": len(self.workers),
                        "status": status,
                    },
                ),
            )
        except Exception:
            pass

    def _publish_interval_s(self) -> float:
        try:
            from ray_tpu._private.worker import get_runtime

            cfg = getattr(get_runtime(), "config", None)
            return float(
                getattr(cfg, "train_goodput_publish_interval_s", 5.0) or 5.0
            )
        except Exception:
            return 5.0

    def _maybe_publish(self, run_name: str) -> None:
        """Live goodput on a periodic cadence: dashboards see the run
        mid-flight, not only at fit() teardown."""
        now = time.monotonic()
        if now - self._last_publish < self._publish_interval_s():
            return
        self._last_publish = now
        self._publish_goodput(run_name)
        self._push_run_meta(run_name)

    def _publish_goodput(self, run_name: str) -> None:
        try:
            _get_metrics()["goodput"].set(
                round(self.goodput_stats()["goodput"], 4), tags={"run": run_name}
            )
        except Exception:
            pass

    # -- proactive failure detection (cluster-event subscription) ----------

    def _list_events(self, limit: int = 256) -> List[dict]:
        from ray_tpu._private.worker import get_runtime

        rt = get_runtime()
        try:
            if hasattr(rt, "scheduler_rpc"):
                return rt.scheduler_rpc("list_cluster_events", (limit,)) or []
            return rt.rpc("list_cluster_events", limit) or []
        except Exception:
            return []

    def _event_horizon(self) -> int:
        rows = self._list_events(limit=1)
        return rows[-1].get("event_id", 0) if rows else 0

    def _poll_cluster_events(self, ref_to_rank: Dict) -> Dict[int, Exception]:
        """Ranks the scheduler's forensics plane says we should give up on
        — before their pending run ref fails or a collective times out.
        WORKER_DIED/NODE_DEAD name preempted ranks; STRAGGLER (opt-in via
        FailureConfig.replace_stragglers) names slow ones, which we kill
        so they fail over to a replacement."""
        now = time.monotonic()
        if now - self._last_event_poll < 1.0:
            return {}
        self._last_event_poll = now
        rows = self._list_events()
        fresh = [e for e in rows if e.get("event_id", 0) > self._last_event_id]
        if fresh:
            self._last_event_id = max(e.get("event_id", 0) for e in fresh)
        if not fresh:
            return {}
        by_actor = {
            w._actor_id.hex(): rank for rank, w in enumerate(self.workers)
        }
        by_task = {ref.id().task_id().hex(): rank for ref, rank in ref_to_rank.items()}
        dead: Dict[int, Exception] = {}
        dead_nodes = set()
        for ev in fresh:
            etype = ev.get("type")
            if etype == "PREEMPTED" and ev.get("actor_id") in by_actor:
                # the arbitration plane is taking capacity back FROM THIS
                # GANG: classify the next detected death as preemption,
                # not a crash (another job's preemption must not relabel
                # our crash recovery)
                self._preempt_seen_at = time.monotonic()
            if etype == "WORKER_DIED" and ev.get("actor_id") in by_actor:
                rank = by_actor[ev["actor_id"]]
                dead[rank] = exc.ActorDiedError(
                    ev.get("actor_id"), f"preempted: {ev.get('message', '')}"
                )
            elif etype == "NODE_DEAD" and ev.get("node_id"):
                dead_nodes.add(ev["node_id"])
            elif (
                etype == "STRAGGLER"
                and self.failure.replace_stragglers
                and ev.get("task_id") in by_task
            ):
                rank = by_task[ev["task_id"]]
                try:
                    ray_tpu.kill(self.workers[rank])
                except Exception:
                    pass
                dead[rank] = exc.ActorDiedError(
                    self.workers[rank]._actor_id,
                    f"straggler replaced: {ev.get('message', '')}",
                )
        if dead_nodes:
            # a node died: consult the actor table for which of our ranks
            # went with it, without waiting for their collectives/reports
            # to time out. (A ping would NOT work here: _TrainWorker is a
            # serial actor, so a ping queues behind the whole run() and a
            # healthy busy rank would look dead.)
            try:
                from ray_tpu.util.state import list_actors

                rows = {row.get("actor_id"): row for row in list_actors()}
            except Exception:
                rows = {}
            for aid, rank in by_actor.items():
                if rank in dead:
                    continue
                row = rows.get(aid)
                if row is not None and (
                    row.get("node_id") in dead_nodes
                    or row.get("state") == "DEAD"
                ):
                    dead[rank] = exc.ActorDiedError(
                        aid, "node died under this rank"
                    )
        return dead

    # -- the elastic run loop ----------------------------------------------

    def run(
        self,
        train_fn: Callable,
        config: Optional[dict],
        latest_ckpt=None,
        report_callback: Optional[Callable] = None,
        timeout: Optional[float] = None,
        *,
        resume_fn: Optional[Callable[[], Any]] = None,
        prepare_resume: Optional[Callable[[], None]] = None,
        on_resize: Optional[Callable[[int], None]] = None,
        attempt_tag: Any = 0,
        run_name: str = "train",
    ) -> List[Any]:
        """Run the user loop on every rank; survive worker loss in-run.

        ``resume_fn`` returns the checkpoint to resume from after a
        recovery (the latest *committed* one); ``prepare_resume`` runs
        before each re-dispatch (drain + reset the checkpoint barrier);
        ``on_resize`` is told the new world size when the group shrinks or
        regrows. Raises :class:`WorkerGroupError` when recovery cannot
        hold ``min_workers`` ranks — the caller's whole-gang restart is
        the fallback."""
        fn_blob = cloudpickle.dumps(train_fn)
        self._run_name = run_name
        if self._gp["wall_start"] is None:
            self._gp["wall_start"] = time.monotonic()
        self._gp["last_ts"] = None
        gen = 0
        stalled_recoveries = 0
        progress_mark = self._gp["max_step"]
        results: Dict[int, Any] = {}
        ref_to_rank: Dict[Any, int] = {}
        current_ckpt = [latest_ckpt]

        def dispatch(ckpt, only_ranks=None):
            if only_ranks is None:
                results.clear()
                ref_to_rank.clear()
                self._gp["last_ts"] = None
            current_ckpt[0] = ckpt
            world = len(self.workers)
            cfg = config
            if (
                gen
                and isinstance(config, dict)
                and "__jaxdist_attempt__" in config
            ):
                # fresh jax.distributed rendezvous key per re-dispatch: the
                # dead attempt's coordinator record must never be joined.
                # Only rewritten when fit() put the key there (jax
                # distributed runs) — other loops' configs stay untouched
                cfg = dict(config)
                cfg["__jaxdist_attempt__"] = f"{attempt_tag}g{gen}"
            ranks = range(world) if only_ranks is None else sorted(only_ranks)
            for rank in ranks:
                ref = self.workers[rank].run.remote(
                    fn_blob, cfg, self.collector, ckpt, rank, world, run_name
                )
                ref_to_rank[ref] = rank
            # downtime ledger: the open window (recovery, gang restart)
            # now runs until the resumed generation's FIRST report lands —
            # dispatch alone is not recovery (session re-setup and the
            # survivors' discarded partial steps are still loss)
            if self._open_dt is not None:
                self._open_dt["until_report"] = True

        dispatch(latest_ckpt)
        deadline = None if timeout is None else time.monotonic() + timeout
        while ref_to_rank:
            ready, _ = ray_tpu.wait(
                list(ref_to_rank), num_returns=1, timeout=0.5
            )
            self._drain_reports(report_callback)
            dead: Dict[int, Exception] = {}
            redispatch: set = set()
            for r in ready:
                rank = ref_to_rank.pop(r)
                try:
                    res = ray_tpu.get(r)
                    if isinstance(res, str) and res == _ABORTED:
                        # stale abort (a cleared signal raced a report):
                        # the actor is healthy, just needs re-dispatching
                        redispatch.add(rank)
                    else:
                        results[rank] = res
                except _DEATH_ERRORS as e:
                    dead[rank] = e
            self._maybe_publish(run_name)
            dead.update(self._poll_cluster_events(ref_to_rank))
            if dead:
                # the goodput gap starts accruing now: everything from the
                # last drained report to the recovery's re-dispatch is
                # attributed downtime (the aborted ranks' partial work is
                # discarded)
                self.open_downtime(
                    self._dead_cause(),
                    detail=f"ranks {sorted(dead)} lost",
                )
                gen += 1
                # progress-aware recovery budget: churn that advances the
                # run recovers for free, a rank dying deterministically at
                # the same step must not kill/replace/resume forever
                if self._gp["max_step"] > progress_mark:
                    progress_mark = self._gp["max_step"]
                    stalled_recoveries = 0
                else:
                    stalled_recoveries += 1
                    if stalled_recoveries > self.failure.max_recoveries_without_progress:
                        raise WorkerGroupError(
                            f"run {run_name}: {stalled_recoveries} consecutive "
                            f"recoveries without completing a step (ranks keep "
                            f"dying at step {progress_mark + 1}?) — falling "
                            f"back to gang restart"
                        ) from next(iter(dead.values()))
                    # backed-off like gang restarts, so a crash-looping
                    # rank doesn't hammer provisioning in a hot loop
                    time.sleep(
                        min(
                            self.failure.retry_backoff_max_s,
                            self.failure.retry_backoff_s
                            * (2 ** (stalled_recoveries - 1)),
                        )
                    )
                self._recover(
                    dead,
                    ref_to_rank,
                    results,
                    report_callback,
                    gen,
                    run_name,
                    resume_fn=resume_fn,
                    prepare_resume=prepare_resume,
                    on_resize=on_resize,
                )
                ckpt = resume_fn() if resume_fn else latest_ckpt
                dispatch(ckpt)
            elif redispatch:
                # stale abort (cleared signal raced a report): the actors
                # are healthy — re-dispatch just those ranks
                dispatch(current_ckpt[0], only_ranks=redispatch)
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("training run timed out")
        self._drain_reports(report_callback)
        self._close_downtime()  # a window no report ever closed (rare)
        self._publish_goodput(run_name)
        self._push_run_meta(run_name)
        return [results[rank] for rank in sorted(results)]

    # -- recovery -----------------------------------------------------------

    def _recover(
        self,
        dead: Dict[int, Exception],
        ref_to_rank: Dict[Any, int],
        results: Dict[int, Any],
        report_callback: Optional[Callable],
        gen: int,
        run_name: str,
        *,
        resume_fn=None,
        prepare_resume=None,
        on_resize=None,
    ) -> None:
        """Re-form the worker group after losing ranks: abort survivors,
        provision replacements (or shrink/regrow within the elasticity
        band), and leave ``self.workers`` ready for a full re-dispatch
        from the last committed step."""
        if not self.failure.replace_workers:
            raise WorkerGroupError(
                f"ranks {sorted(dead)} died and in-run replacement is "
                f"disabled (FailureConfig.replace_workers=False)"
            ) from next(iter(dead.values()))
        m = _get_metrics()
        try:
            m["lost_workers"].inc(len(dead))
            m["restarts"].inc(tags={"kind": "in_run"})
        except Exception:
            pass
        old_world = len(self.workers)
        for rank, err in sorted(dead.items()):
            _record_event(
                "TRAIN_WORKER_DIED",
                f"run {run_name}: rank {rank}/{old_world} lost "
                f"({type(err).__name__}: {err}); re-forming the group",
                severity="WARNING",
                run=run_name,
                rank=rank,
                world_size=old_world,
                generation=gen,
            )

        # 1. abort survivors: they unwind at their next train.report and
        # return the abort sentinel, keeping their processes warm
        ray_tpu.get(self.collector.signal_abort.remote(gen), timeout=30)
        drain_deadline = time.monotonic() + self.failure.abort_drain_timeout_s
        while any(rank not in dead for rank in ref_to_rank.values()):
            live_refs = [r for r, rank in ref_to_rank.items() if rank not in dead]
            ready, _ = ray_tpu.wait(live_refs, num_returns=1, timeout=0.5)
            self._drain_reports(report_callback)
            for r in ready:
                rank = ref_to_rank.pop(r)
                try:
                    # abort sentinel or a full result (a rank that finished
                    # before noticing the abort) — either way the rank is
                    # settled and gets re-dispatched with everyone else
                    ray_tpu.get(r)
                except _DEATH_ERRORS as e:
                    dead[rank] = e
            if time.monotonic() > drain_deadline:
                # survivors stuck outside report() (a wedged collective):
                # kill them — their actors are lost, but the group can
                # still re-form around replacements
                for r, rank in list(ref_to_rank.items()):
                    if rank in dead:
                        continue
                    try:
                        ray_tpu.kill(self.workers[rank])
                    except Exception:
                        pass
                    dead[rank] = exc.ActorDiedError(
                        None, "worker did not drain by abort_drain_timeout_s"
                    )
                    ref_to_rank.pop(r, None)
                break
        # dead ranks' refs are settled failures; drop them. Kill their
        # actors explicitly too: a rank marked dead PROACTIVELY (node-dead
        # table lookup, transient ActorUnavailableError) might still be
        # executing the user loop — a zombie reporting its old rank into
        # the shared collector could otherwise complete the re-formed
        # group's shard barrier with stale-generation shards
        for r, rank in list(ref_to_rank.items()):
            if rank in dead:
                ref_to_rank.pop(r)
        for rank in dead:
            try:
                ray_tpu.kill(self.workers[rank])
            except Exception:
                pass
        results.clear()

        # 2. re-provision toward the full num_workers (a previously shrunk
        # group regrows here), falling back to the elasticity band
        survivors = [
            w for rank, w in enumerate(self.workers) if rank not in dead
        ]
        survivor_bundles = [
            b for rank, b in enumerate(self._bundles) if rank not in dead
        ]
        free_bundles = sorted(
            set(range(self.scaling.num_workers))
            - {b for b in survivor_bundles if b is not None}
        )
        want = self.scaling.num_workers - len(survivors)
        replacements = self._provision(want, free_bundles) if want > 0 else []
        new_world = len(survivors) + len(replacements)
        min_workers = self.scaling.effective_min_workers()
        if new_world < min_workers:
            # the fallback is a whole-gang restart: the replacements we DID
            # provision must not outlive this recovery, or they'd keep
            # holding resources the restarted gang needs
            for w, _b in replacements:
                try:
                    ray_tpu.kill(w)
                except Exception:
                    pass
            raise WorkerGroupError(
                f"run {run_name}: only {new_world} of min {min_workers} "
                f"workers available after losing ranks {sorted(dead)}"
            ) from next(iter(dead.values()))
        for i in range(len(replacements)):
            _record_event(
                "TRAIN_WORKER_REPLACED",
                f"run {run_name}: provisioned replacement worker "
                f"{i + 1}/{len(replacements)} (generation {gen})",
                run=run_name,
                generation=gen,
            )
        # survivors keep their relative order (stable re-ranking),
        # replacements fill in after them
        self.workers = survivors + [w for w, _b in replacements]
        self._bundles = survivor_bundles + [b for _w, b in replacements]
        if new_world != old_world:
            try:
                m["resizes"].inc()
            except Exception:
                pass
            _record_event(
                "TRAIN_RESIZED",
                f"run {run_name}: elastic resize {old_world} -> {new_world} "
                f"workers (band {min_workers}..{self.scaling.num_workers})",
                severity="WARNING",
                run=run_name,
                old_world=old_world,
                new_world=new_world,
                generation=gen,
            )
            if on_resize:
                on_resize(new_world)
        # 3. quiesce the checkpoint plane (drain in-flight commits, reset
        # the shard barrier) before ranks start rewriting step dirs
        if prepare_resume:
            prepare_resume()
        ray_tpu.get(self.collector.clear_abort.remote(), timeout=30)

    def _provision(
        self, want: int, free_bundles: List[Optional[int]]
    ) -> List[Tuple[Any, Optional[int]]]:
        """Spawn up to ``want`` replacement workers, each proven alive by a
        ping within FailureConfig.replacement_timeout_s. Dead ranks'
        placement-group bundles are reused first (their resources were
        released with the dead workers); a bundle that cannot be re-filled
        (its node died with it) falls back to unconstrained scheduling for
        the remaining timeout. Returns ``(worker, bundle_or_None)``
        pairs."""
        if want <= 0:
            return []
        deadline = time.monotonic() + self.failure.replacement_timeout_s
        out: List[Tuple[Any, Optional[int]]] = []
        for use_pg in (True, False):
            need = want - len(out)
            if need <= 0 or time.monotonic() >= deadline:
                break
            cand: List[Tuple[Any, Optional[int]]] = []
            for i in range(need):
                bundle = None
                if use_pg:
                    if self.pg is None or i >= len(free_bundles):
                        continue
                    bundle = free_bundles[i]
                try:
                    cand.append(
                        (
                            self._spawn(
                                0, self.scaling.num_workers, bundle_index=bundle
                            ),
                            bundle,
                        )
                    )
                except Exception:
                    continue
            if not cand:
                continue
            pings = {w.ping.remote(): (w, b) for w, b in cand}
            budget = max(0.1, deadline - time.monotonic())
            if use_pg:
                # the pinned pass must not eat the whole window: a bundle
                # whose node died never schedules, and the documented
                # unconstrained fallback still needs its share
                budget = min(budget, self.failure.replacement_timeout_s / 2)
            ready, _ = ray_tpu.wait(
                list(pings), num_returns=len(pings), timeout=budget
            )
            for r in ready:
                w, b = pings.pop(r)
                try:
                    ray_tpu.get(r)
                    out.append((w, b))
                    if b is not None and b in free_bundles:
                        free_bundles.remove(b)
                except Exception:
                    try:
                        ray_tpu.kill(w)
                    except Exception:
                        pass
            for w, _b in pings.values():  # unproven: give up on them
                try:
                    ray_tpu.kill(w)
                except Exception:
                    pass
        return out[:want]
