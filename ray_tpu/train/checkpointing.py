"""Checkpoint plane: async sharded save/restore with atomic commit.

Parity targets: the reference's ``CheckpointManager``
(``python/ray/train/_internal/checkpoint_manager.py``) and storage context
upload, re-architected around two properties the seed lacked (motivated by
Gemini SOSP'23 / Check-N-Run NSDI'22 — checkpoint frequency is bounded by
how well save overlaps training and how cheaply restores can be trusted):

* **save overlaps training** — ``train.report(checkpoint=)`` returns after a
  local snapshot (O(local-copy)); upload + commit run in a bounded-queue
  background thread on the driver;
* **restores are trusted** — per-rank shards (``shard-{rank}-of-{world}``)
  barrier at the head, which assembles a manifest (per-file sizes + sha256
  digests) and writes an atomic ``COMMIT`` marker *last*
  (``ray_tpu._private.external_storage`` commit protocol). Readers —
  :func:`latest_checkpoint`, ``Checkpoint.from_uri`` — only ever observe
  committed, digest-verified checkpoints; a crash at any point of
  save/upload leaves an uncommitted prefix that GC reclaims.

The plane rides the telemetry/forensics infrastructure: ``checkpoint_save``
/ ``checkpoint_commit`` profile spans in the timeline,
``ray_tpu_checkpoint_{save_seconds,bytes,last_committed_step,uploads_inflight}``
metrics, ``CHECKPOINT_COMMITTED`` / ``CHECKPOINT_FAILED`` cluster events,
and a GCS-KV run registry behind ``state.list_checkpoints()`` and the
``ray_tpu ckpt`` CLI.
"""

from __future__ import annotations

import json
import os
import queue
import shutil
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu._private import external_storage as _storage

CHECKPOINT_PREFIX = "checkpoint_"
_KV_NS = "ckpt"


class CheckpointDrainError(RuntimeError):
    """fit() gave up waiting for in-flight checkpoint commits. The listed
    steps were fully reported by the workers but their background
    upload/commit had not finished when the drain timeout expired — they
    may still commit later, or never."""

    def __init__(self, undrained_steps, timeout_s: float):
        self.undrained_steps = sorted(undrained_steps)
        self.timeout_s = timeout_s
        super().__init__(
            f"checkpoint commit drain timed out after {timeout_s:.0f}s; "
            f"steps {self.undrained_steps} were reported but not committed"
        )


def step_dir_name(step: int) -> str:
    return f"{CHECKPOINT_PREFIX}{step:06d}"


def shard_dir_name(rank: int, world_size: int) -> str:
    """Per-rank shard directory. A world of one collapses the shard into the
    step directory itself, so single-worker checkpoints keep the flat
    dir-of-files layout every existing consumer expects."""
    if world_size <= 1:
        return ""
    return f"shard-{rank:05d}-of-{world_size:05d}"


def parse_step(name: str) -> Optional[int]:
    if not name.startswith(CHECKPOINT_PREFIX):
        return None
    digits = name[len(CHECKPOINT_PREFIX) :].split("_")[0].split("/")[0]
    try:
        return int(digits)
    except ValueError:
        return None


def _join(base: str, name: str) -> str:
    if _storage.has_scheme(base):
        return _storage.join(base, name)
    return os.path.join(base, name)


def resolve_staging(storage_path: str, name: str, kind: str = "trial"):
    """One run's ``(local staging dir, external mirror URI or None)``.

    External (``scheme://``, non-file) storage stages locally under the
    temp dir and mirrors through the commit protocol; ``file://`` and
    plain paths train in place with no mirror. Shared by the trainer and
    the tuner so both agree on where checkpoints stage."""
    import tempfile

    if _storage.has_scheme(storage_path) and not storage_path.startswith("file://"):
        return (
            os.path.join(
                tempfile.gettempdir(), f"ray_tpu_{kind}_{name}_{os.getpid()}"
            ),
            _storage.join(storage_path, name),
        )
    if storage_path.startswith("file://"):
        return os.path.join(storage_path[len("file://") :], name), None
    return os.path.join(storage_path, name), None


def discover_steps(base: str) -> Dict[int, str]:
    """Scan a base path-or-URI for checkpoint step prefixes: step ->
    prefix. Flat-key backends (memory://, object stores) are walked through
    ``list``; local paths through ``listdir``."""
    base = (base or "").rstrip("/")
    if not base:
        return {}
    names: set = set()
    if _storage.has_scheme(base) and not base.startswith("file://"):
        try:
            keys = _storage.list_uri(base + "/")
        except ValueError:
            return {}
        for key in keys:
            rest = key[len(base) + 1 :]
            first = rest.split("/", 1)[0]
            if first.startswith(CHECKPOINT_PREFIX):
                names.add(first)
    else:
        root = base[len("file://") :] if base.startswith("file://") else base
        if not os.path.isdir(root):
            return {}
        for name in os.listdir(root):
            if name.startswith(CHECKPOINT_PREFIX) and os.path.isdir(
                os.path.join(root, name)
            ):
                names.add(name)
    out: Dict[int, str] = {}
    for name in names:
        step = parse_step(name)
        if step is not None:
            # later duplicate names for one step (legacy uuid suffixes) keep
            # the lexicographically greatest — deterministic either way
            cur = out.get(step)
            cand = _join(base, name)
            if cur is None or cand > cur:
                out[step] = cand
    return out


def list_checkpoints(base: str) -> List[dict]:
    """Every checkpoint prefix under a base, committed or not, newest
    first. Committed rows carry the manifest's metadata (size, file count,
    world size, creation time)."""
    rows: List[dict] = []
    for step, prefix in sorted(discover_steps(base).items(), reverse=True):
        manifest = _storage.read_committed_manifest(prefix)
        row = {
            "step": step,
            "path": prefix,
            "committed": manifest is not None,
        }
        if manifest is not None:
            files = manifest.get("files", {})
            row.update(
                size_bytes=sum(e.get("size", 0) for e in files.values()),
                num_files=len(files),
                world_size=manifest.get("world_size"),
                created=manifest.get("created"),
                run=manifest.get("run"),
            )
        rows.append(row)
    return rows


def latest_step(base: str) -> Optional[int]:
    """The newest *committed* step under a base, or None. Uncommitted
    prefixes (in-flight or crashed saves) are never considered."""
    for step, prefix in sorted(discover_steps(base).items(), reverse=True):
        if _storage.is_committed(prefix):
            return step
    return None


def latest_checkpoint(base: str):
    """``Checkpoint`` for the newest committed step under a base (local
    path: points at the directory; URI: verified download), or None."""
    steps = discover_steps(base)
    for step in sorted(steps, reverse=True):
        prefix = steps[step]
        if not _storage.is_committed(prefix):
            continue
        return load_checkpoint(prefix)
    return None


def load_checkpoint(path_or_uri: str):
    """Materialize one checkpoint reference. URIs restore through the
    digest-verified path (``Checkpoint.from_uri``); local paths are used in
    place. This is the one funnel every resume path routes through, so a
    trial restarted on another node restores from the URI instead of a
    dead node's local directory."""
    from ray_tpu.train._checkpoint import Checkpoint

    if _storage.has_scheme(path_or_uri) and not path_or_uri.startswith("file://"):
        return Checkpoint.from_uri(path_or_uri)
    path = path_or_uri[len("file://") :] if path_or_uri.startswith("file://") else path_or_uri
    if not os.path.isdir(path):
        raise FileNotFoundError(f"checkpoint directory {path} does not exist")
    return Checkpoint(path)


def verify_checkpoint(prefix: str) -> dict:
    """Re-read a committed prefix and verify every file against its
    manifest digest (in place, no local materialization). Returns the
    manifest; raises
    :class:`~ray_tpu._private.external_storage.IntegrityError` on any
    mismatch or when the prefix is uncommitted."""
    manifest = _storage.read_committed_manifest(prefix)
    if manifest is None:
        raise _storage.IntegrityError(f"no committed manifest under {prefix}")
    for rel, entry in manifest.get("files", {}).items():
        _storage.verify_file(prefix, rel, entry)
    return manifest


def _classify_steps(base: str):
    """(step -> prefix, sorted committed steps) for one base — shared by
    scoring and GC so a retention pass lists/reads each prefix once."""
    steps = discover_steps(base)
    committed = [s for s in sorted(steps) if _storage.is_committed(steps[s])]
    return steps, committed


def gc_checkpoints(
    base: str,
    *,
    keep: Optional[int] = None,
    max_age_s: Optional[float] = None,
    protect: Optional[set] = None,
    doomed_steps: Optional[set] = None,
    classified=None,
) -> List[int]:
    """Retention GC over one base: keep the newest ``keep`` committed
    checkpoints (or an explicit ``doomed_steps`` set chosen by score),
    drop committed ones older than ``max_age_s``, and reclaim uncommitted
    garbage older than the newest committed step (crashed/partial saves).
    The newest committed checkpoint is never deleted — a run must always
    keep its resume point. Returns the deleted steps. ``classified`` is an
    optional precomputed :func:`_classify_steps` result (spares a second
    remote scan when the caller already classified the base)."""
    steps, committed = classified if classified is not None else _classify_steps(base)
    if not steps:
        return []
    protect = protect or set()
    doomed: set = set()
    if committed:
        newest = committed[-1]
        if doomed_steps is not None:
            doomed |= {s for s in doomed_steps if s in steps}
        elif keep is not None and keep > 0 and len(committed) > keep:
            doomed |= set(committed[:-keep])
        if max_age_s is not None:
            now = time.time()
            for s in committed:
                manifest = _storage.read_committed_manifest(steps[s]) or {}
                created = manifest.get("created")
                if created is not None and now - created > max_age_s:
                    doomed.add(s)
        # uncommitted prefixes older than the newest committed step are
        # crashed saves (anything newer may be an in-flight upload)
        doomed |= {s for s in steps if s not in committed and s < newest}
        doomed.discard(newest)
    doomed -= protect
    deleted = []
    for s in sorted(doomed):
        try:
            _storage.delete_prefix(steps[s])
            deleted.append(s)
        except Exception:
            pass  # a half-deleted prefix is uncommitted: the next GC retries
    return deleted


# --------------------------------------------------------------------------
# telemetry surface
# --------------------------------------------------------------------------

_metrics_lock = threading.Lock()
_metrics: Optional[Dict[str, Any]] = None


def _get_metrics() -> Dict[str, Any]:
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge, Histogram

            _metrics = {
                "save_seconds": Histogram(
                    "ray_tpu_checkpoint_save_seconds",
                    "in-loop checkpoint snapshot latency (what train.report blocks on)",
                    boundaries=[0.001, 0.01, 0.05, 0.1, 0.5, 1, 5, 30],
                ),
                "commit_seconds": Histogram(
                    "ray_tpu_checkpoint_commit_seconds",
                    "background upload+commit latency per checkpoint",
                    boundaries=[0.01, 0.1, 0.5, 1, 5, 30, 120],
                ),
                "bytes": Counter(
                    "ray_tpu_checkpoint_bytes",
                    "total bytes committed by the checkpoint plane",
                ),
                "last_committed_step": Gauge(
                    "ray_tpu_checkpoint_last_committed_step",
                    "newest committed checkpoint step",
                    tag_keys=("run",),
                ),
                "uploads_inflight": Gauge(
                    "ray_tpu_checkpoint_uploads_inflight",
                    "checkpoint commits queued or running in the background uploader",
                    tag_keys=("run",),
                ),
                "failed_total": Counter(
                    "ray_tpu_checkpoint_failed_total",
                    "checkpoint commits that failed (no COMMIT written)",
                    tag_keys=("run",),
                ),
            }
    return _metrics


def observe_save_seconds(seconds: float) -> None:
    """Record one in-loop snapshot latency (called by the train session)."""
    try:
        _get_metrics()["save_seconds"].observe(seconds)
    except Exception:
        pass  # telemetry must never take a save down


# --------------------------------------------------------------------------
# preemption hooks (SIGTERM drain integration)
# --------------------------------------------------------------------------

_preemption_hooks: List[Callable[[], None]] = []
_live_managers: List["CheckpointManager"] = []
_hooks_lock = threading.Lock()


def register_preemption_hook(fn: Callable[[], None]) -> Callable[[], None]:
    """Register a callable to run when this process is being preempted
    (SIGTERM drain). Typical use from a train loop: snapshot model state
    and ``train.report(checkpoint=...)`` one last time. Best-effort — the
    drain window is bounded. Returns ``fn`` so it can be used as a
    decorator."""
    with _hooks_lock:
        _preemption_hooks.append(fn)
    return fn


def unregister_preemption_hook(fn: Callable[[], None]) -> None:
    with _hooks_lock:
        try:
            _preemption_hooks.remove(fn)
        except ValueError:
            pass


def run_preemption_hooks(timeout_s: float = 5.0) -> None:
    """Best-effort final snapshot on preemption: run user hooks (each may
    report a final checkpoint), then drain every live manager so barriered
    saves reach COMMIT before the process dies. Called from the worker's
    SIGTERM drain thread; the caller's hard-exit backstop bounds us."""
    deadline = time.monotonic() + timeout_s
    with _hooks_lock:
        hooks = list(_preemption_hooks)
        managers = list(_live_managers)
    for fn in hooks:
        if time.monotonic() >= deadline:
            break
        try:
            fn()
        except Exception:
            pass
    for mgr in managers:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        try:
            mgr.wait(timeout=remaining)
        except Exception:
            pass


# --------------------------------------------------------------------------
# CheckpointManager
# --------------------------------------------------------------------------


class CheckpointManager:
    """Head-side coordinator: shard barrier -> async commit -> retention.

    Workers snapshot shards locally and report; the manager (running where
    reports arrive — the trainer driver or a tune trial actor) completes
    the barrier when all ``world_size`` ranks have reported a step, then
    hands the step to a bounded-queue background thread that writes the
    manifest, commits locally, mirrors to ``storage_uri`` (committed there
    too), updates the KV run registry, and enforces retention."""

    def __init__(
        self,
        local_base: str,
        *,
        storage_uri: Optional[str] = None,
        world_size: int = 1,
        keep: Optional[int] = None,
        max_age_s: Optional[float] = None,
        max_inflight: int = 2,
        run_name: Optional[str] = None,
        score_attribute: Optional[str] = None,
        score_order: str = "max",
        sync: bool = False,
    ):
        self.local_base = os.path.abspath(local_base)
        self.storage_uri = storage_uri
        self.world_size = max(1, int(world_size))
        self.keep = keep
        self.max_age_s = max_age_s
        self.run_name = run_name or os.path.basename(self.local_base)
        self.score_attribute = score_attribute
        self.score_order = score_order
        self.sync = sync
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending: Dict[int, set] = {}  # step -> ranks with a shard in
        self._reported: Dict[int, set] = {}  # step -> ranks reported at all
        self._step_dirs: Dict[int, str] = {}
        self._step_metrics: Dict[int, dict] = {}
        self._committed: Dict[int, dict] = {}  # step -> manifest
        self._failed: Dict[int, str] = {}
        self._outstanding = 0  # queued + running commits
        self._inflight_steps: set = set()  # the steps behind _outstanding
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, max_inflight))
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        with _hooks_lock:
            _live_managers.append(self)
        self._update_registry()

    # -- save path ---------------------------------------------------------

    def note_shard(
        self,
        rank: int,
        step: int,
        shard_path: str,
        metrics: Optional[dict] = None,
    ) -> bool:
        """One rank's shard for a step has landed locally. Returns True
        when this report completed the barrier (all ranks in) and the
        commit was scheduled."""
        return self.note_report(rank, step, shard_path, metrics=metrics)

    def note_report(
        self,
        rank: int,
        step: int,
        shard_path: Optional[str] = None,
        metrics: Optional[dict] = None,
    ) -> bool:
        """One rank reported a step — with a local shard (``shard_path``)
        or metrics-only. The barrier completes when every rank's shard is
        in, OR when every rank has reported the step and at least one
        brought a shard: rank-0-only checkpointing (the reference's
        default gather pattern, ``if rank == 0: report(ckpt)``) commits a
        single-shard checkpoint instead of stalling forever. Returns True
        when this call scheduled the commit."""
        with self._lock:
            if self._closed or step in self._committed:
                return False
            reported = self._reported.setdefault(step, set())
            reported.add(rank)
            shards = self._pending.setdefault(step, set())
            if shard_path is not None:
                # a re-reported step clears its earlier failure: the
                # retried attempt re-saves it and the commit (a full
                # overwrite) runs again
                self._failed.pop(step, None)
                shards.add(rank)
                step_dir = os.path.abspath(shard_path)
                if self.world_size > 1 and os.path.basename(step_dir).startswith(
                    "shard-"
                ):
                    step_dir = os.path.dirname(step_dir)
                self._step_dirs[step] = step_dir
            if metrics is not None and (rank == 0 or step not in self._step_metrics):
                self._step_metrics[step] = dict(metrics)
            complete = bool(shards) and (
                len(shards) >= self.world_size
                or len(reported) >= self.world_size
            )
            if complete:
                del self._pending[step]
                self._reported.pop(step, None)
                self._outstanding += 1
                self._inflight_steps.add(step)
            elif len(reported) >= self.world_size and not shards:
                # metrics-only step: every rank is in, nobody checkpointed
                self._pending.pop(step, None)
                self._reported.pop(step, None)
        if complete:
            self._set_inflight_gauge()
            if self.sync:
                self._commit_one(step)
            else:
                self._ensure_thread()
                self._queue.put(step)  # bounded: blocks = backpressure
        return complete

    def reset_barrier(self) -> None:
        """Forget partially-reported steps. Called between retry attempts:
        a dead attempt's half-complete barrier must not count toward the
        retried attempt's reports — stale ranks could otherwise complete
        the barrier while the retry is still rewriting the step dir,
        committing a torn mix of the two attempts' bytes."""
        with self._lock:
            self._pending.clear()
            self._reported.clear()

    def resize(self, world_size: int) -> None:
        """Elastic resize: the worker group shrank or grew (N→M). Future
        barriers complete at the NEW world size; partially-reported steps
        from the old world are forgotten (their surviving ranks are about
        to resume from the last committed step and re-report them)."""
        with self._lock:
            self.world_size = max(1, int(world_size))
            self._pending.clear()
            self._reported.clear()
        self._update_registry()

    def pending_steps(self) -> List[int]:
        """Steps whose background upload/commit is queued or running —
        what a drain timeout leaves behind."""
        with self._lock:
            return sorted(self._inflight_steps)

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        t = threading.Thread(
            target=self._uploader_loop, name="ray_tpu-ckpt-uploader", daemon=True
        )
        self._thread = t
        t.start()

    def _uploader_loop(self) -> None:
        while True:
            step = self._queue.get()
            if step is None:
                return
            try:
                self._commit_one(step)
            except Exception:
                pass  # accounted inside _commit_one

    def _commit_one(self, step: int) -> None:
        from ray_tpu._private import telemetry
        from ray_tpu._private.profiling import profile

        step_dir = self._step_dirs.get(step)
        t0 = time.monotonic()
        try:
            with profile(
                "checkpoint_commit", {"step": step, "run": self.run_name}
            ):
                manifest = _storage.build_manifest(
                    step_dir,
                    step=step,
                    world_size=self.world_size,
                    run=self.run_name,
                    created=time.time(),
                )
                if not manifest["files"]:
                    # the step dir vanished under us (concurrent GC / CLI):
                    # committing would mint a "valid" EMPTY checkpoint that
                    # latest() prefers and resume restores nothing from
                    raise _storage.IntegrityError(
                        f"step dir {step_dir} is empty or missing at commit time"
                    )
                # local commit first: the step becomes resumable the moment
                # its bytes are safe on local disk, before the (slow) mirror
                _storage.write_commit_markers(step_dir, manifest)
                if self.storage_uri:
                    with profile(
                        "checkpoint_upload", {"step": step, "run": self.run_name}
                    ):
                        _storage.commit_dir_to_uri(
                            step_dir,
                            _storage.join(self.storage_uri, step_dir_name(step)),
                            manifest,
                        )
        except Exception as e:  # noqa: BLE001
            with self._cv:
                self._failed[step] = repr(e)
                self._outstanding -= 1
                self._inflight_steps.discard(step)
                self._cv.notify_all()
            self._set_inflight_gauge()
            try:
                _get_metrics()["failed_total"].inc(tags={"run": self.run_name})
                telemetry.record_cluster_event(
                    "CHECKPOINT_FAILED",
                    f"checkpoint step {step} of run {self.run_name} failed to "
                    f"commit: {e!r}",
                    severity="ERROR",
                    source="TRAIN",
                    step=step,
                    run=self.run_name,
                )
            except Exception:
                pass
            return
        size = sum(e.get("size", 0) for e in manifest["files"].values())
        with self._cv:
            self._committed[step] = manifest
        if self.world_size > 1:
            shards = {
                rel.split("/", 1)[0].split(os.sep, 1)[0]
                for rel in manifest["files"]
                if rel.startswith("shard-")
            }
            if 0 < len(shards) < self.world_size:
                # legitimate for the rank-0-gather pattern, but loud: a
                # rank whose reports drifted out of step would silently
                # lose its shard otherwise
                try:
                    telemetry.record_cluster_event(
                        "CHECKPOINT_COMMITTED",
                        f"checkpoint step {step} of run {self.run_name} "
                        f"committed with {len(shards)}/{self.world_size} "
                        f"shards (rank-0-gather pattern, or rank report skew)",
                        severity="WARNING",
                        source="TRAIN",
                        step=step,
                        run=self.run_name,
                    )
                except Exception:
                    pass
        try:
            m = _get_metrics()
            m["commit_seconds"].observe(time.monotonic() - t0)
            m["bytes"].inc(size)
            m["last_committed_step"].set(step, tags={"run": self.run_name})
            telemetry.record_cluster_event(
                "CHECKPOINT_COMMITTED",
                f"checkpoint step {step} of run {self.run_name} committed "
                f"({len(manifest['files'])} files, {size} bytes"
                + (f", mirrored to {self.storage_uri}" if self.storage_uri else "")
                + ")",
                source="TRAIN",
                step=step,
                run=self.run_name,
            )
        except Exception:
            pass
        try:
            self.gc()
        except Exception:
            pass
        self._update_registry()
        # the decrement comes LAST: wait() returning means commit AND
        # retention have fully settled, so a resume or shutdown never races
        # a half-finished GC
        with self._cv:
            self._outstanding -= 1
            self._inflight_steps.discard(step)
            self._cv.notify_all()
        self._set_inflight_gauge()

    # -- read path ---------------------------------------------------------

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until every queued/running commit finishes. True when the
        plane is drained."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._outstanding > 0:
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(remaining if remaining is not None else 1.0)
        return True

    def latest_step(self) -> Optional[int]:
        """Newest committed step (disk truth, not just this manager's
        in-memory view — a restarted driver sees prior commits)."""
        return latest_step(self.local_base)

    def latest_checkpoint(self):
        """``Checkpoint`` for the newest committed step: local directory
        when present, else verified restore from the storage mirror."""
        ckpt = latest_checkpoint(self.local_base)
        if ckpt is None and self.storage_uri:
            ckpt = latest_checkpoint(self.storage_uri)
        return ckpt

    def list(self) -> List[dict]:
        return list_checkpoints(self.local_base)

    def failures(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._failed)

    # -- retention ---------------------------------------------------------

    def _score_doomed(self, committed: List[int]) -> Optional[set]:
        """Score-based retention (CheckpointConfig.checkpoint_score_attribute):
        doom all but the best ``keep`` of the given committed steps by the
        recorded metric (per base — local and the mirror can hold
        different step sets). None = use recency. Committed steps found on
        disk but not scored by THIS incarnation (a restarted driver)
        default to score 0.0, so prior runs' checkpoints still participate
        in retention instead of accumulating forever."""
        if not self.score_attribute or self.keep is None:
            return None
        with self._lock:
            scores = {
                s: (self._step_metrics.get(s) or {}).get(self.score_attribute, 0.0)
                for s in committed
            }
        if len(committed) <= self.keep:
            return set()
        reverse = self.score_order == "max"
        ranked = sorted(committed, key=lambda s: scores[s], reverse=reverse)
        return set(ranked[self.keep :])

    def gc(self) -> List[int]:
        """Enforce retention on the local staging base and the storage
        mirror. In-flight and barrier-pending steps are protected. With no
        retention policy configured this is a no-op — the per-commit scan
        of every prior step (remote reads on the mirror) would otherwise
        grow O(steps) for nothing."""
        if self.keep is None and self.max_age_s is None and not self.score_attribute:
            return []
        with self._lock:
            protect = set(self._pending) | {
                s
                for s in self._step_dirs
                if s not in self._committed and s not in self._failed
            }
        classified = _classify_steps(self.local_base)
        deleted = gc_checkpoints(
            self.local_base,
            keep=self.keep,
            max_age_s=self.max_age_s,
            protect=protect,
            doomed_steps=self._score_doomed(classified[1]),
            classified=classified,
        )
        if self.storage_uri:
            classified = _classify_steps(self.storage_uri)
            deleted_remote = gc_checkpoints(
                self.storage_uri,
                keep=self.keep,
                max_age_s=self.max_age_s,
                protect=protect,
                doomed_steps=self._score_doomed(classified[1]),
                classified=classified,
            )
            deleted = sorted(set(deleted) | set(deleted_remote))
        if deleted:
            with self._lock:
                for s in deleted:
                    self._committed.pop(s, None)
                    self._step_dirs.pop(s, None)
                    self._step_metrics.pop(s, None)
        return deleted

    # -- registry / lifecycle ---------------------------------------------

    def _set_inflight_gauge(self) -> None:
        try:
            with self._lock:
                n = self._outstanding
            _get_metrics()["uploads_inflight"].set(n, tags={"run": self.run_name})
        except Exception:
            pass

    def _update_registry(self) -> None:
        """Advertise this run in the GCS KV so ``state.list_checkpoints()``
        and the CLI can find it without being handed a path."""
        rt = _runtime()
        if rt is None:
            return
        with self._lock:
            last = max(self._committed) if self._committed else None
        entry = {
            "run": self.run_name,
            "local_base": self.local_base,
            "storage_uri": self.storage_uri,
            "world_size": self.world_size,
            "last_committed_step": last,
            "updated": time.time(),
        }
        try:
            blob = json.dumps(entry).encode()
            key = self.run_name.encode()
            if hasattr(rt, "scheduler_rpc"):
                rt.scheduler_rpc("kv_put", (_KV_NS, key, blob, True))
            else:
                rt.rpc("kv_put", _KV_NS, key, blob, True)
        except Exception:
            pass

    def shutdown(self, wait: bool = True, timeout: Optional[float] = 60.0) -> None:
        if wait:
            self.wait(timeout=timeout)
        with self._lock:
            self._closed = True
        if self._thread is not None and self._thread.is_alive():
            try:
                self._queue.put_nowait(None)
            except queue.Full:
                pass
        with _hooks_lock:
            try:
                _live_managers.remove(self)
            except ValueError:
                pass


def _runtime():
    from ray_tpu._private import worker as worker_mod

    rt = worker_mod._worker_runtime
    if rt is not None:
        return rt
    return worker_mod._driver


def registered_runs() -> List[dict]:
    """Every run advertised in the KV checkpoint registry."""
    rt = _runtime()
    if rt is None:
        return []
    try:
        if hasattr(rt, "scheduler_rpc"):
            keys = rt.scheduler_rpc("kv_keys", (_KV_NS, b""))
            get = lambda k: rt.scheduler_rpc("kv_get", (_KV_NS, k))  # noqa: E731
        else:
            keys = rt.rpc("kv_keys", _KV_NS, b"")
            get = lambda k: rt.rpc("kv_get", _KV_NS, k)  # noqa: E731
    except Exception:
        return []
    out = []
    for key in sorted(keys or ()):
        try:
            blob = get(key)
            if blob:
                out.append(json.loads(blob))
        except Exception:
            continue
    return out


def clear_restore_cache() -> int:
    """Drop the ``Checkpoint.from_uri`` restore cache (the fix for the
    seed's per-call ``ckpt_dl_*`` temp-dir leak caches by manifest digest;
    this reclaims the disk). Returns the number of entries removed."""
    from ray_tpu.train._checkpoint import _cache_root

    root = _cache_root()
    if not os.path.isdir(root):
        return 0
    n = 0
    for name in os.listdir(root):
        shutil.rmtree(os.path.join(root, name), ignore_errors=True)
        n += 1
    return n
