"""Result of a training/tuning run. Parity: ``python/ray/air/result.py``."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.train._checkpoint import Checkpoint


@dataclass
class Result:
    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    path: str = ""
    error: Optional[Exception] = None
    metrics_dataframe: Optional[Any] = None
    best_checkpoints: List = field(default_factory=list)
    # elastic-training accounting: wall_s / useful_step_s / steps_redone /
    # goodput (useful-step-time over wall-time) for the whole fit() call,
    # across every in-run recovery and gang restart
    goodput: Optional[Dict[str, Any]] = None

    @property
    def config(self):
        return self.metrics.get("config")
