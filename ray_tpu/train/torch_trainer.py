"""TorchTrainer: data-parallel PyTorch training on the worker group.

Parity: ``TorchTrainer`` (``python/ray/train/torch/torch_trainer.py``) and
its backend (``python/ray/train/torch/config.py:65`` —
``_setup_torch_process_group``: worker 0 publishes addr/port, every worker
joins the process group; ``:150`` ``_TorchBackend``). The rendezvous rides
this framework's cluster KV instead of a raw TCP store bootstrap; the
process group uses gloo (CPU) — CUDA/NCCL has no seat on a TPU cluster, and
torch models on TPU hosts run CPU-side feeding JAX, or pure-CPU workloads.

``prepare_model`` / ``prepare_data_loader`` mirror
``python/ray/train/torch/train_loop_utils.py``.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Callable, Dict, Optional

from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._config import RunConfig, ScalingConfig
from ray_tpu.train.jax_trainer import JaxTrainer


def _node_ip() -> str:
    """This node's address as reachable by peers (loopback only as a last
    resort — workers may be on different node daemons)."""
    import socket

    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))  # no packets sent; just picks a route
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"


def _setup_torch_process_group(rendezvous_key: str):
    """Join the gloo process group; rank 0 publishes the store address."""
    import socket

    import torch.distributed as dist

    from ray_tpu._private.worker import get_runtime
    from ray_tpu.train._session import get_context

    ctx = get_context()
    rank, world = ctx.get_world_rank(), ctx.get_world_size()
    if world <= 1:
        return False
    rt = get_runtime()
    if rank == 0:
        host = _node_ip()
        s = socket.socket()
        s.bind(("0.0.0.0", 0))
        port = s.getsockname()[1]
        s.close()
        addr = f"tcp://{host}:{port}"
        rt.rpc("kv_put", "torch_rendezvous", rendezvous_key.encode(), addr.encode(), True)
        dist.init_process_group(
            backend="gloo",
            init_method=addr,
            rank=rank,
            world_size=world,
            # bounded: a peer dying pre-join must not stall rank 0 for
            # gloo's 30-minute default
            timeout=__import__("datetime").timedelta(seconds=120),
        )
        return True
    # non-zero ranks: the key may briefly hold a previous (failed) attempt's
    # address — retry with a fresh read if joining fails
    last_err = None
    for _ in range(3):
        deadline = time.monotonic() + 60
        addr = None
        while time.monotonic() < deadline:
            raw = rt.rpc("kv_get", "torch_rendezvous", rendezvous_key.encode())
            if raw:
                addr = raw.decode()
                break
            time.sleep(0.05)
        if addr is None:
            raise RuntimeError("torch rendezvous timed out")
        try:
            dist.init_process_group(
                backend="gloo",
                init_method=addr,
                rank=rank,
                world_size=world,
                timeout=__import__("datetime").timedelta(seconds=60),
            )
            return True
        except Exception as e:  # noqa: BLE001
            last_err = e
            time.sleep(1.0)
    raise RuntimeError(f"could not join torch process group: {last_err}")


def prepare_model(model):
    """Wrap in DDP when the group is initialized (parity:
    ``train.torch.prepare_model``, ``train_loop_utils.py``)."""
    import torch.distributed as dist
    from torch.nn.parallel import DistributedDataParallel

    if dist.is_available() and dist.is_initialized() and dist.get_world_size() > 1:
        return DistributedDataParallel(model)
    return model


def prepare_data_loader(data_loader):
    """Shard a DataLoader across the group with a DistributedSampler,
    preserving the source loader's ordering and settings."""
    import torch.distributed as dist
    from torch.utils.data import DataLoader, RandomSampler
    from torch.utils.data.distributed import DistributedSampler

    if not (dist.is_available() and dist.is_initialized() and dist.get_world_size() > 1):
        return data_loader
    shuffle = isinstance(getattr(data_loader, "sampler", None), RandomSampler)
    sampler = DistributedSampler(data_loader.dataset, shuffle=shuffle)
    loader = DataLoader(
        data_loader.dataset,
        batch_size=data_loader.batch_size,
        sampler=sampler,
        num_workers=data_loader.num_workers,
        pin_memory=data_loader.pin_memory,
        collate_fn=data_loader.collate_fn,
        drop_last=data_loader.drop_last,
    )
    return _EpochAdvancingLoader(loader, sampler)


class _EpochAdvancingLoader:
    """Advances the DistributedSampler epoch per iteration so shuffled
    loaders reshuffle each epoch (the reference's prepare_data_loader does
    this inside its iterator wrapper)."""

    def __init__(self, loader, sampler):
        self._loader = loader
        self._sampler = sampler
        self._epoch = 0

    def __iter__(self):
        self._sampler.set_epoch(self._epoch)
        self._epoch += 1
        return iter(self._loader)

    def __len__(self):
        return len(self._loader)

    def __getattr__(self, name):
        return getattr(self._loader, name)


class TorchTrainer(JaxTrainer):
    """Same fit machinery (worker group in a PG, report/checkpoint plumbing);
    the train loop is wrapped with the gloo process-group lifecycle."""

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        key = f"tt_{uuid.uuid4().hex[:12]}"
        user_fn = train_loop_per_worker

        def wrapped(config=None):
            import inspect

            joined = _setup_torch_process_group(key)
            try:
                if config is not None and len(inspect.signature(user_fn).parameters):
                    return user_fn(config)
                return user_fn()
            finally:
                if joined:
                    import torch.distributed as dist

                    dist.destroy_process_group()
                    from ray_tpu.train._session import get_context

                    if get_context().get_world_rank() == 0:
                        # drop the published address so a failure-retry never
                        # reads a dead store's endpoint
                        try:
                            from ray_tpu._private.worker import get_runtime

                            get_runtime().rpc(
                                "kv_del", "torch_rendezvous", key.encode()
                            )
                        except Exception:
                            pass

        super().__init__(
            wrapped,
            train_loop_config=train_loop_config,
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint,
        )
