"""TensorflowTrainer: multi-worker TF training on the worker group.

Parity: ``TensorflowTrainer`` + ``_TensorflowBackend``
(``python/ray/train/tensorflow/config.py`` — ``_setup_tensorflow_environment``
assembles ``TF_CONFIG`` from the workers' published addresses so
``tf.distribute.MultiWorkerMirroredStrategy`` can rendezvous). Here every
worker publishes host:port through the cluster KV, worker 0 collects the
roster, and each worker exports TF_CONFIG before the user loop runs.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Any, Callable, Dict, Optional

from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._config import RunConfig, ScalingConfig
from ray_tpu.train.jax_trainer import JaxTrainer
from ray_tpu.train.torch_trainer import _node_ip


def _setup_tf_config(rendezvous_key: str) -> bool:
    """Publish this worker's address, gather the full roster, set TF_CONFIG."""
    import socket

    from ray_tpu._private.worker import get_runtime
    from ray_tpu.train._session import get_context

    ctx = get_context()
    rank, world = ctx.get_world_rank(), ctx.get_world_size()
    if world <= 1:
        os.environ.pop("TF_CONFIG", None)
        return False
    rt = get_runtime()
    # reserve a port (close before TF binds it; the small race window is the
    # same one the reference accepts in its setup_address)
    s = socket.socket()
    s.bind(("0.0.0.0", 0))
    port = s.getsockname()[1]
    s.close()
    rt.rpc(
        "kv_put",
        "tf_rendezvous",
        f"{rendezvous_key}:{rank}".encode(),
        f"{_node_ip()}:{port}".encode(),
        True,
    )
    roster = [None] * world
    # generous: TF imports + worker spawn can take tens of seconds on a
    # loaded single-core box
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        missing = False
        for r in range(world):
            if roster[r] is None:
                raw = rt.rpc("kv_get", "tf_rendezvous", f"{rendezvous_key}:{r}".encode())
                if raw:
                    roster[r] = raw.decode()
                else:
                    missing = True
        if not missing:
            break
        time.sleep(0.05)
    else:
        raise RuntimeError("tensorflow rendezvous timed out")
    os.environ["TF_CONFIG"] = json.dumps(
        {"cluster": {"worker": roster}, "task": {"type": "worker", "index": rank}}
    )
    return True


def prepare_dataset_shard(dataset):
    """Disable TF auto-sharding (the data is already per-worker sharded by
    this framework's Data library; parity: train.tensorflow.prepare_dataset_shard)."""
    import tensorflow as tf

    options = tf.data.Options()
    options.experimental_distribute.auto_shard_policy = (
        tf.data.experimental.AutoShardPolicy.OFF
    )
    return dataset.with_options(options)


class TensorflowTrainer(JaxTrainer):
    """Same fit machinery (worker group in a PG, report/checkpoint plumbing);
    the train loop runs with TF_CONFIG exported for MultiWorkerMirroredStrategy."""

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        key = f"tf_{uuid.uuid4().hex[:12]}"
        user_fn = train_loop_per_worker

        def wrapped(config=None):
            import inspect

            joined = _setup_tf_config(key)
            try:
                if config is not None and len(inspect.signature(user_fn).parameters):
                    return user_fn(config)
                return user_fn()
            finally:
                if joined:
                    os.environ.pop("TF_CONFIG", None)
                    from ray_tpu._private.worker import get_runtime
                    from ray_tpu.train._session import get_context

                    try:
                        rank = get_context().get_world_rank()
                        get_runtime().rpc(
                            "kv_del", "tf_rendezvous", f"{key}:{rank}".encode()
                        )
                    except Exception:
                        pass

        super().__init__(
            wrapped,
            train_loop_config=train_loop_config,
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint,
        )
