"""Train/Tune shared configs.

Parity: ``python/ray/air/config.py`` (``ScalingConfig``, ``RunConfig``,
``FailureConfig``, ``CheckpointConfig``). The TPU extension: ``ScalingConfig``
can name a slice topology, which the placement layer turns into a
slice-atomic placement group (SURVEY.md §7 step 4).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    num_workers: int = 1
    # elasticity band: when workers die and replacements cannot be
    # provisioned within FailureConfig.replacement_timeout_s, the group may
    # shrink down to this floor (and grow back toward num_workers on later
    # recoveries) instead of failing the attempt. None = not elastic: the
    # run needs exactly num_workers.
    min_workers: Optional[int] = None
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    # TPU slice topology, e.g. "v5litepod-16": one worker per slice host,
    # gang-scheduled onto an ICI-connected slice
    topology: Optional[str] = None
    # Multi-host SPMD: each worker process joins a jax.distributed
    # coordination service (rendezvous over the cluster KV) so jax.devices()
    # becomes the global device set and one jitted step spans all hosts.
    # The TPU-native replacement for the reference's NCCL process-group
    # setup (python/ray/train/torch/config.py:65).
    use_jax_distributed: bool = False
    # runtime_env applied to each train worker actor (env_vars etc.) — used
    # e.g. to force per-worker virtual CPU device counts in tests
    worker_runtime_env: Optional[Dict] = None

    def worker_resources(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            return dict(self.resources_per_worker)
        res: Dict[str, float] = {"CPU": 1.0}
        if self.use_tpu:
            res["TPU"] = 1.0
        return res

    def effective_min_workers(self) -> int:
        if self.min_workers is None:
            return self.num_workers
        return max(1, min(self.min_workers, self.num_workers))

    @property
    def total_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for k, v in self.worker_resources().items():
            out[k] = v * self.num_workers
        return out


@dataclass
class FailureConfig:
    max_failures: int = 0  # -1 = infinite
    # Backoff between whole-gang restart attempts: exponential from
    # retry_backoff_s (doubling per consecutive failure) capped at
    # retry_backoff_max_s, with +/- retry_backoff_jitter fraction of
    # randomization so a crash-looping gang doesn't hammer the scheduler
    # in lockstep. jitter=0 makes the schedule deterministic.
    retry_backoff_s: float = 1.0
    retry_backoff_max_s: float = 30.0
    retry_backoff_jitter: float = 0.5
    # In-run worker replacement (elastic training): on worker/actor death,
    # keep surviving workers' processes alive, provision replacements for
    # the dead ranks, and resume every rank from the last committed
    # checkpoint — the whole-gang restart above becomes the fallback. A
    # replacement that is not up within replacement_timeout_s is given up
    # on (the group then shrinks if ScalingConfig.min_workers allows).
    replace_workers: bool = True
    replacement_timeout_s: float = 20.0
    # how long to wait for surviving ranks to unwind (they notice the
    # abort at their next train.report) before they are killed and treated
    # as lost too
    abort_drain_timeout_s: float = 60.0
    # in-run recoveries are free while the run makes progress (real
    # preemption churn advances steps between losses), but a
    # deterministically crashing rank would otherwise kill/replace/resume
    # forever: after this many consecutive recoveries with NO new step
    # completed, the attempt fails over to the (max_failures-capped,
    # backed-off) gang restart
    max_recoveries_without_progress: int = 3
    # proactively replace a rank flagged by the scheduler's STRAGGLER
    # watchdog (kill + re-provision) instead of waiting for the collective
    # to time out. Off by default: a straggler still makes progress, and
    # the watchdog pools runtimes by METHOD name — short aborted run()
    # attempts seed a small p95 that can flag legitimate long runs
    # (bounded by straggler_min_runtime_s); tune straggler_* system
    # config before enabling on long train loops.
    replace_stragglers: bool = False


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    # fit() drains in-flight checkpoint commits for at most this long
    # before returning; a drain timeout surfaces as a CHECKPOINT_FAILED
    # cluster event plus CheckpointDrainError context on Result.error
    # (never a silent return that looks fully committed)
    drain_timeout_s: float = 120.0


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 1
    # stop criteria for Tune trials: a tune.Stopper, a {"metric": threshold}
    # dict, or a callable(trial_id, result) -> bool
    stop: Optional[Any] = None

    def resolved_storage_path(self) -> str:
        return self.storage_path or os.path.expanduser("~/ray_tpu_results")
