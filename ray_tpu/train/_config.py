"""Train/Tune shared configs.

Parity: ``python/ray/air/config.py`` (``ScalingConfig``, ``RunConfig``,
``FailureConfig``, ``CheckpointConfig``). The TPU extension: ``ScalingConfig``
can name a slice topology, which the placement layer turns into a
slice-atomic placement group (SURVEY.md §7 step 4).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    # TPU slice topology, e.g. "v5litepod-16": one worker per slice host,
    # gang-scheduled onto an ICI-connected slice
    topology: Optional[str] = None
    # Multi-host SPMD: each worker process joins a jax.distributed
    # coordination service (rendezvous over the cluster KV) so jax.devices()
    # becomes the global device set and one jitted step spans all hosts.
    # The TPU-native replacement for the reference's NCCL process-group
    # setup (python/ray/train/torch/config.py:65).
    use_jax_distributed: bool = False
    # runtime_env applied to each train worker actor (env_vars etc.) — used
    # e.g. to force per-worker virtual CPU device counts in tests
    worker_runtime_env: Optional[Dict] = None

    def worker_resources(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            return dict(self.resources_per_worker)
        res: Dict[str, float] = {"CPU": 1.0}
        if self.use_tpu:
            res["TPU"] = 1.0
        return res

    @property
    def total_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for k, v in self.worker_resources().items():
            out[k] = v * self.num_workers
        return out


@dataclass
class FailureConfig:
    max_failures: int = 0  # -1 = infinite


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 1
    # stop criteria for Tune trials: a tune.Stopper, a {"metric": threshold}
    # dict, or a callable(trial_id, result) -> bool
    stop: Optional[Any] = None

    def resolved_storage_path(self) -> str:
        return self.storage_path or os.path.expanduser("~/ray_tpu_results")
