"""Distributed training library (Ray Train equivalent).

Parity: ``python/ray/train`` — ``BaseTrainer.fit`` (``base_trainer.py:567``),
``DataParallelTrainer`` (``data_parallel_trainer.py:25``), ``BackendExecutor``
(``_internal/backend_executor.py:67``), in-worker session with
``train.report`` (``_internal/session.py:667``). The framework backend is JAX:
worker group = one actor per TPU host; collectives run inside jit over ICI
(SURVEY.md §2.3 DP row), so there is no NCCL rendezvous step — the backend
just aligns mesh construction across hosts.
"""

from ray_tpu.train import checkpointing, elastic
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train.checkpointing import CheckpointManager, register_preemption_hook
from ray_tpu.train._config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train._result import Result
from ray_tpu.train._session import (
    get_checkpoint,
    get_context,
    get_dataset_shard,
    load_elastic,
    report,
    report_elastic,
)
from ray_tpu.train.jax_trainer import JaxTrainer
from ray_tpu.train.tensorflow_trainer import TensorflowTrainer, prepare_dataset_shard
from ray_tpu.train.torch_trainer import TorchTrainer, prepare_data_loader, prepare_model

__all__ = [
    "Checkpoint",
    "CheckpointConfig",
    "CheckpointManager",
    "checkpointing",
    "register_preemption_hook",
    "FailureConfig",
    "RunConfig",
    "ScalingConfig",
    "Result",
    "JaxTrainer",
    "TorchTrainer",
    "TensorflowTrainer",
    "prepare_dataset_shard",
    "prepare_model",
    "prepare_data_loader",
    "report",
    "report_elastic",
    "load_elastic",
    "elastic",
    "get_context",
    "get_checkpoint",
    "get_dataset_shard",
]

from ray_tpu._private import usage as _usage

_usage.record_library_usage("train")
del _usage
