"""Elastic checkpoint format: N→M rank re-sharding over committed shards.

The checkpoint plane (PR 5) commits per-rank shard directories
(``shard-{rank:05d}-of-{world:05d}``) under one step prefix with a manifest
+ atomic COMMIT marker. That made restores *trusted*; this module makes
them *elastic*: a checkpoint committed by N ranks can be restored into M
ranks, for any N and M, without staging the whole checkpoint anywhere.

The format is deliberately simple — the reference's train library has no
equivalent (its restore path assumes the same world size; a resized run
falls back to rank-0 gather), and orbax's process-sharded formats assume a
live global mesh. Here a shard is raw row-partitioned arrays plus a tiny
index:

* each array is partitioned along axis 0 into contiguous, balanced row
  ranges (:func:`partition_rows`) — the ZeRO/optimizer-state layout;
* a shard directory holds one ``<name>.bin`` per array (C-order bytes of
  this rank's rows) and an ``ELASTIC.json`` index: per-array dtype, global
  shape, row offset/count, and per-chunk sha256 digests of the bin file;
* on restore, each *new* rank computes the row range it owns under the new
  world size, consults every old shard's index, and reads only the byte
  ranges that overlap its rows through the storage layer's ranged-read
  path (``external_storage.read_range``) — chunk digests verify exactly
  the chunks it touched, so a corrupted shard is refused without hashing
  whole files.

Covered layouts: N→M for any N, M (including N→1 and 1→M); M>N (new ranks
whose balanced partition is empty get zero-row slices); rank-0-only
checkpoints (one shard carrying full rows 0..R) restored into any world.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu._private import external_storage as _storage

ELASTIC_INDEX = "ELASTIC.json"
ELASTIC_VERSION = 1
# digest granularity of shard bin files: a ranged read rounds out to this
# grid, so it bounds both over-read and the verification unit
_CHUNK = 4 * 1024 * 1024


def partition_rows(total_rows: int, world_size: int) -> List[Tuple[int, int]]:
    """Balanced contiguous row partition: rank r owns ``[lo, hi)``. The
    first ``total_rows % world_size`` ranks get one extra row. With more
    ranks than rows, trailing ranks own empty ranges — legal (M>N growth
    past the row count) and round-trips through save/restore."""
    if world_size < 1:
        raise ValueError(f"world_size must be >= 1, got {world_size}")
    if total_rows < 0:
        raise ValueError(f"total_rows must be >= 0, got {total_rows}")
    q, rem = divmod(total_rows, world_size)
    out = []
    lo = 0
    for r in range(world_size):
        hi = lo + q + (1 if r < rem else 0)
        out.append((lo, hi))
        lo = hi
    return out


def _chunk_digests(data: memoryview) -> List[str]:
    return [
        hashlib.sha256(data[off : off + _CHUNK]).hexdigest()
        for off in range(0, len(data), _CHUNK)
    ] or []


def save_elastic_shard(
    dest_dir: str,
    arrays: Dict[str, Any],
    *,
    rank: int = 0,
    world_size: int = 1,
    extra: Optional[dict] = None,
) -> dict:
    """Write one rank's elastic shard into ``dest_dir``.

    ``arrays`` values are either a *global* array (every rank holds the
    full replica — the common data-parallel case; this rank's balanced row
    partition is sliced out and saved) or a ``(local_slice, row_offset,
    global_rows)`` tuple for callers that already hold only their slice
    (ZeRO-style sharded optimizer state). ``extra`` is a small JSON
    metadata dict (step, hyperparameters, ...) returned verbatim on
    restore. Returns the written index."""
    os.makedirs(dest_dir, exist_ok=True)
    index: Dict[str, Any] = {
        "version": ELASTIC_VERSION,
        "rank": int(rank),
        "world_size": int(world_size),
        "arrays": {},
        "extra": extra or {},
    }
    for name, value in arrays.items():
        if "/" in name or os.sep in name or name in (ELASTIC_INDEX,):
            raise ValueError(f"illegal elastic array name {name!r}")
        if isinstance(value, tuple):
            local, row_offset, global_rows = value
            local = np.ascontiguousarray(local)
            if local.ndim < 1:
                raise ValueError(f"array {name!r} must have ndim >= 1")
            if row_offset < 0 or row_offset + local.shape[0] > global_rows:
                raise ValueError(
                    f"array {name!r}: slice [{row_offset}, "
                    f"{row_offset + local.shape[0]}) outside 0..{global_rows}"
                )
            global_shape = (int(global_rows),) + tuple(local.shape[1:])
        else:
            full = np.ascontiguousarray(value)
            if full.ndim < 1:
                raise ValueError(f"array {name!r} must have ndim >= 1")
            lo, hi = partition_rows(full.shape[0], world_size)[rank]
            local, row_offset = full[lo:hi], lo
            global_shape = tuple(full.shape)
        data = memoryview(np.ascontiguousarray(local)).cast("B")
        fname = f"{name}.bin"
        with open(os.path.join(dest_dir, fname), "wb") as fh:
            fh.write(data)
        index["arrays"][name] = {
            "file": fname,
            "dtype": np.dtype(local.dtype).str,
            "global_shape": [int(s) for s in global_shape],
            "row_offset": int(row_offset),
            "rows": int(local.shape[0]),
            "chunk": _CHUNK,
            "chunk_digests": _chunk_digests(data),
        }
    with open(os.path.join(dest_dir, ELASTIC_INDEX), "w") as fh:
        json.dump(index, fh, sort_keys=True, indent=1)
    return index


def _join(prefix: str, name: str) -> str:
    if _storage.has_scheme(prefix):
        return _storage.join(prefix, name)
    return os.path.join(prefix, name)


def _read_index(shard_prefix: str) -> Optional[dict]:
    blob = _storage.read_bytes(_join(shard_prefix, ELASTIC_INDEX))
    if blob is None:
        return None
    try:
        index = json.loads(blob)
    except ValueError as e:
        raise _storage.IntegrityError(
            f"corrupt elastic index under {shard_prefix}: {e}"
        ) from e
    if index.get("version") != ELASTIC_VERSION:
        raise _storage.IntegrityError(
            f"unsupported elastic index version {index.get('version')!r} "
            f"under {shard_prefix}"
        )
    return index


def discover_shards(source: str) -> List[str]:
    """Shard prefixes (each holding an ``ELASTIC.json``) under one step
    prefix. A world-of-one checkpoint collapses the shard into the step
    dir itself; a committed prefix is discovered through its manifest so
    the index files we are about to trust are exactly the committed
    ones."""
    return _discover(source.rstrip("/"))[1]


def _discover(source: str, manifest: Optional[dict] = None):
    """(committed manifest or None, sorted shard prefixes) — one manifest
    read serves discovery AND per-shard index verification."""
    if manifest is None:
        manifest = _storage.read_committed_manifest(source)
    names: set = set()
    if manifest is not None:
        for rel in manifest.get("files", {}):
            rel = rel.replace(os.sep, "/")
            if rel == ELASTIC_INDEX:
                names.add("")
            elif rel.endswith("/" + ELASTIC_INDEX):
                names.add(rel[: -len("/" + ELASTIC_INDEX)])
    elif _storage.has_scheme(source) and not source.startswith("file://"):
        for key in _storage.list_uri(source + "/"):
            if key.endswith("/" + ELASTIC_INDEX):
                rest = key[len(source) + 1 :]
                shard = rest[: -len("/" + ELASTIC_INDEX)]
                names.add("" if shard == "" else shard)
            elif key == _join(source, ELASTIC_INDEX):
                names.add("")
    else:
        root = source[len("file://") :] if source.startswith("file://") else source
        if os.path.isfile(os.path.join(root, ELASTIC_INDEX)):
            names.add("")
        if os.path.isdir(root):
            for name in os.listdir(root):
                if os.path.isfile(os.path.join(root, name, ELASTIC_INDEX)):
                    names.add(name)
    return manifest, sorted(_join(source, n) if n else source for n in names)


def is_elastic(source: str) -> bool:
    """Whether a step prefix (or single shard dir) carries elastic
    indexes — i.e. :func:`load_elastic_state` can re-shard it."""
    return bool(discover_shards(source))


def _verify_index_against_manifest(
    source: str, shard_prefix: str, manifest: Optional[dict]
) -> None:
    """When the step prefix is committed, the index file itself must match
    its manifest entry — the chunk digests we are about to trust inherit
    the manifest's integrity."""
    if manifest is None:
        return
    rel = ELASTIC_INDEX
    if shard_prefix != source:
        shard_name = shard_prefix[len(source) + 1 :]
        rel = f"{shard_name}/{ELASTIC_INDEX}"
    entry = manifest.get("files", {}).get(rel) or manifest.get("files", {}).get(
        rel.replace("/", os.sep)
    )
    if entry is None:
        raise _storage.IntegrityError(
            f"{source}: elastic index {rel!r} not in the committed manifest"
        )
    _storage.verify_file(source, rel, entry)


def load_elastic_state(
    source: str,
    *,
    rank: int = 0,
    world_size: int = 1,
    arrays: Optional[List[str]] = None,
) -> Tuple[Dict[str, np.ndarray], dict]:
    """Restore this rank's row partition of every array from an elastic
    checkpoint committed at ANY world size.

    ``source`` is a step prefix (local path or URI) — or a single shard
    dir for world-of-one layouts. Each requested array is materialized as
    this rank's balanced partition under ``world_size``
    (:func:`partition_rows` of its global rows); the bytes are assembled
    from whichever old shards overlap, via ranged reads rounded out to
    the digest-chunk grid, and every chunk read is verified against the
    shard index's sha256 before a byte of it lands in the result. Raises
    :class:`~ray_tpu._private.external_storage.IntegrityError` on any
    digest mismatch, truncated shard, or uncovered row range.

    Returns ``(arrays, extra)``: name → this rank's slice (C-contiguous
    ndarray; zero-row slices when the partition is empty), and the saver's
    ``extra`` metadata (rank 0's copy when ranks disagree).
    """
    if not 0 <= rank < world_size:
        raise ValueError(
            f"rank must be in [0, world_size): got rank={rank}, "
            f"world_size={world_size}"
        )
    source = source.rstrip("/")
    manifest, shard_prefixes = _discover(source)
    if not shard_prefixes:
        raise _storage.IntegrityError(
            f"no elastic shard indexes under {source} — not an elastic "
            f"checkpoint (save with save_elastic_shard / train.report_elastic)"
        )
    indexes: List[Tuple[str, dict]] = []
    for sp in shard_prefixes:
        _verify_index_against_manifest(source, sp, manifest)
        idx = _read_index(sp)
        if idx is not None:
            indexes.append((sp, idx))
    if not indexes:
        raise _storage.IntegrityError(f"no readable elastic index under {source}")
    # one step = one save generation: shards from two world sizes in one
    # prefix are a torn mix of attempts (the writer clears stale layouts,
    # so this only trips on externally corrupted/hand-merged dirs) — the
    # overlap would silently interleave generations' rows
    worlds = {idx.get("world_size") for _sp, idx in indexes}
    if len(worlds) > 1:
        raise _storage.IntegrityError(
            f"{source}: shards from multiple world sizes {sorted(worlds)} "
            f"under one step — refusing a mixed-generation restore"
        )
    indexes.sort(key=lambda pair: pair[1].get("rank", 0))
    extra = dict(indexes[0][1].get("extra") or {})

    # union of array specs across shards, consistency-checked
    specs: Dict[str, dict] = {}
    for sp, idx in indexes:
        for name, meta in idx.get("arrays", {}).items():
            prev = specs.get(name)
            if prev is not None and (
                prev["dtype"] != meta["dtype"]
                or prev["global_shape"] != meta["global_shape"]
            ):
                raise _storage.IntegrityError(
                    f"{source}: shards disagree on array {name!r}: "
                    f"{prev['dtype']}{prev['global_shape']} vs "
                    f"{meta['dtype']}{meta['global_shape']}"
                )
            if prev is None:
                specs[name] = {
                    "dtype": meta["dtype"],
                    "global_shape": meta["global_shape"],
                }

    wanted = list(specs) if arrays is None else list(arrays)
    missing = [n for n in wanted if n not in specs]
    if missing:
        raise KeyError(f"{source}: arrays not in elastic checkpoint: {missing}")

    out: Dict[str, np.ndarray] = {}
    for name in wanted:
        spec = specs[name]
        dtype = np.dtype(spec["dtype"])
        gshape = tuple(int(s) for s in spec["global_shape"])
        rowbytes = int(np.prod(gshape[1:], dtype=np.int64)) * dtype.itemsize
        lo, hi = partition_rows(gshape[0], world_size)[rank]
        dest = np.empty((hi - lo,) + gshape[1:], dtype=dtype)
        if hi > lo:
            if rowbytes == 0:
                pass  # zero-width rows: nothing to read, shape is enough
            else:
                covered = _fill_from_shards(
                    source, indexes, name, dest, lo, hi, rowbytes
                )
                _check_coverage(source, name, lo, hi, covered)
        out[name] = dest
    return out, extra


def _fill_from_shards(
    source: str,
    indexes: List[Tuple[str, dict]],
    name: str,
    dest: np.ndarray,
    lo: int,
    hi: int,
    rowbytes: int,
) -> List[Tuple[int, int]]:
    """Assemble dest rows [lo, hi) of one array from every old shard that
    overlaps, with chunk-verified ranged reads. Returns the covered row
    intervals."""
    dest_bytes = memoryview(dest).cast("B")
    covered: List[Tuple[int, int]] = []
    for sp, idx in indexes:
        meta = idx.get("arrays", {}).get(name)
        if meta is None:
            continue
        olo = int(meta["row_offset"])
        ohi = olo + int(meta["rows"])
        ilo, ihi = max(lo, olo), min(hi, ohi)
        if ihi <= ilo:
            continue
        chunk = int(meta.get("chunk") or _CHUNK)
        digests = meta.get("chunk_digests") or []
        file_size = int(meta["rows"]) * rowbytes
        # byte range inside the old shard's bin file, rounded out to the
        # digest-chunk grid so every chunk we read verifies
        b0 = (ilo - olo) * rowbytes
        b1 = (ihi - olo) * rowbytes
        c0 = (b0 // chunk) * chunk
        c1 = min(file_size, ((b1 + chunk - 1) // chunk) * chunk)
        buf = bytearray(c1 - c0)

        def make_dest(n, _want=c1 - c0, _buf=buf):
            return memoryview(_buf) if n == _want else None

        key = _join(sp, meta["file"])
        n = _storage.read_range(key, c0, c1 - c0, make_dest)
        if n != c1 - c0:
            raise _storage.IntegrityError(
                f"{source}: shard file {key} truncated or missing "
                f"(wanted bytes [{c0}, {c1}), got {n})"
            )
        view = memoryview(buf)
        for ci in range(c0 // chunk, (c1 + chunk - 1) // chunk):
            off = ci * chunk - c0
            piece = view[off : off + min(chunk, c1 - c0 - off)]
            if ci >= len(digests) or hashlib.sha256(piece).hexdigest() != digests[ci]:
                raise _storage.IntegrityError(
                    f"{source}: digest mismatch in shard file {key} "
                    f"chunk {ci} — refusing to re-shard from a corrupt shard"
                )
        span = memoryview(buf)[b0 - c0 : b1 - c0]
        dest_bytes[(ilo - lo) * rowbytes : (ihi - lo) * rowbytes] = span
        covered.append((ilo, ihi))
    return covered


def _check_coverage(
    source: str, name: str, lo: int, hi: int, covered: List[Tuple[int, int]]
) -> None:
    covered.sort()
    cursor = lo
    for a, b in covered:
        if a > cursor:
            break
        cursor = max(cursor, b)
    if cursor < hi:
        raise _storage.IntegrityError(
            f"{source}: array {name!r} rows [{cursor}, {hi}) not covered by "
            f"any shard — incomplete elastic checkpoint"
        )


def load_elastic_full(
    source: str, *, arrays: Optional[List[str]] = None
) -> Tuple[Dict[str, np.ndarray], dict]:
    """The whole-array view (world of one): every array fully assembled.
    What a replicated data-parallel loop restores regardless of how many
    ranks saved — or will run."""
    return load_elastic_state(source, rank=0, world_size=1, arrays=arrays)
