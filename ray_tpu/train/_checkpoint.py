"""Checkpoint: a directory-of-files abstraction.

Parity: ``python/ray/train/_checkpoint.py`` — ``Checkpoint.from_directory``
/ ``to_directory`` / ``as_directory``; storage via filesystem paths
(``_internal/storage.py``). Model-state serialization for JAX pytrees rides
orbax (``ray_tpu.train.jax_utils``).
"""

from __future__ import annotations

import contextlib
import os
import shutil
import tempfile
import uuid
from typing import Optional


class Checkpoint:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_uri(cls, uri: str) -> "Checkpoint":
        """Materialize a checkpoint from external storage (parity:
        ``Checkpoint.from_uri``): the ``scheme://`` prefix downloads into a
        local temp directory through the storage backend registry."""
        from ray_tpu._private import external_storage as storage

        dest = os.path.join(tempfile.gettempdir(), f"ckpt_dl_{uuid.uuid4().hex[:8]}")
        files = storage.sync_uri_to_dir(uri, dest)
        if not files:
            raise FileNotFoundError(f"no checkpoint files under {uri}")
        return cls(dest)

    def to_uri(self, uri: str) -> str:
        """Upload this checkpoint's directory to external storage."""
        from ray_tpu._private import external_storage as storage

        storage.sync_dir_to_uri(self.path, uri)
        return uri

    def to_directory(self, path: Optional[str] = None) -> str:
        dest = path or os.path.join(tempfile.gettempdir(), f"ckpt_{uuid.uuid4().hex[:8]}")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextlib.contextmanager
    def as_directory(self):
        yield self.path

    def __repr__(self):
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))
