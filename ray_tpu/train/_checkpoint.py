"""Checkpoint: a directory-of-files abstraction.

Parity: ``python/ray/train/_checkpoint.py`` — ``Checkpoint.from_directory``
/ ``to_directory`` / ``as_directory``; storage via filesystem paths
(``_internal/storage.py``). Model-state serialization for JAX pytrees rides
orbax (``ray_tpu.train.jax_utils``).

``to_uri``/``from_uri`` speak the checkpoint plane's commit protocol
(``ray_tpu._private.external_storage``): uploads end with a manifest plus an
atomic ``COMMIT`` marker, and restores of committed prefixes are
digest-verified and cached by manifest digest — the seed downloaded every
``from_uri`` call into a fresh, never-reclaimed ``ckpt_dl_*`` temp dir.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import shutil
import tempfile
import uuid
from typing import Optional

_CACHE_DIRNAME = "ray_tpu_ckpt_cache"
_CACHE_DONE = ".complete"


def _cache_root() -> str:
    return os.path.join(tempfile.gettempdir(), _CACHE_DIRNAME)


class Checkpoint:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_uri(cls, uri: str, *, allow_uncommitted: bool = False) -> "Checkpoint":
        """Materialize a checkpoint from external storage (parity:
        ``Checkpoint.from_uri``).

        Committed prefixes (manifest + COMMIT marker) restore through the
        verified path — every file checked against its manifest size and
        sha256 — into a cache slot keyed by the manifest digest, so
        repeated restores of one committed checkpoint share a single local
        copy (the markers are re-written into the slot, so the cached copy
        is itself a committed, verifiable directory). Because the slot is
        SHARED, treat the returned directory as read-only; call
        ``to_directory()`` for a private mutable copy. An uncommitted
        prefix — a crashed or in-flight upload — raises
        ``FileNotFoundError`` instead of silently restoring half a model;
        ``allow_uncommitted=True`` opts back into the bare-mirror restore
        for pre-protocol prefixes, via a per-URI slot that is
        re-materialized each call (bounded disk, unlike the seed's
        fresh-dir-per-call leak).
        """
        from ray_tpu._private import external_storage as storage

        manifest = storage.read_committed_manifest(uri)
        if manifest is not None:
            digest = storage.manifest_digest(manifest)
            dest = os.path.join(_cache_root(), f"c-{digest[:16]}")
            if os.path.exists(os.path.join(dest, _CACHE_DONE)):
                return cls(dest)
            tmp = f"{dest}.tmp.{os.getpid()}.{uuid.uuid4().hex[:6]}"
            try:
                storage.restore_committed_uri_to_dir(uri, tmp, manifest)
            except BaseException:
                # a failed verified restore must not strand its partial
                # download in the cache root
                shutil.rmtree(tmp, ignore_errors=True)
                raise
            storage.write_commit_markers(tmp, manifest)
            open(os.path.join(tmp, _CACHE_DONE), "w").close()
            try:
                os.rename(tmp, dest)
            except OSError:
                # lost the create race (or a stale incomplete slot): the
                # winner's copy is digest-identical, use it
                if os.path.exists(os.path.join(dest, _CACHE_DONE)):
                    shutil.rmtree(tmp, ignore_errors=True)
                else:
                    shutil.rmtree(dest, ignore_errors=True)
                    os.rename(tmp, dest)
            return cls(dest)

        if not allow_uncommitted:
            raise FileNotFoundError(
                f"no COMMITTED checkpoint under {uri} — either a partial/"
                f"crashed upload (never restorable) or a pre-protocol bare "
                f"mirror (pass allow_uncommitted=True to restore it unverified)"
            )
        # legacy (pre-protocol) prefix: no manifest to verify or key by.
        # Each call materializes a fresh GENERATION under the per-URI slot
        # and prunes all but the two newest — re-download semantics with
        # bounded disk (the seed leaked a dir per call), while the previous
        # generation survives one refresh for readers still holding it.
        import glob as _glob
        import time as _time

        key = hashlib.sha256(uri.encode()).hexdigest()[:16]
        slot = os.path.join(_cache_root(), f"u-{key}")
        dest = os.path.join(slot, f"g{_time.time_ns():020d}_{uuid.uuid4().hex[:6]}")
        tmp = f"{dest}.tmp"
        try:
            files = storage.sync_uri_to_dir(uri, tmp)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)  # no strand on mid-sync error
            raise
        if not files:
            shutil.rmtree(tmp, ignore_errors=True)
            raise FileNotFoundError(f"no checkpoint files under {uri}")
        os.rename(tmp, dest)
        gens = sorted(
            d for d in _glob.glob(os.path.join(slot, "g*")) if not d.endswith(".tmp")
        )
        for old in gens[:-2]:
            shutil.rmtree(old, ignore_errors=True)
        return cls(dest)

    def to_uri(self, uri: str, *, commit: bool = True) -> str:
        """Upload this checkpoint's directory to external storage. With
        ``commit`` (default) the upload ends with the manifest + atomic
        COMMIT marker so readers can trust it; ``commit=False`` reproduces
        the bare mirror for raw-prefix consumers."""
        from ray_tpu._private import external_storage as storage

        if commit:
            storage.commit_dir_to_uri(self.path, uri)
        else:
            storage.sync_dir_to_uri(self.path, uri)
        return uri

    def to_directory(self, path: Optional[str] = None) -> str:
        dest = path or os.path.join(tempfile.gettempdir(), f"ckpt_{uuid.uuid4().hex[:8]}")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextlib.contextmanager
    def as_directory(self):
        yield self.path

    def __repr__(self):
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))
