"""Checkpoint: a directory-of-files abstraction.

Parity: ``python/ray/train/_checkpoint.py`` — ``Checkpoint.from_directory``
/ ``to_directory`` / ``as_directory``; storage via filesystem paths
(``_internal/storage.py``). Model-state serialization for JAX pytrees rides
orbax (``ray_tpu.train.jax_utils``).
"""

from __future__ import annotations

import contextlib
import os
import shutil
import tempfile
import uuid
from typing import Optional


class Checkpoint:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, path: Optional[str] = None) -> str:
        dest = path or os.path.join(tempfile.gettempdir(), f"ckpt_{uuid.uuid4().hex[:8]}")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextlib.contextmanager
    def as_directory(self):
        yield self.path

    def __repr__(self):
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))
