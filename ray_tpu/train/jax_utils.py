"""JAX-specific training utilities: pytree checkpoints, mesh helpers.

Parity note: plays the role of ``python/ray/train/torch/train_loop_utils.py``
(prepare_model / prepare_data_loader) for the JAX world — but "preparation"
here is sharding annotation, not module wrapping (SURVEY.md §2.3 FSDP row).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


def ensure_platform() -> None:
    """Honor JAX_PLATFORMS inside worker processes.

    Hardware plugins can pin the default backend regardless of the env var
    (the env alone is ignored by plugin builds); only ``jax.config`` wins.
    Call before first backend use in any worker-side jax entry point — a
    worker silently grabbing the (single, possibly tunneled) accelerator
    instead of CPU turns microsecond steps into network round-trips.
    """
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            jax.config.update("jax_platforms", plat)
        except Exception:
            pass


def save_pytree(state: Any, path: str) -> None:
    """Save a pytree of arrays to ``path`` (orbax if available, else msgpack
    via flax, else numpy .npz of flattened leaves)."""
    os.makedirs(path, exist_ok=True)
    try:
        import orbax.checkpoint as ocp
    except ImportError:
        ocp = None
    if ocp is not None:
        # real save failures (disk full, permissions, serialization bugs) must
        # propagate — only a missing orbax falls back to npz
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.join(os.path.abspath(path), "state"), state, force=True)
        ckptr.wait_until_finished()
        return
    import numpy as np

    leaves, treedef = jax.tree.flatten(state)
    np.savez(
        os.path.join(path, "state.npz"),
        *[np.asarray(l) for l in leaves],
        treedef=str(treedef),
    )


def load_pytree(path: str, target: Optional[Any] = None) -> Any:
    """Load a pytree saved by :func:`save_pytree`. ``target`` (a pytree of
    like-shaped arrays or ShapeDtypeStructs) guides orbax restoration and
    sharding."""
    orbax_path = os.path.join(os.path.abspath(path), "state")
    if os.path.exists(orbax_path):
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        if target is not None:
            abstract = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))
                if hasattr(x, "shape")
                else x,
                target,
            )
            return ckptr.restore(orbax_path, abstract)
        return ckptr.restore(orbax_path)
    import numpy as np

    npz = np.load(os.path.join(path, "state.npz"), allow_pickle=True)
    leaves = [npz[k] for k in npz.files if k != "treedef"]
    if target is None:
        raise ValueError("numpy-fallback checkpoints need a target pytree")
    treedef = jax.tree.structure(target)
    return jax.tree.unflatten(treedef, leaves)
