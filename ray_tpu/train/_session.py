"""In-worker training session.

Parity: ``_TrainSession`` (``python/ray/train/_internal/session.py:111``) with
``report`` (``:667``) and ``get_checkpoint`` (``:754``). Reports flow to the
driver through a named collector actor instead of the reference's in-process
queue+thread (workers here are separate processes).
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

from ray_tpu.train import checkpointing
from ray_tpu.train._checkpoint import Checkpoint

_session_local = threading.local()


def _manifest_step(path: str):
    """Step recorded in a restored checkpoint's manifest (from_uri cache
    slots keep their MANIFEST.json precisely so resume can continue the
    numbering)."""
    import json

    from ray_tpu._private.external_storage import MANIFEST_FILE

    try:
        with open(os.path.join(path, MANIFEST_FILE)) as fh:
            step = json.load(fh).get("step")
        return int(step) if step is not None else None
    except (OSError, ValueError, TypeError):
        return None


@dataclass
class TrainContext:
    world_rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    node_rank: int = 0
    experiment_name: str = ""
    trial_dir: str = ""

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_trial_dir(self) -> str:
        return self.trial_dir


class _Session:
    def __init__(self, context: TrainContext, collector, latest_checkpoint: Optional[Checkpoint]):
        self.context = context
        self.collector = collector  # ActorHandle of _ReportCollector (or None)
        # resume continues the step numbering: a restarted attempt must not
        # re-emit checkpoint_000001 over an already-committed step 1 (the
        # overwrite would invalidate its manifest digests)
        self.iteration = 0
        if latest_checkpoint is not None:
            step = checkpointing.parse_step(
                os.path.basename(latest_checkpoint.path.rstrip("/"))
            )
            if step is None:
                step = _manifest_step(latest_checkpoint.path)
            if step is not None:
                self.iteration = step
        # sharded resume: a multi-rank committed checkpoint is a step dir of
        # shard-{rank}-of-{world} subdirs; each rank sees its own shard,
        # falling back to rank 0's (a rank-0-only checkpoint carries the
        # gathered state every rank restores from)
        if latest_checkpoint is not None and context.world_size > 1:
            for rank in (context.world_rank, 0):
                shard = os.path.join(
                    latest_checkpoint.path,
                    checkpointing.shard_dir_name(rank, context.world_size),
                )
                if os.path.isdir(shard):
                    latest_checkpoint = Checkpoint(shard)
                    break
        self.latest_checkpoint = latest_checkpoint

    def report(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
        self.iteration += 1
        ckpt_path = None
        if checkpoint is not None:
            # checkpoint plane save path: EVERY rank snapshots its shard
            # locally (O(local-copy) — this is all train.report blocks on)
            # and reports it; the head-side manager barriers the shards,
            # then uploads + commits in the background (parity upgrade over
            # the reference's rank-0-only blocking upload)
            from ray_tpu._private.profiling import profile

            step_dir = os.path.join(
                self.context.trial_dir, checkpointing.step_dir_name(self.iteration)
            )
            shard = checkpointing.shard_dir_name(
                self.context.world_rank, self.context.world_size
            )
            dest = os.path.join(step_dir, shard) if shard else step_dir
            t0 = time.monotonic()
            with profile(
                "checkpoint_save",
                {"step": self.iteration, "rank": self.context.world_rank},
            ):
                from ray_tpu._private import external_storage as _xstorage

                # a committed step dir is NEVER mutated in place (an
                # explicit resume below an old run's latest step can land
                # here): demote it by unlinking just its markers — each
                # write is atomic and idempotent, so concurrent ranks can
                # all demote without wiping each other's fresh shards (a
                # full delete_prefix here raced exactly that way)
                for mark in (_xstorage.COMMIT_FILE, _xstorage.MANIFEST_FILE):
                    try:
                        os.unlink(os.path.join(step_dir, mark))
                    except OSError:
                        pass
                if os.path.abspath(checkpoint.path) != dest:
                    shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
                # a RESTORED checkpoint carries its old markers (and the
                # restore cache's .complete): drop them from the snapshot,
                # or the new step dir looks committed before it is — and a
                # crash before the real commit would resume from a torn dir
                for mark in (_xstorage.COMMIT_FILE, _xstorage.MANIFEST_FILE, ".complete"):
                    try:
                        os.unlink(os.path.join(dest, mark))
                    except OSError:
                        pass
            checkpointing.observe_save_seconds(time.monotonic() - t0)
            ckpt_path = dest
        if self.collector is not None:
            import ray_tpu

            ray_tpu.get(
                self.collector.report.remote(
                    self.context.world_rank, self.iteration, metrics, ckpt_path
                )
            )


_session_fallback: Optional[_Session] = None


def _set_session(session: Optional[_Session]):
    global _session_fallback
    _session_local.session = session
    # process-wide fallback: the SIGTERM preemption drain runs hooks on a
    # side thread, where the thread-local is unset — a worker runs one
    # train session at a time, so the fallback is unambiguous there
    _session_fallback = session


def _get_session() -> Optional[_Session]:
    session = getattr(_session_local, "session", None)
    return session if session is not None else _session_fallback


def report(metrics: Dict[str, Any], *, checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (and optionally a checkpoint) from the train loop.
    Parity: ``ray.train.report``."""
    s = _get_session()
    if s is None:
        raise RuntimeError("train.report() called outside a training session")
    s.report(metrics, checkpoint)


def get_context() -> TrainContext:
    s = _get_session()
    if s is None:
        return TrainContext()
    return s.context


def get_checkpoint() -> Optional[Checkpoint]:
    s = _get_session()
    return s.latest_checkpoint if s else None
