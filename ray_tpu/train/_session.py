"""In-worker training session.

Parity: ``_TrainSession`` (``python/ray/train/_internal/session.py:111``) with
``report`` (``:667``) and ``get_checkpoint`` (``:754``). Reports flow to the
driver through a named collector actor instead of the reference's in-process
queue+thread (workers here are separate processes).
"""

from __future__ import annotations

import os
import shutil
import threading
import uuid
from dataclasses import dataclass
from typing import Any, Dict, Optional

from ray_tpu.train._checkpoint import Checkpoint

_session_local = threading.local()


@dataclass
class TrainContext:
    world_rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    node_rank: int = 0
    experiment_name: str = ""
    trial_dir: str = ""

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_trial_dir(self) -> str:
        return self.trial_dir


class _Session:
    def __init__(self, context: TrainContext, collector, latest_checkpoint: Optional[Checkpoint]):
        self.context = context
        self.collector = collector  # ActorHandle of _ReportCollector (or None)
        self.latest_checkpoint = latest_checkpoint
        self.iteration = 0

    def report(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
        self.iteration += 1
        ckpt_path = None
        # only rank 0's checkpoint is persisted and tracked (parity: Train's
        # default; per-shard checkpointing composes via rank-0 gathering) —
        # other ranks' copies would otherwise accumulate untracked on disk
        if checkpoint is not None and self.context.world_rank != 0:
            checkpoint = None
        if checkpoint is not None:
            # persist the checkpoint under the trial dir (parity: StorageContext
            # upload, _internal/storage.py)
            dest = os.path.join(
                self.context.trial_dir,
                f"checkpoint_{self.iteration:06d}_{uuid.uuid4().hex[:6]}",
            )
            if os.path.abspath(checkpoint.path) != dest:
                shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
            ckpt_path = dest
        if self.collector is not None:
            import ray_tpu

            ray_tpu.get(
                self.collector.report.remote(
                    self.context.world_rank, self.iteration, metrics, ckpt_path
                )
            )


def _set_session(session: Optional[_Session]):
    _session_local.session = session


def _get_session() -> Optional[_Session]:
    return getattr(_session_local, "session", None)


def report(metrics: Dict[str, Any], *, checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (and optionally a checkpoint) from the train loop.
    Parity: ``ray.train.report``."""
    s = _get_session()
    if s is None:
        raise RuntimeError("train.report() called outside a training session")
    s.report(metrics, checkpoint)


def get_context() -> TrainContext:
    s = _get_session()
    if s is None:
        return TrainContext()
    return s.context


def get_checkpoint() -> Optional[Checkpoint]:
    s = _get_session()
    return s.latest_checkpoint if s else None
