"""In-worker training session.

Parity: ``_TrainSession`` (``python/ray/train/_internal/session.py:111``) with
``report`` (``:667``) and ``get_checkpoint`` (``:754``). Reports flow to the
driver through a named collector actor instead of the reference's in-process
queue+thread (workers here are separate processes).
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

from ray_tpu.train import checkpointing
from ray_tpu.train._checkpoint import Checkpoint

_session_local = threading.local()


class AttemptAborted(Exception):
    """Internal control-flow signal: the backend executor aborted this
    attempt (a peer rank died and the group is re-forming). Raised out of
    ``train.report`` to unwind the user loop; the train worker catches it
    and returns an abort sentinel instead of an error, so the actor
    process stays alive for the next dispatch."""

    def __init__(self, generation: int):
        self.generation = generation
        super().__init__(
            f"training attempt aborted by the executor (generation "
            f"{generation}); the worker group is re-forming"
        )


def _manifest_step(path: str):
    """Step recorded in a restored checkpoint's manifest (from_uri cache
    slots keep their MANIFEST.json precisely so resume can continue the
    numbering)."""
    import json

    from ray_tpu._private.external_storage import MANIFEST_FILE

    try:
        with open(os.path.join(path, MANIFEST_FILE)) as fh:
            step = json.load(fh).get("step")
        return int(step) if step is not None else None
    except (OSError, ValueError, TypeError):
        return None


import re as _re

_SHARD_RE = _re.compile(r"^shard-(\d{5})-of-(\d{5})$")


def _shard_dirs(step_dir: str):
    """(name, rank, world) of every shard-XXXXX-of-YYYYY subdir of a
    step dir."""
    out = []
    try:
        names = os.listdir(step_dir)
    except OSError:
        return out
    for name in names:
        m = _SHARD_RE.match(name)
        if m and os.path.isdir(os.path.join(step_dir, name)):
            out.append((name, int(m.group(1)), int(m.group(2))))
    return out


def _pick_shard(step_dir: str, rank: int, world_size: int) -> Optional[str]:
    """The shard subdirectory this rank should restore from, or None to
    use the step dir itself. Exact (rank, world) match first. Across a
    world-size CHANGE, a cross-world shard is only safe when it carries
    the FULL state — the rank-0-gather pattern, recognizable as a step
    dir whose sole shard is rank 0's. Anything else (a truly partitioned
    layout at another world) returns the step dir: a different world's
    per-rank slice is the wrong rows, and the elastic loader
    (train.load_elastic) is the path that can re-shard it correctly."""
    exact = os.path.join(
        step_dir, checkpointing.shard_dir_name(rank, world_size)
    )
    if world_size > 1 and os.path.isdir(exact):
        return exact
    shards = _shard_dirs(step_dir)
    if len(shards) == 1 and shards[0][1] == 0:
        return os.path.join(step_dir, shards[0][0])
    return None


def _clear_stale_layouts(step_dir: str, world_size: int) -> None:
    """Remove entries of a step dir that belong to a DIFFERENT world-size
    layout: shard dirs whose ``-of-NNNNN`` suffix isn't the current world,
    and (when the current world is sharded) leftover flat root residue
    from a world-of-one attempt. The keep/delete decision is made from
    each entry's NAME alone, in one pass — concurrent ranks snapshot the
    same step simultaneously, and a peer's current-world shard dir
    appearing between two listings must never be judged by a stale
    snapshot (name-based judgment is time-independent)."""
    try:
        names = os.listdir(step_dir)
    except OSError:
        return
    for name in names:
        m = _SHARD_RE.match(name)
        if m is not None:
            stale = int(m.group(2)) != world_size  # other-world shard
        else:
            # non-shard root entry: legit only in a flat (world-1) layout
            stale = world_size > 1
        if not stale:
            continue
        p = os.path.join(step_dir, name)
        if os.path.isdir(p):
            shutil.rmtree(p, ignore_errors=True)
        else:
            try:
                os.unlink(p)
            except OSError:
                pass


@dataclass
class TrainContext:
    world_rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    node_rank: int = 0
    experiment_name: str = ""
    trial_dir: str = ""

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_trial_dir(self) -> str:
        return self.trial_dir


def _preempt_shield():
    """The active runtime's preemption-shield toggle, or a no-op when the
    session runs somewhere without one (driver-local trainers, tests)."""
    try:
        from ray_tpu._private.worker import get_runtime

        fn = getattr(get_runtime(), "protect_from_preemption", None)
    except Exception:
        fn = None
    return fn if fn is not None else (lambda delta: None)


class _Session:
    def __init__(
        self,
        context: TrainContext,
        collector,
        latest_checkpoint: Optional[Checkpoint],
        run_name: str = "train",
        datasets: Optional[Dict[str, Any]] = None,
    ):
        self.context = context
        self.collector = collector  # ActorHandle of _ReportCollector (or None)
        self.run_name = run_name
        # trainer-attached datasets (JaxTrainer(datasets=...)); consumed via
        # train.get_dataset_shard — the instrumented ingest seam
        self.datasets: Dict[str, Any] = dict(datasets or {})
        # step plane: per-step stage decomposition between report boundaries
        # (None when train_obs_enabled is off — zero hot-path cost)
        from ray_tpu._private import stepplane

        self._step_timer = stepplane.make_timer(
            run_name, context.world_rank, context.world_size
        )
        # resume continues the step numbering: a restarted attempt must not
        # re-emit checkpoint_000001 over an already-committed step 1 (the
        # overwrite would invalidate its manifest digests)
        self.iteration = 0
        if latest_checkpoint is not None:
            step = checkpointing.parse_step(
                os.path.basename(latest_checkpoint.path.rstrip("/"))
            )
            if step is None:
                step = _manifest_step(latest_checkpoint.path)
            if step is not None:
                self.iteration = step
        # the step-dir-level restore root (pre shard-pick): the elastic
        # N→M loader needs ALL old shards' indexes, not one rank's view
        self._restore_root = (
            latest_checkpoint.path if latest_checkpoint is not None else None
        )
        # sharded resume: a multi-rank committed checkpoint is a step dir
        # of shard-{rank}-of-{world} subdirs; each rank sees its exact
        # (rank, world) shard, or the sole rank-0 shard of a gather-
        # pattern checkpoint (full state, safe at any world). Any other
        # world-size mismatch keeps the whole step dir — a different
        # world's per-rank slice would be the wrong rows, and
        # train.load_elastic() is the path that re-shards it correctly.
        if latest_checkpoint is not None:
            shard = _pick_shard(
                latest_checkpoint.path, context.world_rank, context.world_size
            )
            if shard is not None:
                latest_checkpoint = Checkpoint(shard)
        self.latest_checkpoint = latest_checkpoint

    def report(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
        if checkpoint is None:
            return self._report(metrics, None)
        # preemption shield: the window from snapshot start to the shard's
        # arrival at the head barrier must not be a preemption/OOM-kill
        # target — victim selection skips shielded workers, so an
        # arbitration kill never tears a shard racing toward its commit
        shield = _preempt_shield()
        shield(+1)
        try:
            return self._report(metrics, checkpoint)
        finally:
            shield(-1)

    def _report(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint]):
        self.iteration += 1
        timer = self._step_timer
        if timer is not None:
            # the loop half of the step (data_wait/h2d/compile/compute)
            # ends here; everything below is the report half
            timer.mark_pre_report()
        ckpt_path = None
        if checkpoint is not None:
            # checkpoint plane save path: EVERY rank snapshots its shard
            # locally (O(local-copy) — this is all train.report blocks on)
            # and reports it; the head-side manager barriers the shards,
            # then uploads + commits in the background (parity upgrade over
            # the reference's rank-0-only blocking upload)
            from ray_tpu._private.profiling import profile

            step_dir = os.path.join(
                self.context.trial_dir, checkpointing.step_dir_name(self.iteration)
            )
            shard = checkpointing.shard_dir_name(
                self.context.world_rank, self.context.world_size
            )
            dest = os.path.join(step_dir, shard) if shard else step_dir
            t0 = time.monotonic()
            with profile(
                "checkpoint_save",
                {"step": self.iteration, "rank": self.context.world_rank},
            ):
                from ray_tpu._private import external_storage as _xstorage

                # a committed step dir is NEVER mutated in place (an
                # explicit resume below an old run's latest step can land
                # here): demote it by unlinking just its markers — each
                # write is atomic and idempotent, so concurrent ranks can
                # all demote without wiping each other's fresh shards (a
                # full delete_prefix here raced exactly that way)
                for mark in (_xstorage.COMMIT_FILE, _xstorage.MANIFEST_FILE):
                    try:
                        os.unlink(os.path.join(step_dir, mark))
                    except OSError:
                        pass
                # an elastic resize can leave THIS step dir holding a dead
                # attempt's shards from another world size (or stale flat
                # files when the world grew past 1): the commit manifests
                # whatever is on disk, so a mixed-layout dir would become
                # a trusted checkpoint that restores mixed-generation
                # state. Every rank clears the stale layout; ranks of one
                # generation write only current-world entries, so the
                # deletions never race a live shard.
                _clear_stale_layouts(step_dir, self.context.world_size)
                if os.path.abspath(checkpoint.path) != dest:
                    shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
                # a RESTORED checkpoint carries its old markers (and the
                # restore cache's .complete): drop them from the snapshot,
                # or the new step dir looks committed before it is — and a
                # crash before the real commit would resume from a torn dir
                for mark in (_xstorage.COMMIT_FILE, _xstorage.MANIFEST_FILE, ".complete"):
                    try:
                        os.unlink(os.path.join(dest, mark))
                    except OSError:
                        pass
            elapsed = time.monotonic() - t0
            checkpointing.observe_save_seconds(elapsed)
            if timer is not None:
                # the blocking (local-snapshot) portion only — the upload +
                # commit ride the checkpoint plane's background queue
                timer.note_checkpoint_stall(elapsed)
            ckpt_path = dest
        if self.collector is not None:
            import ray_tpu

            # the PREVIOUS step's finalized record rides this report rpc
            # (zero extra messages on the step hot path); the session's
            # last record drains via telemetry when the timer deactivates
            step_rec = timer.pop_pending_record() if timer is not None else None
            resp = ray_tpu.get(
                self.collector.report.remote(
                    self.context.world_rank,
                    self.iteration,
                    metrics,
                    ckpt_path,
                    step_rec,
                )
            )
            # the collector doubles as the executor's control plane: a
            # non-bool int response is an abort generation — a peer rank
            # died and the executor wants every survivor to unwind NOW
            # (instead of timing out in the next collective) so the group
            # can re-form and resume from the last committed step
            if isinstance(resp, int) and not isinstance(resp, bool):
                raise AttemptAborted(resp)
        if timer is not None:
            # close the step at the report boundary (an aborted attempt
            # never reaches here — its partial step is discarded work and
            # lands in the executor's downtime ledger instead)
            from ray_tpu.util import tracing as _tracing

            timer.finalize_step(
                self.iteration, trace_id=_tracing.current_trace_id()
            )

    # -- elastic state ------------------------------------------------------

    def load_elastic(self, arrays=None, *, full: bool = False):
        """This rank's re-sharded slice of the latest elastic checkpoint
        (or the fully assembled arrays with ``full=True``), plus the
        saver's extra metadata — or None when there is nothing to resume
        from. Works across world-size changes: the slice is computed from
        the CURRENT (rank, world_size) over whatever shard layout was
        committed."""
        from ray_tpu.train import elastic

        root = self._restore_root
        if root is None:
            return None
        if full:
            return elastic.load_elastic_full(root, arrays=arrays)
        return elastic.load_elastic_state(
            root,
            rank=self.context.world_rank,
            world_size=self.context.world_size,
            arrays=arrays,
        )

    def report_elastic(self, metrics: Dict[str, Any], arrays, extra=None):
        """Snapshot ``arrays`` as this rank's elastic shard and report it.
        The shard carries only this rank's balanced row partition, so a
        full-world save costs ~1/world of the state per rank and any
        future world size can restore it."""
        import tempfile

        from ray_tpu.train import elastic

        d = tempfile.mkdtemp(prefix="elastic_shard_")
        try:
            elastic.save_elastic_shard(
                d,
                arrays,
                rank=self.context.world_rank,
                world_size=self.context.world_size,
                extra=extra,
            )
            self.report(metrics, Checkpoint(d))
        finally:
            # report() copied the shard into the step dir (or raised —
            # including AttemptAborted): the staging dir must not leak one
            # shard-sized /tmp directory per rank per step
            shutil.rmtree(d, ignore_errors=True)


_session_fallback: Optional[_Session] = None


def _set_session(session: Optional[_Session]):
    global _session_fallback
    _session_local.session = session
    # process-wide fallback: the SIGTERM preemption drain runs hooks on a
    # side thread, where the thread-local is unset — a worker runs one
    # train session at a time, so the fallback is unambiguous there
    _session_fallback = session
    # step plane: make this session's timer the process's active step so
    # the data iterator and the jax monitoring listener publish into it
    from ray_tpu._private import stepplane

    stepplane.activate(session._step_timer if session is not None else None)


def _get_session() -> Optional[_Session]:
    session = getattr(_session_local, "session", None)
    return session if session is not None else _session_fallback


def report(metrics: Dict[str, Any], *, checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (and optionally a checkpoint) from the train loop.
    Parity: ``ray.train.report``."""
    s = _get_session()
    if s is None:
        raise RuntimeError("train.report() called outside a training session")
    s.report(metrics, checkpoint)


def get_context() -> TrainContext:
    s = _get_session()
    if s is None:
        return TrainContext()
    return s.context


def get_checkpoint() -> Optional[Checkpoint]:
    s = _get_session()
    return s.latest_checkpoint if s else None


def get_dataset_shard(name: str = "train"):
    """The :class:`~ray_tpu.data.iterator.DataIterator` over the dataset
    the trainer attached under ``name`` (``JaxTrainer(datasets=...)``), or
    None when the trainer attached none. Parity: ``ray.train
    .get_dataset_shard``. Iteration through it is the instrumented ingest
    seam: batch-fetch blocking lands in the step plane's ``data_wait``
    stage (with per-operator stall attribution) and ``iter_jax_batches``'
    device transfer in ``host_to_device``."""
    s = _get_session()
    if s is None:
        raise RuntimeError(
            "train.get_dataset_shard() called outside a training session"
        )
    ds = s.datasets.get(name)
    if ds is None:
        return None
    from ray_tpu.data.dataset import Dataset
    from ray_tpu.data.iterator import DataIterator

    world = s.context.world_size
    if world > 1 and isinstance(ds, Dataset):
        # per-rank shard: round-robin slice of the SOURCE refs/read tasks
        # with the operator stages preserved — lazy (no materialize), and
        # ranks see disjoint data (a rank count above the block count
        # leaves trailing ranks empty; repartition first for balance)
        ds = Dataset(
            ds._block_refs[s.context.world_rank :: world],
            stages=ds._stages,
            owned_actors=ds._owned_actors,
        )
    return ds if isinstance(ds, DataIterator) else DataIterator(ds)


def load_elastic(arrays=None, *, full: bool = False):
    """Restore this rank's slice of the latest elastic checkpoint —
    re-sharded on the fly when the world size changed since the save
    (N→M). ``full=True`` assembles the complete arrays instead (what a
    replicated data-parallel loop wants). Returns ``(arrays, extra)`` or
    None when there is no checkpoint to resume from."""
    s = _get_session()
    if s is None:
        raise RuntimeError("train.load_elastic() called outside a training session")
    return s.load_elastic(arrays, full=full)


def report_elastic(metrics: Dict[str, Any], arrays, *, extra=None) -> None:
    """Report metrics plus an elastic checkpoint of ``arrays`` (this
    rank's balanced row partition of each). The committed result can be
    restored at ANY world size via :func:`load_elastic`."""
    s = _get_session()
    if s is None:
        raise RuntimeError("train.report_elastic() called outside a training session")
    s.report_elastic(metrics, arrays, extra=extra)
