"""Mutable shared-memory channels: the compiled-DAG data plane.

Parity: the reference's mutable plasma objects + shm channels
(``src/ray/core_worker/experimental_mutable_object_manager.h``,
``python/ray/experimental/channel/shared_memory_channel.py:88``): a
fixed-capacity buffer written in place per execution instead of allocating a
new immutable object per call — the lock-free fast path that lets a compiled
actor pipeline run without per-hop RPC or store allocation.

Implementation: one mmap'd file per channel in the session's shm dir with a
seqlock header — writer bumps ``version`` to odd, copies the payload, bumps
to even; readers wait for a fresh even version and then validate it was
stable across their copy. Readers track the last version consumed so each
``read`` returns a *new* write (reference semantics: one read per write per
reader).

Cross-node edges use :class:`SocketChannelWriter` / :class:`SocketChannelReader`
— an authenticated point-to-point socket with the same one-slot
acquire-release semantics (writer blocks until the reader acks the previous
payload), playing the role of the reference's cross-node mutable-object
forwarding (``experimental_mutable_object_provider.h`` gRPC path).
"""

from __future__ import annotations

import mmap
import os
import struct
import time
from typing import Any, Optional

from ray_tpu._private import serialization

_HDR = struct.Struct("<QQQQ")  # version, payload_len, closed, consumed_version
_CLOSED = 1


class ChannelClosedError(Exception):
    pass


class Channel:
    """Single-writer multi-reader mutable channel."""

    def __init__(self, path: str, capacity: int = 4 * 1024 * 1024, create: bool = False):
        self.path = path
        self.capacity = capacity
        total = _HDR.size + capacity
        if create:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o600)
            try:
                os.ftruncate(fd, total)
            finally:
                pass
        else:
            fd = os.open(path, os.O_RDWR)
        try:
            self._mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        self._mv = memoryview(self._mm)
        self._serde = serialization.get_context()
        self._last_read_version = 0

    # -- writer ------------------------------------------------------------

    def write(self, value: Any, timeout: Optional[float] = 60.0) -> None:
        """Acquire-release, one slot: blocks until the single reader has
        consumed the previous write (reference mutable-object semantics —
        the writer never overruns the reader)."""
        blob = self._serde.serialize_to_bytes(value)
        if len(blob) > self.capacity:
            raise ValueError(
                f"value ({len(blob)} bytes) exceeds channel capacity "
                f"({self.capacity}); recreate the channel larger"
            )
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 0.000_05
        while True:
            version, _, closed, consumed = _HDR.unpack_from(self._mv, 0)
            if closed:
                raise ChannelClosedError(self.path)
            if consumed >= version:
                break
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(f"channel write timed out ({self.path})")
            time.sleep(delay)
            delay = min(delay * 2, 0.002)
        # seqlock: odd = write in progress
        _HDR.pack_into(self._mv, 0, version + 1, len(blob), 0, consumed)
        self._mv[_HDR.size : _HDR.size + len(blob)] = blob
        _HDR.pack_into(self._mv, 0, version + 2, len(blob), 0, consumed)

    # -- reader ------------------------------------------------------------

    def read(self, timeout: Optional[float] = 10.0) -> Any:
        """Block until a write newer than the last one read; returns value
        and releases the slot back to the writer."""
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 0.000_05
        while True:
            version, length, closed, consumed = _HDR.unpack_from(self._mv, 0)
            if closed:
                raise ChannelClosedError(self.path)
            if version % 2 == 0 and version > self._last_read_version:
                payload = bytes(self._mv[_HDR.size : _HDR.size + length])
                v2, _, _, _ = _HDR.unpack_from(self._mv, 0)
                if v2 == version:  # stable across the copy
                    self._last_read_version = version
                    # release the slot (single-reader ack)
                    _HDR.pack_into(self._mv, 0, version, length, 0, version)
                    return self._serde.deserialize_from(memoryview(payload))
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(f"channel read timed out ({self.path})")
            time.sleep(delay)
            delay = min(delay * 2, 0.002)

    def close(self) -> None:
        try:
            version, length, _, consumed = _HDR.unpack_from(self._mv, 0)
            _HDR.pack_into(self._mv, 0, version, length, _CLOSED, consumed)
        except (ValueError, OSError):
            pass

    def release(self) -> None:
        try:
            self._mv.release()
            self._mm.close()
        except (BufferError, OSError):
            pass

    def __reduce__(self):
        return (Channel, (self.path, self.capacity, False))


# -- cross-node channels -----------------------------------------------------

_FRAME_DATA = b"D"
_FRAME_CLOSE = b"C"
_FRAME_ACK = b"A"


class SocketChannelWriter:
    """Writer endpoint of a cross-node single-reader channel.

    One listener per edge (the reader dials this address), HMAC-challenge
    authenticated like every other socket in the framework. One-slot
    semantics: ``write`` blocks until the reader has acked the previous
    payload, so a slow consumer backpressures the producer exactly like the
    shm seqlock channel."""

    def __init__(self, auth_key: bytes, host: str = "127.0.0.1"):
        from multiprocessing.connection import Listener

        # bind all interfaces; advertise an address the reader's host can
        # dial (binding the head's cluster_host would fail on daemon hosts)
        self._listener = Listener(("0.0.0.0", 0), authkey=auth_key)
        port = tuple(self._listener.address)[1]
        self.address = (_advertised_host(host), port)
        self._conn = None
        self._awaiting_ack = False
        self._serde = serialization.get_context()
        self._closed = False

    def _ensure_conn(self, timeout: Optional[float]):
        if self._conn is not None:
            return
        # honor the write timeout during the initial accept too — a reader
        # that never dials (stage failed to start) must not hang the writer
        sock = getattr(getattr(self._listener, "_listener", None), "_socket", None)
        if sock is not None and timeout is not None:
            sock.settimeout(timeout)
        try:
            self._conn = self._listener.accept()
            from ray_tpu._private.object_transfer import set_nodelay

            set_nodelay(self._conn)
        except (TimeoutError, OSError) as e:
            if isinstance(e, OSError) and not isinstance(e, TimeoutError):
                raise
            raise TimeoutError(
                f"socket channel accept timed out ({self.address})"
            ) from e
        finally:
            if sock is not None:
                sock.settimeout(None)
        self._listener.close()

    def write(self, value: Any, timeout: Optional[float] = 60.0) -> None:
        if self._closed:
            raise ChannelClosedError(str(self.address))
        try:
            self._ensure_conn(timeout)
            if self._awaiting_ack:
                if not self._conn.poll(timeout):
                    raise TimeoutError(
                        f"socket channel write timed out ({self.address})"
                    )
                ack = self._conn.recv_bytes()
                if ack != _FRAME_ACK:
                    raise ChannelClosedError(str(self.address))
                self._awaiting_ack = False
            blob = self._serde.serialize_to_bytes(value)
            self._conn.send_bytes(_FRAME_DATA + blob)
            self._awaiting_ack = True
        except (EOFError, OSError, BrokenPipeError) as e:
            self._closed = True
            raise ChannelClosedError(str(self.address)) from e

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._ensure_conn(timeout=1.0)
            self._conn.send_bytes(_FRAME_CLOSE)
        except Exception:
            pass
        for c in (self._conn, self._listener):
            try:
                if c is not None:
                    c.close()
            except Exception:
                pass


class SocketChannelReader:
    """Reader endpoint: dials the writer's address; read() returns one
    payload per write and acks it (releasing the writer's slot)."""

    def __init__(self, address, auth_key: bytes):
        from multiprocessing.connection import Client

        self._conn = Client(tuple(address), authkey=auth_key)
        from ray_tpu._private.object_transfer import set_nodelay

        set_nodelay(self._conn)
        self._serde = serialization.get_context()
        self._closed = False

    def read(self, timeout: Optional[float] = 10.0) -> Any:
        if self._closed:
            raise ChannelClosedError("socket channel closed")
        try:
            if not self._conn.poll(timeout):
                raise TimeoutError("socket channel read timed out")
            frame = self._conn.recv_bytes()
            if frame[:1] == _FRAME_CLOSE:
                self._closed = True
                raise ChannelClosedError("socket channel closed by writer")
            value = self._serde.deserialize_from(memoryview(frame)[1:])
            self._conn.send_bytes(_FRAME_ACK)
            return value
        except (EOFError, OSError, BrokenPipeError) as e:
            self._closed = True
            raise ChannelClosedError("socket channel peer died") from e

    def close(self) -> None:
        self._closed = True
        try:
            self._conn.close()
        except Exception:
            pass


def _advertised_host(cluster_host: str) -> str:
    """The address peers should dial to reach a listener on THIS host.
    Loopback clusters stay on loopback; otherwise use this host's outbound
    IP (the writer may live on any node, not the head)."""
    if cluster_host in ("", "127.0.0.1", "localhost", "0.0.0.0"):
        return "127.0.0.1"
    import socket as _socket

    s = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
    try:
        s.connect((cluster_host, 9))  # no packets sent; just picks a route
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def node_shm_dir() -> Optional[str]:
    """This process's node-local shm dir — processes that share it can use
    shm channels; otherwise edges go over socket channels."""
    from ray_tpu._private.worker import get_runtime

    rt = get_runtime()
    if hasattr(rt, "node"):  # driver
        return rt.node.shm_dir
    return getattr(rt, "shm_dir", None)


def create_writer(kind: str, edge_id: str, auth_key: bytes, capacity: int,
                  shm_dir: Optional[str] = None, host: str = "127.0.0.1"):
    """Create the writer endpoint of an edge; returns (endpoint, spec). The
    spec travels to the reader, which opens it with open_reader."""
    if kind == "shm":
        path = os.path.join(shm_dir or "/tmp", "channels", edge_id)
        return Channel(path, capacity, create=True), ("shm", path)
    if kind == "sock":
        w = SocketChannelWriter(auth_key, host)
        return w, ("sock", w.address)
    raise ValueError(kind)


def open_reader(spec, auth_key: bytes, capacity: int):
    kind, arg = spec
    if kind == "shm":
        return Channel(arg, capacity, create=False)
    if kind == "sock":
        return SocketChannelReader(arg, auth_key)
    raise ValueError(kind)
