"""Mutable shared-memory channels: the compiled-DAG data plane.

Parity: the reference's mutable plasma objects + shm channels
(``src/ray/core_worker/experimental_mutable_object_manager.h``,
``python/ray/experimental/channel/shared_memory_channel.py:88``): a
fixed-capacity buffer written in place per execution instead of allocating a
new immutable object per call — the lock-free fast path that lets a compiled
actor pipeline run without per-hop RPC or store allocation.

Implementation: one mmap'd file per channel in the session's shm dir with a
seqlock header — writer bumps ``version`` to odd, copies the payload, bumps
to even; readers wait for a fresh even version and then validate it was
stable across their copy. Readers track the last version consumed so each
``read`` returns a *new* write (reference semantics: one read per write per
reader). Channels are intra-node (the reference forwards cross-node via
gRPC; here cross-node DAG edges fall back to the object store path).
"""

from __future__ import annotations

import mmap
import os
import struct
import time
from typing import Any, Optional

from ray_tpu._private import serialization

_HDR = struct.Struct("<QQQQ")  # version, payload_len, closed, consumed_version
_CLOSED = 1


class ChannelClosedError(Exception):
    pass


class Channel:
    """Single-writer multi-reader mutable channel."""

    def __init__(self, path: str, capacity: int = 4 * 1024 * 1024, create: bool = False):
        self.path = path
        self.capacity = capacity
        total = _HDR.size + capacity
        if create:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o600)
            try:
                os.ftruncate(fd, total)
            finally:
                pass
        else:
            fd = os.open(path, os.O_RDWR)
        try:
            self._mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        self._mv = memoryview(self._mm)
        self._serde = serialization.get_context()
        self._last_read_version = 0

    # -- writer ------------------------------------------------------------

    def write(self, value: Any, timeout: Optional[float] = 60.0) -> None:
        """Acquire-release, one slot: blocks until the single reader has
        consumed the previous write (reference mutable-object semantics —
        the writer never overruns the reader)."""
        blob = self._serde.serialize_to_bytes(value)
        if len(blob) > self.capacity:
            raise ValueError(
                f"value ({len(blob)} bytes) exceeds channel capacity "
                f"({self.capacity}); recreate the channel larger"
            )
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 0.000_05
        while True:
            version, _, closed, consumed = _HDR.unpack_from(self._mv, 0)
            if closed:
                raise ChannelClosedError(self.path)
            if consumed >= version:
                break
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(f"channel write timed out ({self.path})")
            time.sleep(delay)
            delay = min(delay * 2, 0.002)
        # seqlock: odd = write in progress
        _HDR.pack_into(self._mv, 0, version + 1, len(blob), 0, consumed)
        self._mv[_HDR.size : _HDR.size + len(blob)] = blob
        _HDR.pack_into(self._mv, 0, version + 2, len(blob), 0, consumed)

    # -- reader ------------------------------------------------------------

    def read(self, timeout: Optional[float] = 10.0) -> Any:
        """Block until a write newer than the last one read; returns value
        and releases the slot back to the writer."""
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 0.000_05
        while True:
            version, length, closed, consumed = _HDR.unpack_from(self._mv, 0)
            if closed:
                raise ChannelClosedError(self.path)
            if version % 2 == 0 and version > self._last_read_version:
                payload = bytes(self._mv[_HDR.size : _HDR.size + length])
                v2, _, _, _ = _HDR.unpack_from(self._mv, 0)
                if v2 == version:  # stable across the copy
                    self._last_read_version = version
                    # release the slot (single-reader ack)
                    _HDR.pack_into(self._mv, 0, version, length, 0, version)
                    return self._serde.deserialize_from(memoryview(payload))
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(f"channel read timed out ({self.path})")
            time.sleep(delay)
            delay = min(delay * 2, 0.002)

    def close(self) -> None:
        try:
            version, length, _, consumed = _HDR.unpack_from(self._mv, 0)
            _HDR.pack_into(self._mv, 0, version, length, _CLOSED, consumed)
        except (ValueError, OSError):
            pass

    def release(self) -> None:
        try:
            self._mv.release()
            self._mm.close()
        except (BufferError, OSError):
            pass

    def __reduce__(self):
        return (Channel, (self.path, self.capacity, False))
