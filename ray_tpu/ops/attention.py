"""Attention: XLA einsum path, Pallas flash path, and ring attention for
context parallelism.

The reference has no in-tree attention/sequence-parallel implementation
(SURVEY.md §5 "Long-context" — absent); here it is first-class. Ring
attention passes KV blocks around the ``context`` mesh axis with
``jax.lax.ppermute`` over ICI while maintaining a numerically-stable online
softmax (flash-attention style m/l accumulators), so sequence length scales
linearly with the number of devices on the axis.

Convention: q/k/v are (batch, seq, heads, head_dim) [BSHD].
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """Grouped-query attention: repeat kv heads to match q heads."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
        b, s, h * n_rep, d
    )


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    mask: Optional[jax.Array] = None,
    q_positions: Optional[jax.Array] = None,
    kv_positions: Optional[jax.Array] = None,
    use_flash: bool = True,
) -> jax.Array:
    """Multi-head attention. On TPU with supported shapes, dispatches to the
    Pallas splash/flash kernel; otherwise a fused-by-XLA einsum softmax."""
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    # the flash path implements only plain (optionally causal) attention —
    # custom masks / explicit positions must take the einsum path
    if (
        use_flash
        and mask is None
        and q_positions is None
        and kv_positions is None
        and _can_use_flash(q, k)
    ):
        out = _flash(q, k, v, causal=causal)
        if out is not None:
            return out
    return _einsum_attention(
        q, k, v, causal=causal, mask=mask, q_positions=q_positions, kv_positions=kv_positions
    )


def _can_use_flash(q, k) -> bool:
    if jax.default_backend() != "tpu":
        return False
    head_dim = q.shape[-1]
    if head_dim % 128 == 0:
        return q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0
    # head_dim 64 (e.g. d_model 1024 / 16 heads): the stock block sizes lose
    # to the XLA einsum path, but 512-blocks win (measured ~1.4x on v5e at
    # seq 1k; see _tuned_block_sizes) — require 512-divisible sequences
    if head_dim == 64:
        return q.shape[1] % 512 == 0 and k.shape[1] % 512 == 0
    return False


def _tuned_block_sizes(head_dim: int, q_seq: int, kv_seq: int):
    """Measured on v5e: the library defaults underfill the MXU at both ends
    of the head_dim range. head_dim 64: 512 blocks throughout beat defaults
    and the einsum path (~1.4x at seq 1k). head_dim 256 (GPT-J geometry):
    block_q 512 / block_k 1024 in all passes cuts the 6B-shaped train step
    ~19% vs defaults (957 -> 773 ms, seq 2048, with dots-saveable remat).
    None = library defaults."""
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

    def pick(seq: int, *prefs: int):
        # largest preferred block that tiles the sequence (the kernel
        # requires block | seq); a short sequence is its own block
        for p in prefs:
            if seq % p == 0:
                return p
        return seq if seq <= prefs[0] else None

    if head_dim == 256:
        bq = pick(q_seq, 512, 256)
        bk = pick(kv_seq, 1024, 512, 256)
    elif head_dim == 64:
        bq = pick(q_seq, 512, 256)
        bk = pick(kv_seq, 512, 256)
    else:
        return None
    if bq is None or bk is None:
        return None  # library defaults
    return BlockSizes(
        block_q=bq,
        block_k_major=bk,
        block_k=bk,
        block_b=1,
        block_q_major_dkv=bq,
        block_k_major_dkv=bk,
        block_k_dkv=bk,
        block_q_dkv=bq,
        block_k_major_dq=bk,
        block_k_dq=bk,
        block_q_dq=bq,
    )


def _flash(q, k, v, *, causal):
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention,
        )
    except ImportError:
        return None
    # pallas kernel wants BHSD
    qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    block_sizes = _tuned_block_sizes(q.shape[-1], q.shape[1], k.shape[1])
    try:
        if block_sizes is not None:
            out = flash_attention(
                qt, kt, vt, causal=causal, sm_scale=sm_scale, block_sizes=block_sizes
            )
        else:
            out = flash_attention(qt, kt, vt, causal=causal, sm_scale=sm_scale)
    except Exception:
        return None
    return jnp.swapaxes(out, 1, 2)


def _einsum_attention(
    q, k, v, *, causal, mask=None, q_positions=None, kv_positions=None
):
    scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    if causal:
        if q_positions is None:
            q_positions = jnp.arange(q.shape[1])
        if kv_positions is None:
            kv_positions = jnp.arange(k.shape[1])
        causal_mask = q_positions[:, None] >= kv_positions[None, :]
        scores = jnp.where(causal_mask[None, None, :, :], scores, _NEG_INF)
    if mask is not None:
        scores = jnp.where(mask, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# ---------------------------------------------------------------------------
# ring attention (context parallelism)
# ---------------------------------------------------------------------------


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = True,
) -> jax.Array:
    """Blockwise ring attention over the ``axis_name`` mesh axis.

    Must be called inside ``shard_map`` (or an equivalent SPMD context) where
    ``q``/``k``/``v`` are the *local* sequence shards, laid out so device i on
    the ring holds tokens [i*S, (i+1)*S). Each step computes one KV block's
    contribution with online-softmax accumulation, then rotates K/V one hop
    around the ring via ``ppermute`` (ICI neighbor transfer); compute and
    transfer overlap under XLA's async collective scheduling.
    """
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, s, h, d = q.shape
    n_rep = h // k.shape[2]

    scale = 1.0 / (d**0.5)
    q32 = q.astype(jnp.float32) * scale

    q_pos = my_idx * s + jnp.arange(s)

    def step(carry, _):
        o, m, l, k_blk, v_blk, blk_idx = carry
        kv_pos = blk_idx * s + jnp.arange(s)
        kf = _repeat_kv(k_blk, n_rep).astype(jnp.float32)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q32, kf)
        if causal:
            visible = q_pos[:, None] >= kv_pos[None, :]
            scores = jnp.where(visible[None, None, :, :], scores, _NEG_INF)
        blk_max = jnp.max(scores, axis=-1)  # (b, h, q)
        m_new = jnp.maximum(m, blk_max)
        correction = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])
        l_new = l * correction + jnp.sum(p, axis=-1)
        vf = _repeat_kv(v_blk, n_rep).astype(jnp.float32)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, vf)
        o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
        # rotate kv to the next device on the ring (device r receives from r-1)
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        blk_next = (blk_idx - 1) % axis_size
        return (o_new, m_new, l_new, k_next, v_next, blk_next), None

    o0 = jnp.zeros((b, s, h, d), jnp.float32)
    m0 = jnp.full((b, h, s), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    # constants start axis-unvarying under shard_map's vma typing; the carry
    # becomes varying after step 1, so mark them varying up front
    if hasattr(jax.lax, "pcast"):
        o0, m0, l0 = (jax.lax.pcast(x, (axis_name,), to="varying") for x in (o0, m0, l0))
    (o, m, l, _, _, _), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v, my_idx), None, length=axis_size
    )
    l = jnp.maximum(l, 1e-20)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def make_context_parallel_attention(mesh, axis_name: str = "context", causal: bool = True):
    """Wrap ``ring_attention`` in shard_map for direct use on global arrays."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel._shard_map import shard_map as _shard_map

    spec = P(None, axis_name, None, None)

    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # ring attention is manual over the context axis only; other mesh
        # axes (batch/model) stay under GSPMD
        axis_names={axis_name},
    )
    def cp_attention(q, k, v):
        return ring_attention(q, k, v, axis_name=axis_name, causal=causal)

    return cp_attention
