"""Normalization and positional-embedding primitives (pure jnp; XLA fuses)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm in fp32 accumulation, cast back to input dtype."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    out = x32 * jax.lax.rsqrt(var + eps)
    return (out * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(
    x: jax.Array, weight: jax.Array, bias: jax.Array, eps: float = 1e-5
) -> jax.Array:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (out * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)


def rope_frequencies(
    head_dim: int, max_len: int, theta: float = 10000.0, dtype=jnp.float32
) -> Tuple[jax.Array, jax.Array]:
    """(cos, sin) tables of shape (max_len, head_dim//2)."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope(
    x: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    positions: Optional[jax.Array] = None,
) -> jax.Array:
    """Rotary position embedding. x: (..., seq, heads, head_dim);
    cos/sin: (max_len, head_dim//2); positions: (..., seq) absolute indices
    (needed under context parallelism where each shard holds a sequence slice).
    """
    seq = x.shape[-3]
    if positions is None:
        c = cos[:seq][:, None, :]
        s = sin[:seq][:, None, :]
    else:
        c = cos[positions][..., :, None, :]
        s = sin[positions][..., :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    c = c.astype(x.dtype)
    s = s.astype(x.dtype)
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)


def swiglu(x_gate: jax.Array, x_up: jax.Array) -> jax.Array:
    return jax.nn.silu(x_gate) * x_up


def gelu(x: jax.Array) -> jax.Array:
    return jax.nn.gelu(x, approximate=True)
