"""TPU compute ops: attention (flash/ring), norms, rotary embeddings.

The reference has no equivalent layer (it delegates kernels to torch); these
ops exist because long-context and model math are first-class here
(SURVEY.md §2.3 sequence-parallel row, §7 step 6).
"""

from ray_tpu.ops.attention import attention, ring_attention
from ray_tpu.ops.layers import apply_rope, rms_norm, rope_frequencies

__all__ = ["attention", "ring_attention", "rms_norm", "apply_rope", "rope_frequencies"]
