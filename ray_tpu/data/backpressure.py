"""Pluggable backpressure policies + per-stage queue metrics.

Parity: ``python/ray/data/_internal/execution/backpressure_policy/`` — the
streaming executor consults a policy chain before submitting more work for a
stage. The round-4 fixed bounded window is now one policy
(:class:`ConcurrencyCapPolicy`); :class:`OutputMemoryPolicy` adds the
reference's streaming-output memory bound: a stage stops submitting while the
bytes of its produced-but-unconsumed blocks exceed the cap, so a slow sink
throttles a fast source under bounded memory.

Custom policies: append a factory to ``DataContext.backpressure_policies``;
it is called per stage as ``factory(stats)`` → policy.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional


class StageStats:
    """Per-stage queue metrics (parity: OpRuntimeMetrics): submission and
    consumption counters plus the ready-but-unconsumed byte estimate the
    memory policy throttles on."""

    def __init__(self, name: str):
        self.name = name
        self.submitted = 0
        self.consumed = 0
        self.started_at = time.monotonic()
        self.last_consumed_at = self.started_at
        self.pending: deque = deque()
        self._size_cache: Dict = {}
        # running mean of materialized block sizes: the memory policy uses
        # it to charge UNREADY in-flight tasks their expected output (the
        # reference throttles on estimated block sizes the same way)
        self.avg_block_bytes: Optional[float] = None
        self._avg_n = 0

    def observe_block(self, nbytes: int) -> None:
        self._avg_n += 1
        if self.avg_block_bytes is None:
            self.avg_block_bytes = float(nbytes)
        else:
            self.avg_block_bytes += (nbytes - self.avg_block_bytes) / self._avg_n

    @property
    def inflight(self) -> int:
        return len(self.pending)

    def ready_bytes(self) -> int:
        return self.ready_info()[0]

    def ready_info(self):
        """(bytes, count) of pending blocks whose result already
        materialized — the output queue the consumer hasn't drained."""
        from ray_tpu._private.worker import get_runtime

        rt = get_runtime()
        # LOCAL readiness only: in worker processes object_ready falls back
        # to a head rpc per oid — O(window) round-trips per policy check
        # would load the very loop this plane offloads. A block that landed
        # remotely but not here reads as unready and is charged the average
        # estimate instead (conservative, still bounded).
        probe = getattr(rt, "object_ready_local", None) or rt.object_ready
        total = 0
        n = 0
        for ref in self.pending:
            oid = ref.id()
            size = self._size_cache.get(oid)
            if size is None:
                if not probe(oid):
                    continue
                size = self._block_size(rt, oid)
                self._size_cache[oid] = size
                self.observe_block(size)
            total += size
            n += 1
        return total, n

    @staticmethod
    def _block_size(rt, oid) -> int:
        try:
            entry = None
            ms = getattr(getattr(rt, "scheduler", None), "memory_store", None)
            if ms is not None:
                entry = ms.get_entry(oid)
            if entry is not None and entry[0] == "inline":
                return len(entry[1])
            store = getattr(rt, "store", None) or getattr(
                getattr(rt, "node", None), "store_client", None
            )
            if store is not None:
                mv = store.get(oid, timeout=0)
                if mv is not None:
                    n = mv.nbytes
                    del mv
                    return n
        except Exception:
            pass
        return 0

    def snapshot(self) -> dict:
        return {
            "stage": self.name,
            "submitted": self.submitted,
            "consumed": self.consumed,
            "inflight": self.inflight,
            "ready_bytes": self.ready_bytes(),
            "wall_s": round(self.last_consumed_at - self.started_at, 4),
        }

    def render(self) -> str:
        """One human line for Dataset.stats() (parity: the reference's
        per-operator stats summary)."""
        wall = self.last_consumed_at - self.started_at
        avg = (
            f", avg_block={int(self.avg_block_bytes):,}B"
            if self.avg_block_bytes
            else ""
        )
        rate = f", {self.consumed / wall:.1f} blocks/s" if wall > 1e-6 else ""
        return (
            f"{self.name}: {self.consumed} blocks in {wall:.2f}s"
            f"{rate}{avg}"
        )


class BackpressurePolicy:
    """Decides whether a stage may submit one more block task."""

    def can_submit(self, stats: StageStats) -> bool:  # pragma: no cover
        return True


class ConcurrencyCapPolicy(BackpressurePolicy):
    """The bounded in-flight window (parity:
    ``ConcurrencyCapBackpressurePolicy``)."""

    def __init__(self, cap: int):
        self.cap = max(1, int(cap))

    def can_submit(self, stats: StageStats) -> bool:
        return stats.inflight < self.cap


class OutputMemoryPolicy(BackpressurePolicy):
    """Stop submitting while this stage's outstanding output exceeds the
    byte cap (parity: ``StreamingOutputBackpressurePolicy``). Ready blocks
    count their true size; UNREADY in-flight tasks are charged the running
    average block size — without the estimate, every task would be
    submitted before the first result lands and the cap could never bind.
    At least one block is always allowed so the pipeline cannot deadlock;
    until the first block calibrates the average, one task at a time runs."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)

    def can_submit(self, stats: StageStats) -> bool:
        if stats.inflight == 0:
            return True
        ready_b, ready_n = stats.ready_info()
        avg = stats.avg_block_bytes
        if avg is None:
            return False  # calibrating: serialize until a size is known
        est = ready_b + (stats.inflight - ready_n) * avg
        return est < self.max_bytes


def build_policies(stats: StageStats, window: int) -> List[BackpressurePolicy]:
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    policies: List[BackpressurePolicy] = [ConcurrencyCapPolicy(window)]
    if ctx.max_inflight_bytes:
        policies.append(OutputMemoryPolicy(ctx.max_inflight_bytes))
    for factory in ctx.backpressure_policies or ():
        policies.append(factory(stats))
    return policies


# stats of recent pipeline compositions (driver-side observability; each
# entry stays live while its stage streams)
last_execution_stats: List[StageStats] = []
_STATS_KEEP = 64


def track_stats(stats: StageStats) -> None:
    """Register a stage's stats, pruning old executions so a long-lived
    driver running many pipelines doesn't accumulate them forever."""
    last_execution_stats.append(stats)
    if len(last_execution_stats) > _STATS_KEEP:
        del last_execution_stats[: len(last_execution_stats) - _STATS_KEEP]
