"""DataContext: execution options for Dataset pipelines.

Parity: ``python/ray/data/context.py`` (``DataContext.get_current``) — the
knobs that matter for the streaming executor's backpressure: the bounded
in-flight window (blocks) that caps memory while a consumer iterates.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass


@dataclass
class DataContext:
    # max result-pending block tasks in flight per consuming iterator
    # (becomes the ConcurrencyCapPolicy of the pluggable policy chain,
    # parity: backpressure_policy/concurrency_cap_backpressure_policy.py)
    max_inflight_blocks: int = 4
    # cap on ready-but-unconsumed output bytes per stage; 0 = unbounded
    # (parity: StreamingOutputBackpressurePolicy — a slow sink throttles a
    # fast source under this memory bound)
    max_inflight_bytes: int = 0
    # extra policy factories, each called per stage as factory(stats)
    # -> BackpressurePolicy (see data/backpressure.py)
    backpressure_policies: list = None
    # rows per block targeted by repartition-by-size paths
    target_block_rows: int = 65536

    _local = threading.local()

    @classmethod
    def get_current(cls) -> "DataContext":
        ctx = getattr(cls._local, "ctx", None)
        if ctx is None:
            ctx = cls._local.ctx = cls()
        return ctx


class ActorPoolStrategy:
    """Compute strategy for ``map_batches``: run the transform in a pool of
    long-lived actors instead of stateless tasks (parity:
    ``ActorPoolMapOperator``, execution/operators/actor_pool_map_operator.py).
    Useful when the fn has expensive setup (model weights). With
    ``max_size > size`` the pool autoscales under backlog (parity:
    ``execution/autoscaler/``)."""

    def __init__(self, size: int = 2, max_size: int = 0):
        self.size = max(1, int(size))
        self.max_size = max(self.size, int(max_size)) if max_size else self.size
