"""DataContext: execution options for Dataset pipelines.

Parity: ``python/ray/data/context.py`` (``DataContext.get_current``) — the
knobs that matter for the streaming executor's backpressure: the bounded
in-flight window (blocks) that caps memory while a consumer iterates.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass


@dataclass
class DataContext:
    # max result-pending block tasks in flight per consuming iterator
    # (the role of the reference's StreamingExecutor backpressure policies,
    # streaming_executor.py:48 + backpressure_policy/)
    max_inflight_blocks: int = 4
    # rows per block targeted by repartition-by-size paths
    target_block_rows: int = 65536

    _local = threading.local()

    @classmethod
    def get_current(cls) -> "DataContext":
        ctx = getattr(cls._local, "ctx", None)
        if ctx is None:
            ctx = cls._local.ctx = cls()
        return ctx


class ActorPoolStrategy:
    """Compute strategy for ``map_batches``: run the transform in a pool of
    long-lived actors instead of stateless tasks (parity:
    ``ActorPoolMapOperator``, execution/operators/actor_pool_map_operator.py).
    Useful when the fn has expensive setup (model weights)."""

    def __init__(self, size: int = 2):
        self.size = max(1, int(size))
