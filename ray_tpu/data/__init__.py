"""Distributed datasets (Ray Data equivalent).

Parity: ``python/ray/data`` (SURVEY.md §2.4): lazy plans over object-store
blocks, task-parallel execution with bounded in-flight windows,
``streaming_split`` feeding trainer workers, file datasources.
"""

from ray_tpu.data import aggregate
from ray_tpu.data.aggregate import Count, Max, Mean, Min, Std, Sum
from ray_tpu.data.context import ActorPoolStrategy, DataContext
from ray_tpu.data.dataset import Dataset
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.read_api import (
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,  # noqa: A004
    read_binary_files,
    read_csv,
    read_json,
    read_parquet,
    read_text,
)

__all__ = [
    "Dataset",
    "DataIterator",
    "DataContext",
    "ActorPoolStrategy",
    "aggregate",
    "Count",
    "Sum",
    "Min",
    "Max",
    "Mean",
    "Std",
    "range",
    "from_arrow",
    "from_items",
    "from_numpy",
    "from_pandas",
    "read_binary_files",
    "read_csv",
    "read_json",
    "read_parquet",
    "read_text",
]

from ray_tpu._private import usage as _usage

_usage.record_library_usage("data")
del _usage
