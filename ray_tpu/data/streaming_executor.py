"""Streaming executor: a concurrent operator pipeline over bounded windows.

Parity: ``python/ray/data/_internal/execution/streaming_executor.py:48`` (the
operator loop at ``:270``) + the backpressure policies — redesigned around
object-ref future-chaining instead of a scheduler thread:

* a *stage* transforms a stream of block refs into a stream of block refs;
* task stages submit downstream tasks on upstream refs **without waiting**
  (refs are futures — the cluster scheduler starts the consumer task the
  moment its input lands), so every stage of the pipeline runs concurrently
  on workers while the driver merely tops up submission windows;
* each stage keeps at most ``DataContext.max_inflight_blocks`` (scaled by
  pool size for actor stages) results outstanding — the backpressure bound
  that lets arbitrarily large datasets stream through bounded memory;
* the rare driver-side stage (rebatch) prefetches a window of upstream refs
  so workers stay busy while the driver re-slices.

Stage kinds mirror the reference's physical operators: ``SourceStage`` =
InputDataBuffer + bounded read-task submission, ``TaskMapStage`` =
TaskPoolMapOperator (with op *fusion* — a chain of map/filter/flat_map runs
as ONE task per block), ``ActorMapStage`` = ActorPoolMapOperator,
``RebatchStage`` = the output-splitting/batching operators.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.data.block import (
    Batch,
    block_num_rows,
    concat_blocks,
    normalize_block,
    slice_block,
)


@dataclass
class ReadTask:
    """A lazy source block: ``fn.remote(*args)`` produces the block. Kept
    unsubmitted until the executor's source window has room, so reading a
    100k-file dataset does not flood the cluster with 100k tasks.

    ``supports_columns`` marks readers that can prune columns at the file
    (parquet): the logical optimizer pushes a leading select into
    ``columns`` so pruned data never leaves the source (parity: projection
    pushdown, ``_internal/logical/rules/``)."""

    fn: Any  # a ray_tpu remote function
    args: Tuple
    columns: Optional[List[str]] = None
    supports_columns: bool = False

    def submit(self):
        # name-tagged so the transfer plane's by-task-name ledger rows give
        # per-operator cross-node bytes (summarize_transfers group_by=task)
        fn = self.fn.options(name="data:source")
        if self.columns is not None:
            return fn.remote(*self.args, columns=self.columns)
        return fn.remote(*self.args)


def _window() -> int:
    from ray_tpu.data.context import DataContext

    return max(1, DataContext.get_current().max_inflight_blocks)


# per-operator throughput counters (parity: OpRuntimeMetrics exported by the
# reference's metrics agent): block submissions/consumptions per stage ride
# the batched telemetry plane into /metrics as
# ray_tpu_data_blocks_{submitted,consumed}_total{stage=...}
_op_metrics: dict = {}


def _data_metrics() -> dict:
    if not _op_metrics:
        from ray_tpu.util.metrics import Counter

        _op_metrics["submitted"] = Counter(
            "ray_tpu_data_blocks_submitted_total",
            "block tasks submitted per streaming-executor operator",
            tag_keys=("stage",),
        )
        _op_metrics["consumed"] = Counter(
            "ray_tpu_data_blocks_consumed_total",
            "blocks consumed downstream per streaming-executor operator",
            tag_keys=("stage",),
        )
    return _op_metrics


def _windowed(submitted: Iterator, window: int, name: str = "stage",
              collector: Optional[List] = None) -> Iterator:
    """The backpressure core shared by every stage: pull (and thereby
    submit) ahead of the consumer while the POLICY CHAIN allows, release in
    FIFO order (block order is always preserved). The fixed window is one
    policy; a memory cap on ready-but-unconsumed output is another — see
    ``data/backpressure.py``. When policies block, the stage drains instead
    of submitting: the slow consumer throttles the fast producer."""
    from ray_tpu.data import backpressure as bp

    stats = bp.StageStats(name)
    policies = bp.build_policies(stats, window)
    bp.track_stats(stats)
    if collector is not None:
        collector.append(stats)
    metrics = _data_metrics()
    tags = {"stage": name}
    pending = stats.pending
    exhausted = False
    while True:
        while not exhausted and all(p.can_submit(stats) for p in policies):
            try:
                ref = next(submitted)
            except StopIteration:
                exhausted = True
                break
            pending.append(ref)
            stats.submitted += 1
            metrics["submitted"].inc(tags=tags)
        if not pending:
            if exhausted:
                return
            # every policy refused with nothing in flight — yield anyway via
            # one forced submission so the pipeline cannot wedge
            try:
                ref = next(submitted)
            except StopIteration:
                return
            pending.append(ref)
            stats.submitted += 1
            metrics["submitted"].inc(tags=tags)
        ref = pending.popleft()
        stats._size_cache.pop(ref.id(), None)
        stats.consumed += 1
        stats.last_consumed_at = time.monotonic()
        metrics["consumed"].inc(tags=tags)
        yield ref


class SourceStage:
    """Yields the dataset's source refs; lazy ReadTasks are submitted with a
    bounded look-ahead window."""

    def __init__(self, items: List):
        self.items = items

    def stream(self, collector: Optional[List] = None) -> Iterator:
        return _windowed(
            (
                item.submit() if isinstance(item, ReadTask) else item
                for item in self.items
            ),
            _window(),
            name="source",
            collector=collector,
        )


class TaskMapStage:
    """A fused chain of (kind, fn_blob) ops executed as one task per block.

    Submission chains on upstream refs, so this stage's task for block k
    starts the moment the upstream result for k exists — while upstream is
    still producing block k+n.
    """

    def __init__(self, ops: List):
        self.ops = list(ops)

    def fused(self, more_ops: List) -> "TaskMapStage":
        return TaskMapStage(self.ops + list(more_ops))

    def stream(self, upstream: Iterator, collector: Optional[List] = None) -> Iterator:
        from ray_tpu.data.dataset import _exec_block

        # name-tagged per stage: the link ledger attributes cross-node
        # bytes pulled by these block tasks to `data:map[...]` rows
        stage_name = f"map[{len(self.ops)} ops]"
        fn = _exec_block.options(name=f"data:{stage_name}")
        return _windowed(
            (fn.remote(ref, self.ops) for ref in upstream),
            _window(),
            name=stage_name,
            collector=collector,
        )


class ActorMapStage:
    """Runs a transform in a pool of long-lived actors (expensive setup —
    model weights etc. — amortized across blocks).

    Lazy: the pool is created when the stream is first pulled, not at plan
    time, and blocks are dispatched least-loaded with a bounded per-pool
    window. The pool AUTOSCALES under backlog (parity:
    ``execution/autoscaler/``): when every worker already has
    ``grow_threshold`` unfinished blocks and the pool is below ``max_size``,
    a worker is added before the next dispatch.
    """

    GROW_THRESHOLD = 2  # outstanding blocks per worker before growing

    def __init__(self, fn_blob: bytes, size: int, max_size: Optional[int] = None):
        self.fn_blob = fn_blob
        self.size = max(1, int(size))
        self.max_size = max(self.size, int(max_size)) if max_size else self.size
        self._workers: Optional[List] = None
        self._outstanding: List = []  # per-worker lists of pending refs

    def _pool(self) -> List:
        # one pool per stage, created on first pull and reused across
        # consumptions — re-running expensive __init__ (model weights) for
        # every count()/take()/iter pass would defeat the pool's purpose
        if self._workers is None:
            self._workers = [
                _ActorBlockWorker.remote(self.fn_blob)
                for _ in range(self.size)
            ]
            self._outstanding = [[] for _ in self._workers]
        return self._workers

    def pool_size(self) -> int:
        return len(self._workers or ())

    def _reap(self) -> None:
        import ray_tpu as _rt

        for lst in self._outstanding:
            if lst:
                ready, rest = _rt.wait(lst, num_returns=len(lst), timeout=0)
                lst[:] = rest

    def stream(self, upstream: Iterator, owned_actors: List,
               collector: Optional[List] = None) -> Iterator:
        workers = self._pool()
        # pin on the executing dataset so handle-count reaping cannot kill
        # the pool before its output blocks are consumed
        for w in workers:
            if w not in owned_actors:
                owned_actors.append(w)

        def submitted():
            for ref in upstream:
                self._reap()
                loads = [len(x) for x in self._outstanding]
                i = loads.index(min(loads))
                if (
                    loads[i] >= self.GROW_THRESHOLD
                    and len(workers) < self.max_size
                ):
                    # backlog on every worker: grow the pool
                    w = _ActorBlockWorker.remote(self.fn_blob)
                    workers.append(w)
                    owned_actors.append(w)
                    self._outstanding.append([])
                    i = len(workers) - 1
                out = workers[i].apply.remote(ref)
                self._outstanding[i].append(out)
                yield out

        return _windowed(
            submitted(), _window() * self.max_size, name="actor_map",
            collector=collector,
        )


@ray_tpu.remote
class _ActorBlockWorker:
    def __init__(self, blob):
        import cloudpickle as cp

        obj = cp.loads(blob)
        # callable class -> instantiate once (expensive setup amortized)
        self._fn = obj() if isinstance(obj, type) else obj

    def apply(self, block):
        return normalize_block(self._fn(block))


class RebatchStage:
    """Re-slice the block stream into fixed-row blocks.

    Driver-side by necessity (output blocks span input-block boundaries),
    but *streaming*: a prefetch window of upstream refs keeps workers busy
    while the driver fetches (zero-copy shm reads), slices and re-puts one
    output block at a time. This replaces the old synchronous
    repartition_by_rows barrier on the map_batches(batch_size=...) path.
    """

    def __init__(self, rows_per_block: int):
        self.rows_per_block = int(rows_per_block)

    def stream(self, upstream: Iterator) -> Iterator:
        from ray_tpu.data.dataset import _fetch

        window = _window()
        prefetch: deque = deque()

        def fill():
            while len(prefetch) < window:
                try:
                    prefetch.append(next(upstream))
                except StopIteration:
                    return

        pieces: List[Batch] = []
        buffered = 0
        fill()
        while prefetch:
            block = _fetch(prefetch.popleft())
            fill()
            off = 0
            n = block_num_rows(block)
            while off < n:
                take = min(self.rows_per_block - buffered, n - off)
                pieces.append(slice_block(block, off, off + take))
                buffered += take
                off += take
                if buffered == self.rows_per_block:
                    yield ray_tpu.put(
                        pieces[0] if len(pieces) == 1 else concat_blocks(pieces)
                    )
                    pieces, buffered = [], 0
        if buffered:
            yield ray_tpu.put(concat_blocks(pieces))


def iter_stage_refs(sources: List, stages: List, owned_actors: List,
                    collector: Optional[List] = None) -> Iterator:
    """Compose the stage generators into one lazily-driven pipeline, after
    the logical optimizer has rewritten the plan (projection algebra +
    pushdown into column-pruning reads). ``collector`` (a list) receives
    each stage's StageStats so the owning Dataset can report ITS OWN
    execution metrics, not some other pipeline's."""
    from ray_tpu.data.optimizer import optimize_plan

    sources, stages = optimize_plan(sources, stages)
    stream: Iterator = SourceStage(sources).stream(collector)
    for stage in stages:
        if isinstance(stage, ActorMapStage):
            stream = stage.stream(stream, owned_actors, collector)
        elif isinstance(stage, RebatchStage):
            stream = stage.stream(stream)
        else:
            stream = stage.stream(stream, collector)
    return stream
